"""Ragged paged-PREFILL attention — suffix queries over in-place KV pages.

ISSUE 8 tentpole (ROADMAP open item 1; the Ragged Paged Attention line,
arXiv 2604.15464 in PAPERS.md): every prefill path used to stage the
whole context through a dense ``(1, s_temp, H, D)`` temp cache — gather
the prefix pages in, run the family forward, scatter back. That
gather/scatter is pure HBM traffic that grows with the prefix length,
exactly when the prefix cache should be saving the most, and the static
gather shape ``n_pp`` multiplied the compile grid by the prefix-page
bucket count.

This kernel removes the staging: the suffix tokens' queries attend the
cached prefix **where it sits in the page pool**, by physical page id
via a scalar-prefetched block table, while the suffix's own K/V (not yet
written to pages — the caller scatters it after the layer scan) rides in
as a dense VMEM operand. One flash-style online softmax runs over both
sources: page blocks first (DMA'd by id, masked to ``pos < offset``),
then suffix blocks (masked causally against each query's own position
``offset + j``). Because the block table and ``offset`` are runtime
data, the only compile-relevant shape is the suffix bucket — the
partial-prefill compile grid collapses from O(prefix-buckets ×
suffix-buckets) to O(suffix-buckets).

Ragged across the batch: ``offsets`` and ``seq_lens`` are per-row, so
one dispatch serves rows with different prefix and suffix lengths (rows
are padded to the bucket; padded rows produce finite garbage that
callers slice off).

:func:`ragged_prefill_reference` is the XLA twin — the CPU golden and
the non-TPU execution path — written with the same einsum/softmax
structure as the dense ``_attention`` so the serving engine's greedy
outputs stay bit-stable when it swaps the staging path for this one.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bigdl_tpu.utils.jax_compat import tpu_compiler_params

from bigdl_tpu.llm.kernels.paged_attention import LANE

# scratch budget: acc/m/l rows are hkv * qt * g fp32 vectors; cap the
# row count so the three accumulators stay within a few MB of VMEM at
# production head counts (7B: hkv=32, g=1 -> qt=128)
_MAX_SCRATCH_ROWS = 4096


def _ragged_prefill_kernel(off_ref, len_ref, bt_ref, q_ref, ks_ref,
                           vs_ref, k_hbm, v_hbm, o_ref, kbuf, vbuf, sem,
                           acc_ref, m_ref, l_ref, *, page: int, ppb: int,
                           pages_max: int, hkv: int, g: int, qt: int,
                           nblk_pages: int, scale: float,
                           window: Optional[int] = None):
    """One (batch row b, query block qb, kv block kb) step.

    off_ref/len_ref: (B,) prefix / suffix lengths; bt_ref:
    (B * pages_max,) flat block tables; q_ref (1, hkv, qt*g, D) VMEM
    (row = token*g + group); ks/vs_ref (1, hkv, LANE, D) the kv block's
    slice of the dense suffix K/V; k/v_hbm (P, Hkv, page, D) stay in
    HBM, pages DMA'd by id. kv blocks [0, nblk_pages) read pages
    (masked to pos < offset — the request's own pages are not written
    yet); blocks >= nblk_pages read the suffix operand at positions
    offset + local (masked causally per query row). Scratch carries the
    online-softmax state across the kv dimension; a block that is
    skipped or fully masked for some rows is self-correcting: the
    running-max rescale zeroes its contribution as soon as a real block
    lands (the same argument as the decode kernel's window masking).
    """
    b = pl.program_id(0)
    qb = pl.program_id(1)
    kb = pl.program_id(2)
    nkv = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -1e30)
        l_ref[...] = jnp.zeros_like(l_ref)

    off = off_ref[b]
    slen = len_ref[b]
    rows = qt * g
    d = q_ref.shape[-1]
    # per-row query position: row r holds token (qb*qt + r//g)
    qpos = (off + qb * qt
            + jax.lax.broadcasted_iota(jnp.int32, (rows, LANE), 0) // g)

    def accum(h, k2d, v2d, valid):
        q2d = q_ref[0, h].astype(jnp.float32)              # (rows, D)
        s = jax.lax.dot_general(
            q2d, k2d, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale    # (rows, LANE)
        s = jnp.where(valid, s, -1e30)
        r0 = h * rows
        m_prev = m_ref[r0:r0 + rows]
        l_prev = l_ref[r0:r0 + rows]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, jnp.broadcast_to(m_cur, m_prev.shape))
        alpha = jnp.exp(m_prev[:, :1] - m_new[:, :1])
        p_ = jnp.exp(s - m_new[:, :1])
        l_new = alpha * l_prev[:, :1] + jnp.sum(p_, axis=1, keepdims=True)
        acc_ref[r0:r0 + rows] = (
            acc_ref[r0:r0 + rows] * alpha + jax.lax.dot_general(
                p_, v2d, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32))
        m_ref[r0:r0 + rows] = m_new
        l_ref[r0:r0 + rows] = jnp.broadcast_to(l_new, l_prev.shape)

    # ---- page blocks: prefix K/V read in place, by physical id --------
    base_tok = kb * (ppb * page)

    @pl.when((kb < nblk_pages) & (base_tok < off))
    def _pages():
        # per-page liveness gate: only pages whose first token sits
        # below the prefix end are fetched — a mid-block offset leaves
        # the trailing pages un-DMA'd (their lanes are masked below, so
        # stale buffer contents never contribute). Starts and waits run
        # under the SAME predicate, keeping the semaphore balanced.
        for i in range(ppb):                    # static unroll
            @pl.when(base_tok + i * page < off)
            def _start(i=i):
                pid = bt_ref[b * pages_max + kb * ppb + i]
                pltpu.make_async_copy(k_hbm.at[pid], kbuf.at[i],
                                      sem).start()
                pltpu.make_async_copy(v_hbm.at[pid], vbuf.at[i],
                                      sem).start()
        for i in range(ppb):
            @pl.when(base_tok + i * page < off)
            def _wait(i=i):
                pid = bt_ref[b * pages_max + kb * ppb + i]
                pltpu.make_async_copy(k_hbm.at[pid], kbuf.at[i],
                                      sem).wait()
                pltpu.make_async_copy(v_hbm.at[pid], vbuf.at[i],
                                      sem).wait()
        kvpos = base_tok + jax.lax.broadcasted_iota(
            jnp.int32, (rows, LANE), 1)
        valid = kvpos < off
        if window is not None:
            valid &= kvpos > qpos - window
        # lanes of pages the gate skipped hold UNINITIALIZED scratch
        # (NaN in interpret mode). Masked scores handle K, but a row
        # with no valid lane yet has p_ = exp(0) = 1 everywhere, so V
        # must be finite: zero the dead lanes (the rescale then wipes
        # their garbage weight exactly as before the gating)
        live = (base_tok + jax.lax.broadcasted_iota(
            jnp.int32, (ppb * page, 1), 0)) < off
        for h in range(hkv):                    # static unroll over heads
            accum(h, kbuf[:, h].reshape(ppb * page, d).astype(jnp.float32),
                  jnp.where(live,
                            vbuf[:, h].reshape(ppb * page, d),
                            0).astype(jnp.float32),
                  valid)

    # ---- suffix blocks: this dispatch's own K/V, causal ---------------
    s0 = (kb - nblk_pages) * LANE               # local suffix base

    @pl.when((kb >= nblk_pages) & (s0 < slen) & (s0 < (qb + 1) * qt))
    def _suffix():
        local = s0 + jax.lax.broadcasted_iota(jnp.int32, (rows, LANE), 1)
        kvpos = off + local
        valid = (local < slen) & (kvpos <= qpos)
        if window is not None:
            valid &= kvpos > qpos - window
        for h in range(hkv):
            accum(h, ks_ref[0, h].astype(jnp.float32),
                  vs_ref[0, h].astype(jnp.float32), valid)

    @pl.when(kb == nkv - 1)
    def _finish():
        o_ref[0] = (acc_ref[...]
                    / jnp.maximum(l_ref[:, :1], 1e-30)).reshape(
                        hkv, rows, d).astype(o_ref.dtype)


def _pow2_at_least(n: int, floor: int = 8) -> int:
    return max(floor, 1 << max(n - 1, 0).bit_length())


@functools.partial(jax.jit, static_argnames=("page_size", "interpret",
                                             "sliding_window"))
def ragged_prefill_attention(q, k_suf, v_suf, k_pages, v_pages,
                             block_tables, offsets, seq_lens,
                             page_size: int = 16,
                             interpret: bool = False,
                             sliding_window: Optional[int] = None):
    """Mosaic ragged paged-prefill attention.

    q: (B, Tq, Hq, D) suffix queries — row ``(b, j)`` sits at absolute
    position ``offsets[b] + j``; k_suf/v_suf: (B, Tq, Hkv, D) the
    suffix's own K/V (NOT yet in the pool — the caller scatters it into
    the request's pages after the layer scan); k_pages/v_pages:
    (P, Hkv, page_size, D); block_tables: (B, pages_max) physical page
    ids covering positions ``0 .. offsets[b]`` (entries beyond the
    prefix may be any valid id — masked); offsets/seq_lens: (B,) int32
    runtime prefix / true-suffix lengths. ``pages_max`` must be a
    multiple of ``LANE // page_size``. Query rows ``j >= seq_lens[b]``
    return finite garbage (callers slice to the true length). Returns
    (B, Tq, Hq, D) float32.
    """
    b, tq, hq, d = q.shape
    p_, hkv, page, _ = k_pages.shape
    assert page == page_size
    ppb = LANE // page_size
    pages_max = block_tables.shape[1]
    if pages_max % ppb:
        raise ValueError(f"pages_max {pages_max} not a multiple of {ppb}")
    nblk_pages = pages_max // ppb
    g = hq // hkv
    scale = 1.0 / float(np.sqrt(d))

    # pad the suffix to a pow2 tile count (padded rows masked/ignored)
    tq_pad = _pow2_at_least(tq)
    if tq_pad != tq:
        pad = ((0, 0), (0, tq_pad - tq), (0, 0), (0, 0))
        q = jnp.pad(q, pad)
        k_suf = jnp.pad(k_suf, pad)
        v_suf = jnp.pad(v_suf, pad)
    # Mosaic page DMAs need a 128-aligned minor dim (same pad story as
    # the decode kernel: zero K columns leave scores unchanged, padded V
    # columns are sliced off below)
    d_orig = d
    if d % 128:
        dp = -(-d // 128) * 128
        dpad = (0, dp - d)
        q = jnp.pad(q, ((0, 0), (0, 0), (0, 0), dpad))
        k_suf = jnp.pad(k_suf, ((0, 0), (0, 0), (0, 0), dpad))
        v_suf = jnp.pad(v_suf, ((0, 0), (0, 0), (0, 0), dpad))
        k_pages = jnp.pad(k_pages, ((0, 0), (0, 0), (0, 0), dpad))
        v_pages = jnp.pad(v_pages, ((0, 0), (0, 0), (0, 0), dpad))
        d = dp

    # query tile: pow2, scratch rows (hkv * qt * g) bounded
    qt = tq_pad
    while qt > 8 and qt * g * hkv > _MAX_SCRATCH_ROWS:
        qt //= 2
    nqblk = tq_pad // qt
    rows = qt * g

    # row = token*g + group, so one q tile is qt contiguous tokens
    qg = (q.reshape(b, tq_pad, hkv, g, d).transpose(0, 2, 1, 3, 4)
          .reshape(b, hkv, tq_pad * g, d))
    # suffix K/V padded to whole LANE blocks, head-major
    ts = -(-tq_pad // LANE) * LANE
    ks = k_suf.transpose(0, 2, 1, 3)                  # (B, Hkv, Tq, D)
    vs = v_suf.transpose(0, 2, 1, 3)
    if ts != tq_pad:
        ks = jnp.pad(ks, ((0, 0), (0, 0), (0, ts - tq_pad), (0, 0)))
        vs = jnp.pad(vs, ((0, 0), (0, 0), (0, ts - tq_pad), (0, 0)))
    nblk_suf = ts // LANE
    nkv = nblk_pages + nblk_suf

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, nqblk, nkv),
        in_specs=[
            pl.BlockSpec((1, hkv, rows, d),
                         lambda b_, q_, k_, *_: (b_, 0, q_, 0)),
            pl.BlockSpec((1, hkv, LANE, d),
                         lambda b_, q_, k_, *_:
                         (b_, 0, jnp.maximum(k_ - nblk_pages, 0), 0)),
            pl.BlockSpec((1, hkv, LANE, d),
                         lambda b_, q_, k_, *_:
                         (b_, 0, jnp.maximum(k_ - nblk_pages, 0), 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, hkv, rows, d),
                               lambda b_, q_, k_, *_: (b_, 0, q_, 0)),
        scratch_shapes=[
            pltpu.VMEM((ppb, hkv, page, d), k_pages.dtype),
            pltpu.VMEM((ppb, hkv, page, d), v_pages.dtype),
            pltpu.SemaphoreType.DMA,
            pltpu.VMEM((hkv * rows, d), jnp.float32),
            pltpu.VMEM((hkv * rows, LANE), jnp.float32),
            pltpu.VMEM((hkv * rows, LANE), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_ragged_prefill_kernel, page=page_size,
                          ppb=ppb, pages_max=pages_max, hkv=hkv, g=g,
                          qt=qt, nblk_pages=nblk_pages, scale=scale,
                          window=sliding_window),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, tq_pad * g, d),
                                       jnp.float32),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(offsets.astype(jnp.int32), seq_lens.astype(jnp.int32),
      block_tables.reshape(-1).astype(jnp.int32), qg, ks, vs, k_pages,
      v_pages)
    out = (out.reshape(b, hkv, tq_pad, g, d)
           .transpose(0, 2, 1, 3, 4).reshape(b, tq_pad, hq, d))
    return out[:, :tq, :, :d_orig]


def ragged_prefill_reference(q, k_suf, v_suf, k_pages, v_pages,
                             block_tables, offsets, seq_lens,
                             sliding_window: Optional[int] = None):
    """XLA twin of :func:`ragged_prefill_attention` (same contract) —
    the CPU golden and the non-TPU serving path. The einsum/softmax
    structure mirrors the dense ``llama._attention`` single-block path
    so greedy outputs through the engine stay stable when the staging
    prefill is replaced by this one. The page gather is sliced to the
    live prefix span when ``offsets`` is concrete (the padded-capacity
    fix that also covers ``paged_attention_reference``)."""
    from bigdl_tpu.llm.kernels.paged_attention import _sliced_tables
    b, tq, hq, d = q.shape
    p_, hkv, page, _ = k_pages.shape
    g = hq // hkv
    block_tables = _sliced_tables(block_tables, offsets, page)
    pages_max = block_tables.shape[1]
    s_pages = pages_max * page
    k_pre = (k_pages[block_tables].transpose(0, 1, 3, 2, 4)
             .reshape(b, s_pages, hkv, d))
    v_pre = (v_pages[block_tables].transpose(0, 1, 3, 2, 4)
             .reshape(b, s_pages, hkv, d))
    k_all = jnp.concatenate([k_pre, k_suf], axis=1)    # (B, S, Hkv, D)
    v_all = jnp.concatenate([v_pre, v_suf], axis=1)
    qpos = offsets[:, None] + jnp.arange(tq)[None, :]          # (B, Tq)
    kvpos = jnp.concatenate(
        [jnp.broadcast_to(jnp.arange(s_pages)[None, :], (b, s_pages)),
         offsets[:, None] + jnp.arange(tq)[None, :]], axis=1)  # (B, S)
    valid = jnp.concatenate(
        [jnp.arange(s_pages)[None, :] < offsets[:, None],
         jnp.arange(tq)[None, :] < seq_lens[:, None]], axis=1)
    mask = valid[:, None, :] & (kvpos[:, None, :] <= qpos[:, :, None])
    if sliding_window is not None:
        mask &= kvpos[:, None, :] > qpos[:, :, None] - sliding_window
    qg = (q.reshape(b, tq, hkv, g, d).transpose(0, 2, 3, 1, 4)
          .astype(jnp.float32))                       # (B, Hkv, G, Tq, D)
    scale = 1.0 / float(np.sqrt(d))
    s = jnp.einsum("bhgtd,bshd->bhgts", qg,
                   k_all.astype(jnp.float32)) * scale
    s = jnp.where(mask[:, None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhgts,bshd->bhgtd", p, v_all.astype(jnp.float32))
    return (out.transpose(0, 3, 1, 2, 4).reshape(b, tq, hq, d)
            .astype(jnp.float32))


def ragged_prefill(q, k_suf, v_suf, k_pages, v_pages, block_tables,
                   offsets, seq_lens, page_size: int = 16,
                   interpret: Optional[bool] = None,
                   sliding_window: Optional[int] = None):
    """Backend dispatch: Mosaic kernel on TPU, XLA twin elsewhere."""
    if interpret is None:
        if jax.default_backend() != "tpu":
            return ragged_prefill_reference(
                q, k_suf, v_suf, k_pages, v_pages, block_tables,
                offsets, seq_lens, sliding_window=sliding_window)
        interpret = False
    return ragged_prefill_attention(
        q, k_suf, v_suf, k_pages, v_pages, block_tables, offsets,
        seq_lens, page_size=page_size, interpret=interpret,
        sliding_window=sliding_window)

"""On-device next-token sampling for the serving engine (ISSUE 4).

The synchronous engine sampled on the HOST: every decode step pulled the
(B, V) logits' argmax to python before it could dispatch the next step —
one device→host roundtrip per token, serialized against device compute
(on the tunneled TPU runtime that roundtrip is ~100 ms, BENCH_r02's
measured "sync overhead"). Folding sampling INTO the compiled decode
step means the step consumes the previous step's logits entirely on
device and emits ready-to-drain token ids, so the host only fetches a
small int vector — and, under pipelining, fetches it one step late
while the device is already running the next step.

Everything here is plain XLA (argmax / top_k / categorical): it lowers
to the same fused program on TPU and CPU, no Mosaic kernel needed — the
decode step's cost is the weight stream, not the (B, V) reduction.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sample_tokens(logits, key, *, do_sample: bool = False,
                  temperature=1.0, top_k: int = 0):
    """``(B, V)`` logits → ``(B,)`` int32 next tokens.

    ``do_sample``/``top_k`` are trace-time constants (they change the
    program); ``temperature`` is a runtime scalar so serving can tune it
    without a recompile. Greedy (``do_sample=False``) is bit-identical
    to the host-side ``argmax`` it replaces — the serving parity tests
    assert served tokens equal ``generate()``'s.
    """
    if not do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temperature, 1e-6)
    if 0 < top_k < scaled.shape[-1]:
        # top_k >= vocab is a no-op filter — and lax.top_k rejects
        # k > minor dim outright, so the clamp is correctness, not
        # just a shortcut (locked by tests/test_sampling.py)
        kth = jax.lax.top_k(scaled, top_k)[0][:, -1][:, None]
        scaled = jnp.where(scaled < kth, -1e30, scaled)
    return jax.random.categorical(key, scaled).astype(jnp.int32)


def fence_token(*arrays):
    """A ``(1,)`` int32 whose VALUE is garbage but whose availability
    data-depends on every input array.

    ``jax.block_until_ready`` is unreliable on the axon-tunneled TPU
    runtime (serving._sync_barrier's round-4 finding); the only portable
    completion fence is a real device→host fetch of data that depends on
    the computation. The engine concatenates this element onto the
    sampled token vector, so ONE small fetch both delivers the tokens
    and bounds the step's pool writes — no second roundtrip.

    The first element of each array is summed (never multiplied by zero:
    XLA may constant-fold ``x*0`` for ints and would sever the data
    dependence), NaN-scrubbed and clipped so the int cast is defined.
    """
    acc = jnp.float32(0.0)
    for a in arrays:
        acc = acc + a.ravel()[0].astype(jnp.float32)
    acc = jnp.clip(jnp.nan_to_num(acc), -1e9, 1e9)
    return acc.astype(jnp.int32)[None]


def spec_accept(ctoks, chunk_logits, n_draft):
    """Fused speculative verify-accept (ISSUE 19), greedy exact-match.

    ``ctoks`` (W,) int32 is the verify chunk — the on-device greedy
    token ``g0`` followed by ``n_draft`` host drafts (zero-padded to the
    bucket width W); ``chunk_logits`` (W, V) f32 are the ragged chunk
    leg's logits, where row ``j`` is the distribution AFTER consuming
    chunk token ``j`` (i.e. it predicts position ``offset + j + 1``).
    Draft ``j`` (= ``ctoks[j]``, j >= 1) is accepted iff it equals
    ``argmax(chunk_logits[j-1])`` — exactly the token greedy decode
    would have emitted there — and every earlier draft was accepted.

    Returns ``(n_acc, new_last)``: the emitted-token count (the
    accepted-draft prefix plus the always-valid ``g0``, so
    ``1 <= n_acc <= n_draft + 1``) and ``chunk_logits[n_acc - 1]`` —
    the distribution following the LAST emitted token, which becomes
    the row's ``last`` for the next engine step. With zero drafts
    accepted this degenerates to a plain decode step: emit ``g0``,
    carry ``chunk_logits[0]``.

    Pad rows (``j >= n_draft``) can never match (the arange mask), so
    garbage logits at padded positions — finite by the kernels'
    masked-lane contract — cannot extend the accepted prefix.

    Greedy only: the rejection-sampling acceptance rule for
    ``temperature > 0`` hangs off this same contract (replace the
    exact-match test with the p/q coin flip) but is gated off with the
    engine's ``do_sample`` path for now.
    """
    w = ctoks.shape[0]
    greedy = jnp.argmax(chunk_logits, axis=-1).astype(jnp.int32)  # (W,)
    match = (ctoks[1:] == greedy[:-1]) & \
        (jnp.arange(w - 1, dtype=jnp.int32) < n_draft)
    # longest all-accepted prefix: cumprod zeroes everything after the
    # first rejection, the sum counts the survivors
    n_acc = jnp.sum(jnp.cumprod(match.astype(jnp.int32))) + 1
    new_last = jnp.take(chunk_logits, n_acc - 1, axis=0)
    return n_acc.astype(jnp.int32), new_last.astype(jnp.float32)


def make_sampled_step(fam_step):
    """Lift a family ``paged_decode_step`` (toks-in, logits-out) into the
    pipelined engine's step shape (logits-in, sampled-ids-out).

    The lifted step:

    - samples the next token for every row from ``last`` ON DEVICE;
    - masks block-table rows and lengths of inactive rows to the trash
      page (page 0 / length 0), so rows whose dispatch budget is spent
      — or whose slot is empty — dummy-write into the trash page
      exactly like the synchronous engine's zeroed ``bt`` rows did;
    - advances ``lens`` for active rows on device (the host never
      re-uploads the length vector);
    - returns ``(out, logits, k_pages, v_pages, new_lens, key)`` where
      ``out`` is ``(B+1,)`` int32: the B sampled ids plus a
      :func:`fence_token` element bounding the pool writes.

    Each family module exposes ``paged_decode_step_sampled =
    make_sampled_step(paged_decode_step)`` so the engine dispatches one
    compiled program per family with no per-family sampling code.
    """

    def sampled_step(params, cfg, k_pages, v_pages, bt, lens, last,
                     active, temperature, key, *, page: int,
                     do_sample: bool = False, top_k: int = 0):
        key, sub = jax.random.split(key)
        toks = sample_tokens(last, sub, do_sample=do_sample,
                             temperature=temperature, top_k=top_k)
        bt_eff = jnp.where(active[:, None], bt, 0)
        lens_eff = jnp.where(active, lens, 0)
        logits, k_pages, v_pages = fam_step(
            params, cfg, k_pages, v_pages, bt_eff, lens_eff, toks,
            page=page)
        # inactive rows carry their previous logits forward instead of
        # the trash-page garbage their masked leg computed: a row
        # sitting out passes while its speculative verify is in flight
        # (ISSUE 19) must find its ``last`` intact at the drain, and an
        # empty slot's lane was never read either way
        logits = jnp.where(active[:, None], logits, last)
        new_lens = lens + active.astype(lens.dtype)
        out = jnp.concatenate(
            [toks, fence_token(k_pages, v_pages, logits)])
        return out, logits, k_pages, v_pages, new_lens, key

    return sampled_step

"""INT4/INT8 block-dequant matmul Pallas kernels.

Reference counterpart: bigdl-llm's native q4_0 matvec (ctypes →
llama.cpp-family C kernels, SURVEY.md §3.4 hot loop). TPU design:

- weights stream packed from HBM (uint8, 2 nibbles/byte) — 4.5 bits/
  weight including scales, ~3.5x less HBM traffic than bf16. Decode is
  HBM-bandwidth-bound, so this is where the speed comes from (same
  reason the reference's CPU kernels win on DDR bandwidth).
- **k-major "TPU layout"**: packed weights are stored (K/2, N) and
  scales (K/QK, N) — transposed once at load by :func:`to_tpu_layout` —
  so the kernel's dequantized tile feeds ``jnp.dot`` directly with no
  in-register transpose, and every BlockSpec dim is either 128-aligned
  or the full array dim (the r2 kernel's (bn, bk//QK) scale block
  violated Pallas's last-dim rule and never lowered on real TPU).
- the per-32-group scale broadcast runs on the **MXU, not the VPU**: an
  expansion matrix E (K/2, G) with E[i, g] = [i//16 == g] is built from
  two iotas and ``s_exp = E @ scales`` expands group scales to per-row
  scales as a matmul. The naive reshape-broadcast costs a Mosaic
  relayout per weight and measured 3x slower on chip.
- the q4_0 zero-point (-8) is algebraic, not elementwise:
  sum_k x_k*(q-8)*s = sum_k x_k*q*s - 8*sum_g (sum_{k in g} x_k)*s[g]
  so decode (m small, bandwidth-bound) folds it into one extra skinny
  dot against ``s_exp``; prefill (m large, MXU-bound) subtracts 8 on
  the VPU instead, trading VPU ops for a third of the MXU work.
- float16 never enters the kernel: this Mosaic build cannot load fp16
  (verified on chip: "Unsupported cast"-class remote-compile failures),
  so ggml's fp16 scales are converted to f32 on the host.

Measured on TPU v5 lite (1 chip, 819 GB/s HBM), (1, 4096)x(4096, 11008)
Llama-2-7B decode matvec: ~130 us — parity with XLA's dense bf16 matvec
(~122 us, which runs at the full 740 GB/s HBM rate) while streaming
3.2x fewer bytes. At m=1 both are bounded by per-weight compute/issue
rate, not bandwidth: the kernel's VPU dequant (~7 ops/packed byte:
widen, 2x mask/shift, 2x cast, 2x scale-mul) runs at the ~1.7 T op/s
effective VPU rate, which lands within 10% of the dense matvec's
bandwidth floor. Alternatives measured and rejected on chip: VPU-only
matvec (no MXU) 174 us; scale expansion via in-kernel expansion-matrix
matmul vs pltpu.repeat — identical; int8 MXU dots offer no rate gain on
this toolchain (1.09x), closing the W4A8 route. The win int4 keeps:
4x less HBM *footprint* (7B fits comfortably beside its KV cache) and
4x less HBM traffic, which turns into throughput wherever the batch
dimension (m >= 16) lifts the compute floor — batched decode and
prefill — and on bandwidth-richer TPUs.

Round-4 additions to the measured-alternatives ledger (all on the same
v5e, 7B decode shapes, m=1): (a) fusing q/k/v and gate/up into single
kernel calls (7 → 4 launches/layer) is perf-neutral within the ~20%
tenancy noise — per-launch overhead is NOT a bottleneck on this
runtime; (b) unrolling the 32-layer scan is strictly worse (unroll=8:
-27%; full python-loop: -18%) — the rolled scan pipelines the weight
stream best; (c) bf16 scale storage is SLOWER than f32 (140 vs 115 us
micro) despite 12% fewer bytes — the f32 DMA pipelines better and the
kernel casts scales to bf16 in-register either way; (d) bn=512 blocks
exceed the 16M scoped-vmem limit at full-K chunks. The in-context
matmul-only decode floor is ~0.88 ms/layer (34.9 tok/s for 7B) — the
per-layer cost in a live scan runs ~40% above the lone-kernel micro
because consecutive distinct kernels cannot share the double-buffered
stream an identical-kernel micro loop enjoys.

Round-5 ledger entry (closes VERDICT r4 weak #3 / next-round item 5):
the proposed per-layer **megakernel** (qkv+o+gate/up+down sharing one
double-buffered weight stream) is REFUTED by direct measurement
(tools/exp_stream_sharing.py, on-chip fori-loop slope harness, 500-iter
pairs): a loop alternating the two largest distinct-shape matvecs costs
**1.012×** the sum of their individual slope times, and the full
4-matvec dependency chain (qkv→o→gate_up→down, the live layer minus
norm/rope/attention) costs **1.019×** the 4-kernel sum (669 → 682
µs/layer). Kernel-to-kernel transitions therefore lose ~2%, not the
~40% the r4 ledger hypothesized — a fused megakernel's maximum recovery
is ~13 µs/layer ≈ 0.4 tok/s at 7B. The remaining b1 gap
(~0.35 ms/layer between the 0.68 ms matmul chain and the ~1.0 ms live
layer) sits in the non-matmul work (rms_norm, rope, cache attention,
scan plumbing) — small latency-bound VPU ops, not weight streaming.
Measured slopes for the record: qkv 124.7 µs, gate_up 220.5, o 223.5,
down 100.6, alt 349.4, chain 682.0. Per-shape micros show large
run-to-run swings beyond the 20% tenancy band on the small shapes
(o measured 71/155/223 µs across three sessions; a qkv bn=512 micro
read 977 GB/s packed — above HBM spec, i.e. an artifact), so the
tile-size question was settled END-TO-END instead: interleaved A/B of
the full b1 7B decode bench with DEFAULT_BN 256 vs 512 (2 reps each)
measured 29.83/29.83 vs 29.87/29.77 tok/s — dead even. bn stays 256;
b1 decode is not kernel-tile-bound.

``interpret=True`` runs the same kernel on CPU for tests (SURVEY.md §4:
golden parity against an independent implementation — here the numpy
dequant reference).
"""

from __future__ import annotations

import functools
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bigdl_tpu.utils.jax_compat import tpu_compiler_params

from bigdl_tpu.llm.ggml.quantize import QK

HALF = QK // 2          # scale-group size within one nibble plane
_MAX_BK = 8192          # K above this is chunked to bound VMEM
                        # (K=11008 at bm=128 overflowed the 16M scoped
                        # vmem limit on chip with full-K blocks)


def _align_bm(bm: int, m: int) -> int:
    """Round the M tile up to a 16-aligned shape: Mosaic rejects
    non-8/16-aligned second-minor block dims, so bm must be a tile
    multiple even when 16 < m < 128 (e.g. m=100 -> bm=112, pad M)."""
    return min(bm, max(16, -(-m // 16) * 16))


def _scale_expand(scale_ref, half: int, cdt):
    """(G, bn) group scales → (half, bn) per-row scales via an MXU matmul
    against an iota-built expansion matrix (no VPU relayout)."""
    g = half // HALF
    sc = scale_ref[:].astype(cdt)
    row = jax.lax.broadcasted_iota(jnp.int32, (half, g), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (half, g), 1)
    e = jnp.where(row // HALF == col, 1.0, 0.0).astype(cdt)
    return jnp.dot(e, sc, preferred_element_type=jnp.float32).astype(cdt)


def _int4_kernel(xe_ref, xo_ref, q_ref, scale_ref, o_ref, *, sub8: bool,
                 cdt=jnp.bfloat16):
    """One (bm, bn) output tile.

    xe/xo: (bm, K/2) even/odd k-plane activations; q: (K/2, bn) packed
    uint8 (low nibble = even k, high = odd k); scale: (G, bn).
    ``cdt`` is the MXU operand dtype (f32 under interpret: the CPU thunk
    cannot execute bf16 x bf16 dots).
    """
    q = q_ref[:].astype(jnp.int32)
    half, _ = q.shape
    s_exp = _scale_expand(scale_ref, half, cdt)
    xe = xe_ref[:].astype(cdt)
    xo = xo_ref[:].astype(cdt)
    if sub8:
        lo = ((q & 0xF) - 8).astype(cdt) * s_exp
        hi = ((q >> 4) - 8).astype(cdt) * s_exp
        acc = jnp.dot(xe, lo, preferred_element_type=jnp.float32)
        acc += jnp.dot(xo, hi, preferred_element_type=jnp.float32)
    else:
        lo = (q & 0xF).astype(cdt) * s_exp
        hi = (q >> 4).astype(cdt) * s_exp
        acc = jnp.dot(xe, lo, preferred_element_type=jnp.float32)
        acc += jnp.dot(xo, hi, preferred_element_type=jnp.float32)
        acc -= 8.0 * jnp.dot(xe + xo, s_exp,
                             preferred_element_type=jnp.float32)
    o_ref[:] = acc.astype(o_ref.dtype)


def _asym_int4_kernel(xe_ref, xo_ref, q_ref, scale_ref, zero_ref, o_ref,
                      *, cdt=jnp.bfloat16):
    """q4_1: w = q * scale + zero (zero = per-group minimum)."""
    q = q_ref[:].astype(jnp.int32)
    half, _ = q.shape
    s_exp = _scale_expand(scale_ref, half, cdt)
    z_exp = _scale_expand(zero_ref, half, cdt)
    lo = (q & 0xF).astype(cdt) * s_exp
    hi = (q >> 4).astype(cdt) * s_exp
    xe = xe_ref[:].astype(cdt)
    xo = xo_ref[:].astype(cdt)
    acc = jnp.dot(xe, lo, preferred_element_type=jnp.float32)
    acc += jnp.dot(xo, hi, preferred_element_type=jnp.float32)
    acc += jnp.dot(xe + xo, z_exp, preferred_element_type=jnp.float32)
    o_ref[:] = acc.astype(o_ref.dtype)


def _int8_kernel(x_ref, q_ref, scale_ref, o_ref, *, cdt=jnp.bfloat16):
    """q8_0: w = q * scale, q int8 (K, bn) — unpack-free stream."""
    q = q_ref[:].astype(jnp.int32)
    k, _ = q.shape
    g = k // QK
    sc = scale_ref[:].astype(cdt)
    row = jax.lax.broadcasted_iota(jnp.int32, (k, g), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (k, g), 1)
    e = jnp.where(row // QK == col, 1.0, 0.0).astype(cdt)
    s_exp = jnp.dot(e, sc, preferred_element_type=jnp.float32).astype(cdt)
    w = q.astype(cdt) * s_exp
    o_ref[:] = jnp.dot(x_ref[:].astype(cdt), w,
                       preferred_element_type=jnp.float32) \
        .astype(o_ref.dtype)


def _pad_nk(q_t, scale_t, bn, pad_byte, extras=()):
    n = q_t.shape[1]
    n_pad = -n % bn
    if n_pad:
        q_t = jnp.pad(q_t, ((0, 0), (0, n_pad)), constant_values=pad_byte)
        scale_t = jnp.pad(scale_t, ((0, 0), (0, n_pad)))
        extras = tuple(jnp.pad(z, ((0, 0), (0, n_pad))) for z in extras)
    return (q_t, scale_t) + extras


def _chunk_k(k: int):
    """Split K into <= _MAX_BK chunks (each a multiple of QK)."""
    if k <= _MAX_BK:
        return [(0, k)]
    n_chunks = -(-k // _MAX_BK)
    per = -(-k // (n_chunks * QK)) * QK
    out, s = [], 0
    while s < k:
        out.append((s, min(per, k - s)))
        s += per
    return out


# default N tile; module-level so A/B harnesses can flip it globally
# (bn=512 fits scoped vmem for every 7B decode shape with the _MAX_BK
# K-chunking; bn=1024 OOMs at 18.5M > 16M)
DEFAULT_BN = 256


def int4_matmul(x, q_t, scale_t, bm: int = 128, bn: Optional[int] = None,
                interpret: bool = False, out_dtype=jnp.bfloat16,
                mode: str = "auto"):
    """y = x @ dequant_q4_0(q, scale) in TPU layout.

    x: (M, K) activations; q_t: (K/2, N) packed uint8 (low nibble =
    even k); scale_t: (K/QK, N) float32 (fp16 accepted, converted).
    ``mode``: "corr" folds the -8 zero-point into an extra skinny dot
    (best for decode), "sub8" subtracts on the VPU (best for prefill),
    "auto" picks by M. ``bn=None`` resolves :data:`DEFAULT_BN` HERE,
    outside the jit, so flipping the module default retraces."""
    return _int4_matmul_jit(x, q_t, scale_t, bm=bm,
                            bn=bn if bn is not None else DEFAULT_BN,
                            interpret=interpret, out_dtype=out_dtype,
                            mode=mode)


@functools.partial(jax.jit, static_argnames=("bm", "bn", "interpret",
                                             "out_dtype", "mode"))
def _int4_matmul_jit(x, q_t, scale_t, bm: int, bn: int,
                     interpret: bool, out_dtype, mode: str):
    m, k = x.shape
    n = q_t.shape[1]
    if q_t.shape[0] * 2 != k:
        raise ValueError(
            f"q_t {q_t.shape} is not the (K/2, N) TPU layout for K={k}; "
            "convert ggml (N, K/2) dicts with to_tpu_layout() first")
    sub8 = (m >= 256) if mode == "auto" else (mode == "sub8")
    scale_t = scale_t.astype(jnp.float32)
    bm = _align_bm(bm, m)
    m_pad = -m % bm
    if m_pad:
        x = jnp.pad(x, ((0, m_pad), (0, 0)))
    q_t, scale_t = _pad_nk(q_t, scale_t, bn, 0x88)
    mp, np_ = x.shape[0], q_t.shape[1]
    x = x.astype(jnp.bfloat16)

    out = None
    for k0, kc in _chunk_k(k):
        xe = x[:, k0:k0 + kc:2]
        xo = x[:, k0 + 1:k0 + kc:2]
        qc = q_t[k0 // 2:(k0 + kc) // 2]
        sc = scale_t[k0 // QK:(k0 + kc) // QK]
        half, g = kc // 2, kc // QK
        part = pl.pallas_call(
            functools.partial(_int4_kernel, sub8=sub8,
                              cdt=jnp.float32 if interpret
                              else jnp.bfloat16),
            grid=(mp // bm, np_ // bn),
            in_specs=[
                pl.BlockSpec((bm, half), lambda i, j: (i, 0)),
                pl.BlockSpec((bm, half), lambda i, j: (i, 0)),
                pl.BlockSpec((half, bn), lambda i, j: (0, j)),
                pl.BlockSpec((g, bn), lambda i, j: (0, j)),
            ],
            out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
            out_shape=jax.ShapeDtypeStruct((mp, np_), jnp.float32),
            compiler_params=tpu_compiler_params(
                dimension_semantics=("parallel", "parallel")),
            interpret=interpret,
        )(xe, xo, qc, sc)
        out = part if out is None else out + part
    return out[:m, :n].astype(out_dtype)


@functools.partial(jax.jit, static_argnames=("bm", "bn", "interpret",
                                             "out_dtype"))
def asym_int4_matmul(x, q_t, scale_t, zero_t, bm: int = 128, bn: int = 256,
                     interpret: bool = False, out_dtype=jnp.bfloat16):
    """y = x @ dequant_q4_1(q, scale, zero) in TPU layout."""
    m, k = x.shape
    n = q_t.shape[1]
    scale_t = scale_t.astype(jnp.float32)
    zero_t = zero_t.astype(jnp.float32)
    bm = _align_bm(bm, m)
    m_pad = -m % bm
    if m_pad:
        x = jnp.pad(x, ((0, m_pad), (0, 0)))
    q_t, scale_t, zero_t = _pad_nk(q_t, scale_t, bn, 0, (zero_t,))
    mp, np_ = x.shape[0], q_t.shape[1]
    x = x.astype(jnp.bfloat16)

    out = None
    for k0, kc in _chunk_k(k):
        xe = x[:, k0:k0 + kc:2]
        xo = x[:, k0 + 1:k0 + kc:2]
        qc = q_t[k0 // 2:(k0 + kc) // 2]
        sc = scale_t[k0 // QK:(k0 + kc) // QK]
        zc = zero_t[k0 // QK:(k0 + kc) // QK]
        half, g = kc // 2, kc // QK
        part = pl.pallas_call(
            functools.partial(_asym_int4_kernel,
                              cdt=jnp.float32 if interpret
                              else jnp.bfloat16),
            grid=(mp // bm, np_ // bn),
            in_specs=[
                pl.BlockSpec((bm, half), lambda i, j: (i, 0)),
                pl.BlockSpec((bm, half), lambda i, j: (i, 0)),
                pl.BlockSpec((half, bn), lambda i, j: (0, j)),
                pl.BlockSpec((g, bn), lambda i, j: (0, j)),
                pl.BlockSpec((g, bn), lambda i, j: (0, j)),
            ],
            out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
            out_shape=jax.ShapeDtypeStruct((mp, np_), jnp.float32),
            compiler_params=tpu_compiler_params(
                dimension_semantics=("parallel", "parallel")),
            interpret=interpret,
        )(xe, xo, qc, sc, zc)
        out = part if out is None else out + part
    return out[:m, :n].astype(out_dtype)


@functools.partial(jax.jit, static_argnames=("bm", "bn", "interpret",
                                             "out_dtype"))
def int8_matmul(x, q_t, scale_t, bm: int = 128, bn: int = 256,
                interpret: bool = False, out_dtype=jnp.bfloat16):
    """y = x @ dequant_q8_0(q, scale) — the BigQuant INT8 gemm
    equivalent (SURVEY.md §2.2). q_t: (K, N) int8; scale_t: (K/QK, N)."""
    m, k = x.shape
    n = q_t.shape[1]
    if q_t.shape[0] != k:
        raise ValueError(
            f"q_t {q_t.shape} is not the (K, N) TPU layout for K={k}; "
            "convert ggml (N, K) dicts with to_tpu_layout() first")
    scale_t = scale_t.astype(jnp.float32)
    bm = _align_bm(bm, m)
    m_pad = -m % bm
    if m_pad:
        x = jnp.pad(x, ((0, m_pad), (0, 0)))
    q_t, scale_t = _pad_nk(q_t, scale_t, bn, 0)
    mp, np_ = x.shape[0], q_t.shape[1]
    x = x.astype(jnp.bfloat16)

    out = None
    for k0, kc in _chunk_k(k):
        xc = x[:, k0:k0 + kc]
        qc = q_t[k0:k0 + kc]
        sc = scale_t[k0 // QK:(k0 + kc) // QK]
        g = kc // QK
        part = pl.pallas_call(
            functools.partial(_int8_kernel,
                              cdt=jnp.float32 if interpret
                              else jnp.bfloat16),
            grid=(mp // bm, np_ // bn),
            in_specs=[
                pl.BlockSpec((bm, kc), lambda i, j: (i, 0)),
                pl.BlockSpec((kc, bn), lambda i, j: (0, j)),
                pl.BlockSpec((g, bn), lambda i, j: (0, j)),
            ],
            out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
            out_shape=jax.ShapeDtypeStruct((mp, np_), jnp.float32),
            compiler_params=tpu_compiler_params(
                dimension_semantics=("parallel", "parallel")),
            interpret=interpret,
        )(xc, qc, sc)
        out = part if out is None else out + part
    return out[:m, :n].astype(out_dtype)


# ---------------------------------------------------------------------------
# layout conversion + reference
# ---------------------------------------------------------------------------

def to_tpu_layout(qdict: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """ggml row-major quantize() dict → k-major TPU kernel layout.

    sym_int4/asym_int4: q (N, K/2) → q_t (K/2, N); scale (N, G) →
    scale_t (G, N) f32 (fp16 is not loadable by this Mosaic build).
    sym_int8: q (N, K) → (K, N). Other qtypes pass through (they use the
    XLA dequant fallback).
    """
    qtype = qdict.get("qtype", "sym_int4")
    if qtype not in ("sym_int4", "asym_int4", "sym_int8"):
        return dict(qdict)
    out = {"qtype": qtype,
           "q": np.ascontiguousarray(np.asarray(qdict["q"]).T),
           "scale": np.ascontiguousarray(
               np.asarray(qdict["scale"], np.float32).T)}
    if "zero" in qdict:
        out["zero"] = np.ascontiguousarray(
            np.asarray(qdict["zero"], np.float32).T)
    return out


def quantize_tpu(w: np.ndarray, qtype: str = "sym_int4"
                 ) -> Dict[str, np.ndarray]:
    """quantize() + to_tpu_layout() in one step — what model loaders use."""
    from bigdl_tpu.llm.ggml.quantize import quantize
    return to_tpu_layout(quantize(w, qtype))


def int4_matmul_reference(x: np.ndarray, q_packed: np.ndarray,
                          scale: np.ndarray) -> np.ndarray:
    """Independent numpy implementation for golden-parity tests.
    Takes the ggml (N, K/2)+(N, G) layout."""
    from bigdl_tpu.llm.ggml.quantize import dequantize

    w = dequantize({"qtype": "sym_int4", "q": np.asarray(q_packed),
                    "scale": np.asarray(scale)})
    return np.asarray(x, np.float32) @ w.T

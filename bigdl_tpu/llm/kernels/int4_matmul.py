"""INT4/INT8 block-dequant matmul Pallas kernels.

Reference counterpart: bigdl-llm's native q4_0 matvec (ctypes →
llama.cpp-family C kernels, SURVEY.md §3.4 hot loop). TPU design:

- weights stay packed in HBM/VMEM (uint8, two nibble-planes) — 4.5 bits/
  weight including scales, so the HBM→VMEM stream is ~3.5x smaller than
  bf16. Decode is HBM-bandwidth-bound, so this is where the speed comes
  from (same reason the CPU kernels win on DDR bandwidth).
- dequant happens in-kernel on the VPU (arithmetic only, no gathers for
  q4_0/q8_0), feeding bf16 tiles straight into the MXU ``jnp.dot``.
- grid = (M/bm, N/bn, K/bk) with a VMEM fp32 accumulator, K innermost so
  the accumulator lives across the K sweep (standard Pallas TPU matmul
  schedule).

Layouts (from llm.ggml.quantize): x (M, K) activations; q packed uint8
(N, K//2) — low nibble = even-k plane, high = odd-k; scale fp16
(N, K//32). Output (M, N) = x @ W^T, matching Linear's y = x W^T.

``interpret=True`` runs the same kernel on CPU for tests (SURVEY.md §4:
golden parity against an independent implementation — here the numpy
dequant reference).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bigdl_tpu.llm.ggml.quantize import QK


def _int4_kernel(x_ref, qlo_ref, qhi_ref, scale_ref, o_ref, acc_ref,
                 *, n_k_tiles):
    """One (bm, bn) tile: accumulate x_tile @ dequant(w_tile)^T over K."""
    k_idx = pl.program_id(2)

    @pl.when(k_idx == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # dequant: interleave the two nibble planes back into k-order
    lo = qlo_ref[:].astype(jnp.int32) - 8          # (bn, bk/2) even k
    hi = qhi_ref[:].astype(jnp.int32) - 8          # (bn, bk/2) odd k
    bn, half = lo.shape
    w = jnp.stack([lo, hi], axis=-1).reshape(bn, half * 2)  # (bn, bk)
    scale = scale_ref[:].astype(jnp.float32)       # (bn, bk/QK)
    w = w.reshape(bn, half * 2 // QK, QK) * scale[..., None]
    w = w.reshape(bn, half * 2).astype(jnp.bfloat16)

    acc_ref[:] += jnp.dot(x_ref[:], w.T, preferred_element_type=jnp.float32)

    @pl.when(k_idx == n_k_tiles - 1)
    def _done():
        o_ref[:] = acc_ref[:].astype(o_ref.dtype)


def _split_planes(q_packed: jnp.ndarray):
    """uint8 (N, K//2) → (lo, hi) nibble planes, each (N, K//2)."""
    return q_packed & 0xF, q_packed >> 4


@functools.partial(jax.jit, static_argnames=("bm", "bn", "bk", "interpret",
                                             "out_dtype"))
def int4_matmul(x, q_packed, scale, bm: int = 128, bn: int = 128,
                bk: int = 512, interpret: bool = False,
                out_dtype=jnp.bfloat16):
    """y = x @ dequant_q4_0(q, scale)^T.

    x: (M, K) bf16/f32; q_packed: (N, K//2) uint8; scale: (N, K//QK) fp16.
    M, N, K padded internally to tile multiples.
    """
    m, k = x.shape
    n = q_packed.shape[0]
    bm = min(bm, max(8, m))
    bk = min(bk, k)
    if bk % QK:
        raise ValueError(f"bk must be a multiple of {QK}")

    qlo, qhi = _split_planes(q_packed)

    m_pad = -m % bm
    n_pad = -n % bn
    k_pad = -k % bk
    if m_pad or k_pad:
        x = jnp.pad(x, ((0, m_pad), (0, k_pad)))
    if n_pad or k_pad:
        qlo = jnp.pad(qlo, ((0, n_pad), (0, k_pad // 2)),
                      constant_values=8)
        qhi = jnp.pad(qhi, ((0, n_pad), (0, k_pad // 2)),
                      constant_values=8)
        scale = jnp.pad(scale, ((0, n_pad), (0, k_pad // QK)))
    mp, kp = x.shape
    np_ = qlo.shape[0]
    n_k_tiles = kp // bk

    out = pl.pallas_call(
        functools.partial(_int4_kernel, n_k_tiles=n_k_tiles),
        grid=(mp // bm, np_ // bn, n_k_tiles),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bn, bk // 2), lambda i, j, kk: (j, kk)),
            pl.BlockSpec((bn, bk // 2), lambda i, j, kk: (j, kk)),
            pl.BlockSpec((bn, bk // QK), lambda i, j, kk: (j, kk)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=interpret,
    )(x.astype(jnp.bfloat16), qlo, qhi, scale)
    return out[:m, :n]


def _int8_kernel(x_ref, q_ref, scale_ref, o_ref, acc_ref, *, n_k_tiles):
    k_idx = pl.program_id(2)

    @pl.when(k_idx == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    w = q_ref[:].astype(jnp.float32)               # (bn, bk)
    scale = scale_ref[:].astype(jnp.float32)       # (bn, bk/QK)
    bn, bk = w.shape
    w = (w.reshape(bn, bk // QK, QK) * scale[..., None]) \
        .reshape(bn, bk).astype(jnp.bfloat16)
    acc_ref[:] += jnp.dot(x_ref[:], w.T, preferred_element_type=jnp.float32)

    @pl.when(k_idx == n_k_tiles - 1)
    def _done():
        o_ref[:] = acc_ref[:].astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("bm", "bn", "bk", "interpret",
                                             "out_dtype"))
def int8_matmul(x, q, scale, bm: int = 128, bn: int = 128, bk: int = 512,
                interpret: bool = False, out_dtype=jnp.bfloat16):
    """y = x @ dequant_q8_0(q, scale)^T — the BigQuant INT8 gemm
    equivalent (SURVEY.md §2.2). q: (N, K) int8."""
    m, k = x.shape
    n = q.shape[0]
    bm = min(bm, max(8, m))
    bk = min(bk, k)
    m_pad, n_pad, k_pad = -m % bm, -n % bn, -k % bk
    if m_pad or k_pad:
        x = jnp.pad(x, ((0, m_pad), (0, k_pad)))
    if n_pad or k_pad:
        q = jnp.pad(q, ((0, n_pad), (0, k_pad)))
        scale = jnp.pad(scale, ((0, n_pad), (0, k_pad // QK)))
    mp, kp = x.shape
    np_ = q.shape[0]
    n_k_tiles = kp // bk

    out = pl.pallas_call(
        functools.partial(_int8_kernel, n_k_tiles=n_k_tiles),
        grid=(mp // bm, np_ // bn, n_k_tiles),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bn, bk), lambda i, j, kk: (j, kk)),
            pl.BlockSpec((bn, bk // QK), lambda i, j, kk: (j, kk)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=interpret,
    )(x.astype(jnp.bfloat16), q, scale)
    return out[:m, :n]


def int4_matmul_reference(x: np.ndarray, q_packed: np.ndarray,
                          scale: np.ndarray) -> np.ndarray:
    """Independent numpy implementation for golden-parity tests."""
    from bigdl_tpu.llm.ggml.quantize import dequantize

    w = dequantize({"qtype": "sym_int4", "q": np.asarray(q_packed),
                    "scale": np.asarray(scale)})
    return np.asarray(x, np.float32) @ w.T

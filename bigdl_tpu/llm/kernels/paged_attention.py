"""Paged KV-cache attention — the serving-side ragged-attention kernel.

Reference counterpart: the vLLM PagedAttention integration in bigdl-llm's
serving stack (SURVEY.md §2.2 ggml row "ragged paged attention for
serving"; §2.8 llm serving row). The reference binds vLLM's CUDA paged
kernels; on TPU the design is rebuilt for Mosaic:

- the KV cache is a **page pool** ``(num_pages, H_kv, page_size, D)`` per
  layer; a request owns ``ceil(tokens/page_size)`` pages named by a
  **block table** ``(B, pages_max)`` of physical page ids. HBM in use is
  proportional to tokens in flight, not ``B × max_seq_len`` (the r3
  slot-static cache's bound — VERDICT r3 missing #1).
- the decode kernel runs one grid step per ``(batch row, kv head,
  page block)``; each step **async-copies ``ppb = 128 // page_size``
  pages** from HBM into one contiguous VMEM buffer, so the score tile is
  ``(G, 128)`` — full lane width, no sub-128 relayouts (the same reason
  the int4 kernel stores k-major: every compute shape is lane-aligned).
  Pages are fetched by physical id via scalar-prefetched block tables;
  only blocks below the row's length are copied at all, so HBM traffic
  scales with actual context, not the padded maximum.
- online softmax (flash-style running max/sum) accumulates across page
  blocks in VMEM scratch; GQA query groups ride the sublane dim padded
  to 8 (``Gp``).

The XLA fallback (:func:`paged_attention_reference`) is the same math as
a gather + masked attention — it is both the CPU-test golden and the
non-TPU execution path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bigdl_tpu.utils.jax_compat import tpu_compiler_params

LANE = 128          # score-tile lane width: pages per block × page_size


def _paged_decode_kernel(len_ref, bt_ref, q_ref, k_hbm, v_hbm, o_ref,
                         kbuf, vbuf, sem, acc_ref, m_ref, l_ref,
                         *, page: int, ppb: int, pages_max: int,
                         scale: float, window: Optional[int] = None,
                         m_out=None, l_out=None):
    """One (batch row b, kv head h, page block blk) step.

    len_ref: (B,) lengths INCLUDING the current token; bt_ref:
    (B * pages_max,) flattened block tables; q (1, 1, Gp, D) VMEM;
    k/v_hbm: (P, Hkv, page, D) stay in HBM, pages DMA'd by id.
    """
    b = pl.program_id(0)
    h = pl.program_id(1)
    blk = pl.program_id(2)
    nblk = pl.num_programs(2)

    @pl.when(blk == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -1e30)
        l_ref[...] = jnp.zeros_like(l_ref)

    seq = len_ref[b]
    base_tok = blk * (ppb * page)

    @pl.when(base_tok < seq)
    def _compute():
        copies = []
        for i in range(ppb):                    # static unroll
            pid = bt_ref[b * pages_max + blk * ppb + i]
            ck = pltpu.make_async_copy(k_hbm.at[pid, h], kbuf.at[i], sem)
            cv = pltpu.make_async_copy(v_hbm.at[pid, h], vbuf.at[i], sem)
            ck.start()
            cv.start()
            copies += [ck, cv]
        for c in copies:
            c.wait()
        gp, d = q_ref.shape[2], q_ref.shape[3]
        q = q_ref[0, 0].astype(jnp.float32)               # (Gp, D)
        k = kbuf[...].reshape(ppb * page, d).astype(jnp.float32)
        v = vbuf[...].reshape(ppb * page, d).astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (Gp, LANE)
        pos = base_tok + jax.lax.broadcasted_iota(
            jnp.int32, (gp, ppb * page), 1)
        valid = pos < seq
        if window is not None:
            valid &= pos >= seq - window
        s = jnp.where(valid, s, -1e30)
        m_prev = m_ref[...]                               # (Gp, LANE)
        l_prev = l_ref[...]
        m_cur = jnp.max(s, axis=1, keepdims=True)         # (Gp, 1)
        m_new = jnp.maximum(m_prev, jnp.broadcast_to(m_cur, m_prev.shape))
        alpha = jnp.exp(m_prev[:, :1] - m_new[:, :1])     # (Gp, 1)
        p_ = jnp.exp(s - m_new[:, :1])                    # (Gp, LANE)
        l_new = alpha * l_prev[:, :1] + jnp.sum(p_, axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p_, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # (Gp, D)
        m_ref[...] = m_new
        l_ref[...] = jnp.broadcast_to(l_new, l_prev.shape)

    @pl.when(blk == nblk - 1)
    def _finish():
        if m_out is None:
            o_ref[0, 0] = (acc_ref[...]
                           / jnp.maximum(l_ref[:, :1], 1e-30)).astype(
                               o_ref.dtype)
        else:
            # stats mode: UNNORMALIZED accumulator + running (max, sum),
            # so the caller can merge further tokens (e.g. the current
            # decode token, written to its page only after attention)
            # with the flash-style combine rule
            o_ref[0, 0] = acc_ref[...].astype(o_ref.dtype)
            m_out[0, 0] = m_ref[...]
            l_out[0, 0] = l_ref[...]


def _paged_decode_kernel_stats(len_ref, bt_ref, q_ref, k_hbm, v_hbm,
                               o_ref, mo_ref, lo_ref, kbuf, vbuf, sem,
                               acc_ref, m_ref, l_ref, *, page: int,
                               ppb: int, pages_max: int, scale: float,
                               window: Optional[int] = None):
    _paged_decode_kernel(len_ref, bt_ref, q_ref, k_hbm, v_hbm, o_ref,
                         kbuf, vbuf, sem, acc_ref, m_ref, l_ref,
                         page=page, ppb=ppb, pages_max=pages_max,
                         scale=scale, window=window,
                         m_out=mo_ref, l_out=lo_ref)


def _paged_decode_kernel_pm(len_ref, bt_ref, q_ref, k_hbm, v_hbm, o_ref,
                            kbuf, vbuf, sem, acc_ref, m_ref, l_ref,
                            *, page: int, ppb: int, pages_max: int,
                            hkv: int, scale: float,
                            window: Optional[int] = None,
                            m_out=None, l_out=None):
    """PAGE-MAJOR variant: one (batch row b, page block blk) step copies
    each page ACROSS ALL KV HEADS in a single contiguous DMA.

    The head-minor kernel above issues ``2·ppb`` DMAs of one head-page
    (page·D·2 bytes ≈ 4 KB) per grid cell over a (B, Hkv, nblk) grid —
    at 7B decode that is ~16k 4 KB copies per layer, and the measured
    cost is DMA-issue-bound: attention was 27.8 ms of the 55 ms paged
    step (tools/exp_paged_gap.py) vs ~17 ms for the dense cache path.
    Here the grid is (B, nblk) and each cell copies ``2·ppb`` blocks of
    ``(Hkv, page, D)`` (≈128 KB contiguous at 7B) — 32× fewer, 32×
    larger DMAs — then statically loops the Hkv heads in-register.
    Measured effect on the full 7B b8/ctx256 serving decode step:
    54.2 → 37.0 ms (147.7 → 216.3 tok/s), taking the paged path ~21%
    PAST the dense fused-scan step (~44.7 ms) — the page pool's DMA
    pattern is now cheaper than XLA's dense cache attention.

    Measured alternative, rejected: DOUBLE-BUFFERING the page stream
    (two (ppb, Hkv, page, D) buffer/semaphore slots, next block's
    copies started during the current block's compute, static-slot
    pl.when duplication) passed on-chip parity but measured 211.2
    tok/s vs 215.7-216.3 for this synchronous version across repeated
    runs — the ~128 KB contiguous copies already complete within the
    32-head compute window, so pipelining buys nothing and costs 2×
    scratch VMEM. Kept simple on purpose.

    len_ref: (B,) lengths; bt_ref: (B·pages_max,) flat tables; q_ref
    (1, hkv, gp, D) VMEM; k/v_hbm (P, Hkv, page, D) in ANY space;
    o_ref (1, hkv, gp, D); kbuf/vbuf (ppb, Hkv, page, D) VMEM scratch;
    acc (hkv·gp, D) f32; m/l (hkv·gp, LANE) f32 running stats."""
    b = pl.program_id(0)
    blk = pl.program_id(1)
    nblk = pl.num_programs(1)

    @pl.when(blk == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -1e30)
        l_ref[...] = jnp.zeros_like(l_ref)

    seq = len_ref[b]
    base_tok = blk * (ppb * page)

    @pl.when(base_tok < seq)
    def _compute():
        copies = []
        for i in range(ppb):                    # static unroll
            pid = bt_ref[b * pages_max + blk * ppb + i]
            ck = pltpu.make_async_copy(k_hbm.at[pid], kbuf.at[i], sem)
            cv = pltpu.make_async_copy(v_hbm.at[pid], vbuf.at[i], sem)
            ck.start()
            cv.start()
            copies += [ck, cv]
        for c in copies:
            c.wait()
        gp, d = q_ref.shape[2], q_ref.shape[3]
        pos = base_tok + jax.lax.broadcasted_iota(
            jnp.int32, (gp, ppb * page), 1)
        valid = pos < seq
        if window is not None:
            valid &= pos >= seq - window
        for h in range(hkv):                    # static unroll over heads
            q = q_ref[0, h].astype(jnp.float32)               # (gp, D)
            k = kbuf[:, h].reshape(ppb * page, d).astype(jnp.float32)
            v = vbuf[:, h].reshape(ppb * page, d).astype(jnp.float32)
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale   # (gp, LANE)
            s = jnp.where(valid, s, -1e30)
            # static-slice loads/stores on the scratch refs per head
            # (functional .at[].set on a value lowers to scatter, which
            # Mosaic does not implement)
            r0 = h * gp
            m_prev = m_ref[r0:r0 + gp]
            l_prev = l_ref[r0:r0 + gp]
            m_cur = jnp.max(s, axis=1, keepdims=True)
            m_new = jnp.maximum(m_prev, jnp.broadcast_to(m_cur,
                                                         m_prev.shape))
            alpha = jnp.exp(m_prev[:, :1] - m_new[:, :1])
            p_ = jnp.exp(s - m_new[:, :1])
            l_new = (alpha * l_prev[:, :1]
                     + jnp.sum(p_, axis=1, keepdims=True))
            acc_ref[r0:r0 + gp] = (
                acc_ref[r0:r0 + gp] * alpha + jax.lax.dot_general(
                    p_, v, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32))
            m_ref[r0:r0 + gp] = m_new
            l_ref[r0:r0 + gp] = jnp.broadcast_to(l_new, l_prev.shape)

    @pl.when(blk == nblk - 1)
    def _finish():
        gp, d = q_ref.shape[2], q_ref.shape[3]
        if m_out is None:
            o_ref[0] = (acc_ref[...]
                        / jnp.maximum(l_ref[:, :1], 1e-30)).reshape(
                            hkv, gp, d).astype(o_ref.dtype)
        else:
            o_ref[0] = acc_ref[...].reshape(hkv, gp, d).astype(o_ref.dtype)
            m_out[0] = m_ref[...].reshape(hkv, gp, LANE)
            l_out[0] = l_ref[...].reshape(hkv, gp, LANE)


def _paged_decode_kernel_pm_stats(len_ref, bt_ref, q_ref, k_hbm, v_hbm,
                                  o_ref, mo_ref, lo_ref, kbuf, vbuf, sem,
                                  acc_ref, m_ref, l_ref, *, page: int,
                                  ppb: int, pages_max: int, hkv: int,
                                  scale: float,
                                  window: Optional[int] = None):
    _paged_decode_kernel_pm(len_ref, bt_ref, q_ref, k_hbm, v_hbm, o_ref,
                            kbuf, vbuf, sem, acc_ref, m_ref, l_ref,
                            page=page, ppb=ppb, pages_max=pages_max,
                            hkv=hkv, scale=scale, window=window,
                            m_out=mo_ref, l_out=lo_ref)


@functools.partial(jax.jit, static_argnames=("page_size", "interpret",
                                             "sliding_window",
                                             "page_major"))
def paged_attention_decode(q, k_pages, v_pages, block_tables, lengths,
                           page_size: int = 16, interpret: bool = False,
                           sliding_window: Optional[int] = None,
                           page_major: bool = True):
    """Decode-step attention over a paged KV cache.

    q: (B, Hq, D) current-token queries; k_pages/v_pages:
    (P, Hkv, page_size, D); block_tables: (B, pages_max) int32 physical
    page ids; lengths: (B,) int32 context lengths INCLUDING the current
    token (whose K/V must already be written to its page).
    Returns (B, Hq, D) in q.dtype.

    ``pages_max`` must be a multiple of ``LANE // page_size`` (the server
    buckets tables to this), and page ids must be < P (unused table
    entries may be any valid id — their tokens are masked by lengths).
    """
    b, hq, d = q.shape
    p_, hkv, page, _ = k_pages.shape
    assert page == page_size
    ppb = LANE // page_size
    pages_max = block_tables.shape[1]
    if pages_max % ppb:
        raise ValueError(f"pages_max {pages_max} not a multiple of {ppb}")
    nblk = pages_max // ppb
    g = hq // hkv
    gp = max(8, -(-g // 8) * 8)
    scale = 1.0 / float(np.sqrt(d))

    qg = q.reshape(b, hkv, g, d)
    if gp != g:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, gp - g), (0, 0)))
    # Mosaic page DMAs need a 128-aligned minor dim: head_dim < 128
    # (test-size models; every production Llama head is 128) is
    # zero-padded. Zero K columns leave scores unchanged; padded V
    # columns are sliced off below. The pool pad is a copy — fine for
    # tiny models, free (no-op) at d=128.
    d_orig = d
    if d % 128:
        dp = -(-d // 128) * 128
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, 0), (0, dp - d)))
        k_pages = jnp.pad(k_pages, ((0, 0), (0, 0), (0, 0), (0, dp - d)))
        v_pages = jnp.pad(v_pages, ((0, 0), (0, 0), (0, 0), (0, dp - d)))
        d = dp

    if page_major:
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, nblk),
            in_specs=[
                pl.BlockSpec((1, hkv, gp, d), lambda b_, k_, *_:
                             (b_, 0, 0, 0)),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=pl.BlockSpec((1, hkv, gp, d),
                                   lambda b_, k_, *_: (b_, 0, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((ppb, hkv, page, d), k_pages.dtype),
                pltpu.VMEM((ppb, hkv, page, d), v_pages.dtype),
                pltpu.SemaphoreType.DMA,
                pltpu.VMEM((hkv * gp, d), jnp.float32),
                pltpu.VMEM((hkv * gp, LANE), jnp.float32),
                pltpu.VMEM((hkv * gp, LANE), jnp.float32),
            ],
        )
        out = pl.pallas_call(
            functools.partial(_paged_decode_kernel_pm, page=page_size,
                              ppb=ppb, pages_max=pages_max, hkv=hkv,
                              scale=scale, window=sliding_window),
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((b, hkv, gp, d), jnp.float32),
            compiler_params=tpu_compiler_params(
                dimension_semantics=("parallel", "arbitrary")),
            interpret=interpret,
        )(lengths.astype(jnp.int32),
          block_tables.reshape(-1).astype(jnp.int32), qg, k_pages,
          v_pages)
        return (out[:, :, :g, :d_orig].reshape(b, hq, d_orig)
                .astype(q.dtype))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, hkv, nblk),
        in_specs=[
            pl.BlockSpec((1, 1, gp, d), lambda b_, h_, k_, *_: (b_, h_, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, 1, gp, d),
                               lambda b_, h_, k_, *_: (b_, h_, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((ppb, page, d), k_pages.dtype),
            pltpu.VMEM((ppb, page, d), v_pages.dtype),
            pltpu.SemaphoreType.DMA,
            pltpu.VMEM((gp, d), jnp.float32),
            pltpu.VMEM((gp, LANE), jnp.float32),
            pltpu.VMEM((gp, LANE), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_decode_kernel, page=page_size, ppb=ppb,
                          pages_max=pages_max, scale=scale,
                          window=sliding_window),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, gp, d), jnp.float32),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(lengths.astype(jnp.int32), block_tables.reshape(-1).astype(jnp.int32),
      qg, k_pages, v_pages)
    return (out[:, :, :g, :d_orig].reshape(b, hq, d_orig)
            .astype(q.dtype))


@functools.partial(jax.jit, static_argnames=("page_size", "interpret",
                                             "sliding_window",
                                             "page_major"))
def paged_attention_decode_stats(q, k_pages, v_pages, block_tables,
                                 lengths, page_size: int = 16,
                                 interpret: bool = False,
                                 sliding_window: Optional[int] = None,
                                 page_major: bool = True):
    """Like :func:`paged_attention_decode` but over the first ``lengths``
    tokens WITHOUT normalizing, returning the flash-style partial state
    ``(acc (B, Hq, D) f32 unnormalized, m (B, Hq) f32, l (B, Hq) f32)``
    so the caller can fold in further key/value tokens (the current
    decode token before its page write) with the online-softmax combine.
    Rows with ``lengths == 0`` return ``(0, -1e30, 0)`` — the identity
    of the combine."""
    b, hq, d = q.shape
    p_, hkv, page, _ = k_pages.shape
    assert page == page_size
    ppb = LANE // page_size
    pages_max = block_tables.shape[1]
    if pages_max % ppb:
        raise ValueError(f"pages_max {pages_max} not a multiple of {ppb}")
    nblk = pages_max // ppb
    g = hq // hkv
    gp = max(8, -(-g // 8) * 8)
    scale = 1.0 / float(np.sqrt(d))

    qg = q.reshape(b, hkv, g, d)
    if gp != g:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, gp - g), (0, 0)))
    d_orig = d
    if d % 128:
        dp = -(-d // 128) * 128
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, 0), (0, dp - d)))
        k_pages = jnp.pad(k_pages, ((0, 0), (0, 0), (0, 0), (0, dp - d)))
        v_pages = jnp.pad(v_pages, ((0, 0), (0, 0), (0, 0), (0, dp - d)))
        d = dp

    if page_major:
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, nblk),
            in_specs=[
                pl.BlockSpec((1, hkv, gp, d), lambda b_, k_, *_:
                             (b_, 0, 0, 0)),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=[
                pl.BlockSpec((1, hkv, gp, d),
                             lambda b_, k_, *_: (b_, 0, 0, 0)),
                pl.BlockSpec((1, hkv, gp, LANE),
                             lambda b_, k_, *_: (b_, 0, 0, 0)),
                pl.BlockSpec((1, hkv, gp, LANE),
                             lambda b_, k_, *_: (b_, 0, 0, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((ppb, hkv, page, d), k_pages.dtype),
                pltpu.VMEM((ppb, hkv, page, d), v_pages.dtype),
                pltpu.SemaphoreType.DMA,
                pltpu.VMEM((hkv * gp, d), jnp.float32),
                pltpu.VMEM((hkv * gp, LANE), jnp.float32),
                pltpu.VMEM((hkv * gp, LANE), jnp.float32),
            ],
        )
        acc, m, l = pl.pallas_call(
            functools.partial(_paged_decode_kernel_pm_stats,
                              page=page_size, ppb=ppb,
                              pages_max=pages_max, hkv=hkv, scale=scale,
                              window=sliding_window),
            grid_spec=grid_spec,
            out_shape=[
                jax.ShapeDtypeStruct((b, hkv, gp, d), jnp.float32),
                jax.ShapeDtypeStruct((b, hkv, gp, LANE), jnp.float32),
                jax.ShapeDtypeStruct((b, hkv, gp, LANE), jnp.float32)],
            compiler_params=tpu_compiler_params(
                dimension_semantics=("parallel", "arbitrary")),
            interpret=interpret,
        )(lengths.astype(jnp.int32),
          block_tables.reshape(-1).astype(jnp.int32), qg, k_pages,
          v_pages)
        return (acc[:, :, :g, :d_orig].reshape(b, hq, d_orig),
                m[:, :, :g, 0].reshape(b, hq),
                l[:, :, :g, 0].reshape(b, hq))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, hkv, nblk),
        in_specs=[
            pl.BlockSpec((1, 1, gp, d), lambda b_, h_, k_, *_: (b_, h_, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, gp, d),
                         lambda b_, h_, k_, *_: (b_, h_, 0, 0)),
            pl.BlockSpec((1, 1, gp, LANE),
                         lambda b_, h_, k_, *_: (b_, h_, 0, 0)),
            pl.BlockSpec((1, 1, gp, LANE),
                         lambda b_, h_, k_, *_: (b_, h_, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((ppb, page, d), k_pages.dtype),
            pltpu.VMEM((ppb, page, d), v_pages.dtype),
            pltpu.SemaphoreType.DMA,
            pltpu.VMEM((gp, d), jnp.float32),
            pltpu.VMEM((gp, LANE), jnp.float32),
            pltpu.VMEM((gp, LANE), jnp.float32),
        ],
    )
    acc, m, l = pl.pallas_call(
        functools.partial(_paged_decode_kernel_stats, page=page_size,
                          ppb=ppb, pages_max=pages_max, scale=scale,
                          window=sliding_window),
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((b, hkv, gp, d), jnp.float32),
                   jax.ShapeDtypeStruct((b, hkv, gp, LANE), jnp.float32),
                   jax.ShapeDtypeStruct((b, hkv, gp, LANE), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(lengths.astype(jnp.int32), block_tables.reshape(-1).astype(jnp.int32),
      qg, k_pages, v_pages)
    return (acc[:, :, :g, :d_orig].reshape(b, hq, d_orig),
            m[:, :, :g, 0].reshape(b, hq),
            l[:, :, :g, 0].reshape(b, hq))


def _sliced_tables(block_tables, lengths, page: int,
                   max_live_tokens: Optional[int] = None):
    """Slice the table columns to the LIVE page span before the dense
    gather. The references gather every ``pages_max × page`` slot, but
    tables are bucketed to the engine's worst case — on CPU (tier-1
    tests, the non-TPU serving path) that pads the gather with capacity
    nobody owns. When ``lengths`` is concrete (tests, tools, host-side
    callers) or the caller passes a static ``max_live_tokens`` bound,
    the gather shrinks to ``ceil(max_live / page)`` columns; under a
    jit trace with no bound, the full table is kept (shapes must stay
    static). Masking is untouched: every valid position is below the
    live span by construction."""
    pages_max = block_tables.shape[1]
    if max_live_tokens is not None:
        live = -(-int(max_live_tokens) // page)
    else:
        try:
            live = -(-int(np.max(np.asarray(lengths))) // page)
        except Exception:       # traced lengths: keep the static shape
            return block_tables
    return block_tables[:, :max(1, min(live, pages_max))]


def paged_attention_reference_stats(q, k_pages, v_pages, block_tables,
                                    lengths,
                                    sliding_window: Optional[int] = None,
                                    max_live_tokens: Optional[int] = None):
    """XLA twin of :func:`paged_attention_decode_stats` (same contract)."""
    b, hq, d = q.shape
    p_, hkv, page, _ = k_pages.shape
    g = hq // hkv
    block_tables = _sliced_tables(block_tables, lengths, page,
                                  max_live_tokens)
    pages_max = block_tables.shape[1]
    s_max = pages_max * page
    k_all = (k_pages[block_tables].transpose(0, 1, 3, 2, 4)
             .reshape(b, s_max, hkv, d))
    v_all = (v_pages[block_tables].transpose(0, 1, 3, 2, 4)
             .reshape(b, s_max, hkv, d))
    qg = q.reshape(b, hkv, g, d).astype(jnp.float32)
    scale = 1.0 / float(np.sqrt(d))
    s = jnp.einsum("bhgd,bshd->bhgs", qg,
                   k_all.astype(jnp.float32)) * scale
    pos = jnp.arange(s_max)[None, :]
    mask = pos < lengths[:, None]                              # (B, S)
    if sliding_window is not None:
        mask &= pos >= lengths[:, None] - sliding_window
    s = jnp.where(mask[:, None, None, :], s, -1e30)
    m = jnp.max(s, axis=-1)                                    # (B,H,G)
    # p must be 0 (not exp(0)) on masked slots of all-masked rows,
    # where m == -1e30 would make s - m == 0
    p = jnp.where(mask[:, None, None, :], jnp.exp(s - m[..., None]), 0.0)
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhgs,bshd->bhgd", p, v_all.astype(jnp.float32))
    any_valid = jnp.any(mask, axis=-1)[:, None, None]          # (B,1,1)
    m = jnp.where(any_valid, m, -1e30)
    return (acc.reshape(b, hq, d), m.reshape(b, hq), l.reshape(b, hq))


def paged_attention_stats(q, k_pages, v_pages, block_tables, lengths,
                          page_size: int = 16,
                          interpret: Optional[bool] = None,
                          sliding_window: Optional[int] = None):
    """Backend dispatch for the stats variant: Mosaic kernel on TPU, XLA
    gather elsewhere."""
    if interpret is None:
        if jax.default_backend() != "tpu":
            return paged_attention_reference_stats(
                q, k_pages, v_pages, block_tables, lengths,
                sliding_window=sliding_window)
        interpret = False
    return paged_attention_decode_stats(
        q, k_pages, v_pages, block_tables, lengths, page_size=page_size,
        interpret=interpret, sliding_window=sliding_window)


def merge_attention_partial(acc, m, l, q, k_new, v_new):
    """Fold one extra key/value token into a flash-style partial state.

    ``(acc, m, l)`` from :func:`paged_attention_stats` (acc (B, Hq, D)
    f32 unnormalized); ``q`` (B, Hq, D) current queries; ``k_new/v_new``
    (B, Hkv, D) the token being decoded (pre page-write). Returns the
    NORMALIZED attention output (B, Hq, D) f32 over the union — exactly
    ``paged_attention`` after writing the token, but with the pool
    untouched (what lets the serving decode scan keep the page pool
    read-only and defer all layers' page writes to one post-scan
    scatter)."""
    b, hq, d = q.shape
    hkv = k_new.shape[1]
    g = hq // hkv
    scale = 1.0 / float(np.sqrt(d))
    kr = jnp.repeat(k_new.astype(jnp.float32), g, axis=1)     # (B, Hq, D)
    vr = jnp.repeat(v_new.astype(jnp.float32), g, axis=1)
    s_self = jnp.sum(q.astype(jnp.float32) * kr, axis=-1) * scale
    m_new = jnp.maximum(m, s_self)
    alpha = jnp.exp(m - m_new)                                # (B, Hq)
    beta = jnp.exp(s_self - m_new)
    l_new = l * alpha + beta
    out = (acc * alpha[..., None] + vr * beta[..., None]) \
        / jnp.maximum(l_new, 1e-30)[..., None]
    return out


def paged_attention_reference(q, k_pages, v_pages, block_tables, lengths,
                              sliding_window: Optional[int] = None,
                              max_live_tokens: Optional[int] = None):
    """XLA gather + masked attention — golden for the kernel and the
    execution path on non-TPU backends. Same contract as
    :func:`paged_attention_decode`. The gather is sliced to the live
    page span when the lengths are concrete (see
    :func:`_sliced_tables`)."""
    b, hq, d = q.shape
    p_, hkv, page, _ = k_pages.shape
    g = hq // hkv
    block_tables = _sliced_tables(block_tables, lengths, page,
                                  max_live_tokens)
    pages_max = block_tables.shape[1]
    s_max = pages_max * page
    # gather: (B, maxp, Hkv, page, D) -> (B, S, Hkv, D)
    k_all = (k_pages[block_tables].transpose(0, 1, 3, 2, 4)
             .reshape(b, s_max, hkv, d))
    v_all = (v_pages[block_tables].transpose(0, 1, 3, 2, 4)
             .reshape(b, s_max, hkv, d))
    qg = q.reshape(b, hkv, g, d).astype(jnp.float32)
    scale = 1.0 / float(np.sqrt(d))
    s = jnp.einsum("bhgd,bshd->bhgs", qg,
                   k_all.astype(jnp.float32)) * scale
    pos = jnp.arange(s_max)[None, :]
    mask = pos < lengths[:, None]                              # (B, S)
    if sliding_window is not None:
        mask &= pos >= lengths[:, None] - sliding_window
    s = jnp.where(mask[:, None, None, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhgs,bshd->bhgd", p, v_all.astype(jnp.float32))
    return out.reshape(b, hq, d).astype(q.dtype)


def paged_attention(q, k_pages, v_pages, block_tables, lengths,
                    page_size: int = 16, interpret: Optional[bool] = None,
                    sliding_window: Optional[int] = None):
    """Backend dispatch: Mosaic kernel on TPU, XLA gather elsewhere."""
    if interpret is None:
        if jax.default_backend() != "tpu":
            return paged_attention_reference(
                q, k_pages, v_pages, block_tables, lengths,
                sliding_window=sliding_window)
        interpret = False
    return paged_attention_decode(q, k_pages, v_pages, block_tables,
                                  lengths, page_size=page_size,
                                  interpret=interpret,
                                  sliding_window=sliding_window)

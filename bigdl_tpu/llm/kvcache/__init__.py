"""Prefix-aware KV-cache subsystem (ISSUE 5 tentpole).

``bigdl_tpu/llm/kvcache`` owns the page pool that used to be embedded in
``LLMServer`` and adds prefix reuse on top of it:

- :mod:`~bigdl_tpu.llm.kvcache.pool` — refcounted page pool with
  copy-on-write fork semantics and the admission-budget ledger;
- :mod:`~bigdl_tpu.llm.kvcache.radix` — radix prefix index keyed on
  page-size token chunks, leaf-first LRU eviction;
- :mod:`~bigdl_tpu.llm.kvcache.prefill` — the family-generic partial
  prefill (gather prefix pages → run suffix at a position offset →
  scatter back, with the COW tail fork fused into the scatter);
- :class:`KVCacheManager` (here) — the engine-facing façade: admission
  lookup + suffix-only budget charging, adoption refcounts/pins,
  chain insertion at prefill and EOS, on-demand LRU eviction (the
  ``kvcache.evict`` fault site), and hit/miss/evict accounting.

``bigdl.llm.kvcache.enabled=false`` (the default) keeps the manager as
a pure pool wrapper: no radix index is constructed, no
``bigdl_kvcache_*`` series are declared, every admission charges the
full worst case, and page ids flow in the seed engine's exact order —
the engine is bit-identical to the pre-kvcache one (asserted in
tests/test_kvcache.py).

See docs/KVCACHE.md for the page lifecycle and the invariants.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from bigdl_tpu.llm.kvcache.pool import PagePool, PagePoolError
from bigdl_tpu.llm.kvcache.prefill import (make_partial_prefill,
                                           make_spec_step)
from bigdl_tpu.llm.kvcache.radix import PrefixMatch, RadixIndex


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


class Admission:
    """One admitted request's cache grant, held per engine slot.

    ``charge`` is the suffix-only budget reservation (released wholesale
    at EOS); ``shared_pages`` the adopted full-prefix pages (one pool
    ref + a possibly-shared pin each); ``tail_src`` the COW fork source
    page when the match ended mid-page (a transient ref/pin dropped as
    soon as the partial prefill is dispatched).

    Host-tier extension (ISSUE 6): when part of the matched prefix is
    resident in the host arena, ``fetch`` names its ``(key, slot)``
    chunks, ``fetch_job`` the in-flight migration uploading them, and
    ``fetch_reserved`` the budget pre-charged for their future pool
    pages. ``matched_len`` already INCLUDES the host chunks; if the
    fetch fails, :meth:`KVCacheManager.degrade` rolls it back to
    ``device_matched`` and converts the pre-charge into plain suffix
    budget — a host miss, never a stall."""

    __slots__ = ("matched_len", "shared_pages", "tail_src", "tail_len",
                 "charge", "fetch", "fetch_job", "fetch_reserved",
                 "device_matched")

    def __init__(self, matched_len: int = 0,
                 shared_pages: Optional[List[int]] = None,
                 tail_src: Optional[int] = None, tail_len: int = 0,
                 charge: int = 0):
        self.matched_len = matched_len
        self.shared_pages = shared_pages or []
        self.tail_src = tail_src
        self.tail_len = tail_len
        self.charge = charge
        self.fetch: List[Any] = []
        self.fetch_job = None
        self.fetch_reserved = 0
        self.device_matched = matched_len


class KVCacheManager:
    """Engine-facing façade over the pool + radix index.

    Thread-safe (its own RLock): the engine thread admits/releases under
    the engine lock, while ``submit`` peeks suffix costs from client
    threads for shed diagnostics."""

    def __init__(self, num_pages: int, page_size: int,
                 enabled: bool = False):
        self.pool = PagePool(num_pages, page_size)
        self.page = page_size
        self.enabled = bool(enabled)
        self.index: Optional[RadixIndex] = (
            RadixIndex(self.pool) if self.enabled else None)
        # host tier (ISSUE 6): attached by the engine when
        # bigdl.llm.kvtier.enabled — None means every tier branch below
        # is structurally absent (the PR 5 manager exactly)
        self.tier = None
        self._read_page = None     # engine: pid -> (k_dev, v_dev) gather
        self._write_pages = None   # engine: (pids, k_devs, v_devs) scatter
        self._lock = threading.RLock()
        # always-on plain accounting (tools/microbench_prefix.py and
        # GET /debug/kvcache read these; metric series mirror them only
        # when observability is enabled)
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.prefix_tokens_reused = 0
        self._ins: Optional[Dict[str, Any]] = None

    # -- observability -------------------------------------------------------
    def _instruments(self):
        from bigdl_tpu import observability as obs
        if not (self.enabled and obs.enabled()):
            return None
        if self._ins is None:
            self._ins = {
                "hits": obs.counter(
                    "bigdl_kvcache_hits_total",
                    "Admissions that reused a cached prefix"),
                "misses": obs.counter(
                    "bigdl_kvcache_misses_total",
                    "Admissions with no cached prefix"),
                "evictions": obs.counter(
                    "bigdl_kvcache_evictions_total",
                    "Pages evicted from the prefix index under pool "
                    "pressure"),
                "reused": obs.counter(
                    "bigdl_kvcache_prefix_tokens_reused_total",
                    "Prompt tokens served from cached prefixes instead "
                    "of prefill"),
                "indexed": obs.gauge(
                    "bigdl_kvcache_indexed_pages",
                    "Pages currently referenced by the prefix index"),
                "shared": obs.gauge(
                    "bigdl_kvcache_shared_pages",
                    "Pages with more than one reference (index + live "
                    "requests)"),
                "occupancy": obs.gauge(
                    "bigdl_kvcache_pool_occupancy",
                    "Fraction of the usable page pool allocated "
                    "(live + indexed)"),
            }
        return self._ins

    def record_gauges(self):
        ins = self._instruments()
        if ins is None:
            return
        ins["indexed"].set(self.index.indexed_pages())
        ins["shared"].set(self.pool.shared_pages())
        ins["occupancy"].set(
            self.pool.allocated() / max(self.pool.num_pages - 1, 1))
        if self.tier is not None:
            self.tier.record_gauges()

    # -- host tier (ISSUE 6) -------------------------------------------------
    def attach_tier(self, tier, reader, writer):
        """Arm the host spill tier. ``reader(pid)`` must DISPATCH a
        per-page gather of the engine's pools and return the standalone
        device arrays (engine thread only — eviction runs under the
        engine lock, and engine-thread dispatch order is what keeps the
        gather ahead of any reuse of the page id). ``writer(pids,
        k_devs, v_devs)`` scatters fetched pages into the pools."""
        if not self.enabled:
            raise ValueError(
                "the host tier extends the prefix cache: enable "
                "bigdl.llm.kvcache first")
        self.tier = tier
        self._read_page = reader
        self._write_pages = writer

    def _spill(self, token_path, pid: int):
        """Eviction hook: capture the page into the host arena before
        its id is freed. Best-effort by contract — any failure here
        (arena saturated, injected ``kvtier.spill``) leaves the
        eviction a plain drop."""
        if len(token_path) % self.page:
            return              # partial tails re-prefill on miss
        try:
            slot = self.tier.arena.reserve(tuple(token_path))
            if slot is None:
                return          # every slot pinned: skip this spill
            k_dev, v_dev = self._read_page(pid)
            self.tier.migrator.submit_spill(tuple(token_path), slot,
                                            k_dev, v_dev)
            self.tier.count_spill()
        except Exception:
            pass

    def materialize(self, adm: Admission, k_devs, v_devs):
        """Land a completed fetch: allocate pool pages (pre-evicting if
        needed — may raise the injected ``kvcache.evict``, in which
        case the caller retries, nothing committed), scatter the
        uploaded pages in, index the chunks, and convert the admission
        pre-charge into ordinary pinned-shared adoption. After this the
        admission is indistinguishable from a device prefix hit."""
        with self._lock:
            n = len(adm.fetch)
            if n == 0:
                return
            self.ensure_free(n)             # retryable injected raise
            pids = [self.pool.take_free() for _ in range(n)]
            self._write_pages(pids, k_devs, v_devs)
            # index under the chain identity: the device-matched chunks
            # already have nodes (kept as-is), the fetched chunks take
            # one index ref each. A chunk some concurrent request
            # indexed meanwhile keeps ITS page; ours then stays a
            # request-private ref that frees at EOS.
            chain = list(adm.fetch[-1][0])
            self.index.insert(chain, list(adm.shared_pages) + pids)
            for pid in pids:
                # take_free's ref becomes the request's adoption ref;
                # the pin consumes the admission-time pre-charge
                self.pool.pin_precharged(pid)
            adm.shared_pages.extend(pids)
            adm.fetch_reserved = 0
            adm.fetch = []
            adm.fetch_job = None
            host_tokens = n * self.page
            self.prefix_tokens_reused += host_tokens
            self._count("reused", host_tokens)
            self.tier.count_fetch(n)
            self.record_gauges()

    def degrade(self, adm: Admission):
        """A failed / timed-out / cancelled fetch becomes a plain cache
        miss: the matched prefix rolls back to the device-resident part
        and the fetch pre-charge converts 1:1 into the suffix budget
        the extra prefill pages need (the arena pins are the migration
        worker's to release)."""
        with self._lock:
            if not adm.fetch:
                return
            if adm.fetch_job is not None:
                adm.fetch_job.cancelled = True
            adm.charge += adm.fetch_reserved
            adm.fetch_reserved = 0
            adm.fetch = []
            adm.fetch_job = None
            adm.matched_len = adm.device_matched
            adm.tail_src, adm.tail_len = None, 0
            self.tier.count_fetch_failure()

    def _count(self, name: str, n: int = 1):
        ins = self._instruments()
        if ins is not None:
            ins[name].inc(n)

    # -- admission -----------------------------------------------------------
    def suffix_budget(self, prompt_len: int, max_new: int,
                      matched_len: int) -> int:
        """Worst-case pages the request may still need to OWN: every
        page from the first non-fully-shared one through the last
        decode token. The COW fork target (a mid-page match's page) is
        inside this range, so forks are pre-reserved too."""
        full = _ceil_div(prompt_len + max_new, self.page)
        return full - matched_len // self.page

    def peek(self, prompt_ids, max_new: int) -> Dict[str, int]:
        """Lock-held read-only suffix cost for shed/reject diagnostics:
        no refs taken, no LRU touch, no counters."""
        with self._lock:
            matched = 0
            matched_total = 0
            if self.enabled:
                m = self.index.lookup(prompt_ids, touch=False)
                matched = min(m.matched_len, len(prompt_ids) - 1)
                matched_total = matched
                if self.tier is not None:
                    # host-resident chunks reduce prefill, not budget:
                    # each fetched page still pre-charges one page, so
                    # pages_needed stays the device-matched suffix cost
                    base = len(m.full_pages) * self.page
                    host = self.tier.arena.lookup_chunks(
                        prompt_ids, base, len(prompt_ids) - 1,
                        touch=False)
                    if host:
                        matched = base
                        matched_total = base + len(host) * self.page
            return {
                "pages_needed": self.suffix_budget(
                    len(prompt_ids), max_new, matched),
                "pages_free": self.pool.budget_avail,
                "matched_tokens": matched_total,
                # the device-only match (host chunks excluded) — the
                # engine's chunked-admission decision needs the suffix
                # it would actually chunk (ISSUE 14)
                "matched_device": matched,
            }

    def admit(self, prompt_ids, max_new: int,
              chunk_pages: Optional[int] = None) -> Optional[Admission]:
        """Look up the longest cached prefix, charge the suffix-only
        budget (+ pins for newly-adopted shared pages), take adoption
        refs, and pre-evict enough free pages for the prompt's own
        pages. Returns None when the budget cannot cover it (the
        engine's head-of-line wait). Raises only from the seeded
        ``kvcache.evict`` fault site, with NOTHING charged or adopted —
        the engine retries the whole admission.

        ``chunk_pages`` (ISSUE 14, chunked admission): charge only that
        many pages — the FIRST prefill chunk's — instead of the whole
        worst case; later chunks extend the ledger incrementally via
        :meth:`charge_chunk` and the final chunk tops up the decode
        budget. The host tier is bypassed in this mode (the engine
        routes arena-extending admissions through the unchunked path),
        and pre-eviction covers only the first chunk's own pages."""
        T = len(prompt_ids)
        with self._lock:
            if not self.enabled:
                charge = (chunk_pages if chunk_pages is not None
                          else self.suffix_budget(T, max_new, 0))
                if charge > self.pool.budget_avail:
                    return None
                self.pool.charge(charge)
                return Admission(charge=charge)
            m = self.index.lookup(prompt_ids)
            # host-tier extension (ISSUE 6): consecutive arena-resident
            # chunks past the device full-page boundary extend the
            # match; a host chunk always beats a device tail (>= one
            # full page vs < one), so the tail is dropped un-adopted
            host_chunks = []
            if self.tier is not None and chunk_pages is None:
                base = len(m.full_pages) * self.page
                host_chunks = self.tier.arena.lookup_chunks(
                    prompt_ids, base, T - 1)
                if host_chunks:
                    m.matched_len = base + len(host_chunks) * self.page
                    m.tail_src, m.tail_len = None, 0
            # a fully-cached prompt still runs >= 1 suffix token — the
            # engine needs its logits to start decoding
            if m.matched_len > T - 1:
                m.matched_len = T - 1
                if m.tail_len > 1:
                    m.tail_len -= 1
                elif m.tail_len == 1:
                    m.tail_src, m.tail_len = None, 0
                else:
                    # pure full-page match: the last page turns into a
                    # COW tail source missing its final slot
                    m.tail_src = m.full_pages.pop()
                    m.tail_len = self.page - 1
            if not m.tail_len:
                m.tail_src = None
            n_fetch = len(host_chunks)
            charge = (chunk_pages if chunk_pages is not None
                      else self.suffix_budget(T, max_new, m.matched_len))
            adopt = list(m.full_pages)
            if m.tail_src is not None:
                adopt.append(m.tail_src)
            # each fetched chunk pre-charges the pool page it will
            # occupy, so materialization can never overdraft — and a
            # degraded fetch converts the pre-charge 1:1 into the
            # suffix budget those extra prefill pages need
            need = charge + n_fetch + self.pool.pin_cost(adopt)
            if need > self.pool.budget_avail:
                return None
            self.pool.charge(charge + n_fetch)
            for pid in adopt:
                self.pool.incref(pid)
                self.pool.pin(pid)
            adm = Admission(m.matched_len, m.full_pages, m.tail_src,
                            m.tail_len, charge)
            adm.fetch_reserved = n_fetch
            adm.device_matched = (len(m.full_pages) * self.page
                                  if host_chunks else m.matched_len)
            try:
                own_prompt = (chunk_pages if chunk_pages is not None
                              else _ceil_div(T, self.page)
                              - m.matched_len // self.page)
                self.ensure_free(own_prompt)
            except BaseException:
                self.cancel(adm)
                raise
            # arm the fetch LAST: nothing below can raise, so cancel()
            # never races the migration worker's arena unpins
            if host_chunks:
                for _key, slot in host_chunks:
                    self.tier.arena.pin(slot)
                adm.fetch = host_chunks
                adm.fetch_job = self.tier.migrator.submit_fetch(
                    host_chunks)
            if m.matched_len:
                # host tokens count toward ``reused`` only once their
                # fetch materializes — a degraded fetch must not have
                # inflated the savings tally
                dev_reused = adm.device_matched
                self.hits += 1
                self.prefix_tokens_reused += dev_reused
                self._count("hits")
                if dev_reused:
                    self._count("reused", dev_reused)
            else:
                self.misses += 1
                self._count("misses")
            return adm

    def cancel(self, adm: Admission):
        """Roll an admission back (failed prefill / injected fault /
        engine stop with a fetch still parked): drop adoption
        refs+pins, the budget charge and any fetch pre-charge. Arena
        pins belong to the migration worker — cancelling the job makes
        it release them."""
        with self._lock:
            self.release_transient(adm)
            for pid in adm.shared_pages:
                self.pool.decref(pid)
                self.pool.unpin(pid)
            adm.shared_pages = []
            if adm.fetch_job is not None:
                adm.fetch_job.cancelled = True
            self.pool.release(adm.charge + adm.fetch_reserved)
            adm.charge = 0
            adm.fetch_reserved = 0
            adm.fetch = []
            adm.fetch_job = None

    def charge_chunk(self, adm: Admission, n: int) -> bool:
        """Extend a chunked admission's ledger charge by ``n`` pages —
        the next prefill chunk's own pages, plus (at the final chunk)
        the decode-budget top-up (ISSUE 14). False = the ledger cannot
        cover it RIGHT NOW with nothing charged; the engine keeps
        decoding and retries next pass, shedding (full rollback) after
        its bounded wait so concurrent chunkers can never deadlock the
        pool. Σ(chunk charges) over a completed admission equals the
        unchunked worst-case charge exactly, so EOS release balances."""
        if n <= 0:
            return True
        with self._lock:
            if n > self.pool.budget_avail:
                return False
            self.pool.charge(n)
            adm.charge += n
            return True

    def uncharge_chunk(self, adm: Admission, n: int):
        """Return an unused chunk charge (a chunk dispatch that failed
        after charging): the exact inverse of :meth:`charge_chunk`, so
        the engine's pass retry starts from the pre-pass ledger."""
        if n <= 0:
            return
        with self._lock:
            self.pool.release(n)
            adm.charge -= n

    def release_transient(self, adm: Admission):
        """Drop the COW fork source's transient ref/pin — safe as soon
        as the partial prefill consuming it has been dispatched (the
        donated-pool data dependency orders any later overwrite after
        the gather)."""
        with self._lock:
            if adm.tail_src is not None:
                self.pool.decref(adm.tail_src)
                self.pool.unpin(adm.tail_src)
                adm.tail_src = None

    def release_slot(self, charge: int, owned, adopted):
        """EOS/eviction release: decrement refcounts instead of freeing
        — pages the index still references stay warm for reuse."""
        with self._lock:
            for pid in owned:
                self.pool.decref(pid)
            for pid in adopted:
                self.pool.decref(pid)
                self.pool.unpin(pid)
            self.pool.release(charge)

    # -- index maintenance ---------------------------------------------------
    def insert(self, tokens, pages):
        """Index a chain (prompt at prefill time; prompt+generated at
        EOS). The index takes its own ref on each newly-indexed page."""
        if not self.enabled or not len(tokens):
            return
        with self._lock:
            self.index.insert(tokens, pages)
            self.record_gauges()

    def chain_locations(self, tokens):
        """Where a chain's cached FULL pages live right now (the
        handoff export walk): device page ids for the radix-resident
        prefix, then ``(key, slot)`` arena chunks continuing it. The
        caller (engine, under its lock) pulls the device pages while
        eviction cannot run."""
        with self._lock:
            m = self.index.lookup(tokens)
            dev = list(m.full_pages)
            host = []
            if self.tier is not None:
                base = len(dev) * self.page
                host = self.tier.arena.lookup_chunks(
                    tokens, base, len(tokens))
            return dev, host

    # -- physical pages ------------------------------------------------------
    def ensure_free(self, n: int):
        """Make ``n`` pages allocatable, LRU-evicting index-only chains
        under pool pressure. The ``kvcache.evict`` fault site arms
        eviction races (chaos_check --kvcache); it fires BEFORE any
        mutation so an injected raise is cleanly retryable."""
        short = n - self.pool.free_pages()
        if short <= 0:
            return
        if not self.enabled:
            raise PagePoolError(
                "page shortage with the prefix cache disabled: the "
                "admission budget should have prevented this")
        from bigdl_tpu import reliability
        reliability.inject("kvcache.evict")
        with self._lock:
            freed = self.index.evict_lru(
                short, spill=self._spill if self.tier is not None
                else None)
            self.evictions += len(freed)
            self._count("evictions", len(freed))
            if freed:
                # same site as the counter: the flight cross-check
                # asserts Σ(evict event pages) == evictions_total
                from bigdl_tpu.observability import flight
                flight.record("evict", pages=len(freed),
                              requested=short)
            self.record_gauges()
            if len(freed) < short:
                raise PagePoolError(
                    f"eviction reclaimed {len(freed)}/{short} pages: "
                    "the pin/budget invariant is broken")

    def take_free(self) -> int:
        with self._lock:
            return self.pool.take_free()

    def alloc(self, n: int) -> List[int]:
        with self._lock:
            return self.pool.alloc(n)

    def free_owned(self, pages):
        with self._lock:
            for pid in pages:
                self.pool.decref(pid)

    # -- introspection -------------------------------------------------------
    @property
    def budget_avail(self) -> int:
        return self.pool.budget_avail

    def debug_stats(self) -> Dict[str, Any]:
        """The ``GET /debug/kvcache`` body."""
        with self._lock:
            out = {
                "enabled": self.enabled,
                "page_size": self.page,
                "num_pages": self.pool.num_pages,
                "pages_free": self.pool.free_pages(),
                "pages_allocated": self.pool.allocated(),
                "pages_shared": self.pool.shared_pages(),
                "pages_pinned": self.pool.pinned_pages(),
                "budget_avail": self.pool.budget_avail,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "prefix_tokens_reused": self.prefix_tokens_reused,
            }
            if self.index is not None:
                out["index"] = self.index.stats()
            if self.tier is not None:
                out["tier"] = self.tier.debug_stats()
            return out


__all__ = ["Admission", "KVCacheManager", "PagePool", "PagePoolError",
           "PrefixMatch", "RadixIndex", "make_partial_prefill",
           "make_spec_step"]

"""Refcounted KV page pool with copy-on-write semantics (ISSUE 5
tentpole part 1).

The serving engine's page pool used to be three bare attributes on
``LLMServer`` (``_free`` / ``_budget_avail`` / ``_slot_pages``) with
exactly one owner per page. Prefix sharing changes the ownership story:
a page holding the KV of a common prompt prefix is referenced by the
radix index AND by every live request that adopted it, so pages carry
**refcounts** and are freed only when the last reference drops.

Two kinds of capacity live here, deliberately separate:

- **physical** pages — the free-id list. ``take_free``/``decref`` move
  ids between the free list and the allocated map. The id order is the
  seed engine's exactly (ids pop low-first, frees append), so a server
  with the cache disabled allocates bit-identically to the pre-kvcache
  engine.
- **budget** — the admission reservation counter (the vLLM-style
  worst-case reserve that makes decode deadlock-free). Reservations are
  bookkeeping only; they never touch the free list. With prefix reuse
  the engine charges only the *uncached suffix* plus one reservation per
  newly **pinned** shared page (see below), so shared prefixes stop
  eating admission capacity.

**Pinning.** An index-held page (refcount 1) is evictable and costs no
budget. The moment a live request adopts it the page becomes
unevictable, so capacity must be reserved for it — but only ONCE no
matter how many requests share it. ``pin``/``unpin`` keep a per-page
live-adopter count and charge/release a single reservation on the
0→1 / 1→0 transitions. The pool-wide invariant that keeps allocation
deadlock-free::

    unevictable pages  =  owned-by-live  +  pinned-shared
                       <=  Σ admission charges  +  Σ pins
                       =  (num_pages - 1) - budget_avail

so ``free + evictable >= any remaining reservation`` always holds and a
charged request can always obtain its physical pages (after eviction).

**Copy-on-write.** The write barrier is a refcount rule, not a method:
a page you solely own may be written in place; a shared page
(refcount > 1) must never be — the writer allocates a fresh page and
copies the shared slots. The serving engine realizes the fork inside
the partial-prefill scatter: the adopted tail page is gathered
read-only and its live slots are re-scattered into the adopter's own
page, which is exactly fork-then-write in one dispatch (no separate
copy kernel, no window where a half-forked page is visible).

Pure host-side bookkeeping: no jax imports, trivially unit-testable.
"""

from __future__ import annotations

from typing import Dict, List


class PagePoolError(RuntimeError):
    """Internal-invariant violation (double free, free-list underflow)."""


class PagePool:
    """Refcounted page-id allocator over ``num_pages`` physical pages.

    Page 0 is the engine's trash page (inactive rows dummy-write there)
    and is never allocatable; usable capacity is ``num_pages - 1``.
    Not thread-safe by itself — the owning :class:`KVCacheManager`
    serializes access (the engine additionally holds its own lock).
    """

    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 2:
            raise ValueError("pool needs at least one usable page "
                             "(page 0 is the reserved trash page)")
        self.num_pages = num_pages
        self.page_size = page_size
        # seed-engine order: list(range(n-1, 0, -1)) popped from the end
        # hands out page 1 first — disabled-mode allocation parity
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        self._ref: Dict[int, int] = {}
        self.budget_avail = num_pages - 1
        # live-adopter counts for shared (index-held) pages; each page
        # with a nonzero count holds exactly ONE budget reservation
        self._pins: Dict[int, int] = {}

    # -- physical pages ------------------------------------------------------
    def free_pages(self) -> int:
        return len(self._free)

    def free_ids(self) -> List[int]:
        """The raw free list (read-only by convention): the engine's
        ``_free`` property and the pool-drained test assertions."""
        return self._free

    def allocated(self) -> int:
        return len(self._ref)

    def take_free(self) -> int:
        """Pop one page (refcount 1). Caller must have reserved budget
        and ensured the free list is non-empty (``ensure`` upstream) —
        an empty list here is an accounting bug, not back-pressure."""
        if not self._free:
            raise PagePoolError(
                "free-list underflow: allocation outside the admission "
                "budget (reservation accounting is broken)")
        pid = self._free.pop()
        self._ref[pid] = 1
        return pid

    def alloc(self, n: int) -> List[int]:
        return [self.take_free() for _ in range(n)]

    def incref(self, pid: int) -> int:
        if pid not in self._ref:
            raise PagePoolError(f"incref of unallocated page {pid}")
        self._ref[pid] += 1
        return self._ref[pid]

    def decref(self, pid: int) -> int:
        """Drop one reference; refcount 0 returns the id to the free
        list (append — the seed engine's ``_free.extend`` order)."""
        r = self._ref.get(pid)
        if r is None:
            raise PagePoolError(f"decref of unallocated page {pid}")
        if r == 1:
            del self._ref[pid]
            self._free.append(pid)
            return 0
        self._ref[pid] = r - 1
        return r - 1

    def refcount(self, pid: int) -> int:
        return self._ref.get(pid, 0)

    def shared_pages(self) -> int:
        """Pages referenced more than once (the shared-page gauge)."""
        return sum(1 for r in self._ref.values() if r > 1)

    # -- admission budget ----------------------------------------------------
    def charge(self, n: int):
        """Reserve ``n`` pages of admission budget (worst-case suffix
        cost). Callers check :attr:`budget_avail` first — going negative
        is an accounting bug."""
        if n > self.budget_avail:
            raise PagePoolError(
                f"budget overdraft: charge {n} with {self.budget_avail} "
                "available")
        self.budget_avail -= n

    def release(self, n: int):
        self.budget_avail += n
        if self.budget_avail > self.num_pages - 1:
            raise PagePoolError("budget over-release")

    def pin(self, pid: int):
        """A live request adopted shared page ``pid``: reserve one page
        of budget on the first adopter only (0→1 transition)."""
        c = self._pins.get(pid, 0)
        if c == 0:
            self.charge(1)
        self._pins[pid] = c + 1

    def pin_cost(self, pids) -> int:
        """Reservations :meth:`pin` would newly take for ``pids`` —
        admission checks ``suffix_budget + pin_cost`` atomically."""
        seen = set()
        cost = 0
        for pid in pids:
            if pid not in seen and self._pins.get(pid, 0) == 0:
                cost += 1
            seen.add(pid)
        return cost

    def pin_precharged(self, pid: int):
        """Pin consuming a reservation the caller already holds (the
        host-tier fetch: admission pre-charges one page per fetched
        chunk so materialization can never overdraft). If another
        request pinned the page while the fetch was in flight, the 0→1
        charge already happened — the caller's pre-charge is surplus
        and is released here so the one-reservation-per-pinned-page
        invariant holds."""
        c = self._pins.get(pid, 0)
        if c != 0:
            self.release(1)
        self._pins[pid] = c + 1

    def unpin(self, pid: int):
        c = self._pins.get(pid, 0)
        if c <= 0:
            raise PagePoolError(f"unpin of unpinned page {pid}")
        if c == 1:
            del self._pins[pid]
            self.release(1)
        else:
            self._pins[pid] = c - 1

    def pinned_pages(self) -> int:
        return len(self._pins)

    # -- eviction support ----------------------------------------------------
    def evictable(self, pid: int) -> bool:
        """Only the index holds it: refcount exactly 1 and unpinned.
        (A pinned page always has refcount >= 2, but the explicit check
        keeps the invariant readable.)"""
        return self.refcount(pid) == 1 and pid not in self._pins

"""Partial prefill over a pre-populated block-table prefix (ISSUE 5/8).

Two implementations live here:

- the **ragged in-place path** (ISSUE 8, the default): shared closures
  (:func:`ragged_prefill_attend`, :func:`fork_tail_pages`,
  :func:`scatter_suffix_kv`) that each family's ``paged_prefill_ragged``
  composes with its own layer math — the suffix attends the prefix
  pages WHERE THEY SIT via the Mosaic ragged kernel
  (llm/kernels/ragged_prefill.py), the COW tail fork is one
  page-to-page copy inside the same dispatch, and ONE post-scan scatter
  writes the suffix K/V into the request's pages. No dense temp cache,
  and the prefix page count is runtime block-table data — the compile
  grid is O(suffix-buckets) only;
- the **dense staging path** (:func:`make_partial_prefill`, the ISSUE 5
  original): kept as the fallback for families without a ragged entry
  point and for the ``bigdl.llm.prefill.ragged=false`` escape hatch.

When admission finds a cached prefix, only the uncached suffix must run
through the model — but the suffix's attention still needs the prefix's
K/V. :func:`make_partial_prefill` lifts a family ``forward`` into a
prefill that:

1. **gathers** the prefix pages (the request's pre-populated block-table
   prefix) into the dense temp cache the family forward already expects,
   at their absolute positions 0..offset;
2. runs the family forward over the suffix tokens only, at a **position
   offset** — the suffix attends to the gathered prefix plus itself
   causally, exactly the math of the full prefill's later rows;
3. **scatters** one page-aligned window back into the (donated) pools:
   the suffix K/V into the request's own pages, PLUS the shared slots of
   a partially-matched tail page into the request's fork target — the
   copy-on-write fork fused into the same dispatch (no separate copy
   kernel, no window where a half-forked page is visible).

Shapes are static per ``(n_pp, bucket)`` — prefix pages padded to a
power of two (pad ids point at trash page 0), suffix length padded like
the full prefill's pow2 buckets — so the compile count stays
logarithmic. The dynamic values (offset, true suffix length, page ids,
per-token scatter targets) are runtime arguments.

Why the garbage in pad pages / beyond-offset slots is harmless: every
temp-cache slot at index >= offset is either overwritten by the
suffix's own in-forward cache write (indices offset..offset+bucket) or
masked by the forward's validity bound (indices >= offset+bucket), and
causal masking orders real queries before any padding position.

Each paged family module exposes::

    paged_prefill_partial = make_partial_prefill(forward, init_cache)

mirroring ``paged_decode_step_sampled = make_sampled_step(...)`` — one
entry point per family, zero per-family math here.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


# ---------------------------------------------------------------------------
# ragged in-place prefill (ISSUE 8): shared closures for the per-family
# ``paged_prefill_ragged`` entry points
# ---------------------------------------------------------------------------

def fork_tail_pages(k_pages, v_pages, fork_dst, fork_src):
    """COW tail fork, fused into the prefill dispatch: copy the adopted
    partial tail page (``fork_src``, shared — never written in place)
    into the page the request owns (``fork_dst``). Runs BEFORE the
    layer scan so the ragged kernel reads the forked slots through the
    request's own block table; the suffix scatter then overwrites the
    slots from ``offset`` on. With no tail both ids are 0 — a trash-
    page self-copy, semantically a no-op."""
    k_pages = k_pages.at[:, fork_dst].set(k_pages[:, fork_src])
    v_pages = v_pages.at[:, fork_dst].set(v_pages[:, fork_src])
    return k_pages, v_pages


def ragged_prefill_attend(k_pages, v_pages, bt_row, offset, seq_len, *,
                          page: int,
                          sliding_window: Optional[int] = None,
                          interpret: Optional[bool] = None):
    """Shared ragged-attention closure for every family's prefill.

    Mirrors ``serving.paged_attend``'s conventions: the pools are
    viewed as one flat ``(L·P, H, page, D)`` page array, the block
    table is offset by ``l·P`` inside the layer scan (layer ``l``'s
    trash page is ``l·P``), and the kernel reads only prefix positions
    ``< offset`` (the suffix's own K/V rides in densely — it is not in
    the pool until the post-scan scatter). Returns
    ``attend(l, q, k, v) -> (1, Tq, Hq, D) f32`` for suffix-shaped
    ``(1, Tq, H*, D)`` projections."""
    from bigdl_tpu.llm.kernels.ragged_prefill import ragged_prefill
    L, P = k_pages.shape[0], k_pages.shape[1]
    kp_flat = k_pages.reshape((L * P,) + k_pages.shape[2:])
    vp_flat = v_pages.reshape((L * P,) + v_pages.shape[2:])
    bt = bt_row.reshape(1, -1)
    offs = jnp.reshape(offset, (1,)).astype(jnp.int32)
    lens = jnp.reshape(seq_len, (1,)).astype(jnp.int32)

    def attend(l, q, k, v):
        return ragged_prefill(q, k, v, kp_flat, vp_flat, bt + l * P,
                              offs, lens, page_size=page,
                              sliding_window=sliding_window,
                              interpret=interpret)

    return attend


def scatter_suffix_kv(k_pages, v_pages, phys, slots, k_new, v_new):
    """ONE vectorized scatter of every layer's suffix K/V into the
    (donated) pools — the write half of the old dense sandwich, kept;
    the gather half is gone. ``k_new``/``v_new`` are the layer-scan ys
    ``(L, Tq, Hkv, D)``; token ``j`` lands in ``(phys[j], slots[j])``
    (entries the request must not write route to trash page 0)."""
    k_pages = k_pages.at[:, phys, :, slots].set(
        k_new.transpose(1, 0, 2, 3).astype(k_pages.dtype))
    v_pages = v_pages.at[:, phys, :, slots].set(
        v_new.transpose(1, 0, 2, 3).astype(v_pages.dtype))
    return k_pages, v_pages


def make_mixed_step(fam_step, fam_ragged):
    """Lift a family ``(paged_decode_step, paged_prefill_ragged)`` pair
    into the engine's UNIFIED mixed prefill+decode step (ISSUE 14).

    The split engine compiles prefill and decode as separate programs,
    so a long admission stalls every in-flight decode for a whole pass.
    The lifted step fuses both legs into ONE compiled program per
    chunk-suffix bucket — the Ragged-Paged-Attention batch shape (one
    dispatch serving rows with suffix length 1 and rows with a chunk of
    suffix tokens) realized by composition of the two proven per-family
    bodies, so each leg's math is BIT-IDENTICAL to the program the
    split engine would have run:

    - the **chunk leg** runs first: exactly the family's
      ``paged_prefill_ragged`` over the ``(1, bucket)`` chunk — COW
      tail fork fused ahead of its layer scan, attention reading the
      cached prefix (and earlier chunks) in place via the ragged
      kernel, one post-scan scatter of the chunk's K/V into its own
      pages. Its page writes are disjoint from every decode row's
      (shared radix pages are never decode-written; the chunk's own
      pages belong to no decode row), so leg order cannot change any
      row's result;
    - the **decode leg** is exactly the family's
      ``make_sampled_step`` body: sample every active row's next token
      from ``last`` on device, one token of forward+attend per row,
      one post-scan scatter, lengths advanced for active rows. The
      chunk's slot rides this leg MASKED INACTIVE (trash-page dummy
      write), exactly like an empty slot in the split engine.

    Returns ``(out, logits, k_pages, v_pages, new_lens, key, clast)``
    — the sampled-ids ‖ fence vector (the fence data-depends on the
    pools AFTER both legs' scatters, so one drain fetch bounds the
    whole pass), the decode logits, and ``clast``: the chunk's
    last-true-token logits, which the engine scatters into its ``last``
    row when the final chunk completes the prompt (mid-prompt chunks
    discard it). Compile-relevant shapes: the decode batch width and
    the chunk bucket ``ctoks.shape[1]`` only — offsets, block tables
    and scatter targets are runtime data, so the grid stays
    O(suffix-buckets).
    """
    from bigdl_tpu.llm.kernels.sampling import make_sampled_step
    sampled = make_sampled_step(fam_step)

    def mixed_step(params, cfg, k_pages, v_pages, bt, lens, last,
                   active, temperature, key, ctoks, clen, coff, cbt_row,
                   cphys, cslots, fork_dst, fork_src, *, page: int,
                   do_sample: bool = False, top_k: int = 0):
        k_pages, v_pages, clast = fam_ragged(
            params, cfg, k_pages, v_pages, ctoks, clen, coff, cbt_row,
            cphys, cslots, fork_dst, fork_src, page=page)
        out, logits, k_pages, v_pages, new_lens, key = sampled(
            params, cfg, k_pages, v_pages, bt, lens, last, active,
            temperature, key, page=page, do_sample=do_sample,
            top_k=top_k)
        return out, logits, k_pages, v_pages, new_lens, key, clast

    return mixed_step


def make_spec_step(fam_step, fam_ragged):
    """Lift a family ``(paged_decode_step, paged_prefill_ragged)`` pair
    into the engine's SPECULATIVE verify step (ISSUE 19).

    The verify chunk is the mixed step's chunk leg re-aimed at decode:
    instead of prompt tokens, the ``(1, W)`` chunk carries the row's
    next greedy token followed by the host's n-gram drafts, run at the
    row's position offset — logits for all chunk positions come back
    from ONE dispatch, and :func:`kernels.sampling.spec_accept` keeps
    the prefix greedy decode would have produced anyway. Structure:

    - **chunk token 0 is computed on device**: ``g0 = argmax(last
      [srow])`` — exactly the token the sampled decode leg would have
      emitted for the row. With every draft rejected the step therefore
      degenerates to a plain decode step for the row (emit ``g0``,
      whose K/V the chunk leg wrote at position ``lens[srow]``, carry
      ``chunk_logits[0]``), preserving the engine invariant that every
      emitted token has its K/V in the pool and ``last`` predicts the
      next position;
    - the **chunk leg** is the family's ``paged_prefill_ragged``
      VERBATIM (``full_logits=True``) at offset ``lens[srow]`` over the
      row's own block table — no COW fork (a decode row's tail pages
      are private by the admission contract), padding past
      ``n_draft + 1`` routed to trash page 0 by the host's scatter
      targets;
    - the **decode leg** is the family's ``make_sampled_step`` body
      with the spec row masked INACTIVE (trash-page dummy write, like
      an empty slot) — every other active row advances exactly as in a
      plain pass;
    - the accepted length then advances the spec row's device length by
      ``n_acc`` and splices ``chunk_logits[n_acc - 1]`` into the
      ``last`` carry. K/V written for the REJECTED tail positions is
      rolled back by length bookkeeping alone: attention reads only
      positions ``< lens``, and later steps overwrite the garbage slots
      as the row advances (docs/KVCACHE.md "Speculative charging").

    Returns ``(out, logits, k_pages, v_pages, new_lens, key)`` where
    ``out`` is ``(B + 1 + W + 1,)`` int32: the decode rows' sampled ids
    (the spec row's lane is garbage — the host skips it), ``n_acc``,
    the W chunk tokens (the host needs ``g0`` back — it was never on
    the host), and one :func:`kernels.sampling.fence_token` bounding
    both legs' pool writes. Compile-relevant shapes: batch width and
    the chunk bucket ``ctoks.shape[1]`` only — ``srow``, ``n_draft``,
    offsets and scatter targets are runtime data, so speculation adds
    O(k-buckets) programs total.
    """
    from bigdl_tpu.llm.kernels.sampling import (fence_token,
                                                make_sampled_step,
                                                spec_accept)
    sampled = make_sampled_step(fam_step)

    def spec_step(params, cfg, k_pages, v_pages, bt, lens, last, active,
                  temperature, key, srow, ctoks, n_draft, cbt_row,
                  cphys, cslots, *, page: int, do_sample: bool = False,
                  top_k: int = 0):
        b = lens.shape[0]
        rows = jnp.arange(b, dtype=jnp.int32)
        onehot = rows == srow
        slast = jnp.take(last, srow, axis=0)                    # (V,)
        g0 = jnp.argmax(slast).astype(jnp.int32)
        ctoks = ctoks.at[0, 0].set(g0)
        clen = (n_draft + 1).astype(jnp.int32)
        coff = jnp.take(lens, srow).astype(jnp.int32)
        k_pages, v_pages, chunk_logits = fam_ragged(
            params, cfg, k_pages, v_pages, ctoks, clen, coff, cbt_row,
            cphys, cslots, jnp.int32(0), jnp.int32(0), page=page,
            full_logits=True)
        n_acc, new_slast = spec_accept(ctoks[0], chunk_logits, n_draft)
        out, logits, k_pages, v_pages, new_lens, key = sampled(
            params, cfg, k_pages, v_pages, bt, lens, last,
            active & ~onehot, temperature, key, page=page,
            do_sample=do_sample, top_k=top_k)
        new_lens = new_lens + jnp.where(onehot, n_acc,
                                        0).astype(new_lens.dtype)
        logits = jnp.where(onehot[:, None], new_slast[None, :], logits)
        out = jnp.concatenate(
            [out[:b], n_acc[None], ctoks[0],
             fence_token(k_pages, v_pages, logits)])
        return out, logits, k_pages, v_pages, new_lens, key

    return spec_step


def make_partial_prefill(forward_fn, init_cache_fn):
    """Lift a family ``forward``/``init_cache`` pair into the engine's
    partial-prefill shape.

    The lifted function (jitted by the engine with the pools donated)::

        partial_prefill(params, cfg, k_pages, v_pages, toks, length,
                        offset, prefix_ids, phys, slots, *,
                        page, n_pp, bucket, cache_dtype)
        -> (k_pages, v_pages, last_logits)

    - ``toks`` (1, bucket) int32 suffix tokens (zero-padded);
    - ``length`` () int32 true suffix length (>= 1);
    - ``offset`` () int32 cached-prefix length (the position offset);
    - ``prefix_ids`` (n_pp,) int32 physical pages holding positions
      ``0..offset`` in order (pad entries 0 = trash page);
    - ``phys``/``slots`` (page + bucket,) int32 scatter targets for the
      window starting at position ``(offset // page) * page``: token
      ``j`` of the window lands in ``(phys[j], slots[j])``; entries the
      request must not write route to trash page 0. The leading
      sub-page slots (window start .. offset) target the COW fork page,
      re-writing the adopted tail's shared slots into a page the
      request owns.
    """

    def partial_prefill(params, cfg, k_pages, v_pages, toks, length,
                        offset, prefix_ids, phys, slots, *, page: int,
                        n_pp: int, bucket: int, cache_dtype):
        L = k_pages.shape[0]
        # one page of slack past the gathered prefix: the scatter window
        # below is page-aligned, so with a page-aligned offset it starts
        # AT the prefix end and must slice page+bucket in-bounds tokens
        s_temp = n_pp * page + page + bucket
        cache = init_cache_fn(cfg, 1, s_temp, dtype=cache_dtype)

        def gathered(pages):
            g = pages[:, prefix_ids]                 # (L,n_pp,H,page,D)
            g = g.transpose(0, 1, 3, 2, 4)           # (L,n_pp,page,H,D)
            return g.reshape(L, n_pp * page, *g.shape[3:])

        cache["k"] = cache["k"].at[:, 0, :n_pp * page].set(
            gathered(k_pages).astype(cache_dtype))
        cache["v"] = cache["v"].at[:, 0, :n_pp * page].set(
            gathered(v_pages).astype(cache_dtype))
        cache["pos"] = offset.astype(jnp.int32)
        positions = (offset + jnp.arange(bucket, dtype=jnp.int32))[None]
        logits, cache2 = forward_fn(params, cfg, toks, cache, positions)

        # page-aligned write-back window: [window0, window0+page+bucket)
        # covers the fork page's shared slots AND every suffix token
        window0 = (offset // page) * page
        ks, vs = cache2["k"][:, 0], cache2["v"][:, 0]  # (L,s_temp,H,D)

        def scatter(pages, vals):
            w = jax.lax.dynamic_slice_in_dim(vals, window0,
                                             page + bucket, axis=1)
            return pages.at[:, phys, :, slots].set(
                w.transpose(1, 0, 2, 3).astype(pages.dtype))

        k_pages = scatter(k_pages, ks)
        v_pages = scatter(v_pages, vs)
        last = jax.lax.dynamic_index_in_dim(logits[0], length - 1, 0,
                                            keepdims=False)
        return k_pages, v_pages, last.astype(jnp.float32)

    return partial_prefill

"""Radix prefix index over the KV page pool (ISSUE 5 tentpole part 2).

Cached prefixes are stored as a radix tree keyed on **token-id chunks of
page size** (the SGLang RadixAttention idea on our paged substrate): an
edge's key is the exact tuple of token ids one page holds, and the node
owns that page's id. Interior nodes are always full pages; a node whose
chunk is shorter than a page is a **tail** — the partially-filled last
page of an indexed chain, adoptable via copy-on-write (the adopter's
first divergent write forks it, see pool.py).

Lookup walks full chunks exactly, then scans the frontier's children
for the best partial overlap (>= 1 token) — a divergent tail still
donates the shared slots of its page, the rest is masked/overwritten by
the adopter's own prefill. Every traversed node is LRU-touched.

Eviction is leaf-first LRU: under pool pressure the least-recently-used
leaf whose page only the index references (``pool.evictable``) is
removed and its page decref'd back to the free list; interior nodes
become leaves as their subtrees drain, so cold chains disappear
back-to-front. Pages adopted by live requests (refcount > 1) are never
eviction candidates.

The index holds exactly one pool reference per node; dropping a node is
one ``decref``. Host-side only — no jax, unit-testable with a bare
:class:`~bigdl_tpu.llm.kvcache.pool.PagePool`.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from bigdl_tpu.llm.kvcache.pool import PagePool


class RadixNode:
    __slots__ = ("chunk", "page", "children", "parent", "last_used")

    def __init__(self, chunk: Tuple[int, ...], page: Optional[int],
                 parent: Optional["RadixNode"]):
        self.chunk = chunk
        self.page = page
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "RadixNode"] = {}
        self.last_used = 0


class PrefixMatch:
    """Result of :meth:`RadixIndex.lookup`.

    ``matched_len`` counts matched TOKENS; ``full_pages`` are the page
    ids of the fully-matched chunks (shareable outright);
    ``tail_src``/``tail_len`` name the partially-matched page (COW
    fork source) when the match ends mid-page."""

    __slots__ = ("matched_len", "full_pages", "tail_src", "tail_len")

    def __init__(self, matched_len: int = 0,
                 full_pages: Optional[List[int]] = None,
                 tail_src: Optional[int] = None, tail_len: int = 0):
        self.matched_len = matched_len
        self.full_pages = full_pages or []
        self.tail_src = tail_src
        self.tail_len = tail_len


def _common_prefix(a, b) -> int:
    n = min(len(a), len(b))
    for i in range(n):
        if a[i] != b[i]:
            return i
    return n


class RadixIndex:
    """The prefix tree. Page references it takes/drops go through the
    shared :class:`PagePool`; hit/miss/evict accounting lives in the
    manager (one layer up) so the tree stays a pure data structure."""

    def __init__(self, pool: PagePool):
        self.pool = pool
        self.page = pool.page_size
        self.root = RadixNode((), None, None)
        self._tick = 0
        # flat registry for O(nodes) LRU scans (node count is bounded by
        # the pool size, so a scan is tiny)
        self._nodes: List[RadixNode] = []

    def __len__(self) -> int:
        return len(self._nodes)

    def indexed_pages(self) -> int:
        return len(self._nodes)

    def _touch(self, node: RadixNode):
        self._tick += 1
        while node is not None and node is not self.root:
            node.last_used = self._tick
            node = node.parent

    # -- lookup --------------------------------------------------------------
    def lookup(self, tokens, *, touch: bool = True) -> PrefixMatch:
        """Longest cached prefix of ``tokens``: exact full-page chunks,
        then the best >=1-token partial overlap among the frontier's
        children (full-page children included — a divergent page still
        shares its common slots)."""
        toks = [int(t) for t in tokens]
        page = self.page
        node = self.root
        full_pages: List[int] = []
        i = 0
        while i + page <= len(toks):
            child = node.children.get(tuple(toks[i:i + page]))
            if child is None:
                break
            node = child
            full_pages.append(child.page)
            i += page
        rem = tuple(toks[i:])
        best: Optional[RadixNode] = None
        best_m = 0
        if rem:
            for child in node.children.values():
                m = _common_prefix(child.chunk, rem)
                if m > best_m or (m == best_m and best is not None
                                  and m and child.last_used
                                  > best.last_used):
                    best, best_m = child, m
        if touch:
            self._touch(best if best_m else node)
        if best_m:
            return PrefixMatch(i + best_m, full_pages, best.page, best_m)
        return PrefixMatch(i, full_pages)

    # -- insert --------------------------------------------------------------
    def insert(self, tokens, pages) -> List[int]:
        """Index ``tokens`` backed by ``pages`` (page ``j`` holds tokens
        ``[j*page, (j+1)*page)``; the last chunk may be partial). Chunks
        already indexed keep their EXISTING node/page — same tokens at
        the same positions hold identical KV, so the duplicate page is
        simply not adopted (it frees at its owner's release). Returns
        the page ids newly referenced (one pool incref each)."""
        toks = [int(t) for t in tokens]
        page = self.page
        taken: List[int] = []
        node = self.root
        for j in range(0, len(toks), page):
            chunk = tuple(toks[j:j + page])
            pid = int(pages[j // page])
            child = node.children.get(chunk)
            if child is None:
                if pid == 0 or self.pool.refcount(pid) == 0:
                    break   # trash/freed page must never be indexed
                child = RadixNode(chunk, pid, node)
                node.children[chunk] = child
                self._nodes.append(child)
                self.pool.incref(pid)
                taken.append(pid)
            node = child
        self._touch(node)
        return taken

    def token_path(self, node: RadixNode) -> Tuple[int, ...]:
        """Every token from the root through ``node``'s chunk — the
        identity a spilled page carries into the host tier (ISSUE 6):
        the arena keys entries on the full prefix, so the chunk alone
        would be ambiguous."""
        parts: List[Tuple[int, ...]] = []
        while node is not None and node is not self.root:
            parts.append(node.chunk)
            node = node.parent
        out: List[int] = []
        for chunk in reversed(parts):
            out.extend(chunk)
        return tuple(out)

    def leaf_paths(self) -> List[Tuple[int, ...]]:
        """Every leaf's full token path — the maximal warm chains this
        index holds (interior nodes are prefixes of some leaf by
        construction). The drain-time KV migration walk (ISSUE 15)
        exports exactly these."""
        return [self.token_path(n) for n in self._nodes
                if not n.children]

    # -- eviction ------------------------------------------------------------
    def evict_lru(self, n_pages: int, spill=None) -> List[int]:
        """Drop least-recently-used evictable leaves until ``n_pages``
        page ids returned to the free list (or nothing evictable is
        left). Leaf-first: interior nodes become candidates only once
        their subtree is gone, so chains evict back-to-front.

        ``spill`` (ISSUE 6) is called as ``spill(token_path, page_id)``
        for each victim BEFORE its page is decref'd — the host tier's
        chance to capture the page's bytes while the id still cannot be
        reissued. Best-effort: a spill failure must not block the
        eviction (the manager's hook swallows and degrades)."""
        freed: List[int] = []
        while len(freed) < n_pages:
            victim: Optional[RadixNode] = None
            for node in self._nodes:
                if node.children:
                    continue
                if not self.pool.evictable(node.page):
                    continue
                if victim is None or node.last_used < victim.last_used:
                    victim = node
            if victim is None:
                break
            if spill is not None:
                spill(self.token_path(victim), victim.page)
            self._remove(victim)
            self.pool.decref(victim.page)
            freed.append(victim.page)
        return freed

    def _remove(self, node: RadixNode):
        del node.parent.children[node.chunk]
        self._nodes.remove(node)

    # -- introspection -------------------------------------------------------
    def stats(self) -> Dict[str, int]:
        leaves = sum(1 for n in self._nodes if not n.children)
        return {"nodes": len(self._nodes), "leaves": leaves,
                "tails": sum(1 for n in self._nodes
                             if len(n.chunk) < self.page)}

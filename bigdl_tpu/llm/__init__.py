"""bigdl_tpu.llm — low-bit LLM inference (ref: python/llm — bigdl-llm).

The reference patches HuggingFace ``from_pretrained(load_in_4bit=True)`` to
replace every ``nn.Linear`` with a ggml-block-quantized ``LowBitLinear``
backed by vendored llama.cpp CPU kernels (SURVEY.md §2.8). Here the same
API surface runs on TPU: q4_0-family block quantization (``llm.ggml``),
Pallas dequant-matmul kernels (``llm.kernels``), a jax Llama with kv cache
and tensor-parallel shardings (``llm.models``), and the
``AutoModelForCausalLM`` facade (``llm.transformers``).
"""

from bigdl_tpu.llm.ggml.quantize import (
    QK, dequantize, ggml_qtypes, quantize)
from bigdl_tpu.llm.transformers.low_bit_linear import LowBitLinear
from bigdl_tpu.llm.transformers.convert import (
    ggml_convert_low_bit, optimize_model)

__all__ = [
    "QK", "dequantize", "ggml_qtypes", "quantize",
    "LowBitLinear", "ggml_convert_low_bit", "optimize_model",
]

"""llm-cli / llm-chat (ref: P:llm/cli — the main/chat wrappers around the
native binaries; here around the jax generate loop)."""

from __future__ import annotations

import argparse
import sys
import time
from typing import Optional


def _load(args):
    from bigdl_tpu.llm.convert_model import load_model

    model = load_model(args.model, max_cache_len=args.ctx_size)
    tok = None
    if args.tokenizer:
        from transformers import AutoTokenizer

        tok = AutoTokenizer.from_pretrained(args.tokenizer)
    return model, tok


def _encode(tok, text: str):
    import numpy as np

    if tok is not None:
        return np.asarray([tok.encode(text)], np.int32)
    # byte-level fallback tokenizer for tokenizer-less runs
    return np.asarray([[b % 256 for b in text.encode()]], np.int32)


def _decode(tok, ids) -> str:
    if tok is not None:
        return tok.decode(list(ids), skip_special_tokens=True)
    return bytes(int(i) % 256 for i in ids).decode(errors="replace")


def main(argv: Optional[list] = None):
    """llm-cli -m <converted-model-dir> -p "prompt" -n 32"""
    ap = argparse.ArgumentParser("llm-cli")
    ap.add_argument("-m", "--model", required=True,
                    help="converted model dir (see convert_model)")
    ap.add_argument("-p", "--prompt", default="Once upon a time")
    ap.add_argument("-n", "--n_predict", type=int, default=32)
    ap.add_argument("-t", "--threads", type=int, default=0)  # parity no-op
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--top_k", type=int, default=40)
    ap.add_argument("--ctx_size", type=int, default=512)
    ap.add_argument("--tokenizer", default=None)
    args = ap.parse_args(argv)

    model, tok = _load(args)
    ids = _encode(tok, args.prompt)
    t0 = time.perf_counter()
    out = model.generate(ids, max_new_tokens=args.n_predict,
                         do_sample=args.temperature > 0,
                         temperature=max(args.temperature, 1e-6),
                         top_k=args.top_k)
    dt = time.perf_counter() - t0
    new = out[0, ids.shape[1]:]
    print(_decode(tok, new))
    print(f"[{len(new)} tokens in {dt:.2f}s — "
          f"{len(new) / dt:.2f} tok/s]", file=sys.stderr)
    return 0


def chat(argv: Optional[list] = None):
    """llm-chat: REPL over the same flags."""
    ap = argparse.ArgumentParser("llm-chat")
    ap.add_argument("-m", "--model", required=True)
    ap.add_argument("-n", "--n_predict", type=int, default=64)
    ap.add_argument("--ctx_size", type=int, default=512)
    ap.add_argument("--tokenizer", default=None)
    args = ap.parse_args(argv)
    model, tok = _load(args)
    print("llm-chat ready — empty line exits")
    while True:
        try:
            line = input("> ")
        except EOFError:
            break
        if not line.strip():
            break
        ids = _encode(tok, line)
        out = model.generate(ids, max_new_tokens=args.n_predict)
        print(_decode(tok, out[0, ids.shape[1]:]))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

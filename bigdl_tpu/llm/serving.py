"""LLM serving worker — continuous-batching generation service.

Reference: ``P:llm/serving`` (the bigdl-llm FastChat model worker and the
later vLLM integration, SURVEY.md §2.8 llm serving/tools row). The
reference wraps its CPU models behind FastChat's worker API; the analog
here is a TPU-shaped **continuous batching** loop:

- requests enter a queue at any time (``submit`` returns a handle);
- the scheduler packs up to ``max_batch`` active sequences into fixed
  batch slots (static shapes: one compiled decode step serves every
  composition of active requests);
- each engine step decodes ONE token for every active slot via the
  fused scan step (llm.models.llama.forward under jit, donated cache);
  finished sequences (EOS or max_tokens) free their slot immediately and
  a queued request takes it over — per-slot prefill writes its prompt
  into the shared cache at the slot's rows (the "continuous" part:
  no waiting for the whole batch to drain, the vLLM scheduling idea on
  a slot-static cache);
- steps are dispatched PIPELINED (ISSUE 4): sampling runs on device
  inside the compiled step, and up to ``bigdl.llm.pipeline_depth``
  steps are in flight before the oldest's tokens are drained — host
  scheduling (admission, prefill, EOS bookkeeping) overlaps device
  compute instead of round-tripping per token;
- results stream out through the handle (``get()`` blocks; ``tokens``
  grows as the loop runs).

Single-process and thread-driven: the engine loop runs on a background
thread like ClusterServing's job loop; the reference's HTTP surface is a
deployment shim over exactly this object.
"""

from __future__ import annotations

import collections
import functools
import heapq
import queue
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu import observability as obs
from bigdl_tpu import reliability
from bigdl_tpu.llm.kernels.sampling import make_sampled_step
from bigdl_tpu.llm.kvcache import KVCacheManager
from bigdl_tpu.observability import flight
from bigdl_tpu.observability import request_context as rc
from bigdl_tpu.observability import utilization


def _trace_of(req) -> Optional[str]:
    """The trace id riding a Request handle, if the submitter had one
    (flight events must stitch into the PR-3 trace model)."""
    t = getattr(req, "trace", None)
    return t.get("trace_id") if t else None


def _llm_instruments():
    """Engine metrics (declared only when observability is on): the
    per-phase signals the Ragged-Paged-Attention line of work says you
    need to diagnose serving — prefill vs decode throughput and KV-pool
    occupancy, not end-of-run aggregates."""
    return {
        "prefill_tokens": obs.counter(
            "bigdl_llm_prefill_tokens_total",
            "Prompt tokens prefilled into the KV cache"),
        "prefill_seconds": obs.histogram(
            "bigdl_llm_prefill_seconds",
            "Host wall of one request prefill (compile excluded after "
            "first hit per length bucket). At pipeline_depth 1 this "
            "covers execution (the prefill barriers); at depth > 1 it "
            "is DISPATCH time — execution overlaps decode by design"),
        "decode_tokens": obs.counter(
            "bigdl_llm_decode_tokens_total",
            "Tokens decoded across all slots"),
        "decode_seconds": obs.histogram(
            "bigdl_llm_decode_step_seconds",
            "Host wall attributed to one decode step: scheduling + "
            "fence stall (under pipelining device compute overlaps the "
            "host, so this is NOT pure device time — see the host/stall "
            "split below and docs/PERFORMANCE.md)"),
        "decode_host": obs.histogram(
            "bigdl_llm_decode_host_seconds",
            "Host-side scheduling slice of one decode step (page "
            "allocation + dispatch; no device wait)",
            buckets=obs.FAST_BUCKETS),
        "decode_stall": obs.histogram(
            "bigdl_llm_decode_stall_seconds",
            "Host time blocked on the device fence when draining a "
            "decode step (the pipeline's residual stall)",
            buckets=obs.FAST_BUCKETS),
        "inflight": obs.gauge(
            "bigdl_llm_pipeline_inflight",
            "Decode steps dispatched but not yet drained (bounded by "
            "bigdl.llm.pipeline_depth)"),
        "requests": obs.counter(
            "bigdl_llm_requests_total",
            "Requests finished by the engine", labelnames=("reason",)),
        "active": obs.gauge(
            "bigdl_llm_active_slots", "Slots currently decoding"),
        "queue": obs.gauge(
            "bigdl_llm_queue_depth",
            "Requests accepted and waiting for an engine slot (the "
            "fleet autoscaler's primary pressure signal)"),
        "kv_pages": obs.gauge(
            "bigdl_llm_kv_pages_in_use",
            "Physical KV pages owned by live requests"),
        "kv_occupancy": obs.gauge(
            "bigdl_llm_kv_pool_occupancy",
            "Fraction of the KV page pool in use (0..1)"),
    }


#: SLO classes in strictly descending scheduling priority (ISSUE 17).
#: The wire form is the case-insensitive ``X-BigDL-Priority`` header
#: (see llm/worker.py); anything unknown normalizes to "standard" so a
#: typo degrades to today's behavior instead of a 4xx.
PRIORITY_CLASSES = ("interactive", "standard", "batch")
_PRIORITY_RANK = {c: r for r, c in enumerate(PRIORITY_CLASSES)}
#: Retry-After queue-depth weights per class (ISSUE 17 satellite):
#: batch clients back off harder than interactive ones under the SAME
#: backlog — reliability.retry_after_seconds scales linearly in depth,
#: so weighting the depth weights the backoff.
CLASS_RETRY_WEIGHTS = {"interactive": 0.5, "standard": 1.0, "batch": 2.0}


def normalize_priority(value) -> str:
    """Map a header/ctor value onto a known SLO class ("standard" for
    None/unknown — misdeclared priority must degrade, never fail)."""
    if value is None:
        return "standard"
    v = str(value).strip().lower()
    return v if v in _PRIORITY_RANK else "standard"


class _PriorityScheduler:
    """Class-ordered admission backlog (ISSUE 17 tentpole). A binary
    heap of ``(rank, seq, req)``: rank orders classes, the monotonic
    sequence keeps FIFO within a class AND makes entries totally
    ordered (Request is not comparable). Engine-thread only — the
    thread-safe boundary stays the intake queue, which `_admit` drains
    into this heap every pass. Constructed ONLY when
    ``bigdl.llm.priority.enabled`` — disabled mode has no scheduler
    object at all (the structural-absence contract)."""

    def __init__(self):
        self._heap: List[tuple] = []
        self._seq = 0

    def push(self, req) -> None:
        self._seq += 1
        heapq.heappush(self._heap,
                       (_PRIORITY_RANK[req.priority], self._seq, req))

    def push_entry(self, ent: tuple) -> None:
        """Re-park a popped entry with its ORIGINAL sequence number —
        a budget-blocked head must keep its place in line, not move to
        the back of its class."""
        heapq.heappush(self._heap, ent)

    def pop_entry(self) -> Optional[tuple]:
        return heapq.heappop(self._heap) if self._heap else None

    def __len__(self) -> int:
        return len(self._heap)

    def live(self) -> int:
        """Entries whose request is still waiting (done handles are
        lazily dropped at the next pop)."""
        return sum(1 for _, _, r in list(self._heap)
                   if not r.done.is_set())

    def best_rank(self) -> Optional[int]:
        ranks = [e[0] for e in list(self._heap)
                 if not e[2].done.is_set()]
        return min(ranks) if ranks else None

    def requests(self) -> List[Any]:
        return [r for _, _, r in list(self._heap)]

    def drain(self) -> List[tuple]:
        ents, self._heap = self._heap, []
        return ents

    def depths(self) -> Dict[str, int]:
        """Live backlog per class (the queue-depth-by-class gauges)."""
        out = {c: 0 for c in PRIORITY_CLASSES}
        for _, _, r in list(self._heap):
            if not r.done.is_set():
                out[r.priority] += 1
        return out

    def parked(self) -> int:
        """Preempted requests waiting to resume (the fleet's scale-in
        victim filter reads this through /healthz)."""
        return sum(1 for _, _, r in list(self._heap)
                   if r.resume_ids is not None and not r.done.is_set())


def _priority_instruments():
    """Priority-scheduler metrics (ISSUE 17) — declared only when the
    scheduler exists AND observability records: ``bigdl.llm.priority.
    enabled`` off must leave no ``bigdl_llm_preemptions_total`` /
    ``bigdl_llm_queue_depth_class`` / ``bigdl_llm_preempt_parked``
    series (the disabled-mode absence contract)."""
    return {
        "preemptions": obs.counter(
            "bigdl_llm_preemptions_total",
            "In-flight decodes losslessly preempted for a higher "
            "SLO class, by the victim's class",
            labelnames=("class",)),
        "queue_class": obs.gauge(
            "bigdl_llm_queue_depth_class",
            "Scheduler backlog by SLO class (the fleet autoscaler's "
            "interactive-starvation signal)",
            labelnames=("class",)),
        "parked": obs.gauge(
            "bigdl_llm_preempt_parked",
            "Preempted requests parked for resume on this engine "
            "(scale-in must not drain the worker holding them)"),
    }


def _sync_barrier(*arrays):
    """Bound the in-flight computations producing ``arrays``.

    ``jax.block_until_ready`` alone is NOT reliable on every runtime
    (the axon-tunneled TPU runtime returns early from it); the only
    portable barrier is a real device-to-host fetch, so we pull one
    element of every array in a single tiny transfer. The pipelined
    engine (ISSUE 4) uses this only at ``pipeline_depth=1`` — its
    steady-state fence is the drain fetch of the step's own
    (tokens ‖ fence) vector, which delivers the data AND the barrier in
    one transfer (kernels.sampling.fence_token).
    """
    jax.block_until_ready(arrays)
    np.asarray(jnp.stack([a.ravel()[0].astype(jnp.float32)
                          for a in arrays]))


# compiled paged steps shared across LLMServer instances of the same
# model config (a fresh server must not recompile: the greedy-parity
# stress test spins up 8 servers under load, and each per-instance
# closure would retrace from scratch)
_PAGED_STEP_CACHE: Dict[tuple, Any] = {}


def paged_attend(k_pages, v_pages, bt, lens, *, page: int,
                 sliding_window: Optional[int] = None):
    """Shared paged-attention closure for every family's decode step.

    Owns the divergence-prone conventions in ONE place (review r5):
    the pools are viewed as one flat ``(L·P, H, page, D)`` page array
    (a ``pool[l]`` slice would copy 2·pool_bytes/L per layer), block
    tables are offset by ``l·P`` inside the layer scan (layer ``l``'s
    trash page is ``l·P``), the kernel sees lengths EXCLUDING the
    current token with the window shrunk by one, and the token's own
    K/V is folded in with the flash combine. Returns
    ``attend(l, q, k, v) -> (B, Hq, D)`` for head-shaped ``(B, 1, H*,
    D)`` current-token projections."""
    from bigdl_tpu.llm.kernels.paged_attention import (
        merge_attention_partial, paged_attention_stats)
    L_times_P = k_pages.shape[0] * k_pages.shape[1]
    num_pages = k_pages.shape[1]
    kp_flat = k_pages.reshape((L_times_P,) + k_pages.shape[2:])
    vp_flat = v_pages.reshape((L_times_P,) + v_pages.shape[2:])
    win_excl = (None if sliding_window is None
                else max(sliding_window - 1, 0))

    def attend(l, q, k, v):
        acc, m, lsum = paged_attention_stats(
            q[:, 0], kp_flat, vp_flat, bt + l * num_pages, lens,
            page_size=page, sliding_window=win_excl)
        return merge_attention_partial(acc, m, lsum, q[:, 0], k[:, 0],
                                       v[:, 0])

    return attend


def scatter_new_kv(k_pages, v_pages, bt, lens, k_new, v_new, *,
                   page: int):
    """ONE vectorized scatter of every layer's new-token K/V into the
    (donated) pools — shared by every family's decode step. ``k_new``/
    ``v_new`` are the layer-scan ys ``(L, B, Hkv, D)``; pools are
    ``(L, P, Hkv, page, D)`` (advanced indices on P/page with slices
    between put the broadcast (B,) dim first)."""
    b = lens.shape[0]
    pidx = lens // page
    slot = lens % page
    phys = bt[jnp.arange(b), pidx]                            # (B,)
    k_pages = k_pages.at[:, phys, :, slot].set(
        k_new.transpose(1, 0, 2, 3).astype(k_pages.dtype))
    v_pages = v_pages.at[:, phys, :, slot].set(
        v_new.transpose(1, 0, 2, 3).astype(v_pages.dtype))
    return k_pages, v_pages


def paged_decode_step(params, cfg, k_pages, v_pages, bt, lens, toks,
                      *, page: int):
    """One paged-KV decode step: next-token logits for every row plus
    the pools with each row's new K/V written at position ``lens``.

    Structure (round 5 — replaces the 32-layer python-unrolled graph,
    which compiled for >20 min at 7B and measured -18% vs a rolled scan
    per the int4_matmul.py ledger):

    - layers run in a **rolled ``lax.scan``** over the stacked weight
      pytree — the per-layer weight stream pipelines best this way;
    - the page pools stay **read-only inside the scan** (scan-invariant
      closures, never carried — a carried pool would be copied wholesale
      every token). Attention over the existing ``lens`` tokens comes
      from the stats kernel, and the current token's own K/V is folded
      in with the flash combine (`merge_attention_partial`) — exactly
      the write-then-attend math, without the write;
    - per-layer pools are addressed WITHOUT slicing (a `pool[l]` slice
      would copy 2×pool_bytes/L per layer): the pool is viewed as one
      flat ``(L·P, H, page, D)`` page array and block tables are offset
      by ``l·P`` inside the scan. Layer ``l``'s trash page is ``l·P``;
    - after the scan, ONE vectorized scatter writes all ``L`` layers'
      new-token K/V into the donated pools in place.

    ``params`` must be the stacked-layer llama pytree; ``bt`` (B, maxp)
    int32 block tables; ``lens`` (B,) int32 lengths EXCLUDING the token
    being decoded; ``toks`` (B,) int32. Returns
    ``(logits (B, V) f32, k_pages, v_pages)``. Callers jit this with
    ``donate_argnums`` on the pools.
    """
    from bigdl_tpu.llm.models.llama import (_linear, _moe_ffn,
                                            attention_qkv, mlp, rms_norm,
                                            rope_cfg)
    b = toks.shape[0]
    L = cfg.num_hidden_layers
    x = params["embed_tokens"][toks][:, None]                 # (B, 1, H)
    positions = lens[:, None].astype(jnp.int32)
    attend = paged_attend(k_pages, v_pages, bt, lens, page=page,
                          sliding_window=cfg.sliding_window)

    def layer_step(carry, inputs):
        x, = carry
        lp, l = inputs
        h = rms_norm(x, lp["input_layernorm"], cfg.rms_norm_eps)
        q, k, v = attention_qkv(lp, h, cfg)
        q = rope_cfg(q, positions, cfg)
        k = rope_cfg(k, positions, cfg)
        attn = attend(l, q, k, v).astype(x.dtype)
        x = x + _linear(lp["o_proj"], attn.reshape(b, 1, -1))
        h2 = rms_norm(x, lp["post_attention_layernorm"], cfg.rms_norm_eps)
        if cfg.num_experts:
            x = x + _moe_ffn(lp, h2, cfg)
        else:
            x = x + mlp(lp, h2, x.dtype)
        return (x,), (k[:, 0], v[:, 0])

    (x,), (k_new, v_new) = jax.lax.scan(
        layer_step, (x,), (params["layers"], jnp.arange(L)))
    x = rms_norm(x, params["norm"], cfg.rms_norm_eps)
    head = params.get("lm_head")
    if head is None:
        logits = x @ params["embed_tokens"].T.astype(x.dtype)
    else:
        logits = _linear(head, x)
    k_pages, v_pages = scatter_new_kv(k_pages, v_pages, bt, lens,
                                      k_new, v_new, page=page)
    return logits[:, 0].astype(jnp.float32), k_pages, v_pages


# pipelined-engine step shape for the llama family (ISSUE 4): greedy/
# temperature/top-k sampling folded into the compiled step, lens carried
# on device, fence element folded onto the token vector
paged_decode_step_sampled = make_sampled_step(paged_decode_step)


class Request:
    """Handle returned by :meth:`LLMServer.submit`."""

    def __init__(self, prompt_ids: np.ndarray, max_new_tokens: int,
                 priority: str = "standard"):
        self.id = str(uuid.uuid4())
        self.prompt_ids = np.asarray(prompt_ids, np.int32).ravel()
        self.max_new_tokens = max_new_tokens
        self.tokens: List[int] = []
        # SLO class (ISSUE 17): normalized on submit; plain metadata
        # unless the server's priority scheduler exists
        self.priority = priority
        # lossless-preemption state (ISSUE 17): after a preempt the
        # request re-queues journal-style as prompt + generated_so_far
        # (resume_ids) with its remaining budget; _hold_rec pins the
        # in-flight fence record whose drain must retire before the
        # request may re-admit (a same-slot re-admission before the old
        # step's fence drains would absorb that step's stale token)
        self.resume_ids: Optional[np.ndarray] = None
        self.preemptions = 0
        self._hold_rec: Optional[dict] = None
        self.error: Optional[str] = None
        self.done = threading.Event()
        # cooperative cancellation (ISSUE 7): set by LLMServer.abort
        # (hedge loser, client gone) or the watchdog (stalled engine) —
        # the engine finishes the slot at its next drain instead of
        # decoding tokens nobody will read
        self.cancel_requested = False
        # distributed tracing (ISSUE 3): the submitter's ambient context
        # rides the handle into the engine thread (contextvars don't
        # cross threads); None when no trace / observability disabled
        self.trace = rc.to_wire(rc.current())
        self.submitted_at = time.time() if self.trace else 0.0
        self.decode_started_at = 0.0
        # always-on TTFT accounting (ISSUE 5 microbench): submit stamp
        # here, first-token stamp at the engine's drain
        self.t_submit = time.perf_counter()
        self.t_first_token = 0.0
        # per-request SLO accounting (ISSUE 12, engine scope): last
        # token's drain stamp and the worst inter-token gap so far —
        # two floats, maintained only when the server's SLO account
        # exists
        self.t_last_token = 0.0
        self.itl_max = -1.0
        # per-token drain stamps (ISSUE 14 microbenches): appended only
        # when the SLO account exists — the exact fence-arrival clocks
        # the ITL sketches observe, so tools can compute per-request
        # gap percentiles without polling
        self.t_tokens: List[float] = []

    def get(self, timeout: Optional[float] = None) -> List[int]:
        if not self.done.wait(timeout):
            raise TimeoutError(f"request {self.id} still running")
        if self.error is not None:
            # the engine failed this request (e.g. its prefill raised):
            # surface it instead of returning an empty "success"
            raise RuntimeError(
                f"request {self.id} failed: {self.error}")
        return list(self.tokens)


class LLMServer:
    """Continuous-batching engine over a Llama-family model.

    ``model`` is a LlamaForCausalLM (quantized or dense). ``max_batch``
    fixes the compiled batch width; ``max_seq_len`` the per-request
    token bound.

    **Paged KV cache (default).** KV lives in a page pool
    ``(L, num_pages, H_kv, page_size, D)``; each request owns
    ``ceil(tokens/page)`` pages named by its block-table row, allocated
    as decode advances and freed the moment the request finishes — HBM
    held is proportional to tokens in flight, not
    ``max_batch × max_seq_len`` (VERDICT r3 missing #1; the reference's
    vLLM-integration lineage, SURVEY §2.8). Admission reserves a page
    *budget* for the request's worst case (prompt + max_new_tokens) so
    decode can never deadlock on an empty pool; physical pages are only
    taken when tokens actually land. Attention over the pool runs the
    Mosaic paged kernel on TPU (kernels/paged_attention.py) and its XLA
    gather twin elsewhere. Decode keeps the layers in a **python loop**
    (not lax.scan) over donated pools: page writes then compile to
    in-place scatters and page reads to views — a scanned pool would be
    copied wholesale every token.

    ``paged=False`` keeps the round-3 slot-static cache (one
    ``max_seq_len`` window per slot).

    **Pipelined dispatch (ISSUE 4).** Decode no longer round-trips to
    the host per token: sampling is folded into the compiled step (next
    ids are produced on device), block tables and lengths live device-
    resident with incremental scatter updates, and up to
    ``pipeline_depth`` steps (``bigdl.llm.pipeline_depth``, default 2)
    are dispatched before the oldest is drained — so admission, prefill
    scheduling and EOS bookkeeping run WHILE the device computes. Each
    in-flight record pins the (non-donated) buffers its step consumes
    until the drain fetch — a real device→host fetch of the step's
    fence — proves the step retired, preserving the round-4
    buffer-lifetime fix without a blocking barrier per token. Steps
    dispatched for a request that drains as finished are speculative;
    their tokens are discarded and their page use stays inside the
    request's admission budget (dispatches per request are capped at
    ``max_new_tokens``). ``pipeline_depth=1`` reproduces the
    synchronous engine exactly: every step drains (and every prefill
    barriers) before the next dispatch, and no buffer outlives its
    iteration. See docs/PERFORMANCE.md.

    **Prefix-aware KV cache (ISSUE 5, ``bigdl.llm.kvcache.enabled`` /
    ``kvcache=`` ctor arg; default off).** The page pool lives in the
    :mod:`bigdl_tpu.llm.kvcache` subsystem: pages are refcounted, a
    radix index keyed on page-size token chunks keeps finished (and
    live) requests' prompt chains warm, and admission looks up the
    longest cached prefix — the budget is charged only for the uncached
    suffix, prefill runs only over the suffix at a position offset, and
    a partially-matched tail page is copy-on-write forked into the
    request's own first page by the same fused scatter. EOS releases
    DECREMENT refcounts instead of freeing; index-only chains are
    LRU-evicted under pool pressure. Disabled, the manager degenerates
    to the old free-list (same allocation order, full-prompt budgets,
    no index, no extra metric series) — bit-identical to the
    pre-kvcache engine. See docs/KVCACHE.md.

    **Host spill tier (ISSUE 6, ``bigdl.llm.kvtier.enabled`` /
    ``kvtier=`` ctor arg; default off; requires the prefix cache).**
    Radix-evicted full-page chains spill to a pinned host-RAM arena
    instead of being dropped: eviction dispatches a per-page gather and
    a background migration thread pulls the bytes to the host, so the
    spill hides behind in-flight decode. An admission whose prefix is
    host-resident charges only the still-uncached suffix (plus one
    pre-charged pool page per fetched chunk), schedules an async
    host→HBM upload, and is PARKED — later requests admit and decode
    meanwhile; the landed pages then make it an ordinary prefix hit. A
    failed or timed-out fetch degrades to a plain cache miss (never a
    stall). The tier is also the door for disaggregated serving:
    :meth:`export_chain` / :meth:`import_chain` move a request's KV
    chain between a prefill-role and a decode-role worker as one
    serialized blob (see llm/worker.py's router). Disabled, no arena,
    no migration thread, no ``bigdl_kvtier_*`` series — bit-identical
    to the PR 5 engine. See docs/KVCACHE.md ("Host tier").

    **Unified mixed prefill+decode dispatch (ISSUE 14,
    ``bigdl.llm.mixed.enabled`` / ``mixed=`` ctor arg; default off;
    needs the ragged prefill).** The two dispatch paths merge: a
    prompt whose uncached suffix exceeds
    ``bigdl.llm.prefill.chunk_tokens`` (``chunk_tokens=``; 0 = 4
    pages) is fed in page-aligned chunks, each fused with the pass's
    decode rows into ONE compiled step (the family's
    ``paged_step_mixed`` — the sampled decode body and the ragged
    chunk body verbatim, so each leg stays bit-identical to the split
    program). A long admission therefore never stalls in-flight
    decodes for a whole prefill pass — the mixed-load microbench's
    stream p99 ITL no longer spikes at admission. Chunks charge the
    page ledger incrementally (final chunk tops up the decode budget;
    a chunk that cannot charge within ``bigdl.llm.prefill.chunk.wait``
    / ``chunk_wait=`` seconds sheds with a complete rollback and a
    retriable failure). Disabled: no chunk state, no
    ``bigdl_llm_pass_*``/``bigdl_llm_prefill_chunks_total`` series —
    the split engine exactly. See docs/PERFORMANCE.md ("Mixed
    prefill+decode dispatch").
    """

    def __init__(self, model, max_batch: int = 4, max_seq_len: int = 256,
                 eos_token_id: Optional[int] = None, paged: bool = True,
                 page_size: int = 16, num_pages: Optional[int] = None,
                 max_queue: int = 0,
                 pipeline_depth: Optional[int] = None,
                 temperature: float = 0.0, top_k: int = 0,
                 sample_seed: int = 0,
                 kvcache: Optional[bool] = None,
                 kvtier: Optional[bool] = None,
                 host_pages: Optional[int] = None,
                 watchdog_timeout: Optional[float] = None,
                 ragged_prefill: Optional[bool] = None,
                 slo: Optional[bool] = None,
                 mixed: Optional[bool] = None,
                 chunk_tokens: Optional[int] = None,
                 chunk_wait: Optional[float] = None,
                 priority: Optional[bool] = None,
                 spec: Optional[bool] = None,
                 spec_k: Optional[int] = None):
        import inspect

        from bigdl_tpu.llm.models.llama import forward, init_cache
        from bigdl_tpu.utils.conf import conf

        self.model = model
        self.cfg = model.config
        # family dispatch: Llama-stack models (incl. Mistral/Qwen2/GLM/
        # MoE) use the llama functions; CausalLMFacade families expose
        # _forward/_init_cache and their module's paged_decode_step
        # (gptneox, starcoder — bloom's ALiBi has no paged kernel hook
        # yet, so it stays generate()-only)
        fam_forward = getattr(type(model), "_forward", None)
        if fam_forward is None:
            from bigdl_tpu.llm.models import llama as _llama_mod
            self._fam_forward, self._fam_init_cache = forward, init_cache
            self._fam_paged_step = paged_decode_step
            self._fam_sampled_step = paged_decode_step_sampled
            self._fam_partial_prefill = _llama_mod.paged_prefill_partial
            self._fam_ragged_prefill = _llama_mod.paged_prefill_ragged
            self._fam_mixed_step = _llama_mod.paged_step_mixed
            self._fam_spec_step = _llama_mod.paged_step_spec
            self._family = "llama"
        else:
            self._fam_forward = fam_forward
            self._fam_init_cache = type(model)._init_cache
            fam_mod = inspect.getmodule(fam_forward)
            self._fam_paged_step = getattr(fam_mod, "paged_decode_step",
                                           None)
            self._fam_sampled_step = getattr(
                fam_mod, "paged_decode_step_sampled", None)
            if self._fam_sampled_step is None and \
                    self._fam_paged_step is not None:
                self._fam_sampled_step = make_sampled_step(
                    self._fam_paged_step)
            self._fam_partial_prefill = getattr(
                fam_mod, "paged_prefill_partial", None)
            self._fam_ragged_prefill = getattr(
                fam_mod, "paged_prefill_ragged", None)
            self._fam_mixed_step = getattr(
                fam_mod, "paged_step_mixed", None)
            self._fam_spec_step = getattr(
                fam_mod, "paged_step_spec", None)
            self._family = fam_mod.__name__.rsplit(".", 1)[-1]
            if paged and self._fam_paged_step is None:
                raise NotImplementedError(
                    f"{type(model).__name__} has no paged decode step "
                    "(ALiBi needs a kernel bias hook); use "
                    "generate() or another family")
            if not paged:
                raise NotImplementedError(
                    "the slot-static (paged=False) engine is Llama-stack "
                    "only; non-llama families serve through the paged "
                    "path")
        self.max_batch = max_batch
        self.max_seq_len = (min(max_seq_len, model.max_cache_len)
                            if not paged else
                            min(max_seq_len,
                                self.cfg.max_position_embeddings))
        self.eos_token_id = eos_token_id
        self.paged = paged
        # bounded admission (ISSUE 2): max_queue > 0 caps WAITING
        # requests; submit on a full queue raises OverloadError (the
        # worker's 503 + Retry-After shed) instead of growing forever
        self.max_queue = max_queue
        self._queue: "queue.Queue[Request]" = queue.Queue(
            maxsize=max_queue)
        self._draining = threading.Event()
        self._slots: List[Optional[Request]] = [None] * max_batch
        self._remaining = np.zeros(max_batch, np.int64)
        self._last = jnp.zeros((max_batch, self.cfg.vocab_size),
                               jnp.float32)
        self._stop = threading.Event()
        self._lock = threading.Lock()
        # pipelined dispatch (ISSUE 4): bounded window of dispatched-
        # but-undrained steps; each record pins the non-donated buffers
        # its step consumes until the drain fetch proves it retired
        depth = pipeline_depth if pipeline_depth is not None else \
            conf.get_int("bigdl.llm.pipeline_depth", 2)
        self.pipeline_depth = max(1, int(depth))
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self._do_sample = self.temperature > 0.0
        self._temp = jnp.float32(self.temperature if self._do_sample
                                 else 1.0)
        self._sample_key = jax.random.PRNGKey(sample_seed)
        self._inflight: "collections.deque" = collections.deque()
        # buffers consumed by eagerly-dispatched bookkeeping updates
        # (prefill scatters, freed-row resets): released at the NEXT
        # dispatched step's fence — those updates enqueue after the
        # already-in-flight steps, so only a later fence bounds them
        self._pending_release: List[Any] = []
        # always-on plain-python accounting (not metric series): the
        # host-vs-stall split tools/microbench_decode.py reads, plus the
        # prefill-token tally tools/microbench_prefix.py diffs cache
        # on/off (prefix reuse shows up as fewer prefilled tokens)
        self.host_seconds = 0.0
        self.stall_seconds = 0.0
        self.prefill_tokens_total = 0
        # tokens that round-tripped through a dense temp cache during
        # prefill (the ISSUE 8 staging cost: gathered prefix + slack +
        # suffix bucket). The ragged in-place path adds ZERO here —
        # tools/microbench_ragged.py asserts exactly that.
        self.prefill_dense_staged_tokens = 0
        # unified-dispatch accounting (ISSUE 14, always-on plain ints):
        # chunks dispatched and passes that fused decode rows with a
        # prefill chunk — tools/microbench_mixed.py and the parity
        # tests read these without observability
        self.prefill_chunks_total = 0
        self.mixed_passes = 0
        self._mixed_ins = None
        self._chunk_rr = 0
        self._spec_rr = 0
        # self-speculative decoding accounting (ISSUE 19, always-on
        # plain ints): draft tokens proposed/accepted, tokens emitted
        # by spec passes (accepted drafts + the bonus token) and the
        # verify-pass count — tools/microbench_decode.py computes
        # accepted-tokens-per-tick from these without observability
        self.spec_proposed_total = 0
        self.spec_accepted_total = 0
        self.spec_emitted_total = 0
        self.spec_passes = 0
        self._spec_ins = None
        # ISSUE 3 flight recorder: every jit entry point of the engine
        # is wrapped so compiles/recompiles (the per-length prefill
        # buckets, a batch-width drift on the decode step) are counted,
        # timed and HBM-attributed on /metrics
        self._fwd = obs.compiled(
            functools.partial(self._fam_forward, cfg=self.cfg),
            name="llm/forward")
        self._thread: Optional[threading.Thread] = None
        self.steps = 0
        self._ins = None     # declared lazily: see _instruments()
        # engine watchdog (ISSUE 7): a device step stalled past the
        # timeout flips /healthz to 503, aborts parked fetches and
        # fails pending requests retriably instead of hanging clients
        # forever. 0/None = structurally absent: no monitor thread, no
        # watchdog series, no healthz key.
        # per-request SLO accounting (ISSUE 12): TTFT/ITL quantile
        # sketches + threshold classification, engine scope. None (the
        # default) is structural absence — no sketch series, no
        # bigdl_slo_* series, no extra work in the drain.
        from bigdl_tpu.observability.slo import SLOAccount
        self._slo = SLOAccount.if_enabled("engine", enabled=slo)
        wd = (watchdog_timeout if watchdog_timeout is not None else
              conf.get_float("bigdl.llm.watchdog.step_timeout", 0.0))
        self.watchdog_timeout = float(wd or 0.0)
        self.watchdog_enabled = self.watchdog_timeout > 0.0
        self.watchdog_tripped = False
        self.watchdog_trips = 0
        self._hb = time.monotonic()
        self._watchdog_stop = threading.Event()
        self._watchdog_thread: Optional[threading.Thread] = None

        if paged:
            from bigdl_tpu.llm.kernels.paged_attention import LANE
            cfg = self.cfg
            if page_size <= 0 or LANE % page_size:
                raise ValueError(
                    f"page_size {page_size} must divide the kernel lane "
                    f"width {LANE} (8/16/32/64/128)")
            self._page = page_size
            ppb = LANE // page_size
            cap = -(-self.max_seq_len // page_size)
            self._pages_cap = -(-cap // ppb) * ppb    # kernel block mult
            # page 0 is the trash page: inactive rows and prefill padding
            # write there; no live sequence ever owns it
            self._num_pages = num_pages or (1 + max_batch * cap)
            shape = (cfg.num_hidden_layers, self._num_pages,
                     cfg.num_key_value_heads, page_size, cfg.head_dim)
            self._k_pages = jnp.zeros(shape, model.cache_dtype)
            self._v_pages = jnp.zeros(shape, model.cache_dtype)
            # the page pool now lives in the kvcache subsystem (ISSUE 5
            # tentpole): refcounted pages + admission budget; with the
            # prefix cache on, a radix index keeps finished requests'
            # chains warm for reuse. Disabled (the default) allocates
            # bit-identically to the embedded free-list it replaces.
            kv_on = (kvcache if kvcache is not None else
                     conf.get_bool("bigdl.llm.kvcache.enabled", False))
            if kv_on and self._fam_partial_prefill is None:
                raise NotImplementedError(
                    f"{type(model).__name__} has no partial-prefill "
                    "entry point; the prefix cache needs one per family")
            # ragged in-place prefill (ISSUE 8): prefill attends cached
            # prefix pages where they sit via the ragged kernel instead
            # of staging the context through a dense temp cache. The
            # default is "auto": ON where the Mosaic kernel runs (TPU),
            # dense elsewhere — under jit the XLA twin would gather the
            # full worst-case table per layer, which the dense paths
            # never did. true/false (conf or ctor) force a path; the
            # dense path also stays as the per-family fallback
            # (docs/PERFORMANCE.md "Ragged paged prefill")
            if ragged_prefill is not None:
                rag = bool(ragged_prefill)
            else:
                rag_conf = str(conf.get("bigdl.llm.prefill.ragged",
                                        "auto")).lower()
                if rag_conf == "auto":
                    import jax as _jax
                    rag = _jax.default_backend() == "tpu"
                else:
                    rag = conf.get_bool("bigdl.llm.prefill.ragged")
            self._ragged = rag and self._fam_ragged_prefill is not None
            # unified mixed prefill+decode dispatch (ISSUE 14): one
            # compiled step serves every active decode row PLUS one
            # page-aligned prefill chunk, so a long admission is fed in
            # chunk_tokens slices interleaved with decode instead of
            # monopolizing a pass. Chunking needs the ragged in-place
            # prefill (the chunk attends the prefix and its own earlier
            # chunks where they sit in the pool): under the dense
            # escape hatch (bigdl.llm.prefill.ragged=false) the gate is
            # inert and admissions prefill whole through the split
            # paths — documented + tested, see docs/PERFORMANCE.md.
            mx = (mixed if mixed is not None else
                  conf.get_bool("bigdl.llm.mixed.enabled", False))
            self._mixed = bool(mx)
            ct = (chunk_tokens if chunk_tokens is not None else
                  conf.get_int("bigdl.llm.prefill.chunk_tokens", 0))
            if ct <= 0:
                ct = 4 * page_size          # "a few pages" default
            self._chunk_tokens = max(
                page_size, -(-ct // page_size) * page_size)
            self._chunk_wait = (
                chunk_wait if chunk_wait is not None else
                conf.get_float("bigdl.llm.prefill.chunk.wait", 30.0))
            self._mixed_active = (self._mixed and self._ragged
                                  and self._fam_mixed_step is not None)
            # per-slot chunked-admission state (None entries = slot not
            # chunking); the list itself exists only when the unified
            # dispatch is live — bigdl.llm.mixed.enabled off keeps the
            # engine structurally identical to the split one
            self._chunk_state: Optional[List[Optional[dict]]] = (
                [None] * max_batch if self._mixed_active else None)
            # model-free self-speculative decoding (ISSUE 19): a pass
            # may carry one row's n-gram drafts as a verify chunk and
            # emit up to k+1 tokens for it (llm/spec.py + the family's
            # paged_step_spec). Needs the ragged in-place path (the
            # verify chunk IS a ragged chunk) and greedy sampling (the
            # accept rule is exact-match; the rejection-sampling hook
            # for temperature > 0 is gated off). Disabled (the
            # default) is structurally absent: no proposer state, no
            # bigdl_llm_spec_* series, no new code on the step path.
            sp = (spec if spec is not None else
                  conf.get_bool("bigdl.llm.spec.enabled", False))
            if sp and self._do_sample:
                raise ValueError(
                    "bigdl.llm.spec is greedy-only (temperature == 0): "
                    "the rejection-sampling verify hook for sampled "
                    "decode is gated off")
            self._spec_active = (bool(sp) and self._ragged
                                 and self._fam_spec_step is not None)
            self._spec_state: Optional[List[Optional[dict]]] = (
                [None] * max_batch if self._spec_active else None)
            # slots whose in-flight spec verify has not drained: their
            # host lens advance is data-dependent (accepted length), so
            # they sit out dispatch until the record retires
            self._spec_pending: set = set()
            if self._spec_active:
                from bigdl_tpu.llm.spec import NGramProposer
                self._spec_proposer_cls = NGramProposer
                self._spec_k = max(1, int(
                    spec_k if spec_k is not None else
                    conf.get_int("bigdl.llm.spec.k", 4)))
                self._spec_min_match = max(1, conf.get_int(
                    "bigdl.llm.spec.min_match", 2))
                self._spec_backoff = conf.get_float(
                    "bigdl.llm.spec.backoff", 0.5)
            self._kv = KVCacheManager(self._num_pages, page_size,
                                      enabled=bool(kv_on))
            # host spill tier (ISSUE 6): constructed ONLY when enabled —
            # disabled mode must be structurally absent (no arena, no
            # migration thread, no bigdl_kvtier_* series)
            tier_on = (kvtier if kvtier is not None else
                       conf.get_bool("bigdl.llm.kvtier.enabled", False))
            self._tier = None
            if tier_on:
                if not kv_on:
                    raise ValueError(
                        "bigdl.llm.kvtier extends the prefix cache: "
                        "enable bigdl.llm.kvcache too")
                from bigdl_tpu.llm.kvtier import KVTier
                hp = (host_pages if host_pages is not None else
                      conf.get_int("bigdl.llm.kvtier.host_pages", 0))
                self._tier = KVTier(
                    hp or 4 * self._num_pages, page_size,
                    synchronous=conf.get_bool(
                        "bigdl.llm.kvtier.sync", False),
                    fetch_timeout=conf.get_float(
                        "bigdl.llm.kvtier.fetch.timeout", 30.0))
                self._kv.attach_tier(self._tier,
                                     reader=self._read_page_kv,
                                     writer=self._write_pages_kv)
            # host-tier admissions parked while their pages upload, and
            # the landed ones waiting for a slot (engine thread only)
            self._fetch_wait: List[dict] = []
            self._fetch_ready: List[tuple] = []
            self._bt = np.zeros((max_batch, self._pages_cap), np.int32)
            self._lens = np.zeros(max_batch, np.int32)
            # device-resident twins (ISSUE 4): the step reads/advances
            # these on device; the host applies incremental scatters
            # (page grants, prefills, freed-row resets) instead of
            # re-uploading the whole tables every token. The np arrays
            # above remain the host's dispatch-time bookkeeping view.
            self._bt_dev = jnp.asarray(self._bt)
            self._lens_dev = jnp.asarray(self._lens)
            self._slot_pages: List[List[int]] = [[] for _ in
                                                 range(max_batch)]
            # per-slot cache grant (suffix budget charge + adopted
            # shared pages) — release decrements refcounts at EOS
            self._slot_adm: List[Optional[Any]] = [None] * max_batch
            # SLO-class priority scheduling + lossless preemption
            # (ISSUE 17): constructed ONLY when enabled — disabled mode
            # is structurally absent (no scheduler object, no parked-
            # blob map, no bigdl_llm_preemptions_total / class-gauge
            # series, admission stays FIFO off the intake queue)
            pr = (priority if priority is not None else
                  conf.get_bool("bigdl.llm.priority.enabled", False))
            self._sched = _PriorityScheduler() if pr else None
            # exported-on-preempt KV handoff blobs keyed by request id,
            # dropped at resume (the parked chain survives radix
            # eviction under pool pressure)
            self._parked: Optional[Dict[str, bytes]] = {} if pr else None
            # fence record of the most recent preemption: at most one
            # preemption per in-flight window (its pages free at this
            # fence — preempting again before it drains could not admit
            # the waiter anyway)
            self._preempt_rec: Optional[dict] = None
            self._pri_ins = None
            self.preemptions_total = 0
            self.preempt_resumes_total = 0
        else:
            if kvtier:
                raise ValueError("the host tier is page-pool only; "
                                 "the slot-static cache has no pages")
            if mixed:
                raise ValueError("unified mixed dispatch is page-pool "
                                 "only; the slot-static cache has no "
                                 "chunked prefill")
            if priority:
                raise ValueError("priority scheduling is page-pool "
                                 "only; lossless preemption needs the "
                                 "paged KV chain to park and resume")
            if spec:
                raise ValueError("self-speculative decoding is "
                                 "page-pool only; the verify chunk is "
                                 "a ragged chunk over pool pages")
            self._spec_active = False
            self._spec_state = None
            self._spec_pending = set()
            self._sched = None
            self._parked = None
            self._preempt_rec = None
            self._pri_ins = None
            self.preemptions_total = 0
            self.preempt_resumes_total = 0
            self._mixed = self._mixed_active = False
            self._chunk_state = None
            self._kv = None       # the slot-static cache has no pages
            self._tier = None
            self._fetch_wait, self._fetch_ready = [], []
            self._cache = init_cache(self.cfg, max_batch, self.max_seq_len,
                                     dtype=model.cache_dtype)
            # per-slot write positions (the shared scalar cache["pos"] is
            # replaced by a vector so slots advance independently); the
            # device twin advances inside the compiled step (ISSUE 4)
            self._pos = np.zeros(max_batch, np.int32)
            self._pos_dev = jnp.asarray(self._pos)

    @property
    def pages_in_use(self) -> int:
        """Physical pages currently owned by live requests (the
        proportional-HBM claim, testable) — including the partial
        chains of chunked admissions still mid-prompt (ISSUE 14)."""
        if not self.paged:
            return -1
        n = sum(len(p) for p in self._slot_pages)
        if self._chunk_state is not None:
            n += sum(len(st["own"]) for st in self._chunk_state
                     if st is not None)
        return n

    # the pool moved into the kvcache subsystem (ISSUE 5); these views
    # keep the embedded-pool names the tests and tools read
    @property
    def _free(self) -> List[int]:
        return self._kv.pool.free_ids()

    @property
    def _budget_avail(self) -> int:
        return self._kv.budget_avail

    @property
    def prefix_tokens_saved(self) -> int:
        """Prompt tokens served from the prefix cache instead of being
        prefilled (always-on; 0 with the cache disabled)."""
        return self._kv.prefix_tokens_reused if self._kv else 0

    # -- client API ----------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int = 32,
               priority: Optional[str] = None) -> Request:
        reliability.inject("llm.submit")
        if max_new_tokens < 1:
            # a zero-budget request would occupy a slot with no step
            # ever dispatched for it (dispatches are capped at
            # max_new_tokens) — reject instead of wedging the slot
            raise ValueError("max_new_tokens must be >= 1")
        req = Request(prompt_ids, max_new_tokens,
                      priority=normalize_priority(priority))
        if len(req.prompt_ids) + max_new_tokens > self.max_seq_len:
            raise ValueError("prompt + max_new_tokens exceeds max_seq_len")
        pages = None
        if self.paged:
            # post-lookup suffix cost (ISSUE 5 satellite): a request
            # whose prefix is cached is charged only for the uncached
            # suffix, so feasibility and the shed diagnostics below
            # must be judged on that cost, not the full prompt
            pages = self._kv.peek(req.prompt_ids, req.max_new_tokens)
            if pages["pages_needed"] > self._num_pages - 1:
                raise ValueError(
                    f"request needs {pages['pages_needed']} pages "
                    f"(uncached suffix of prompt + max_new_tokens) but "
                    f"the pool holds {self._num_pages - 1}; it could "
                    "never be admitted")
        if self._draining.is_set():
            reliability.count_shed("llm_server", request_id=req.id,
                                   trace_id=_trace_of(req),
                                   reason="draining")
            err = reliability.OverloadError(
                "server is draining: not accepting new requests")
            # structured marker (ISSUE 15): the worker's 503 body
            # carries {"draining": true} so the router's drain bounce
            # keys on a field, not on the message wording
            err.draining = True
            raise err
        if self.watchdog_enabled and self.watchdog_tripped \
                and time.monotonic() - self._hb > self.watchdog_timeout:
            # the engine is wedged mid-pass RIGHT NOW (tripped flag AND
            # a currently-stale heartbeat — the flag alone lags
            # recovery by up to one monitor tick): anything queued
            # would just hang behind the stalled step until the stream
            # wait times out. Fail fast with the same retriable verdict
            # the trip sweep gives — the stream's terminal chunk
            # carries error+retriable, so a failover router resumes
            # elsewhere (and the prober is already draining us).
            self._watchdog_fail(req, self._watchdog_msg())
            return req
        try:
            # with the priority scheduler the engine drains the intake
            # queue into its heap every pass, so the Queue's own maxsize
            # alone would never fire: bound intake + scheduler backlog
            # together to keep ISSUE 2's backpressure contract
            if self._sched is not None and self.max_queue and \
                    self._queue.qsize() + len(self._sched) >= \
                    self.max_queue:
                raise queue.Full
            self._queue.put_nowait(req)
        except queue.Full:
            # the 503 carries the page accounting (post-lookup suffix
            # cost vs budget actually free) so clients and the shed
            # counter can tell queue pressure from page pressure
            shed_detail = dict(
                request_id=req.id, trace_id=_trace_of(req),
                queue_depth=self._queue.qsize(),
                pages_needed=pages["pages_needed"] if pages else None,
                pages_free=pages["pages_free"] if pages else None)
            if pages is not None and \
                    pages["pages_needed"] > pages["pages_free"]:
                reliability.count_shed("llm_server_pages",
                                       reason="page_pressure",
                                       **shed_detail)
            else:
                reliability.count_shed("llm_server",
                                       reason="queue_full", **shed_detail)
            msg = (f"request queue full ({self.max_queue} waiting); "
                   "retry later")
            if pages is not None:
                msg += (f" [needs {pages['pages_needed']} pages for the "
                        f"uncached suffix, {pages['pages_free']} "
                        "budget-free]")
            err = reliability.OverloadError(msg)
            if pages is not None:
                err.pages_needed = pages["pages_needed"]
                err.pages_free = pages["pages_free"]
            raise err from None
        if flight.enabled:
            flight.record(
                "queue", request_id=req.id, trace_id=_trace_of(req),
                prompt_tokens=len(req.prompt_ids),
                max_new_tokens=req.max_new_tokens,
                queue_depth=self._queue.qsize(),
                pages_needed=pages["pages_needed"] if pages else None,
                pages_free=pages["pages_free"] if pages else None)
        return req

    def retry_depth(self, priority: Optional[str] = None) -> float:
        """Queue depth for Retry-After derivation (ISSUE 17 satellite).
        Scheduler off: the plain intake depth, bit-identical to HEAD.
        Scheduler on: intake + class-ordered backlog, weighted by the
        shedded request's class so batch clients back off harder than
        interactive ones under the SAME backlog (float — the caller's
        ``reliability.retry_after_seconds`` truncates)."""
        depth = self._queue.qsize()
        if self._sched is None:
            return depth
        return ((depth + len(self._sched))
                * CLASS_RETRY_WEIGHTS[normalize_priority(priority)])

    def class_depths(self) -> Optional[Dict[str, int]]:
        """Live scheduler backlog per SLO class; None when the priority
        scheduler is off (callers emit no class keys at all)."""
        return self._sched.depths() if self._sched is not None else None

    @property
    def preempt_parked(self) -> int:
        """Preempted requests parked for resume on this engine (0 when
        the scheduler is off — the fleet's scale-in filter is inert)."""
        return self._sched.parked() if self._sched is not None else 0

    def export_chain(self, tokens) -> bytes:
        """Serialize the cached FULL pages of ``tokens`` into a handoff
        blob (ISSUE 6 disaggregation: the prefill-role side). Device
        pages are pulled under the engine lock — eviction cannot run
        concurrently, and the blocking fetch doubles as the dispatch
        fence; host-resident chunks are read straight from the arena.
        Pages already evicted from both tiers are simply absent: the
        importer's decode worker re-prefills whatever is missing."""
        if self._tier is None:
            raise RuntimeError(
                "KV handoff needs bigdl.llm.kvtier.enabled")
        with self._lock:
            return self._export_chain_locked(tokens)

    def _export_chain_locked(self, tokens) -> bytes:
        """Export body, caller holds ``self._lock`` (the lock is NOT
        reentrant — the engine thread's preempt path at _preempt_slot
        already holds it and must call this directly)."""
        from bigdl_tpu.llm.kvtier.handoff import serialize_chain
        dev, host = self._kv.chain_locations(tokens)
        k_pages = [np.asarray(self._k_pages[:, pid]) for pid in dev]
        v_pages = [np.asarray(self._v_pages[:, pid]) for pid in dev]
        for key, slot in host:
            # keyed copy-read: a concurrent import can LRU-re-key
            # the slot between lookup and here — a mismatch
            # truncates the export (contiguity ends at the first
            # missing chunk) instead of shipping wrong bytes
            pages = self._tier.arena.read_keyed(slot, key)
            if pages is None:
                break
            k_pages.append(pages[0])
            v_pages.append(pages[1])
        blob = serialize_chain(
            np.asarray(tokens, np.int64)[:len(k_pages) * self._page],
            k_pages, v_pages, self._page)
        self._tier.count_handoff("export", len(blob))
        return blob

    def import_chain(self, blob: bytes) -> int:
        """Land a handoff blob's pages in the HOST ARENA (the
        decode-role side). Control-plane only — no engine lock, no
        device writes: the next admission of this prompt hits the host
        tier and the ordinary async fetch uploads the pages behind
        in-flight decode. Returns the number of pages imported."""
        from bigdl_tpu.llm.kvtier.handoff import (HandoffError,
                                                  deserialize_chain)
        if self._tier is None:
            raise RuntimeError(
                "KV handoff needs bigdl.llm.kvtier.enabled")
        toks, k_pages, v_pages, header = deserialize_chain(blob)
        if not k_pages:
            return 0
        cfg = self.cfg
        want_shape = (cfg.num_hidden_layers, cfg.num_key_value_heads,
                      self._page, cfg.head_dim)
        want_dtype = str(jnp.dtype(self.model.cache_dtype))
        if int(header["page_size"]) != self._page or \
                tuple(header["shape"]) != want_shape or \
                header["dtype"] != want_dtype:
            raise HandoffError(
                f"handoff pages {header['shape']}/{header['dtype']}"
                f"/page={header['page_size']} do not fit this pool "
                f"{want_shape}/{want_dtype}/page={self._page}")
        arena = self._tier.arena
        n = 0
        for j in range(len(k_pages)):
            key = tuple(toks[:(j + 1) * self._page])
            slot = arena.reserve(key)
            if slot is None:
                break              # arena saturated: partial import
            arena.commit(slot, k_pages[j], v_pages[j])
            n += 1
        self._tier.count_handoff("import", len(blob))
        return n

    # -- graceful drain (ISSUE 15) -------------------------------------------
    def begin_drain(self):
        """Flip to DRAINING without stopping: new submits shed with 503
        ``"server is draining"`` (the router's drain bounce re-routes
        them), ``/healthz`` reports ``"draining"``, and the engine keeps
        decoding every already-accepted request to completion. The
        fleet drain coordinator calls this, waits for
        :meth:`engine_idle`, migrates :meth:`warm_chains`, then the
        worker exits — see bigdl_tpu/llm/fleet.py."""
        self._draining.set()

    def cancel_drain(self):
        """Abandon a drain (scale-in cancelled): the engine accepts
        work again. A no-op on a server that was never draining."""
        self._draining.clear()

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def engine_idle(self) -> bool:
        """True when no accepted request remains anywhere: queue,
        held head, fetch-parked, or in a slot (chunked admissions hold
        their slot, so they are covered). The drain coordinator polls
        this; ``stop(drain=True)`` uses the same condition inline."""
        with self._lock:
            return (self._queue.empty()
                    and getattr(self, "_pending_head", None) is None
                    and (self._sched is None or self._sched.live() == 0)
                    and not self._fetch_wait
                    and not self._fetch_ready
                    and all(r is None for r in self._slots))

    def warm_chains(self) -> List[List[int]]:
        """Token chains currently warm in this engine's caches — the
        radix index's leaf paths (truncated to full pages: tails
        re-prefill by the handoff contract) plus host-arena entries —
        deduplicated so only maximal chains remain (exporting a chain
        ships every prefix page with it). The drain coordinator
        migrates exactly these via :meth:`export_chain`. Empty when the
        prefix cache is off (nothing is warm by construction)."""
        if not self.paged or self._kv is None or not self._kv.enabled:
            return []
        page = self._page
        chains: Dict[tuple, None] = {}
        with self._lock:
            for path in self._kv.index.leaf_paths():
                full = (len(path) // page) * page
                if full:
                    chains[tuple(path[:full])] = None
            if self._tier is not None:
                for key in self._tier.arena.keys():
                    chains[tuple(key)] = None
        keep: List[tuple] = []
        for c in sorted(chains, key=len, reverse=True):
            if not any(k[:len(c)] == c for k in keep):
                keep.append(c)
        return [list(c) for c in keep]

    def abort(self, req: Request, reason: str = "aborted by caller"):
        """Cooperatively cancel an accepted request (ISSUE 7): the
        hedge loser whose client hung up, or a request nobody will
        read. Thread-safe flag-only — the engine thread finishes the
        slot (releasing its pages through the normal refcounted path)
        at its next drain, and admission skips it if it was still
        queued or fetch-parked."""
        req.cancel_requested = True
        if not req.done.is_set():
            req.error = req.error or f"request aborted: {reason}"
            req.done.set()
        # no metric here: the engine counts the reaped slot as
        # requests{reason="cancelled"} at its next drain — an inc on
        # both sides would double-count every hedge loser

    # -- watchdog (ISSUE 7) --------------------------------------------------
    def _watchdog_loop(self):
        """Step-deadline monitor. The engine loop refreshes ``_hb`` at
        the top of every pass (an idle loop spins every ~2 ms), so a
        stale heartbeat means the engine thread is wedged INSIDE a pass
        — a hung device step, a stuck fetch. Trip: mark unhealthy (the
        worker's /healthz answers 503 and the router's prober drains
        us), abort parked fetches, fail every pending request with a
        retriable error. Recovery: the heartbeat resuming clears the
        tripped flag, and /healthz flips back so the prober re-admits
        this worker.

        An XLA compile is indistinguishable from a hung step from the
        host side, so ``step_timeout`` must sit ABOVE the worst-case
        compile for the served shapes (or the engine warmed first) —
        a cold-start compile longer than the timeout trips exactly
        like a wedged device. The failed requests are retriable
        either way; the cost of a false trip is a failover, not a
        lost answer."""
        interval = min(max(self.watchdog_timeout / 4.0, 0.01), 0.25)
        while not self._watchdog_stop.wait(interval):
            age = time.monotonic() - self._hb
            if age <= self.watchdog_timeout:
                if self.watchdog_tripped:
                    self.watchdog_tripped = False   # engine recovered
                continue
            if self.watchdog_tripped:
                # still wedged: keep sweeping — a request that raced
                # past the submit() gate into the queue after the trip
                # sweep must not hang behind the stalled pass (trip
                # counters fire once per episode, the sweep every tick)
                self._watchdog_sweep(self._watchdog_msg())
                continue
            self._watchdog_trip(age)

    def _watchdog_msg(self) -> str:
        return (f"engine stalled: step exceeded the "
                f"{self.watchdog_timeout:g}s watchdog timeout "
                "(retriable: resubmit to another backend)")

    def _watchdog_trip(self, age: float):
        self.watchdog_tripped = True
        self.watchdog_trips += 1
        failed = self._watchdog_sweep(self._watchdog_msg())
        if obs.enabled():
            obs.counter(
                "bigdl_llm_watchdog_trips_total",
                "Engine stalls detected by the step-deadline "
                "watchdog").inc()
            obs.add_complete("llm/watchdog_trip", time.time() - age, age,
                             stage="llm_server", failed_requests=failed,
                             timeout_s=self.watchdog_timeout)

    def _watchdog_sweep(self, msg: str) -> int:
        failed = 0
        # the engine thread is wedged (possibly holding _lock), so only
        # thread-safe surfaces are touched: the queue, Request handles,
        # and migration-job cancel flags. Page/budget bookkeeping stays
        # with the engine thread — it cleans up when (if) it wakes.
        try:
            while True:
                failed += self._watchdog_fail(self._queue.get_nowait(),
                                              msg)
        except queue.Empty:
            pass
        head = getattr(self, "_pending_head", None)
        if head is not None:
            failed += self._watchdog_fail(head, msg)
        sched = getattr(self, "_sched", None)
        if sched is not None:
            # flag-only, same contract as the queue drain above: the
            # heap itself belongs to the engine thread, which drops
            # done entries at its next pop (if it ever wakes)
            for req in sched.requests():
                failed += self._watchdog_fail(req, msg)
        for req in list(self._slots):
            if req is not None:
                failed += self._watchdog_fail(req, msg)
        for ent in list(self._fetch_wait):
            failed += self._watchdog_fail(ent["req"], msg)
            if self._tier is not None:
                self._tier.cancel_fetch(ent["adm"].fetch_job)
        for req, _adm in list(self._fetch_ready):
            failed += self._watchdog_fail(req, msg)
        return failed

    @staticmethod
    def _watchdog_fail(req: Request, msg: str) -> int:
        req.cancel_requested = True
        if req.done.is_set():
            return 0
        req.error = msg
        req.done.set()
        return 1

    def start(self) -> "LLMServer":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        if self.watchdog_enabled:
            self._hb = time.monotonic()
            self._watchdog_thread = threading.Thread(
                target=self._watchdog_loop, name="bigdl-llm-watchdog",
                daemon=True)
            self._watchdog_thread.start()
        # time-series plane (ISSUE 18): the engine-side refcount on the
        # sampler, so store-backed SLO burn windows work in processes
        # with no HTTP surface. No-op (builds nothing) when the gate is
        # off.
        from bigdl_tpu.observability import timeseries
        self._timeseries = timeseries.acquire()
        return self

    def stop(self, drain: bool = True, timeout: float = 30.0):
        """Graceful drain (default): reject new submits, finish every
        accepted request (queued AND in-slot), then stop the engine
        thread. ``drain=False`` is the old immediate stop — accepted
        requests never complete."""
        self._draining.set()
        if drain and self._thread is not None and self._thread.is_alive():
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                with self._lock:
                    idle = (self._queue.empty()
                            and getattr(self, "_pending_head", None) is None
                            and (self._sched is None
                                 or self._sched.live() == 0)
                            and not self._fetch_wait
                            and not self._fetch_ready
                            and all(r is None for r in self._slots))
                if idle:
                    break
                time.sleep(0.005)
        self._stop.set()
        if self._watchdog_thread is not None:
            self._watchdog_stop.set()
            self._watchdog_thread.join(timeout=5)
        if getattr(self, "_timeseries", None) is not None:
            from bigdl_tpu.observability import timeseries
            timeseries.release()
            self._timeseries = None
        if self._thread:
            self._thread.join(timeout=30)
        if self._thread is not None and self._thread.is_alive():
            # join timed out: the engine thread is wedged but still owns
            # the window — touching the deque here would race it
            return
        # resolve any still-in-flight dispatches (stop(drain=False)
        # abandons their tokens by contract; with drain=True the loop
        # idles only once every request finished, so leftovers here are
        # purely speculative) — the fence fetch guarantees no pinned
        # buffer is dropped while a computation still reads it
        while self._inflight:
            rec = self._inflight.popleft()
            try:
                np.asarray(rec["out"])
            except Exception:   # a dead device can't hold references
                pass
            for args in rec.pop("kv_release", ()):
                self._kv.release_slot(*args)
        # fetch-parked admissions hold budget but no slot: with
        # drain=True the loop already landed them all (the idle check
        # above includes both lists), so anything left here is a
        # drain=False abandonment — return the grants, unblock clients
        for ent in self._fetch_wait:
            self._kv.cancel(ent["adm"])
            ent["req"].error = "server stopped before its KV fetch landed"
            ent["req"].done.set()
        self._fetch_wait = []
        for req, adm in self._fetch_ready:
            self._kv.cancel(adm)
            req.error = "server stopped before the request took a slot"
            req.done.set()
        self._fetch_ready = []
        if self._sched is not None:
            # scheduler entries hold no budget (budget-blocked heads
            # re-park WITHOUT an admission grant) — flag-only cleanup
            for _, _, req in self._sched.drain():
                if not req.done.is_set():
                    req.error = ("server stopped before the request "
                                 "took a slot")
                    req.done.set()
        if self._tier is not None:
            self._tier.close()
        if self._pending_release:
            # bookkeeping scatters enqueued AFTER the newest step have
            # no later fence — bound them via their own outputs (the
            # current device tables data-depend on every such update)
            # before the pinned references drop
            try:
                if self.paged:
                    _sync_barrier(self._k_pages, self._v_pages,
                                  self._bt_dev, self._lens_dev,
                                  self._last)
                else:
                    _sync_barrier(self._cache["k"], self._cache["v"],
                                  self._pos_dev, self._last)
            except Exception:
                pass
            self._pending_release.clear()

    # -- engine --------------------------------------------------------------
    def _pin(self, *arrays):
        """Keep references to buffers consumed by an in-flight dispatch
        until a later step's fence resolves (the round-4 race: a
        released buffer can be recycled for concurrent jax work while
        the enqueued computation still reads it)."""
        self._pending_release.extend(arrays)
    def _read_page_kv(self, pid: int):
        """Spill-side gather (ISSUE 6): one page's K/V as standalone
        device arrays. Engine thread only — the gather is dispatched
        before any later dispatch can reissue and overwrite the page
        id, so engine-thread program order is the lifetime argument
        (the same one the partial prefill's tail gather relies on)."""
        return self._k_pages[:, pid], self._v_pages[:, pid]

    def _write_pages_kv(self, pids, k_devs, v_devs):
        """Fetch-side scatter (ISSUE 6): land uploaded host-tier pages
        in the pool. Incremental — same pin/barrier contract as the
        prefill scatters."""
        idx = jnp.asarray(np.asarray(pids, np.int32))
        k_new = jnp.stack(k_devs, axis=1).astype(self._k_pages.dtype)
        v_new = jnp.stack(v_devs, axis=1).astype(self._v_pages.dtype)
        self._pin(self._k_pages, self._v_pages, k_new, v_new, idx)
        self._k_pages = self._k_pages.at[:, idx].set(k_new)
        self._v_pages = self._v_pages.at[:, idx].set(v_new)
        if self.pipeline_depth == 1:
            _sync_barrier(self._k_pages, self._v_pages)
            self._pending_release.clear()

    def _poll_fetches(self):
        """Land completed host-tier fetches (ISSUE 6): a finished
        upload is scattered into the pool (the admission then looks
        exactly like a device prefix hit); a failed, cancelled or
        timed-out one degrades to a plain cache miss. An injected
        ``kvcache.evict`` raise during materialization leaves the entry
        parked — the resilient engine loop retries the pass."""
        timeout = self._tier.fetch_timeout
        k = 0
        while k < len(self._fetch_wait):
            ent = self._fetch_wait[k]
            req, adm = ent["req"], ent["adm"]
            job = adm.fetch_job
            done = job is None or job.done.is_set()
            if not done and time.perf_counter() - ent["t0"] <= timeout:
                k += 1
                continue
            landed = (done and job is not None and job.ok
                      and not job.cancelled)
            if landed:
                self._kv.materialize(adm, job.k_dev, job.v_dev)
            else:
                self._kv.degrade(adm)   # failure/timeout → plain miss
            del self._fetch_wait[k]
            wait_s = time.perf_counter() - ent["t0"]
            if flight.enabled:
                flight.record(
                    "fetch", request_id=req.id, trace_id=_trace_of(req),
                    pages=len(adm.shared_pages),
                    wait_ms=round(wait_s * 1000.0, 3),
                    status="landed" if landed else "degraded")
            if req.trace:
                obs.add_complete(
                    "kvtier/fetch_wait", time.time() - wait_s, wait_s,
                    trace=req.trace["trace_id"], request=req.id,
                    pages=len(adm.shared_pages),
                    degraded=adm.matched_len == adm.device_matched
                    and job is not None and not job.ok)
            self._fetch_ready.append((req, adm))

    def _prompt_of(self, req: Request) -> np.ndarray:
        """The token ids admission/prefill must process: the original
        prompt, or prompt + generated_so_far after a preemption
        (ISSUE 17 journal-style resume — greedy decode over the
        extended prompt is deterministic, so the continuation is
        bit-identical to the unpreempted run)."""
        return (req.resume_ids if req.resume_ids is not None
                else req.prompt_ids)

    def _budget_of(self, req: Request) -> int:
        """Decode budget still owed: ``max_new_tokens`` minus tokens
        already drained to the handle before a preemption."""
        return req.max_new_tokens - len(req.tokens)

    def _sched_pop(self) -> Optional[tuple]:
        """Pop the best live, unheld scheduler entry. Done handles are
        dropped; held entries (preempted requests whose old fence
        record has not drained yet — re-admitting one early could
        absorb that step's stale speculative token) are skipped and
        re-parked with their original order."""
        held: List[tuple] = []
        out = None
        while True:
            ent = self._sched.pop_entry()
            if ent is None:
                break
            req = ent[2]
            if req.done.is_set():
                continue           # aborted/failed while queued
            rec = req._hold_rec
            if rec is not None:
                if any(r is rec for r in self._inflight):
                    held.append(ent)
                    continue
                req._hold_rec = None
            out = ent
            break
        for h in held:
            self._sched.push_entry(h)
        return out

    def _admit(self):
        """Fill free slots from the queue; per-slot prefill. Paged mode
        additionally requires the request's worst-case page budget
        (prompt + max_new, the conservative vLLM-style reservation) to be
        available — head-of-line: if the next request doesn't fit, no
        later one is admitted either. Host-tier hits (ISSUE 6) are
        PARKED while their pages upload — they hold their budget but no
        slot, so later requests admit and decode meanwhile; completed
        fetches re-enter here first."""
        if self._fetch_wait:
            self._poll_fetches()
        if self._sched is not None:
            # class-ordered admission (ISSUE 17): drain the thread-safe
            # intake queue into the scheduler heap, then admit in
            # (class rank, arrival) order. The heap is engine-thread
            # only; submit() bounds intake + heap together.
            try:
                while True:
                    self._sched.push(self._queue.get_nowait())
            except queue.Empty:
                pass
        for i in range(self.max_batch):
            if self._slots[i] is not None:
                continue
            if not self._admit_into(i):
                break
        if self._sched is not None and self._sched.live():
            # waiters remain after the sweep (no slot, or the best one
            # is budget-blocked): lossless preemption of a lower-class
            # decode is the relief valve
            self._consider_preempt()

    def _admit_into(self, i: int) -> bool:
        """Admit one request into free slot ``i``. False stops the slot
        sweep: queue exhausted, or the head is budget-blocked
        (head-of-line holds)."""
        while True:
            if self._fetch_ready:
                req, adm = self._fetch_ready[0]
                if req.done.is_set():
                    # aborted / watchdog-failed while fetch-parked: the
                    # grant goes back, nobody decodes for a dead handle
                    self._fetch_ready.pop(0)
                    self._kv.cancel(adm)
                    continue
                # physical headroom for the pages prefill will own,
                # ensured HERE (not at the poll): the entry ahead in
                # this very pass may have consumed what the poll saw
                # free. Peek-then-pop so an injected kvcache.evict
                # raise leaves the entry for the loop's retry.
                own = (-(-len(self._prompt_of(req)) // self._page)
                       - adm.matched_len // self._page)
                if own > 0:
                    self._kv.ensure_free(own)
                self._fetch_ready.pop(0)
                self._slot_adm[i] = adm
                # a landed fetch is indistinguishable from a device
                # prefix hit: a still-long suffix chunks like any
                # other, but its budget was fully charged at admit
                # (the fetch pre-charge contract) — prepaid
                self._prefill_admitted(
                    i, req, adm,
                    chunked=(self._mixed_active
                             and len(self._prompt_of(req))
                             - adm.matched_len > self._chunk_tokens),
                    prepaid=True)
                return True
            ent = None
            if self._sched is not None:
                # class-ordered source (ISSUE 17): the heap replaces
                # both the FIFO queue and the held head — a budget-
                # blocked best entry re-parks below with its ORIGINAL
                # order, so head-of-line becomes head-of-class
                ent = self._sched_pop()
                if ent is None:
                    return False
                req = ent[2]
            else:
                # a budget-blocked head is HELD here (not re-queued:
                # put() appends, and clients submit concurrently, so
                # drain-and-requeue would let a late submit overtake
                # the whole waiting line)
                req = getattr(self, "_pending_head", None)
                if req is None:
                    try:
                        req = self._queue.get_nowait()
                    except queue.Empty:
                        return False
                self._pending_head = None
                if req.done.is_set():
                    # aborted (or watchdog-failed) while queued: skip —
                    # nothing was charged for it yet
                    continue
            ids = self._prompt_of(req)
            budget = self._budget_of(req)
            adm = None
            chunked = False
            if self.paged:
                t_lk = time.perf_counter()
                chunk_first = None
                if self._mixed_active and \
                        len(ids) > self._chunk_tokens:
                    # chunked-admission decision (ISSUE 14): a long
                    # uncached DEVICE suffix is fed in page-aligned
                    # chunks, charging only the first chunk now.
                    # Arena-extending matches keep the unchunked fetch
                    # path (their budget pre-charges at admit); the
                    # peek→admit window is race-free — the engine
                    # thread is the only index mutator. Prompts at or
                    # under chunk_tokens skip the peek outright (no
                    # second radix walk on the short-prompt hot path).
                    pk = self._kv.peek(ids, budget)
                    if pk["matched_tokens"] == pk["matched_device"] \
                            and pk["pages_needed"] <= \
                            self._num_pages - 1:
                        # the pool-size guard keeps never-admittable
                        # requests (cached prefix evicted since
                        # submit) on the unchunked path, where admit
                        # returns None and the permanent-failure
                        # check below fires — a chunked admit would
                        # loop charge→starve→"retriable" shed forever
                        off0 = pk["matched_device"]
                        suffix = len(ids) - off0
                        if suffix > self._chunk_tokens:
                            end0 = self._chunk_end(
                                off0, len(ids))
                            chunk_first = (-(-end0 // self._page)
                                           - off0 // self._page)
                try:
                    # lookup + suffix-only budget charge + adoption refs
                    # + pre-eviction for the prompt's own pages, in one
                    # atomic manager call (ISSUE 5); chunked admissions
                    # charge the first chunk only (ISSUE 14)
                    adm = self._kv.admit(ids, budget,
                                         chunk_pages=chunk_first)
                    chunked = chunk_first is not None
                except BaseException:
                    # injected kvcache.evict fault: nothing was charged
                    # or adopted — hold the head (or re-park the heap
                    # entry in place), let the loop retry
                    if ent is not None:
                        self._sched.push_entry(ent)
                    else:
                        self._pending_head = req
                    raise
                if adm is None:
                    peek = self._kv.peek(ids, budget)
                    if peek["pages_needed"] > self._num_pages - 1:
                        # the cached prefix that made this request
                        # feasible at submit time has been evicted: it
                        # can never be admitted now — fail it instead
                        # of wedging the whole admission line
                        req.error = (
                            f"request needs {peek['pages_needed']} "
                            f"pages but the pool holds "
                            f"{self._num_pages - 1} (cached prefix "
                            "evicted since submit)")
                        req.done.set()
                        continue
                    if ent is not None:
                        # budget-blocked: re-park in place, keep
                        # sweeping nothing — the preempt pass at the
                        # end of _admit is the relief valve
                        self._sched.push_entry(ent)
                    else:
                        self._pending_head = req   # retry next pass
                    return False
                if self._kv.enabled:
                    wall = time.perf_counter() - t_lk
                    obs.add_complete(
                        "kvcache/lookup", time.time() - wall, wall,
                        request=req.id, matched_tokens=adm.matched_len,
                        prompt_tokens=len(ids))
                    if flight.enabled:
                        flight.record(
                            "radix_hit" if adm.matched_len else
                            "radix_miss", request_id=req.id,
                            trace_id=_trace_of(req),
                            matched_tokens=adm.matched_len,
                            device_matched=adm.device_matched,
                            prompt_tokens=len(ids))
                        if adm.tail_src is not None:
                            flight.record(
                                "cow_fork", request_id=req.id,
                                trace_id=_trace_of(req),
                                src_page=adm.tail_src,
                                tail_tokens=adm.tail_len)
                if adm.fetch:
                    # host-tier hit: park until the upload lands; keep
                    # filling this slot from the queue meanwhile
                    if flight.enabled:
                        flight.record(
                            "park", request_id=req.id,
                            trace_id=_trace_of(req),
                            pages=len(adm.fetch))
                    self._fetch_wait.append(
                        {"req": req, "adm": adm,
                         "t0": time.perf_counter()})
                    continue
                self._slot_adm[i] = adm
            self._prefill_admitted(i, req, adm, chunked=chunked)
            return True

    def _prefill_admitted(self, i: int, req: Request, adm,
                          chunked: bool = False, prepaid: bool = False):
        """Prefill a request whose cache grant is already held (shared
        tail of direct and fetch-parked admissions). ``chunked`` routes
        long-suffix admissions to the unified dispatch (ISSUE 14): no
        model dispatch here — the prompt is fed chunk by chunk in
        subsequent engine passes, interleaved with decode."""
        ctx = rc.from_wire(req.trace)
        if ctx is not None and req.submitted_at:
            # engine-side admission wait, parented to the submitter
            args = ({"parent_span": ctx.span_id}
                    if ctx.span_id else {})
            obs.add_complete(
                "llm/queue_wait", req.submitted_at,
                time.time() - req.submitted_at, trace=ctx.trace_id,
                stage="queue", request=req.id, **args)
        ids = self._prompt_of(req)
        if flight.enabled:
            flight.record(
                "admit", request_id=req.id, trace_id=_trace_of(req),
                slot=i, chunked=chunked, prepaid=prepaid,
                matched_tokens=adm.matched_len if adm else 0,
                prompt_tokens=len(ids))
        if self._sched is not None and req.resume_ids is not None:
            # a preempted request re-took a slot (ISSUE 17): the resume
            # event mirrors the preempt one — chaos reconciles the two
            # tallies exactly against preemptions_total
            self.preempt_resumes_total += 1
            if self._parked is not None:
                self._parked.pop(req.id, None)
            if flight.enabled:
                flight.record(
                    "preempt_resume", request_id=req.id,
                    trace_id=_trace_of(req), slot=i,
                    priority=req.priority,
                    tokens_done=len(req.tokens),
                    remaining=self._budget_of(req))
        if chunked:
            self._begin_chunked(i, req, adm, prepaid)
            return
        t0 = time.perf_counter()
        try:
            with rc.activate(ctx), \
                    obs.span("llm/prefill", slot=i,
                             tokens=len(ids),
                             stage="llm_server", request=req.id):
                (self._prefill_paged if self.paged
                 else self._prefill_slot)(i, req)
        except BaseException as e:
            # a failing prefill must not leak its admission budget
            # or adoption refcounts (the resilient _loop would
            # otherwise shrink the pool forever) nor leave the
            # client blocked until timeout
            if self.paged and adm is not None:
                self._kv.cancel(adm)
                self._slot_adm[i] = None
            req.error = f"{type(e).__name__}: {e}"
            req.done.set()
            raise
        req.decode_started_at = time.time()
        suffix = len(ids) - (adm.matched_len if adm else 0)
        self._record_prefill(suffix, time.perf_counter() - t0)

    def _instruments(self):
        """None when observability is off; declared on first use so
        ``obs.enable()`` starts recording on a LIVE server (the runtime-
        override contract), and a disabled run declares nothing."""
        if not obs.enabled():
            return None
        if self._ins is None:
            self._ins = _llm_instruments()
        return self._ins

    def _priority_instruments_get(self):
        """None unless the priority scheduler exists AND observability
        records — same lazy-declaration contract as _instruments(),
        same structural-absence contract as _mixed_instruments()."""
        if not (self._sched is not None and obs.enabled()):
            return None
        if self._pri_ins is None:
            self._pri_ins = _priority_instruments()
        return self._pri_ins

    def _record_kv_gauges(self, ins):
        backlog = len(self._sched) if self._sched is not None else 0
        ins["queue"].set(self._queue.qsize() + backlog)
        pri = self._priority_instruments_get()
        if pri is not None:
            for cls, depth in self._sched.depths().items():
                pri["queue_class"].labels(**{"class": cls}).set(depth)
            pri["parked"].set(self._sched.parked())
        if self.paged:
            ins["kv_pages"].set(self.pages_in_use)
            # page 0 is the reserved trash page, never allocatable
            ins["kv_occupancy"].set(
                self.pages_in_use / max(self._num_pages - 1, 1))
            self._kv.record_gauges()   # bigdl_kvcache_* (enabled only)

    def _record_prefill(self, n_tokens: int, seconds: float):
        self.prefill_tokens_total += n_tokens   # always-on (microbench)
        ins = self._instruments()
        if ins is not None:
            ins["prefill_tokens"].inc(n_tokens)
            ins["prefill_seconds"].observe(seconds)
            self._record_kv_gauges(ins)

    def _prefill_slot(self, i: int, req: Request):
        """Run the prompt through the model writing kv at slot i only.

        Implementation detail: forward() operates on the whole batch, so
        the prompt is broadcast into a (max_batch, T) token block but
        only slot i's cache rows are kept (the other slots' K/V pages
        are restored from the pre-call cache) — one compiled shape per
        prompt length, fully static."""
        t = len(req.prompt_ids)
        toks = jnp.asarray(
            np.broadcast_to(req.prompt_ids, (self.max_batch, t)))
        start = int(self._pos[i])
        positions = jnp.broadcast_to(jnp.arange(start, start + t),
                                     (self.max_batch, t))
        cache_in = dict(self._cache)
        cache_in["pos"] = jnp.asarray(start, jnp.int32)
        logits, new_cache = self._fwd(self.model.params, tokens=toks,
                                      cache=cache_in, positions=positions)
        row = jnp.arange(self.max_batch) == i
        keep = row[None, :, None, None, None]
        old = self._cache
        self._cache = {
            "k": jnp.where(keep, new_cache["k"], old["k"]),
            "v": jnp.where(keep, new_cache["v"], old["v"]),
            "pos": old["pos"],
        }
        # RACE FIX (round 4, pipelined in ISSUE 4): the buffers consumed
        # by the dispatches above must outlive them. Under jax's async
        # dispatch, dropping the previous cache while the computation
        # consuming it is still in flight lets the runtime recycle those
        # buffers for CONCURRENT jax work on other threads, and the
        # in-flight computation then reads overwritten memory
        # (reproduced: 14/30 greedy-parity mismatches with 4 hammer
        # threads; 0/30 with the barrier — see the stress test in
        # tests/test_llm_serving.py). At depth 1 we barrier exactly like
        # the synchronous engine; at depth > 1 the references are pinned
        # until the next drained step's fence instead of blocking.
        self._pin(old["k"], old["v"], cache_in["pos"], toks, positions,
                  logits, new_cache["k"], new_cache["v"], self._last,
                  self._pos_dev)
        self._last = self._last.at[i].set(logits[i, -1])
        self._pos[i] = start + t
        self._pos_dev = self._pos_dev.at[i].set(start + t)
        if self.pipeline_depth == 1:
            _sync_barrier(self._cache["k"], self._cache["v"], self._last,
                          self._pos_dev)
            self._pending_release.clear()
        del old
        self._slots[i] = req
        self._remaining[i] = req.max_new_tokens

    # -- paged engine --------------------------------------------------------
    def _step_cache_key(self) -> tuple:
        """Value key for the shared compiled-step cache. id(cfg) would be
        unsound (a recycled address after GC aliases a different config)
        and the closures bake every cfg field, the page size and the
        cache dtype — so all of them key the entry."""
        import dataclasses
        return (self._family, dataclasses.astuple(self.cfg), self._page,
                str(jnp.dtype(self.model.cache_dtype)))

    def _build_paged_prefill(self, bucket: int):
        """Compile a prompt prefill for one padded length ``bucket``:
        run the prompt through forward() with a temporary dense cache of
        exactly ``bucket`` tokens (small, request-local), then scatter
        the resulting K/V into the page pool at this request's physical
        pages. Pad pages beyond ceil(len/page) land in trash page 0."""
        cfg = self.cfg
        page = self._page
        hkv, hd = cfg.num_key_value_heads, cfg.head_dim
        nl = cfg.num_hidden_layers

        cache_dtype = self.model.cache_dtype
        fam_forward, fam_init_cache = self._fam_forward, self._fam_init_cache

        def build(params, k_pages, v_pages, toks, length, page_ids):
            # the temp cache must match the pool dtype: a bf16 default
            # would round f32-cache models' prompt KV before it reaches
            # the f32 pool, diverging served tokens from generate()
            cache = fam_init_cache(cfg, 1, bucket, dtype=cache_dtype)
            positions = jnp.arange(bucket)[None, :]
            logits, cache2 = fam_forward(params, cfg, toks, cache,
                                         positions)
            ks, vs = cache2["k"][:, 0], cache2["v"][:, 0]  # (L,bucket,H,D)

            def pageify(a):
                return a.reshape(nl, bucket // page, page, hkv,
                                 hd).transpose(0, 1, 3, 2, 4)

            k_pages = k_pages.at[:, page_ids].set(
                pageify(ks).astype(k_pages.dtype))
            v_pages = v_pages.at[:, page_ids].set(
                pageify(vs).astype(v_pages.dtype))
            last = jax.lax.dynamic_index_in_dim(logits[0], length - 1, 0,
                                                keepdims=False)
            return k_pages, v_pages, last.astype(jnp.float32)

        return obs.compiled(build, name="llm/prefill_paged",
                            donate_argnums=(1, 2))

    def _finish_prefill(self, i: int, req: Request, row_pages, own,
                        last, pins, adm=None):
        """Shared epilogue of the three paged prefill paths (full /
        dense-partial / ragged): pin every buffer the dispatch consumed
        (the PR 4 buffer-lifetime invariant, docs/PERFORMANCE.md), land
        the slot's block table + length host- and device-side,
        reproduce the synchronous cadence at depth 1, drop the
        admission's transient tail ref (consumed in program order by
        the dispatch), then hand the slot to the request. ONE copy so a
        fix to the pin set or barrier cadence cannot drift between the
        paths."""
        self._pin(*pins, last, self._last, self._bt_dev, self._lens_dev)
        self._last = self._last.at[i].set(last)
        T = len(self._prompt_of(req))
        npages = len(row_pages)
        self._bt[i, :] = 0
        self._bt[i, :npages] = row_pages
        self._lens[i] = T
        row = np.zeros(self._pages_cap, np.int32)
        row[:npages] = row_pages
        row_d = jnp.asarray(row)
        self._pin(row_d)
        self._bt_dev = self._bt_dev.at[i].set(row_d)
        self._lens_dev = self._lens_dev.at[i].set(T)
        if self.pipeline_depth == 1:
            _sync_barrier(self._k_pages, self._v_pages, self._last,
                          self._bt_dev, self._lens_dev)
            self._pending_release.clear()
        if adm is not None:
            self._kv.release_transient(adm)
        self._slot_pages[i] = own
        self._slots[i] = req
        self._remaining[i] = self._budget_of(req)
        self._index_prompt(i, req)

    def _prefill_paged(self, i: int, req: Request):
        # the slot's admission grant was stored by _admit; the ragged
        # in-place path (ISSUE 8) serves BOTH the full and the
        # partial-prefix case — offset is runtime data there; the
        # dense-staging paths below are the fallback
        adm = self._slot_adm[i]
        if self._ragged:
            return self._prefill_ragged(i, req, adm)
        if adm is not None and adm.matched_len:
            return self._prefill_paged_partial(i, req, adm)
        prompt = self._prompt_of(req)
        t = len(prompt)
        page = self._page
        npages = -(-t // page)
        ids = self._kv.alloc(npages)
        try:
            bucket = max(page, 1 << (t - 1).bit_length())  # pow2, >= page
            key = self._step_cache_key() + ("prefill", bucket)
            fn = _PAGED_STEP_CACHE.get(key)
            if fn is None:
                fn = _PAGED_STEP_CACHE[key] = \
                    self._build_paged_prefill(bucket)
            toks = np.zeros((1, bucket), np.int32)
            toks[0, :t] = prompt
            pids = np.zeros(bucket // page, np.int32)
            pids[:npages] = ids
            toks_d = jnp.asarray(toks)
            t_d = jnp.asarray(t, jnp.int32)
            pids_d = jnp.asarray(pids)
            self._k_pages, self._v_pages, last = fn(
                self.model.params, self._k_pages, self._v_pages,
                toks_d, t_d, pids_d)
            self.prefill_dense_staged_tokens += bucket
        except BaseException:
            self._kv.free_owned(ids)   # physical pages must not leak
            raise
        # shared epilogue: pin + slot bookkeeping + depth-1 barrier
        self._finish_prefill(i, req, ids, ids, last,
                             (toks_d, t_d, pids_d))

    def _build_partial_prefill(self, n_pp: int, bucket: int):
        """Compile the family's partial prefill for one (prefix-pages,
        suffix-length) bucket pair — see llm/kvcache/prefill.py for the
        gather → offset-forward → fused-COW-scatter structure."""
        cfg, page = self.cfg, self._page
        fam = self._fam_partial_prefill
        cache_dtype = self.model.cache_dtype

        def build(params, k_pages, v_pages, toks, length, offset,
                  prefix_ids, phys, slots):
            return fam(params, cfg, k_pages, v_pages, toks, length,
                       offset, prefix_ids, phys, slots, page=page,
                       n_pp=n_pp, bucket=bucket, cache_dtype=cache_dtype)

        return obs.compiled(build, name="llm/prefill_partial",
                            donate_argnums=(1, 2))

    def _prefill_paged_partial(self, i: int, req: Request, adm):
        """Prefill only the uncached suffix (ISSUE 5): the block-table
        prefix is pre-populated with adopted shared pages, the suffix
        runs at position offset ``matched_len``, and a partially-matched
        tail page is copy-on-write forked into the request's own first
        suffix page by the same scatter."""
        page = self._page
        prompt = self._prompt_of(req)
        T = len(prompt)
        off = adm.matched_len
        koff = off // page
        own = self._kv.alloc(-(-T // page) - koff)
        try:
            row_pages = list(adm.shared_pages) + own
            gsrc = list(adm.shared_pages)
            if adm.tail_src is not None:
                gsrc.append(adm.tail_src)
            n_pp = 1 << (len(gsrc) - 1).bit_length()     # pow2 bucket
            t_suf = T - off
            bucket = max(page, 1 << (t_suf - 1).bit_length())
            key = self._step_cache_key() + ("prefill_partial", n_pp,
                                            bucket)
            fn = _PAGED_STEP_CACHE.get(key)
            if fn is None:
                fn = _PAGED_STEP_CACHE[key] = \
                    self._build_partial_prefill(n_pp, bucket)
            toks = np.zeros((1, bucket), np.int32)
            toks[0, :t_suf] = prompt[off:]
            pids = np.zeros(n_pp, np.int32)
            pids[:len(gsrc)] = gsrc
            # scatter targets for the page-aligned window at koff*page:
            # leading sub-page slots re-write the adopted tail into the
            # fork page the request owns; suffix tokens land in their
            # own pages; padding routes to trash page 0
            W = page + bucket
            p0 = koff * page
            phys = np.zeros(W, np.int32)
            slots = np.zeros(W, np.int32)
            for j in range(W):
                p = p0 + j
                if p < T:
                    phys[j] = row_pages[p // page]
                slots[j] = p % page
            toks_d = jnp.asarray(toks)
            len_d = jnp.asarray(t_suf, jnp.int32)
            off_d = jnp.asarray(off, jnp.int32)
            pids_d = jnp.asarray(pids)
            phys_d = jnp.asarray(phys)
            slots_d = jnp.asarray(slots)
            self._k_pages, self._v_pages, last = fn(
                self.model.params, self._k_pages, self._v_pages,
                toks_d, len_d, off_d, pids_d, phys_d, slots_d)
            # the dense sandwich staged the gathered prefix + one page
            # of slack + the suffix bucket through a temp cache
            self.prefill_dense_staged_tokens += n_pp * page + page \
                + bucket
        except BaseException:
            self._kv.free_owned(own)
            raise
        # shared epilogue; the dispatch consumed the tail source in
        # order, so _finish_prefill drops its transient ref/pin (the
        # donated-pool dependency chain orders any later overwrite
        # after the gather)
        self._finish_prefill(i, req, row_pages, own, last,
                             (toks_d, len_d, off_d, pids_d, phys_d,
                              slots_d), adm=adm)

    def _build_ragged_prefill(self, bucket: int):
        """Compile the family's ragged in-place prefill for ONE suffix
        bucket (ISSUE 8). Prefix pages, the position offset and the
        scatter targets are all runtime arguments — unlike the dense
        partial prefill there is no ``n_pp`` in the static shape, so
        the compile grid is O(suffix-buckets) (guarded by the
        compile-recorder regression test)."""
        cfg, page = self.cfg, self._page
        fam = self._fam_ragged_prefill

        def build(params, k_pages, v_pages, toks, length, offset,
                  bt_row, phys, slots, fork_dst, fork_src):
            return fam(params, cfg, k_pages, v_pages, toks, length,
                       offset, bt_row, phys, slots, fork_dst, fork_src,
                       page=page)

        return obs.compiled(build, name="llm/prefill_ragged",
                            donate_argnums=(1, 2))

    def _prefill_ragged(self, i: int, req: Request, adm):
        """Prefill in place on the page pool (ISSUE 8): the suffix runs
        at position offset ``matched_len`` while attention reads the
        adopted prefix pages through the block table — no dense temp
        cache, no prefix gather/scatter. One program serves the full-
        prefill (offset 0) and every partial-prefix case, including
        tier re-prefills (a materialized fetch is indistinguishable
        from a device prefix hit by the time prefill runs). The COW
        tail fork is a single page copy fused ahead of the layer scan."""
        page = self._page
        prompt = self._prompt_of(req)
        T = len(prompt)
        off = adm.matched_len if adm is not None else 0
        koff = off // page
        shared = list(adm.shared_pages) if adm is not None else []
        own = self._kv.alloc(-(-T // page) - koff)
        try:
            row_pages = shared + own
            tail = adm is not None and adm.tail_src is not None
            t_suf = T - off
            bucket = max(page, 1 << (t_suf - 1).bit_length())  # pow2
            key = self._step_cache_key() + ("prefill_ragged", bucket)
            fn = _PAGED_STEP_CACHE.get(key)
            if fn is None:
                fn = _PAGED_STEP_CACHE[key] = \
                    self._build_ragged_prefill(bucket)
            toks = np.zeros((1, bucket), np.int32)
            toks[0, :t_suf] = prompt[off:]
            bt_row = np.zeros(self._pages_cap, np.int32)
            bt_row[:len(row_pages)] = row_pages
            # scatter targets for the suffix window [off, off+bucket):
            # token j lands in (phys[j], slots[j]); positions past the
            # true prompt route to trash page 0
            pos = off + np.arange(bucket)
            phys = np.where(pos < T,
                            bt_row[np.minimum(pos // page,
                                              self._pages_cap - 1)],
                            0).astype(np.int32)
            slots = (pos % page).astype(np.int32)
            toks_d = jnp.asarray(toks)
            len_d = jnp.asarray(t_suf, jnp.int32)
            off_d = jnp.asarray(off, jnp.int32)
            bt_d = jnp.asarray(bt_row)
            phys_d = jnp.asarray(phys)
            slots_d = jnp.asarray(slots)
            fork_dst = jnp.asarray(own[0] if tail else 0, jnp.int32)
            fork_src = jnp.asarray(adm.tail_src if tail else 0,
                                   jnp.int32)
            self._k_pages, self._v_pages, last = fn(
                self.model.params, self._k_pages, self._v_pages,
                toks_d, len_d, off_d, bt_d, phys_d, slots_d, fork_dst,
                fork_src)
        except BaseException:
            self._kv.free_owned(own)
            raise
        # shared epilogue; the fork copy consumed the tail source in
        # dispatch order, so the transient ref/pin drops there (same
        # argument as the dense path's gather)
        self._finish_prefill(i, req, row_pages, own, last,
                             (toks_d, len_d, off_d, bt_d, phys_d,
                              slots_d, fork_dst, fork_src), adm=adm)

    def _index_prompt(self, i: int, req: Request):
        """Make this request's FULL prompt pages reusable immediately
        (not at EOS): concurrent requests sharing the prompt adopt them
        while this one is still decoding. The partially-filled prompt
        tail stays private — it is indexed at EOS, and adopters fork it
        (COW) rather than racing this request's decode writes."""
        if self._kv is None or not self._kv.enabled:
            return
        prompt = self._prompt_of(req)
        nfull = len(prompt) // self._page
        if nfull:
            self._kv.insert(prompt[:nfull * self._page],
                            self._bt[i, :nfull])

    # -- unified mixed prefill+decode dispatch (ISSUE 14) --------------------
    def _chunk_end(self, off: int, T: int) -> int:
        """Page-aligned end of the next chunk from offset ``off``: the
        largest page multiple within ``chunk_tokens`` of ``off`` — so
        every chunk after the first starts page-aligned and only the
        final one (which runs to the prompt end) may end mid-page."""
        end = ((off + self._chunk_tokens) // self._page) * self._page
        return T if end >= T else max(end, off + 1)

    def _begin_chunked(self, i: int, req: Request, adm, prepaid: bool):
        """Admit a long-suffix request WITHOUT prefilling it: the
        prompt is fed in page-aligned chunks by subsequent engine
        passes (fused with decode rows — see ``_dispatch_mixed``), so
        one admission never monopolizes a pass. The slot is held
        (admission order and ``stop(drain=True)`` semantics preserved)
        but stays decode-inactive until the final chunk lands.
        ``prepaid`` admissions (host-tier fetches) charged their whole
        budget at admit; everyone else charges chunk by chunk."""
        self._chunk_state[i] = {
            "req": req, "adm": adm, "off": adm.matched_len,
            "row_pages": list(adm.shared_pages), "own": [],
            "prepaid": prepaid, "first": True,
            "t0": time.perf_counter(), "wait_t0": None,
        }
        self._slots[i] = req
        self._remaining[i] = 0
        self._slot_adm[i] = adm

    def _chunk_slot(self) -> Optional[int]:
        """Round-robin pick of ONE chunking slot to advance this pass —
        the scheduler's per-pass prefill budget is a single chunk of at
        most ``chunk_tokens`` tokens, so concurrent chunkers share the
        engine fairly. Dead requests (aborted, watchdog-failed) roll
        back here before they can waste a dispatch."""
        if self._chunk_state is None:
            return None
        n = self.max_batch
        if self._sched is not None:
            # class-ordered chunk selection (ISSUE 17): the per-pass
            # prefill budget goes to the highest-class chunker —
            # within a class, lowest slot keeps the pick stable (no
            # round-robin: two equal-class chunkers alternate only
            # when the leader stalls on the ledger)
            best = None
            for i in range(n):
                st = self._chunk_state[i]
                if st is None:
                    continue
                if st["req"].cancel_requested or \
                        st["req"].done.is_set():
                    self._rollback_chunk(i, None)
                    continue
                key = (_PRIORITY_RANK[st["req"].priority], i)
                if best is None or key < best[0]:
                    best = (key, i)
            return best[1] if best is not None else None
        for k in range(n):
            i = (self._chunk_rr + k) % n
            st = self._chunk_state[i]
            if st is None:
                continue
            if st["req"].cancel_requested or st["req"].done.is_set():
                self._rollback_chunk(i, None)
                continue
            self._chunk_rr = (i + 1) % n
            return i
        return None

    def _prepare_chunk(self, i: int) -> Optional[dict]:
        """Ledger charge + operand build for slot ``i``'s next chunk.
        None = nothing to dispatch this pass: the ``llm.chunk`` fault
        fired (chain rolled back, request failed retriably) or the
        ledger cannot cover the chunk yet — the engine keeps decoding
        and retries next pass, shedding past ``chunk_wait`` so
        concurrent chunkers can never deadlock the pool against each
        other (each holds pages the others wait on)."""
        st = self._chunk_state[i]
        req, adm = st["req"], st["adm"]
        page = self._page
        ids = self._prompt_of(req)
        T = len(ids)
        off = st["off"]
        if not st["first"]:
            # the mid-admission fault site (ISSUE 14): a raise between
            # chunks frees the partial chain and fails the request
            # retriably — chaos_check --mixed proves a resubmission is
            # then bit-identical
            try:
                reliability.inject("llm.chunk")
            except BaseException as e:
                self._rollback_chunk(
                    i, f"chunked admission failed between chunks: "
                       f"{type(e).__name__}: {e} (retriable: partial "
                       "chain rolled back; resubmit)")
                return None
        end = self._chunk_end(off, T)
        c = end - off
        n_new = -(-end // page) - len(st["row_pages"])
        final = end == T
        need = n_new
        if final and not st["prepaid"]:
            # decode-budget top-up: every page the request may still
            # need past its prompt — the reserve that keeps decode
            # deadlock-free, charged at the last possible moment so
            # Σ(admit + chunk charges) equals the unchunked worst case
            # exactly (the first chunk never charges here: suffix >
            # chunk_tokens means it never reaches the prompt end)
            need += (-(-(T + self._budget_of(req)) // page)
                     - (-(-T // page)))
        # ledger FIRST: admit(chunk_pages=) already charged the FIRST
        # chunk, and prepaid (fetch-path) admissions charged in full —
        # only later chunks extend the charge here. A successful
        # charge guarantees free+evictable covers n_new (allocated <=
        # charged pool-wide), so the disabled-cache ensure_free can
        # never hit its "shortage with the cache disabled" invariant.
        # An ensure_free raise (the injected kvcache.evict) uncharges
        # before propagating — the pass retry starts from a clean
        # ledger.
        charge_now = 0 if (st["prepaid"] or st["first"]) else need
        if charge_now and not self._kv.charge_chunk(adm, charge_now):
            now = time.perf_counter()
            if st["wait_t0"] is None:
                st["wait_t0"] = now
            elif now - st["wait_t0"] > self._chunk_wait:
                victim = i
                if self._sched is not None:
                    # class-ordered shed victim (ISSUE 17): a starved
                    # HIGH-class chunker sheds the worst strictly-
                    # lower-class chunker instead of itself — freeing
                    # that chain is exactly what unblocks the ledger.
                    # No lower-class peer → shed self (unchanged).
                    rank_i = _PRIORITY_RANK[req.priority]
                    worst = None
                    for j in range(self.max_batch):
                        sj = self._chunk_state[j]
                        if sj is None or j == i:
                            continue
                        rj = _PRIORITY_RANK[sj["req"].priority]
                        if rj > rank_i and (worst is None
                                            or (rj, j) > worst[0]):
                            worst = ((rj, j), j)
                    if worst is not None:
                        victim = worst[1]
                        st["wait_t0"] = now   # fresh window for i: the
                        # shed frees pages only after the rollback
                self._rollback_chunk(
                    victim,
                    f"chunked admission starved: the ledger could "
                    f"not cover the next {charge_now} pages within "
                    f"{self._chunk_wait:g}s (retriable: partial "
                    "chain rolled back; resubmit)")
            return None
        st["wait_t0"] = None
        try:
            if n_new > 0:
                self._kv.ensure_free(n_new)
            new_pages = self._kv.alloc(n_new) if n_new > 0 else []
        except BaseException:
            self._kv.uncharge_chunk(adm, charge_now)
            raise
        row_pages = st["row_pages"] + new_pages
        tail = st["first"] and adm.tail_src is not None
        bucket = max(page, 1 << (c - 1).bit_length())   # pow2 ladder
        bt_row = np.zeros(self._pages_cap, np.int32)
        bt_row[:len(row_pages)] = row_pages
        # scatter targets for the window [off, off+bucket): positions
        # past this chunk's end route to trash page 0 — their pages may
        # not exist yet (they are a LATER chunk's)
        pos = off + np.arange(bucket)
        phys = np.where(pos < end,
                        bt_row[np.minimum(pos // page,
                                          self._pages_cap - 1)],
                        0).astype(np.int32)
        slots = (pos % page).astype(np.int32)
        toks = np.zeros((1, bucket), np.int32)
        toks[0, :c] = ids[off:end]
        ops = (jnp.asarray(toks), jnp.asarray(c, jnp.int32),
               jnp.asarray(off, jnp.int32), jnp.asarray(bt_row),
               jnp.asarray(phys), jnp.asarray(slots),
               jnp.asarray(new_pages[0] if tail else 0, jnp.int32),
               jnp.asarray(adm.tail_src if tail else 0, jnp.int32))
        if flight.enabled:
            flight.record(
                "chunk_charge", request_id=req.id,
                trace_id=_trace_of(req), chunk_tokens=c, off=off,
                end=end, final=final, charged_pages=charge_now,
                new_pages=len(new_pages))
        return {"i": i, "c": c, "end": end, "final": final,
                "bucket": bucket, "new_pages": new_pages,
                "charged": charge_now, "ops": ops}

    def _chunk_dispatched(self, cargs: dict, clast):
        """Post-dispatch chunk bookkeeping (host side, overlapping the
        device): advance the chunk cursor; on the FINAL chunk run the
        ``_finish_prefill`` epilogue — the slot flips to an ordinary
        decode row with the chunk-accumulated page chain. Runs AFTER
        the pass's in-flight record is cut, so the epilogue's scatters
        pin into the NEXT fence (or the depth-1 barrier here), never
        the already-sealed record's."""
        i = cargs["i"]
        st = self._chunk_state[i]
        req, adm = st["req"], st["adm"]
        st["row_pages"].extend(cargs["new_pages"])
        st["own"].extend(cargs["new_pages"])
        st["off"] = cargs["end"]
        if st["first"]:
            st["first"] = False
            # the fork copy consumed the tail source in dispatch order
            # (the _prefill_ragged argument, unchanged)
            self._kv.release_transient(adm)
        c = cargs["c"]
        self.prefill_tokens_total += c
        self.prefill_chunks_total += 1
        ins = self._instruments()
        if ins is not None:
            ins["prefill_tokens"].inc(c)
        if not cargs["final"]:
            if self.pipeline_depth == 1:
                # synchronous cadence per chunk: pool writes resolve
                # before their consumed buffers drop (the
                # _finish_prefill contract)
                _sync_barrier(self._k_pages, self._v_pages)
                self._pending_release.clear()
            return
        # -- final chunk: the SHARED _finish_prefill epilogue (one copy
        # — a fix to the pin set or barrier cadence cannot drift
        # between the whole-prompt paths and this one). The tail ref
        # was already dropped at the first chunk, so adm stays None.
        self._chunk_state[i] = None
        self._finish_prefill(i, req, st["row_pages"], st["own"], clast,
                             ())
        req.decode_started_at = time.time()
        if ins is not None:
            # admission→prompt-complete wall (decode passes interleave
            # by design, so this is CHUNKED-prefill latency, not pure
            # dispatch time — documented in docs/PERFORMANCE.md)
            ins["prefill_seconds"].observe(
                time.perf_counter() - st["t0"])
            self._record_kv_gauges(ins)

    def _rollback_chunk(self, i: int, msg: Optional[str]):
        """Mid-prompt shed/abort/fault (ISSUE 14): free the partial
        chain's pages and every ledger charge taken so far, drop the
        adoption refs, fail the request retriably (``msg`` None =
        already-dead handle, nothing to report). Pages a still-in-
        flight chunk or mixed step reads are released at the newest
        in-flight fence (the PR 4 pin invariant extended to chunk
        chains); with nothing in flight, a barrier bounds any pending
        bookkeeping first."""
        st = self._chunk_state[i]
        req, adm = st["req"], st["adm"]
        self._kv.release_transient(adm)
        entry = (adm.charge + adm.fetch_reserved, list(st["own"]),
                 list(adm.shared_pages))
        adm.charge = 0
        adm.fetch_reserved = 0
        adm.shared_pages = []
        if self._pending_release:
            # bookkeeping or a SOLO chunk dispatched AFTER the newest
            # in-flight record may still read this chain's pages, and
            # no record's fence bounds it — barrier on the current
            # arrays (they data-depend on everything enqueued) before
            # the pages go back. Rollback is rare; the stall is not.
            try:
                _sync_barrier(self._k_pages, self._v_pages,
                              self._bt_dev, self._lens_dev,
                              self._last)
            except Exception:
                pass
            self._pending_release.clear()
            self._kv.release_slot(*entry)
        elif self._inflight:
            # every dispatch touching the chain is inside the window:
            # the newest fence bounds them all (in-order stream)
            self._inflight[-1].setdefault("kv_release", []).append(
                entry)
        else:
            self._kv.release_slot(*entry)
        self._chunk_state[i] = None
        self._slots[i] = None
        self._remaining[i] = 0
        self._slot_adm[i] = None
        if flight.enabled:
            flight.record(
                "rollback", request_id=req.id, trace_id=_trace_of(req),
                reason="cancelled" if msg is None else "starved",
                released_pages=len(entry[1]) + len(entry[2]))
        if msg is not None and not req.done.is_set():
            req.error = msg
            req.done.set()
        ins = self._instruments()
        if ins is not None:
            ins["requests"].labels(
                reason="cancelled" if msg is None else "error").inc()

    def _build_mixed_step(self):
        """Compile the family's unified mixed step for ONE chunk-suffix
        bucket (the chunk operand shapes fix it): the decode leg is the
        family sampled step VERBATIM, the chunk leg the family ragged
        prefill VERBATIM — see ``kvcache.prefill.make_mixed_step``.
        Offsets, block tables and scatter targets are runtime data, so
        the mixed grid adds O(suffix-buckets) programs total (guarded
        by the compile-recorder test in tests/test_mixed_dispatch.py)."""
        cfg, page = self.cfg, self._page
        fam = self._fam_mixed_step
        do_sample, top_k = self._do_sample, self.top_k

        def step(params, k_pages, v_pages, bt, lens, last, active,
                 temp, key, ctoks, clen, coff, cbt_row, cphys, cslots,
                 fork_dst, fork_src):
            return fam(params, cfg, k_pages, v_pages, bt, lens, last,
                       active, temp, key, ctoks, clen, coff, cbt_row,
                       cphys, cslots, fork_dst, fork_src, page=page,
                       do_sample=do_sample, top_k=top_k)

        return obs.compiled(step, name="llm/step_mixed",
                            donate_argnums=(1, 2))

    def _mixed_instruments(self):
        """Unified-dispatch pass metrics — None unless the mixed gate
        is live AND observability records. ``bigdl.llm.mixed.enabled``
        off must leave no ``bigdl_llm_pass_rows_total`` /
        ``bigdl_llm_prefill_chunks_total`` / ``bigdl_llm_pass_mix``
        series (the disabled-mode absence contract)."""
        if not (self._mixed_active and obs.enabled()):
            return None
        if self._mixed_ins is None:
            self._mixed_ins = {
                "pass_rows": obs.counter(
                    "bigdl_llm_pass_rows_total",
                    "Rows served by unified engine passes, by kind",
                    labelnames=("kind",)),
                "chunks": obs.counter(
                    "bigdl_llm_prefill_chunks_total",
                    "Prefill chunks dispatched by the unified engine"),
                "mix": obs.gauge(
                    "bigdl_llm_pass_mix",
                    "Decode-row fraction of the last unified pass "
                    "(1.0 = pure decode, 0.0 = chunk-only)"),
            }
        return self._mixed_ins

    def _record_mixed_pass(self, n_decode: int, cargs: dict,
                           t_step: float):
        """Per-pass batch-mix attribution (ISSUE 14 observability)."""
        if n_decode:
            self.mixed_passes += 1
        ins = self._mixed_instruments()
        if ins is None:
            return
        wall = time.perf_counter() - t_step
        ins["pass_rows"].labels(kind="prefill_chunk").inc()
        if n_decode:
            ins["pass_rows"].labels(kind="decode").inc(n_decode)
        ins["chunks"].inc()
        ins["mix"].set(n_decode / (n_decode + 1))
        obs.add_complete(
            "llm/mixed_step", time.time() - wall, wall,
            decode_rows=n_decode, chunk_tokens=cargs["c"],
            offset=cargs["end"] - cargs["c"], final=cargs["final"],
            slot=cargs["i"])

    def _restore_chunk_pass(self, cargs: dict):
        """A pass failed AFTER _prepare_chunk allocated/charged but
        before (or at) the dispatch: restore the chunk's pages and
        ledger exactly so the engine loop's pass retry re-prepares the
        same chunk from a clean state (nothing in ``st`` advanced —
        row_pages/own only extend in ``_chunk_dispatched``)."""
        self._kv.free_owned(cargs["new_pages"])
        self._kv.uncharge_chunk(self._chunk_state[cargs["i"]]["adm"],
                                cargs["charged"])

    def _dispatch_chunk_solo(self, cargs: dict, t_step: float):
        """A chunk with no live decode rows to fuse with: dispatch it
        through the per-bucket ragged-prefill program (identical chunk
        math to the mixed program's chunk leg — the parity matrix
        covers both routes) with prefill-style pinning/barriers."""
        key = self._step_cache_key() + ("prefill_ragged",
                                        cargs["bucket"])
        fn = _PAGED_STEP_CACHE.get(key)
        if fn is None:
            fn = _PAGED_STEP_CACHE[key] = \
                self._build_ragged_prefill(cargs["bucket"])
        try:
            self._k_pages, self._v_pages, clast = fn(
                self.model.params, self._k_pages, self._v_pages,
                *cargs["ops"])
        except BaseException:
            # dispatch failed before any state advanced: restore the
            # chunk's ledger/pages exactly — the engine loop retries
            # the whole pass, chunk included
            self._restore_chunk_pass(cargs)
            raise
        self._pin(*cargs["ops"])
        self._chunk_dispatched(cargs, clast)
        self._record_mixed_pass(0, cargs, t_step)

    def _dispatch_mixed(self, disp, active, cargs: dict,
                        t_step: float) -> bool:
        """One UNIFIED pass (the ISSUE 14 tentpole): every active
        decode row plus one prefill chunk in a single compiled program
        — the chunk no longer stalls the decode stream, and the
        drain/fence machinery treats the pass exactly like a decode
        pass (the chunk row emitted no token, so it drains an empty
        slot)."""
        key = self._step_cache_key() + ("mixed", cargs["bucket"],
                                        self._do_sample, self.top_k)
        pmixed = _PAGED_STEP_CACHE.get(key)
        if pmixed is None:
            pmixed = _PAGED_STEP_CACHE[key] = self._build_mixed_step()
        bt_in, lens_in = self._bt_dev, self._lens_dev
        last_in, key_in = self._last, self._sample_key
        try:
            out, logits, self._k_pages, self._v_pages, \
                self._lens_dev, self._sample_key, clast = pmixed(
                    self.model.params, self._k_pages, self._v_pages,
                    bt_in, lens_in, last_in, active, self._temp,
                    key_in, *cargs["ops"])
        except BaseException:
            self._restore_chunk_pass(cargs)
            raise
        self._last = logits
        for i in disp:
            self._lens[i] += 1
            self._remaining[i] -= 1
        rec = {"out": out, "fn": "llm/step_mixed",
               "pairs": [(i, self._slots[i]) for i in disp],
               "refs": (bt_in, lens_in, last_in, active, key_in)
               + cargs["ops"],
               "pinned": self._pending_release}
        self._pending_release = []
        # chunk bookkeeping AFTER the record is cut: the finalize
        # epilogue's scatters dispatch behind this step, so their pins
        # must ride the NEXT fence (or the depth-1 barrier inside
        # _chunk_dispatched), never this record's
        self._chunk_dispatched(cargs, clast)
        self._record_mixed_pass(len(disp), cargs, t_step)
        return self._after_dispatch(rec, t_step)

    # -- self-speculative decoding (ISSUE 19) --------------------------------
    def _spec_instruments(self):
        """Speculation counters — None unless the spec gate is live AND
        observability records. ``bigdl.llm.spec.enabled`` off must
        leave no ``bigdl_llm_spec_*`` series (the disabled-mode
        absence contract)."""
        if not (self._spec_active and obs.enabled()):
            return None
        if self._spec_ins is None:
            self._spec_ins = {
                "proposed": obs.counter(
                    "bigdl_llm_spec_proposed_tokens_total",
                    "Draft tokens dispatched to speculative verify"),
                "accepted": obs.counter(
                    "bigdl_llm_spec_accepted_tokens_total",
                    "Draft tokens accepted by speculative verify"),
                "passes": obs.counter(
                    "bigdl_llm_spec_passes_total",
                    "Engine passes carrying a speculative verify "
                    "chunk"),
            }
        return self._spec_ins

    def _spec_proposer(self, i: int, req: Request):
        """Slot ``i``'s draft proposer, (re)created lazily per request
        — the adaptive-k state (acceptance EMA, live draft length) is
        the request's own, so a new occupant starts optimistic."""
        st = self._spec_state[i]
        if st is None or st["req"] is not req:
            st = self._spec_state[i] = {
                "req": req,
                "prop": self._spec_proposer_cls(
                    k=self._spec_k, min_match=self._spec_min_match,
                    backoff=self._spec_backoff)}
        return st["prop"]

    def _prepare_spec(self) -> Optional[dict]:
        """Pick one decode row whose token history predicts its future
        and draft for it. None = no row proposes this pass (or the
        ``llm.spec`` fault fired) — the pass degrades to plain decode,
        bit-identically.

        Two-phase on purpose: drafting needs the row's EXACT emitted
        history and length, which at depth > 1 are only current after
        the in-flight window drains — but draining costs the pipeline
        overlap. So a cheap pre-check proposes on the possibly-stale
        context first, and only a hit pays the drain (then re-proposes
        on the now-exact context). Zero-match rows keep full
        pipelining."""
        cand = None
        start = self._spec_rr % self.max_batch
        for i in (list(range(start, self.max_batch))
                  + list(range(start))):
            req = self._slots[i]
            if req is None or req.cancel_requested:
                continue
            if i in self._spec_pending or self._remaining[i] < 2:
                continue
            if self._chunk_state is not None and \
                    self._chunk_state[i] is not None:
                continue     # mid-prompt chunked admission: not a
                             # decode row yet
            prop = self._spec_proposer(i, req)
            ids = list(map(int, req.prompt_ids)) + \
                list(map(int, req.tokens))
            if prop.propose(ids, limit=int(self._remaining[i])):
                cand = i
                break
        if cand is None:
            return None
        # ISSUE 19 fault site: a ``raise`` between drafting and
        # dispatch drops the drafts on the floor — the pass runs as
        # plain decode, so outputs stay bit-identical (chaos_check
        # --spec proves it); a ``delay`` models a slow host proposer
        try:
            reliability.inject("llm.spec")
        except Exception:
            return None
        while self._inflight:
            self._drain_next()
        i = cand
        req = self._slots[i]
        if req is None or req.cancel_requested \
                or self._remaining[i] < 2 or i in self._spec_pending:
            return None       # the drain finished/cancelled the row
        prop = self._spec_proposer(i, req)
        ids = list(map(int, req.prompt_ids)) + \
            list(map(int, req.tokens))
        # the proposal's FIRST token is the proposer's guess at the
        # very next token — a position the compiled step fills with
        # the device-computed bonus token g0 instead (the host never
        # sees g0 before dispatch; see make_spec_step). The usable
        # drafts are the rest; emitted <= len(proposal) <= remaining.
        proposal = prop.propose(ids, limit=int(self._remaining[i]))
        drafts = proposal[1:]
        if not drafts:
            return None
        self._spec_rr = i + 1
        clen = len(drafts) + 1
        bucket = max(2, 1 << (clen - 1).bit_length())   # pow2 ladder
        pos0 = int(self._lens[i])
        end = pos0 + clen
        page = self._page
        p_have = -(-pos0 // page)
        return {"i": i, "req": req, "drafts": drafts, "clen": clen,
                "bucket": bucket, "pos0": pos0, "end": end,
                "p_have": p_have, "n_new": -(-end // page) - p_have,
                "match": prop.last_match}

    def _build_spec_step(self):
        """Compile the family's speculative verify step for ONE chunk
        bucket (the draft operand shape fixes it): the decode leg is
        the family sampled step VERBATIM, the verify leg the family
        ragged prefill VERBATIM (full logits) plus the fused accept —
        see ``kvcache.prefill.make_spec_step``. Row index, drafts,
        offsets and scatter targets are runtime data, so speculation
        adds O(k-buckets) programs total (guarded by the
        compile-recorder test in tests/test_spec_decode.py)."""
        cfg, page = self.cfg, self._page
        fam = self._fam_spec_step
        do_sample, top_k = self._do_sample, self.top_k

        def step(params, k_pages, v_pages, bt, lens, last, active,
                 temp, key, srow, ctoks, n_draft, cbt_row, cphys,
                 cslots):
            return fam(params, cfg, k_pages, v_pages, bt, lens, last,
                       active, temp, key, srow, ctoks, n_draft,
                       cbt_row, cphys, cslots, page=page,
                       do_sample=do_sample, top_k=top_k)

        return obs.compiled(step, name="llm/step_spec",
                            donate_argnums=(1, 2))

    def _dispatch_spec(self, disp, active, sargs: dict,
                       t_step: float) -> bool:
        """One speculative pass (the ISSUE 19 tentpole): every other
        active decode row advances one token while the chosen row's
        drafts run as a verify chunk — up to ``n_draft + 1`` tokens
        for that row through ONE fence. The drain applies the
        accepted prefix; rejected-tail K/V is rolled back by length
        bookkeeping alone (docs/KVCACHE.md)."""
        i, req = sargs["i"], sargs["req"]
        bucket, clen = sargs["bucket"], sargs["clen"]
        n_draft = clen - 1
        page = self._page
        bt_row = self._bt[i].copy()     # post-grant view: the pages
        # for [pos0, end) landed in the host table this pass
        pos = sargs["pos0"] + np.arange(bucket)
        phys = np.where(pos < sargs["end"],
                        bt_row[np.minimum(pos // page,
                                          self._pages_cap - 1)],
                        0).astype(np.int32)
        slots = (pos % page).astype(np.int32)
        toks = np.zeros((1, bucket), np.int32)
        toks[0, 1:clen] = sargs["drafts"]   # slot 0 = g0, set on
        # device inside the compiled step
        ops = (jnp.asarray(i, jnp.int32), jnp.asarray(toks),
               jnp.asarray(n_draft, jnp.int32), jnp.asarray(bt_row),
               jnp.asarray(phys), jnp.asarray(slots))
        ck = self._step_cache_key() + ("spec", bucket,
                                       self._do_sample, self.top_k)
        pspec = _PAGED_STEP_CACHE.get(ck)
        if pspec is None:
            pspec = _PAGED_STEP_CACHE[ck] = self._build_spec_step()
        bt_in, lens_in = self._bt_dev, self._lens_dev
        last_in, key_in = self._last, self._sample_key
        out, logits, self._k_pages, self._v_pages, self._lens_dev, \
            self._sample_key = pspec(
                self.model.params, self._k_pages, self._v_pages,
                bt_in, lens_in, last_in, active, self._temp, key_in,
                *ops)
        self._last = logits
        for j in disp:
            self._lens[j] += 1
            self._remaining[j] -= 1
        # the spec row's host advance happens at DRAIN — the accepted
        # length is data on the device — so it sits out dispatch until
        # its record retires
        self._spec_pending.add(i)
        self.spec_proposed_total += n_draft
        self.spec_passes += 1
        ins = self._spec_instruments()
        if ins is not None:
            ins["proposed"].inc(n_draft)
            ins["passes"].inc()
        if flight.enabled:
            # same site as the proposed counter: the chaos harness
            # reconciles draft events == counter == proposed_total
            flight.record(
                "draft", request_id=req.id, trace_id=_trace_of(req),
                slot=i, n_draft=n_draft, match_len=sargs["match"],
                offset=sargs["pos0"])
        wall = time.perf_counter() - t_step
        obs.add_complete("llm/spec_step", time.time() - wall, wall,
                         decode_rows=len(disp), n_draft=n_draft,
                         slot=i)
        rec = {"out": out, "fn": "llm/step_spec",
               "pairs": [(j, self._slots[j]) for j in disp],
               "spec": {"i": i, "req": req, "n_draft": n_draft,
                        "bucket": bucket},
               "refs": (bt_in, lens_in, last_in, active, key_in)
               + ops,
               "pinned": self._pending_release}
        self._pending_release = []
        return self._after_dispatch(rec, t_step)

    def _build_paged_decode(self):
        """One pipelined decode step over the page pool — the family's
        ``paged_decode_step_sampled`` jitted with donated pools:
        consumes the previous step's logits, samples on device, writes
        K/V, advances the device-resident lengths for active rows and
        returns the sampled ids with a fence element appended."""
        cfg = self.cfg
        page = self._page
        fam_sampled = self._fam_sampled_step
        do_sample, top_k = self._do_sample, self.top_k

        def step(params, k_pages, v_pages, bt, lens, last, active, temp,
                 key):
            return fam_sampled(params, cfg, k_pages, v_pages, bt, lens,
                               last, active, temp, key, page=page,
                               do_sample=do_sample, top_k=top_k)

        return obs.compiled(step, name="llm/decode_paged",
                            donate_argnums=(1, 2))

    def _record_decode(self, n_active: int, applied: int, host_s: float,
                       stall_s: float, finished: int,
                       cancelled: int = 0, fn: Optional[str] = None):
        """Per-step attribution (ISSUE 4 satellite): the old single wall
        number silently included the sync barrier and overstated device
        cost; host scheduling and the device-fence stall are now
        separate series (their sum is the host wall this step cost —
        device compute overlapped by the pipeline shows up in neither).
        ``applied`` counts only DELIVERED tokens — speculative rows
        (finished requests) decoded but discarded don't inflate the
        token counter."""
        if fn is not None:
            # live roofline attribution (ISSUE 16): the drain-fence
            # wall of this dispatch, no new device syncs — gated on
            # the flight switch inside observe()
            utilization.observe(fn, host_s + stall_s)
        ins = self._instruments()
        if ins is None:
            return
        wall = host_s + stall_s
        ins["decode_tokens"].inc(applied)
        ins["decode_seconds"].observe(wall)
        ins["decode_host"].observe(host_s)
        ins["decode_stall"].observe(stall_s)
        # the duration is already measured, so the span is appended
        # directly rather than re-bracketing the step with a context
        # manager
        obs.tracing.add_complete(
            "llm/decode_step", time.time() - wall, wall,
            active=n_active, step=self.steps,
            host_s=round(host_s, 6), stall_s=round(stall_s, 6))
        # live occupancy, not the drained record's pair count: a record
        # may carry speculative pairs for requests finished by an
        # earlier drain, which would leave a phantom nonzero gauge on
        # an idle server
        ins["active"].set(sum(r is not None for r in self._slots))
        if finished:
            ins["requests"].labels(reason="done").inc(finished)
        if cancelled:
            # aborted/watchdog-failed slots reaped this drain — counted
            # HERE only (ISSUE 7): abort() itself does not increment,
            # else every hedge loser would land twice
            ins["requests"].labels(reason="cancelled").inc(cancelled)
        self._record_kv_gauges(ins)

    def _emit_decode_span(self, req: Request):
        """One ``llm/decode`` span covering a finished request's whole
        decode phase, stitched under its trace — decode steps are shared
        by every active slot, so the per-request attribution has to be
        emitted per request, not per step."""
        if not req.trace or not req.decode_started_at:
            return
        args = {"trace": req.trace["trace_id"], "stage": "llm_server",
                "request": req.id, "tokens": len(req.tokens)}
        if req.trace.get("parent_span"):
            args["parent_span"] = req.trace["parent_span"]
        obs.add_complete("llm/decode", req.decode_started_at,
                         time.time() - req.decode_started_at, **args)

    def _dispatchable(self) -> List[int]:
        """Slots a new step should decode for: occupied AND with
        dispatch budget left. A request gets at most ``max_new_tokens``
        dispatched steps — so speculative dispatches past a data-
        dependent EOS never allocate pages beyond the admission
        reserve, and a slot whose final step is in flight goes quiet.
        A slot whose spec verify is in flight (ISSUE 19) also sits
        out: its host length advance is data-dependent (the accepted
        prefix), so the engine cannot place its next token until the
        record drains."""
        return [i for i, r in enumerate(self._slots)
                if r is not None and self._remaining[i] > 0
                and i not in self._spec_pending]

    def _after_dispatch(self, rec: dict, t0: float) -> bool:
        """Shared dispatch epilogue: account host time, push the record
        onto the in-flight window, drain down to the depth bound (depth
        1 drains immediately — the synchronous engine)."""
        rec["host_s"] = time.perf_counter() - t0
        self.host_seconds += rec["host_s"]
        self.steps += 1
        self._inflight.append(rec)
        ins = self._instruments()
        if ins is not None:
            ins["inflight"].set(len(self._inflight))
        while len(self._inflight) >= self.pipeline_depth:
            self._drain_next()
        return True

    def _drain_next(self):
        """Retire the oldest in-flight step: ONE device→host fetch of
        its (tokens ‖ fence) vector — the portable completion barrier —
        then EOS/max-token bookkeeping one step behind dispatch
        (mirroring the optimizer's ``_pending_loss`` drain). Slots whose
        request finished meanwhile discard their speculative token."""
        rec = self._inflight.popleft()
        t0 = time.perf_counter()
        vals = np.asarray(rec["out"])
        stall = time.perf_counter() - t0
        self.stall_seconds += stall
        # the fence proves every computation enqueued before this step —
        # including the updates rec["pinned"] was holding buffers for —
        # has retired; the references may drop now, and so may the page
        # refcounts held for finished requests' in-flight block tables
        rec["pinned"] = rec["refs"] = None
        for args in rec.pop("kv_release", ()):
            self._kv.release_slot(*args)
        finished = applied = cancelled = 0
        # one clock read per drain, shared by every slot's SLO stamps
        # (ISSUE 12): the tokens in this pass became host-visible at
        # the same fence fetch, so one arrival time is the honest one
        now = time.perf_counter() if self._slo is not None else 0.0
        for i, req in rec["pairs"]:
            if self._slots[i] is not req:
                continue   # speculative token for a finished request
            if req.cancel_requested:
                # aborted mid-decode (hedge loser, watchdog, client
                # gone): release the slot and its pages now — the
                # drained token is discarded like any speculative one.
                # Not SLO-classified: an abort is the caller's choice,
                # not a latency verdict.
                self._finish_slot(i, req)
                cancelled += 1
                continue
            tok = int(vals[i])
            applied += 1
            if self._apply_token(i, req, tok, now):
                finished += 1
        sp = rec.get("spec")
        if sp is not None:
            i, req = sp["i"], sp["req"]
            self._spec_pending.discard(i)
            if self._slots[i] is not req:
                pass     # slot reassigned under us: nothing to apply
            elif req.cancel_requested:
                self._finish_slot(i, req)
                cancelled += 1
            else:
                # the accepted-length vector: [B decode ids][n_acc]
                # [bucket chunk toks][fence]. The host learns BOTH the
                # bonus token g0 (device-computed, never seen before)
                # and how many drafts survived from this one fetch.
                n_acc = int(vals[self.max_batch])
                self._lens[i] += n_acc       # device twin advanced in
                self._remaining[i] -= n_acc  # the compiled step
                st = self._spec_state[i] if self._spec_state else None
                if st is not None:
                    st["prop"].observe(sp["n_draft"], n_acc - 1)
                self.spec_accepted_total += n_acc - 1
                self.spec_emitted_total += n_acc
                ins_s = self._spec_instruments()
                if ins_s is not None:
                    ins_s["accepted"].inc(n_acc - 1)
                if flight.enabled:
                    kind = ("verify_accept"
                            if n_acc - 1 == sp["n_draft"]
                            else "verify_reject")
                    flight.record(
                        kind, request_id=req.id,
                        trace_id=_trace_of(req), slot=i,
                        n_draft=sp["n_draft"], accepted=n_acc - 1,
                        emitted=n_acc)
                base = self.max_batch + 1
                for j in range(n_acc):
                    applied += 1
                    if self._apply_token(i, req,
                                         int(vals[base + j]), now):
                        finished += 1
                        break
        if (finished or cancelled) and self.pipeline_depth == 1:
            # strict synchrony at depth 1: the freed-row resets above
            # must resolve before their consumed buffers drop (exactly
            # the old engine's per-step barrier cadence)
            if self.paged:
                _sync_barrier(self._bt_dev, self._lens_dev)
            else:
                _sync_barrier(self._pos_dev)
            self._pending_release.clear()
        ins = self._instruments()
        if ins is not None:
            ins["inflight"].set(len(self._inflight))
        self._record_decode(len(rec["pairs"]), applied,
                            rec.get("host_s", 0.0), stall, finished,
                            cancelled, fn=rec.get("fn"))

    def _apply_token(self, i: int, req: Request, tok: int,
                     now: float) -> bool:
        """Append one drained token to ``req`` with the SLO/TTFT
        stamps, finishing the slot on EOS or budget exhaustion.
        Returns True when the request finished — the shared tail of
        the plain decode drain and the speculative accepted-prefix
        drain (ISSUE 19), which applies up to k+1 tokens per pass
        through this same path so EOS semantics cannot diverge."""
        req.tokens.append(tok)
        if self._slo is not None:
            req.t_tokens.append(now)
        if len(req.tokens) == 1:
            req.t_first_token = time.perf_counter()  # TTFT stamp
            if self._slo is not None:
                self._slo.observe_ttft(now - req.t_submit)
                req.t_last_token = now
        elif self._slo is not None:
            gap = now - req.t_last_token
            req.t_last_token = now
            if gap > req.itl_max:
                req.itl_max = gap
            self._slo.observe_itl(gap)
        if (self.eos_token_id is not None
                and tok == self.eos_token_id) \
                or len(req.tokens) >= req.max_new_tokens:
            self._finish_slot(i, req)
            if self._slo is not None:
                self._slo.finish(
                    (req.t_first_token - req.t_submit
                     if req.t_first_token else None),
                    req.itl_max if req.itl_max >= 0 else None)
            return True
        return False

    def _finish_slot(self, i: int, req: Request):
        self._emit_decode_span(req)
        if flight.enabled:
            flight.record(
                "finish", request_id=req.id, trace_id=_trace_of(req),
                tokens=len(req.tokens),
                cancelled=req.cancel_requested or None,
                ttft_ms=(round((req.t_first_token - req.t_submit)
                               * 1000.0, 3)
                         if req.t_first_token else None))
        req.done.set()
        self._slots[i] = None
        self._remaining[i] = 0
        if self._spec_state is not None:
            self._spec_state[i] = None     # proposer state is per
            # request — the next occupant starts fresh
        if self.paged:
            adm = self._slot_adm[i]
            owned = self._slot_pages[i]
            adopted = adm.shared_pages if adm is not None else []
            charge = adm.charge if adm is not None else 0
            if self._kv.enabled:
                # keep the chain warm (ISSUE 5): index the full pages of
                # prompt+output plus the partial tail, THEN drop this
                # request's refs — indexed pages survive at refcount 1
                # (evictable), unindexed ones free immediately
                toks = list(map(int, req.prompt_ids)) + \
                    list(map(int, req.tokens))
                self._kv.insert(toks,
                                self._bt[i, :-(-len(toks) // self._page)])
            self._slot_pages[i] = []
            self._slot_adm[i] = None
            if self._kv.enabled and self._inflight:
                # pinned pages hold refcounts (the PR 4 buffer-pinning
                # invariant extended): in-flight speculative steps still
                # read these pages through their device block tables, so
                # the decrefs run at the newest in-flight step's fence
                self._inflight[-1].setdefault("kv_release", []).append(
                    (charge, owned, adopted))
            else:
                self._kv.release_slot(charge, owned, adopted)
            self._bt[i, :] = 0    # orphaned rows must point at trash:
            self._lens[i] = 0     # a stale id could alias a reissued
            # page and the inactive row's dummy write would clobber it
            self._pin(self._bt_dev, self._lens_dev)
            self._bt_dev = self._bt_dev.at[i].set(0)
            self._lens_dev = self._lens_dev.at[i].set(0)
        else:
            # freed slot restarts at position 0: stale kv beyond the
            # next request's own positions is masked by the causal
            # valid test and overwritten as it advances
            self._pos[i] = 0
            self._pin(self._pos_dev)
            self._pos_dev = self._pos_dev.at[i].set(0)

    # -- lossless preemption (ISSUE 17) --------------------------------------
    def _consider_preempt(self):
        """A higher-class request is waiting and the admission sweep
        could not seat it: evict the worst strictly-lower-class decode,
        losslessly. At most one preemption per in-flight window — the
        victim's pages only return at the newest fence, so a second
        victim before that drains could not seat the waiter either."""
        rec = self._preempt_rec
        if rec is not None and any(r is rec for r in self._inflight):
            return
        self._preempt_rec = None
        best = self._sched.best_rank()
        if best is None:
            return
        victim = None
        for i in range(self.max_batch):
            req = self._slots[i]
            if req is None or req.done.is_set() or req.cancel_requested:
                continue
            if self._chunk_state is not None and \
                    self._chunk_state[i] is not None:
                continue     # mid-prompt chunked admission: no usable
                             # chain yet, rollback (not preempt) owns it
            if self._remaining[i] <= 0:
                continue     # budget exhausted: finishing at the next
                             # drain anyway, eviction would save nothing
            if i in self._spec_pending:
                continue     # spec verify in flight: the row's length
                             # advance is data-dependent, park/export
                             # bookkeeping would race the drain
            rank = _PRIORITY_RANK[req.priority]
            if rank <= best:
                continue     # only a STRICTLY lower class is evicted
            key = (rank, -len(req.tokens), i)
            if victim is None or key > victim[0]:
                # worst class first; among equals the youngest decode
                # (fewest tokens to re-prefill at resume)
                victim = (key, i)
        if victim is not None:
            self._preempt_slot(victim[1])

    def _preempt_slot(self, i: int):
        """Losslessly evict the decode in slot ``i`` (ISSUE 17): park
        its KV chain (radix index + optional host-tier handoff blob),
        free the slot and pages at the in-flight fence exactly like
        ``_finish_slot``, and re-queue the request journal-style as
        ``prompt + generated_so_far`` with its remaining budget. Greedy
        decode over the extended prompt is deterministic, so the resume
        — with or without a surviving cached chain — continues
        bit-identical to the unpreempted run; the chain only decides
        how much prefill the resume pays, never what it generates."""
        reliability.inject("llm.preempt")
        req = self._slots[i]
        t0 = time.perf_counter()
        with obs.span("llm/preempt", slot=i, stage="llm_server",
                      request=req.id, victim_class=req.priority,
                      tokens_done=len(req.tokens)):
            adm = self._slot_adm[i]
            owned = self._slot_pages[i]
            adopted = adm.shared_pages if adm is not None else []
            charge = adm.charge if adm is not None else 0
            toks = list(map(int, req.prompt_ids)) + \
                list(map(int, req.tokens))
            mode = "dropped"
            if self._kv.enabled:
                # park index-only: the chain survives at refcount 1
                # (evictable) and the resume admission re-adopts it as
                # an ordinary radix hit. In-flight speculative writes
                # land PAST the indexed length — harmless, the same
                # argument _finish_slot relies on.
                self._kv.insert(toks,
                                self._bt[i, :-(-len(toks)
                                               // self._page)])
                mode = "indexed"
                if self._tier is not None:
                    # belt and braces: a handoff blob pins the chain
                    # against radix eviction under pool pressure, and
                    # the router journal can resume on ANOTHER worker
                    # by importing it (the PR 6 disaggregation path)
                    try:
                        self._parked[req.id] = \
                            self._export_chain_locked(toks)
                        mode = "exported"
                    except Exception:
                        pass   # export is an optimization, not a
                               # correctness dependency: resume
                               # re-prefills whatever is missing
            self._slots[i] = None
            self._remaining[i] = 0
            self._slot_pages[i] = []
            self._slot_adm[i] = None
            if self._kv.enabled and self._inflight:
                # the fence-deferred release walk, exactly as
                # _finish_slot: in-flight speculative steps still read
                # these pages through their device block tables
                self._inflight[-1].setdefault("kv_release", []).append(
                    (charge, owned, adopted))
            else:
                self._kv.release_slot(charge, owned, adopted)
            self._bt[i, :] = 0
            self._lens[i] = 0
            self._pin(self._bt_dev, self._lens_dev)
            self._bt_dev = self._bt_dev.at[i].set(0)
            self._lens_dev = self._lens_dev.at[i].set(0)
            # journal-style re-queue: resume = prompt + generated, with
            # the remaining budget; the hold record keeps the request
            # out of a slot until its old steps' fences drain (a
            # same-slot re-admission could absorb a stale speculative
            # token through the drain's identity check)
            req.resume_ids = np.asarray(toks, np.int32)
            req.preemptions += 1
            req._hold_rec = self._inflight[-1] if self._inflight \
                else None
            self._preempt_rec = req._hold_rec
            self.preemptions_total += 1
            self._sched.push(req)
        pri = self._priority_instruments_get()
        if pri is not None:
            pri["preemptions"].labels(**{"class": req.priority}).inc()
        if flight.enabled:
            # same site as the counter: the chaos harness reconciles
            # flight preempt events == counter == preemptions_total
            flight.record(
                "preempt", request_id=req.id, trace_id=_trace_of(req),
                slot=i, priority=req.priority, mode=mode,
                tokens_done=len(req.tokens),
                remaining=self._budget_of(req),
                wall_ms=round((time.perf_counter() - t0) * 1000.0, 3))

    def _step_paged(self) -> bool:
        ci = self._chunk_slot()
        disp = self._dispatchable()
        if not disp and ci is None:
            if self._inflight:   # nothing new to dispatch: keep draining
                self._drain_next()
                return True
            return False
        t_step = time.perf_counter()
        cargs = None
        if ci is not None:
            # unified dispatch (ISSUE 14): this pass carries one
            # prefill chunk — fused with the decode rows when any are
            # live, solo through the ragged-prefill program otherwise.
            # None = the chunk faulted (request already failed) or is
            # budget-stalled (decode continues; the chunk retries)
            cargs = self._prepare_chunk(ci)
        if cargs is None and not disp:
            if self._inflight:
                self._drain_next()
                return True
            return False
        if cargs is not None and not disp:
            self._dispatch_chunk_solo(cargs, t_step)
            return True
        sargs = None
        if cargs is None and ci is None and self._spec_active:
            # self-speculative pass (ISSUE 19): a pass carries EITHER
            # a prefill chunk OR one row's verify chunk (chunked
            # admissions keep priority — TTFT over throughput)
            sargs = self._prepare_spec()
            # _prepare_spec may drain the whole in-flight window, and
            # rows can finish or free at those fences: recompute the
            # decode set either way (minus the verify row — its
            # advance is the chunk's, not the decode leg's)
            si = sargs["i"] if sargs is not None else -1
            disp = [j for j in self._dispatchable() if j != si]
            if sargs is None and not disp:
                if self._inflight:
                    self._drain_next()
                return True
        page = self._page
        # the page for position lens[i] must exist before the step; the
        # grant is an incremental scatter into the device-resident block
        # table, not a re-upload (ISSUE 4). Under the prefix cache the
        # free list may be held by warm chains — pre-evict for ALL the
        # grants this step needs BEFORE mutating any table, so an
        # injected kvcache.evict raise is cleanly retryable. With a
        # chunk prepared, a raise here must also restore the chunk's
        # alloc/charge, or the retried pass re-prepares on top of
        # orphaned pages.
        try:
            boundary = sum(1 for i in disp
                           if int(self._lens[i]) % page == 0)
            need = boundary + (sargs["n_new"] if sargs is not None
                               else 0)
            if need:
                self._kv.ensure_free(need)
            allocs = []
            for i in disp:
                pos = int(self._lens[i])
                if pos % page == 0:
                    pid = self._kv.take_free()  # guaranteed by reserve
                    self._bt[i, pos // page] = pid
                    self._slot_pages[i].append(pid)
                    allocs.append((i, pos // page, pid))
            if sargs is not None:
                # verify-chunk pages (ISSUE 19): every page covering
                # [pos0, pos0 + clen) that the row does not own yet —
                # within the admission worst-case charge (clen <=
                # remaining), so no extra ledger traffic; a fully
                # rejected tail leaves them as the row's ordinary
                # decode pages for later positions
                si = sargs["i"]
                for j in range(sargs["n_new"]):
                    pid = self._kv.take_free()
                    col = sargs["p_have"] + j
                    self._bt[si, col] = pid
                    self._slot_pages[si].append(pid)
                    allocs.append((si, col, pid))
        except BaseException:
            if cargs is not None:
                self._restore_chunk_pass(cargs)
            raise
        if allocs:
            rows, cols, vals = (np.asarray(v, np.int32)
                                for v in zip(*allocs))
            vals_d = jnp.asarray(vals)
            self._pin(self._bt_dev, vals_d)
            self._bt_dev = self._bt_dev.at[rows, cols].set(vals_d)
        mask = np.zeros(self.max_batch, bool)
        mask[disp] = True
        active = jnp.asarray(mask)
        if sargs is not None:
            return self._dispatch_spec(disp, active, sargs, t_step)
        if cargs is not None:
            return self._dispatch_mixed(disp, active, cargs, t_step)
        if self._mixed_active:
            # pure-decode pass on a unified server: the batch-mix
            # series still tell the whole story
            mins = self._mixed_instruments()
            if mins is not None:
                mins["pass_rows"].labels(kind="decode").inc(len(disp))
                mins["mix"].set(1.0)
        key = self._step_cache_key() + ("decode", self._do_sample,
                                        self.top_k)
        pdecode = _PAGED_STEP_CACHE.get(key)
        if pdecode is None:
            pdecode = _PAGED_STEP_CACHE[key] = self._build_paged_decode()
        bt_in, lens_in = self._bt_dev, self._lens_dev
        last_in, key_in = self._last, self._sample_key
        out, logits, self._k_pages, self._v_pages, self._lens_dev, \
            self._sample_key = pdecode(
                self.model.params, self._k_pages, self._v_pages, bt_in,
                lens_in, last_in, active, self._temp, key_in)
        self._last = logits
        for i in disp:
            self._lens[i] += 1
            self._remaining[i] -= 1
        rec = {"out": out, "fn": "llm/decode_paged",
               "pairs": [(i, self._slots[i]) for i in disp],
               "refs": (bt_in, lens_in, last_in, active, key_in),
               "pinned": self._pending_release}
        self._pending_release = []
        return self._after_dispatch(rec, t_step)

    def _step_slotted(self):
        """One pipelined decode step of the slot-static (paged=False)
        engine: same dispatch/drain structure as the paged path, with
        the per-slot position vector device-resident and advanced inside
        the compiled step."""
        disp = self._dispatchable()
        if not disp:
            if self._inflight:
                self._drain_next()
                return True
            return False
        t_step = time.perf_counter()
        step = self._slotted_step()
        mask = np.zeros(self.max_batch, bool)
        mask[disp] = True
        active = jnp.asarray(mask)
        k_in, v_in = self._cache["k"], self._cache["v"]
        pos_in, last_in, key_in = (self._pos_dev, self._last,
                                   self._sample_key)
        out, logits, k_new, v_new, self._pos_dev, self._sample_key = \
            step(self.model.params, k_in, v_in, pos_in, last_in, active,
                 self._temp, key_in)
        old = self._cache
        self._cache = {"k": k_new, "v": v_new, "pos": old["pos"]}
        self._last = logits
        for i in disp:
            self._pos[i] += 1
            self._remaining[i] -= 1
        # the old cache is NOT donated on this legacy path: it is an
        # input of the in-flight step and must be pinned until its fence
        rec = {"out": out, "fn": "llm/decode_slotted",
               "pairs": [(i, self._slots[i]) for i in disp],
               "refs": (k_in, v_in, pos_in, last_in, active, key_in),
               "pinned": self._pending_release}
        self._pending_release = []
        del old
        return self._after_dispatch(rec, t_step)

    def _slotted_step(self):
        """Build (once) the compiled slot-static decode step: on-device
        sampling from the previous logits, per-slot kv scatter at each
        row's own position, device position advance for active rows, and
        the fence element on the token vector."""
        if hasattr(self, "_scatter_step"):
            return self._scatter_step
        from bigdl_tpu.llm.kernels.sampling import (fence_token,
                                                    sample_tokens)
        from bigdl_tpu.llm.models.llama import (_attention, _linear,
                                                attention_qkv, mlp,
                                                rms_norm, rope_cfg)
        cfg = self.cfg
        do_sample, top_k = self._do_sample, self.top_k

        def step(params, cache_k, cache_v, pos_vec, last, active, temp,
                 key):
            key, sub = jax.random.split(key)
            toks = sample_tokens(last, sub, do_sample=do_sample,
                                 temperature=temp, top_k=top_k)
            x = params["embed_tokens"][toks][:, None]         # (B,1,H)
            b = x.shape[0]
            s_max = cache_k.shape[2]
            positions = pos_vec[:, None].astype(jnp.int32)    # (B, 1)
            valid = (jnp.arange(s_max)[None, :]
                     <= positions[:, 0][:, None])             # (B, S)

            def layer_step(carry, inputs):
                x, = carry
                lp, k_cache, v_cache = inputs
                h = rms_norm(x, lp["input_layernorm"],
                             cfg.rms_norm_eps)
                q, k, v = attention_qkv(lp, h, cfg)
                q = rope_cfg(q, positions, cfg)
                k = rope_cfg(k, positions, cfg)
                # scatter each slot's kv at ITS position
                onehot = (jnp.arange(s_max)[None, :]
                          == positions[:, 0][:, None])        # (B, S)
                k_cache = jnp.where(
                    onehot[:, :, None, None],
                    k.astype(k_cache.dtype), k_cache)
                v_cache = jnp.where(
                    onehot[:, :, None, None],
                    v.astype(v_cache.dtype), v_cache)
                attn = _attention(q, k_cache, v_cache, positions,
                                  valid, cfg)
                x = x + _linear(lp["o_proj"], attn)
                h2 = rms_norm(x, lp["post_attention_layernorm"],
                              cfg.rms_norm_eps)
                if cfg.num_experts:
                    from bigdl_tpu.llm.models.llama import _moe_ffn
                    x = x + _moe_ffn(lp, h2, cfg)
                else:
                    x = x + mlp(lp, h2, x.dtype)
                return (x,), (k_cache, v_cache)

            (x,), (k_new, v_new) = jax.lax.scan(
                layer_step, (x,),
                (params["layers"], cache_k, cache_v))
            x = rms_norm(x, params["norm"], cfg.rms_norm_eps)
            head = params.get("lm_head")
            if head is None:
                logits = x @ params["embed_tokens"].T.astype(x.dtype)
            else:
                logits = _linear(head, x)
            logits = logits[:, 0].astype(jnp.float32)
            new_pos = pos_vec + active.astype(pos_vec.dtype)
            out = jnp.concatenate(
                [toks, fence_token(k_new, v_new, logits)])
            return out, logits, k_new, v_new, new_pos, key

        # donate the cache like the paged pools: at depth > 1 each
        # in-flight record would otherwise pin a full (L,B,S,H,D) cache
        # generation until its fence — donation lets the runtime alias
        # generations in place (the records still hold the refs for
        # backends that decline donation; a donated ref holds no HBM)
        self._scatter_step = obs.compiled(step,
                                          name="llm/decode_slotted",
                                          donate_argnums=(1, 2))
        return self._scatter_step

    def _step(self):
        """Decode one token for every active slot."""
        reliability.inject("llm.step")
        # ISSUE 7 fault site: a ``delay`` rule here wedges the engine
        # thread inside its locked pass — exactly what a hung device
        # step looks like to the watchdog (a ``raise`` is just another
        # failing step for the resilient loop). Gated on live slots so
        # idle passes don't burn a seeded plan's bounded stall events
        # before any request is actually mid-step.
        if any(r is not None for r in self._slots):
            reliability.inject("worker.stall")
        if self.paged:
            return self._step_paged()
        return self._step_slotted()

    def _loop(self):
        backoff = reliability.RetryPolicy(max_attempts=1 << 30,
                                          base_delay=0.005, max_delay=0.5)
        delays = None
        while not self._stop.is_set():
            self._hb = time.monotonic()   # watchdog heartbeat: stale =
            try:                          # wedged INSIDE this pass
                with self._lock:
                    self._admit()
                    busy = self._step()
            except Exception as e:  # noqa: BLE001 — the engine thread
                # must survive a failing step (injected or real): count,
                # back off, keep decoding the surviving slots
                from bigdl_tpu.reliability.policies import _count
                _count("bigdl_reliability_retries_total",
                       "Retries performed under a RetryPolicy",
                       component="llm_server")
                if delays is None:
                    delays = backoff.delays()
                time.sleep(next(delays, 0.5))
                continue
            delays = None   # healthy pass resets the backoff
            if not busy:
                time.sleep(0.002)

"""LLM serving worker — continuous-batching generation service.

Reference: ``P:llm/serving`` (the bigdl-llm FastChat model worker and the
later vLLM integration, SURVEY.md §2.8 llm serving/tools row). The
reference wraps its CPU models behind FastChat's worker API; the analog
here is a TPU-shaped **continuous batching** loop:

- requests enter a queue at any time (``submit`` returns a handle);
- the scheduler packs up to ``max_batch`` active sequences into fixed
  batch slots (static shapes: one compiled decode step serves every
  composition of active requests);
- each engine step decodes ONE token for every active slot via the
  fused scan step (llm.models.llama.forward under jit, donated cache);
  finished sequences (EOS or max_tokens) free their slot immediately and
  a queued request takes it over — per-slot prefill writes its prompt
  into the shared cache at the slot's rows (the "continuous" part:
  no waiting for the whole batch to drain, the vLLM scheduling idea on
  a slot-static cache);
- results stream out through the handle (``get()`` blocks; ``tokens``
  grows as the loop runs).

Single-process and thread-driven: the engine loop runs on a background
thread like ClusterServing's job loop; the reference's HTTP surface is a
deployment shim over exactly this object.
"""

from __future__ import annotations

import functools
import queue
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np


def _sync_barrier(*arrays):
    """Bound the in-flight computations producing ``arrays``.

    ``jax.block_until_ready`` alone is NOT reliable on every runtime
    (the axon-tunneled TPU runtime returns early from it); the only
    portable barrier is a real device-to-host fetch, so we pull one
    element of every array in a single tiny transfer. The engine is
    already host-synchronous once per token (the argmax fetch), so this
    adds one small dispatch per step, not a new synchronization regime.
    """
    jax.block_until_ready(arrays)
    np.asarray(jnp.stack([a.ravel()[0].astype(jnp.float32)
                          for a in arrays]))


class Request:
    """Handle returned by :meth:`LLMServer.submit`."""

    def __init__(self, prompt_ids: np.ndarray, max_new_tokens: int):
        self.id = str(uuid.uuid4())
        self.prompt_ids = np.asarray(prompt_ids, np.int32).ravel()
        self.max_new_tokens = max_new_tokens
        self.tokens: List[int] = []
        self.done = threading.Event()

    def get(self, timeout: Optional[float] = None) -> List[int]:
        if not self.done.wait(timeout):
            raise TimeoutError(f"request {self.id} still running")
        return list(self.tokens)


class LLMServer:
    """Continuous-batching engine over a Llama-family model.

    ``model`` is a LlamaForCausalLM (quantized or dense). ``max_batch``
    fixes the compiled batch width; ``max_seq_len`` the per-slot cache
    window.
    """

    def __init__(self, model, max_batch: int = 4, max_seq_len: int = 256,
                 eos_token_id: Optional[int] = None):
        from bigdl_tpu.llm.models.llama import forward, init_cache

        self.model = model
        self.cfg = model.config
        self.max_batch = max_batch
        self.max_seq_len = min(max_seq_len, model.max_cache_len)
        self.eos_token_id = eos_token_id
        self._queue: "queue.Queue[Request]" = queue.Queue()
        self._slots: List[Optional[Request]] = [None] * max_batch
        self._remaining = np.zeros(max_batch, np.int64)
        self._cache = init_cache(self.cfg, max_batch, self.max_seq_len,
                                 dtype=model.cache_dtype)
        # per-slot write positions (the shared scalar cache["pos"] is
        # replaced by a vector so slots advance independently)
        self._pos = np.zeros(max_batch, np.int32)
        self._last = jnp.zeros((max_batch, self.cfg.vocab_size),
                               jnp.float32)
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._fwd = jax.jit(functools.partial(forward, cfg=self.cfg))
        self._thread: Optional[threading.Thread] = None
        self.steps = 0

    # -- client API ----------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int = 32) -> Request:
        req = Request(prompt_ids, max_new_tokens)
        if len(req.prompt_ids) + max_new_tokens > self.max_seq_len:
            raise ValueError("prompt + max_new_tokens exceeds max_seq_len")
        self._queue.put(req)
        return req

    def start(self) -> "LLMServer":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=30)

    # -- engine --------------------------------------------------------------
    def _admit(self):
        """Fill free slots from the queue; per-slot prefill."""
        for i in range(self.max_batch):
            if self._slots[i] is not None:
                continue
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                return
            self._prefill_slot(i, req)

    def _prefill_slot(self, i: int, req: Request):
        """Run the prompt through the model writing kv at slot i only.

        Implementation detail: forward() operates on the whole batch, so
        the prompt is broadcast into a (max_batch, T) token block but
        only slot i's cache rows are kept (the other slots' K/V pages
        are restored from the pre-call cache) — one compiled shape per
        prompt length, fully static."""
        t = len(req.prompt_ids)
        toks = jnp.asarray(
            np.broadcast_to(req.prompt_ids, (self.max_batch, t)))
        start = int(self._pos[i])
        positions = jnp.broadcast_to(jnp.arange(start, start + t),
                                     (self.max_batch, t))
        cache_in = dict(self._cache)
        cache_in["pos"] = jnp.asarray(start, jnp.int32)
        logits, new_cache = self._fwd(self.model.params, tokens=toks,
                                      cache=cache_in, positions=positions)
        row = jnp.arange(self.max_batch) == i
        keep = row[None, :, None, None, None]
        old = self._cache
        self._cache = {
            "k": jnp.where(keep, new_cache["k"], old["k"]),
            "v": jnp.where(keep, new_cache["v"], old["v"]),
            "pos": old["pos"],
        }
        self._last = self._last.at[i].set(logits[i, -1])
        # RACE FIX (round 4): synchronize before the old cache buffers are
        # released. Under jax's async dispatch, dropping the previous
        # cache while the step consuming it is still in flight lets the
        # runtime recycle those buffers for CONCURRENT jax computations on
        # other threads (e.g. another serving loop or test traffic), and
        # the in-flight step then reads overwritten memory. Reproduced:
        # 14/30 greedy-parity mismatches with 4 hammer threads; 0/30 with
        # this barrier (see the stress test in tests/test_llm_serving.py).
        _sync_barrier(self._cache["k"], self._cache["v"], self._last)
        del old
        self._pos[i] = start + t
        self._slots[i] = req
        self._remaining[i] = req.max_new_tokens

    def _step(self):
        """Decode one token for every active slot."""
        active = [i for i, r in enumerate(self._slots) if r is not None]
        if not active:
            return False
        nxt = np.asarray(jnp.argmax(self._last, axis=-1), np.int32)
        toks = jnp.asarray(nxt[:, None])
        positions = jnp.asarray(self._pos[:, None])
        # per-slot positions: slot rows beyond their own pos are masked
        # by the causal test (slot_index <= q_position) in attention;
        # the cache update slices at pos 0..1 would collide — use
        # scatter per slot
        logits, new_cache = self._decode_scatter(toks, positions)
        for i in active:
            tok = int(nxt[i])
            req = self._slots[i]
            req.tokens.append(tok)
            self._remaining[i] -= 1
            self._pos[i] += 1
            if (self.eos_token_id is not None and tok == self.eos_token_id) \
                    or self._remaining[i] <= 0:
                req.done.set()
                self._slots[i] = None
                # freed slot restarts at position 0: stale kv beyond the
                # next request's own positions is masked by the causal
                # valid test and overwritten as it advances
                self._pos[i] = 0
        self._last = logits
        self.steps += 1
        return True

    def _decode_scatter(self, toks, positions):
        """One decode step writing each slot's kv at its own position."""
        if not hasattr(self, "_scatter_step"):
            from bigdl_tpu.llm.models.llama import (_attention, _linear,
                                                    rms_norm, rope)
            cfg = self.cfg

            def step(params, cache_k, cache_v, pos_vec, toks, last_mask):
                x = params["embed_tokens"][toks[:, 0]][:, None]   # (B,1,H)
                b = x.shape[0]
                s_max = cache_k.shape[2]
                positions = pos_vec                               # (B, 1)
                valid = (jnp.arange(s_max)[None, :]
                         <= positions[:, 0][:, None])             # (B, S)

                def layer_step(carry, inputs):
                    x, = carry
                    lp, k_cache, v_cache = inputs
                    h = rms_norm(x, lp["input_layernorm"],
                                 cfg.rms_norm_eps)
                    q = _linear(lp["q_proj"], h).reshape(
                        b, 1, cfg.num_attention_heads, cfg.head_dim)
                    k = _linear(lp["k_proj"], h).reshape(
                        b, 1, cfg.num_key_value_heads, cfg.head_dim)
                    v = _linear(lp["v_proj"], h).reshape(
                        b, 1, cfg.num_key_value_heads, cfg.head_dim)
                    q = rope(q, positions, cfg.rope_theta)
                    k = rope(k, positions, cfg.rope_theta)
                    # scatter each slot's kv at ITS position
                    onehot = (jnp.arange(s_max)[None, :]
                              == positions[:, 0][:, None])        # (B, S)
                    k_cache = jnp.where(
                        onehot[:, :, None, None],
                        k.astype(k_cache.dtype), k_cache)
                    v_cache = jnp.where(
                        onehot[:, :, None, None],
                        v.astype(v_cache.dtype), v_cache)
                    attn = _attention(q, k_cache, v_cache, positions,
                                      valid, cfg)
                    x = x + _linear(lp["o_proj"], attn)
                    h2 = rms_norm(x, lp["post_attention_layernorm"],
                                  cfg.rms_norm_eps)
                    if cfg.num_experts:
                        from bigdl_tpu.llm.models.llama import _moe_ffn
                        x = x + _moe_ffn(lp, h2, cfg)
                    else:
                        gate = jax.nn.silu(_linear(
                            lp["gate_proj"], h2).astype(jnp.float32))
                        up = _linear(lp["up_proj"], h2) \
                            .astype(jnp.float32)
                        x = x + _linear(lp["down_proj"],
                                        (gate * up).astype(x.dtype))
                    return (x,), (k_cache, v_cache)

                (x,), (k_new, v_new) = jax.lax.scan(
                    layer_step, (x,),
                    (params["layers"], cache_k, cache_v))
                x = rms_norm(x, params["norm"], cfg.rms_norm_eps)
                head = params.get("lm_head")
                if head is None:
                    logits = x @ params["embed_tokens"].T.astype(x.dtype)
                else:
                    logits = _linear(head, x)
                return logits[:, 0].astype(jnp.float32), k_new, v_new

            self._scatter_step = jax.jit(step)

        logits, k_new, v_new = self._scatter_step(
            self.model.params, self._cache["k"], self._cache["v"],
            positions, toks, None)
        old = self._cache
        self._cache = {"k": k_new, "v": v_new, "pos": old["pos"]}
        # same async-dispatch buffer-lifetime barrier as _prefill_slot
        _sync_barrier(k_new, v_new, logits)
        del old
        return logits, None

    def _loop(self):
        while not self._stop.is_set():
            with self._lock:
                self._admit()
                busy = self._step()
            if not busy:
                time.sleep(0.002)

"""LangChain integration (ref: P:llm/langchain — LLM + Embeddings
wrappers over the ggml models).

langchain isn't a baked-in dependency; the classes duck-type the
``langchain_core`` interfaces (``invoke``/``_call``, ``embed_documents``/
``embed_query``) so they drop into chains when langchain is installed and
stay usable standalone when it isn't."""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np


class BigdlTpuLLM:
    """ref: BigdlLLM / LlamaLLM — text-in/text-out over a converted model."""

    def __init__(self, model_path: str, tokenizer=None,
                 max_new_tokens: int = 64, temperature: float = 0.0,
                 ctx_size: int = 512):
        from bigdl_tpu.llm.convert_model import load_model

        self.model = load_model(model_path, max_cache_len=ctx_size)
        self.tokenizer = tokenizer
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature

    @classmethod
    def from_model(cls, model, tokenizer=None, **kwargs) -> "BigdlTpuLLM":
        self = cls.__new__(cls)
        self.model = model
        self.tokenizer = tokenizer
        self.max_new_tokens = kwargs.get("max_new_tokens", 64)
        self.temperature = kwargs.get("temperature", 0.0)
        return self

    # langchain LLM protocol
    @property
    def _llm_type(self) -> str:
        return "bigdl_tpu"

    def _encode(self, text: str) -> np.ndarray:
        if self.tokenizer is not None:
            return np.asarray([self.tokenizer.encode(text)], np.int32)
        return np.asarray([[b % 256 for b in text.encode()]], np.int32)

    def _decode(self, ids) -> str:
        if self.tokenizer is not None:
            return self.tokenizer.decode(list(ids),
                                         skip_special_tokens=True)
        return bytes(int(i) % 256 for i in ids).decode(errors="replace")

    def _call(self, prompt: str, stop: Optional[List[str]] = None,
              **kwargs: Any) -> str:
        ids = self._encode(prompt)
        out = self.model.generate(
            ids, max_new_tokens=self.max_new_tokens,
            do_sample=self.temperature > 0,
            temperature=max(self.temperature, 1e-6))
        text = self._decode(out[0, ids.shape[1]:])
        if stop:
            for s in stop:
                cut = text.find(s)
                if cut >= 0:
                    text = text[:cut]
        return text

    invoke = _call
    __call__ = _call


class BigdlTpuEmbeddings:
    """ref: llm embeddings wrapper — mean-pooled final hidden states."""

    def __init__(self, model, tokenizer=None):
        self.model = model
        self.tokenizer = tokenizer

    def _encode(self, text: str) -> np.ndarray:
        if self.tokenizer is not None:
            return np.asarray([self.tokenizer.encode(text)], np.int32)
        return np.asarray([[b % 256 for b in text.encode()]], np.int32)

    def embed_query(self, text: str) -> List[float]:
        import jax.numpy as jnp

        from bigdl_tpu.llm.models.llama import forward, init_cache

        ids = self._encode(text)
        cfg = self.model.config
        cache = init_cache(cfg, 1, ids.shape[1])
        pos = jnp.arange(ids.shape[1])[None, :]
        # logits are a poor embedding; pool the pre-head hidden state by
        # re-running forward without lm_head
        params = dict(self.model.params)
        params.pop("lm_head", None)
        logits, _ = forward(params, cfg, jnp.asarray(ids), cache, pos)
        # tied-embedding logits = h @ E^T; mean-pool over sequence
        emb = np.asarray(logits).mean(axis=1)[0]
        return [float(v) for v in emb]

    def embed_documents(self, texts: List[str]) -> List[List[float]]:
        return [self.embed_query(t) for t in texts]

"""LangChain integration (ref: P:llm/langchain — LLM + Embeddings
wrappers over the ggml models).

langchain isn't a baked-in dependency; the classes duck-type the
``langchain_core`` interfaces (``invoke``/``_call``, ``embed_documents``/
``embed_query``) so they drop into chains when langchain is installed and
stay usable standalone when it isn't.

:class:`BigdlTpuOpenAI` (ISSUE 20) is the remote sibling: the same
duck-typed LLM protocol over a live worker/router's OpenAI gateway
(``base_url`` style, like langchain's ``OpenAI(base_url=...)``) instead
of an in-process model — so a chain can point at a serving fleet by
URL with no langchain and no openai package installed."""

from __future__ import annotations

import json
from typing import Any, Iterator, List, Optional, Tuple

import numpy as np


class BigdlTpuLLM:
    """ref: BigdlLLM / LlamaLLM — text-in/text-out over a converted model."""

    def __init__(self, model_path: str, tokenizer=None,
                 max_new_tokens: int = 64, temperature: float = 0.0,
                 ctx_size: int = 512):
        from bigdl_tpu.llm.convert_model import load_model

        self.model = load_model(model_path, max_cache_len=ctx_size)
        self.tokenizer = tokenizer
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature

    @classmethod
    def from_model(cls, model, tokenizer=None, **kwargs) -> "BigdlTpuLLM":
        self = cls.__new__(cls)
        self.model = model
        self.tokenizer = tokenizer
        self.max_new_tokens = kwargs.get("max_new_tokens", 64)
        self.temperature = kwargs.get("temperature", 0.0)
        return self

    # langchain LLM protocol
    @property
    def _llm_type(self) -> str:
        return "bigdl_tpu"

    def _encode(self, text: str) -> np.ndarray:
        if self.tokenizer is not None:
            return np.asarray([self.tokenizer.encode(text)], np.int32)
        return np.asarray([[b % 256 for b in text.encode()]], np.int32)

    def _decode(self, ids) -> str:
        if self.tokenizer is not None:
            return self.tokenizer.decode(list(ids),
                                         skip_special_tokens=True)
        return bytes(int(i) % 256 for i in ids).decode(errors="replace")

    def _call(self, prompt: str, stop: Optional[List[str]] = None,
              **kwargs: Any) -> str:
        ids = self._encode(prompt)
        out = self.model.generate(
            ids, max_new_tokens=self.max_new_tokens,
            do_sample=self.temperature > 0,
            temperature=max(self.temperature, 1e-6))
        text = self._decode(out[0, ids.shape[1]:])
        if stop:
            for s in stop:
                cut = text.find(s)
                if cut >= 0:
                    text = text[:cut]
        return text

    invoke = _call
    __call__ = _call


class BigdlTpuOpenAI:
    """Remote LLM over the OpenAI gateway (ISSUE 20): the langchain
    ``_call``/``invoke`` protocol backed by ``POST /v1/completions`` on
    a ``bigdl.llm.api.enabled`` worker or router. Prompts may be
    strings (the server needs a tokenizer configured) or token-id
    lists (native, tokenizer-free); ``stream()`` yields the SSE deltas
    as they arrive."""

    def __init__(self, base_url: str, model: str = "bigdl-tpu-llm",
                 max_tokens: int = 64, timeout: float = 120.0,
                 stop: Optional[List[str]] = None):
        self.base_url = base_url
        self.model = model
        self.max_tokens = max_tokens
        self.timeout = timeout
        self.stop = list(stop) if stop else None
        self._addr = self._parse(base_url)

    @staticmethod
    def _parse(base_url: str) -> Tuple[str, int]:
        """``http://host:port[/v1]`` (or bare ``host:port``) → addr."""
        url = base_url
        for prefix in ("http://", "https://"):
            if url.startswith(prefix):
                url = url[len(prefix):]
        url = url.split("/", 1)[0]
        host, _, port = url.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(
                f"base_url must carry host:port, got {base_url!r}")
        return host, int(port)

    @property
    def _llm_type(self) -> str:
        return "bigdl_tpu_openai"

    def _request(self, method: str, path: str, body=None):
        import http.client
        conn = http.client.HTTPConnection(*self._addr,
                                          timeout=self.timeout)
        conn.request(method, path,
                     None if body is None else json.dumps(body),
                     {"Content-Type": "application/json"})
        return conn, conn.getresponse()

    @staticmethod
    def _raise_api_error(status: int, parsed: dict):
        err = parsed.get("error")
        msg = err.get("message", "") if isinstance(err, dict) else err
        raise RuntimeError(f"gateway answered {status}: {msg}")

    def models(self) -> List[str]:
        """Served model ids from ``GET /v1/models``."""
        conn, resp = self._request("GET", "/v1/models")
        try:
            parsed = json.loads(resp.read().decode())
            if resp.status != 200:
                self._raise_api_error(resp.status, parsed)
            return [m["id"] for m in parsed.get("data", [])]
        finally:
            conn.close()

    def _body(self, prompt, stop, stream=False) -> dict:
        body = {"model": self.model, "prompt": prompt,
                "max_tokens": self.max_tokens}
        stops = stop if stop is not None else self.stop
        if stops:
            body["stop"] = stops
        if stream:
            body["stream"] = True
        return body

    def _call(self, prompt, stop: Optional[List[str]] = None,
              **kwargs: Any) -> str:
        conn, resp = self._request(
            "POST", "/v1/completions", self._body(prompt, stop))
        try:
            parsed = json.loads(resp.read().decode())
            if resp.status != 200:
                self._raise_api_error(resp.status, parsed)
            return parsed["choices"][0].get("text", "")
        finally:
            conn.close()

    invoke = _call
    __call__ = _call

    def stream(self, prompt,
               stop: Optional[List[str]] = None) -> Iterator[str]:
        """Yield text deltas from the SSE stream as they arrive."""
        from bigdl_tpu.llm.api.sse import parse_sse
        conn, resp = self._request(
            "POST", "/v1/completions",
            self._body(prompt, stop, stream=True))
        try:
            if resp.status != 200:
                self._raise_api_error(resp.status,
                                      json.loads(resp.read().decode()))
            for obj in parse_sse(resp):
                if "error" in obj:
                    self._raise_api_error(resp.status, obj)
                for choice in obj.get("choices", ()):
                    if choice.get("text"):
                        yield choice["text"]
        finally:
            conn.close()

    def chat(self, messages: List[dict],
             stop: Optional[List[str]] = None) -> str:
        """One ``POST /v1/chat/completions`` turn → assistant text."""
        body = {"model": self.model, "messages": messages,
                "max_tokens": self.max_tokens}
        stops = stop if stop is not None else self.stop
        if stops:
            body["stop"] = stops
        conn, resp = self._request("POST", "/v1/chat/completions", body)
        try:
            parsed = json.loads(resp.read().decode())
            if resp.status != 200:
                self._raise_api_error(resp.status, parsed)
            msg = parsed["choices"][0].get("message", {})
            return msg.get("content", "")
        finally:
            conn.close()


class BigdlTpuEmbeddings:
    """ref: llm embeddings wrapper — mean-pooled final hidden states."""

    def __init__(self, model, tokenizer=None):
        self.model = model
        self.tokenizer = tokenizer

    def _encode(self, text: str) -> np.ndarray:
        if self.tokenizer is not None:
            return np.asarray([self.tokenizer.encode(text)], np.int32)
        return np.asarray([[b % 256 for b in text.encode()]], np.int32)

    def embed_query(self, text: str) -> List[float]:
        import jax.numpy as jnp

        from bigdl_tpu.llm.models.llama import forward, init_cache

        ids = self._encode(text)
        cfg = self.model.config
        cache = init_cache(cfg, 1, ids.shape[1])
        pos = jnp.arange(ids.shape[1])[None, :]
        # logits are a poor embedding; pool the pre-head hidden state by
        # re-running forward without lm_head
        params = dict(self.model.params)
        params.pop("lm_head", None)
        logits, _ = forward(params, cfg, jnp.asarray(ids), cache, pos)
        # tied-embedding logits = h @ E^T; mean-pool over sequence
        emb = np.asarray(logits).mean(axis=1)[0]
        return [float(v) for v in emb]

    def embed_documents(self, texts: List[str]) -> List[List[float]]:
        return [self.embed_query(t) for t in texts]

"""LowBitLinear — quantized drop-in for nn.Linear.

Reference: P:llm/transformers/low_bit_linear.py (``LowBitLinear(nn.Linear)``
holding ``FP4Params`` ggml-quantized weights, forwarding through native
int4 matvec). Here the weight lives as packed uint8 + scales in the
module's state tree — stored in the **k-major TPU kernel layout**
(q (K/2, N), scale (G, N) f32; see llm.kernels.int4_matmul) for
sym_int4/asym_int4/sym_int8 so forward dispatches straight to the Pallas
kernels on TPU (jnp dequant-matmul elsewhere — same math, XLA fuses it).
nf4/fp4/sym_int5/bf16/fp8 keep the row-major ggml layout and always use
the XLA dequant path."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.llm.ggml.quantize import QK, quantize
from bigdl_tpu.nn.module import TensorModule


def _use_pallas() -> bool:
    return jax.default_backend() == "tpu"


class LowBitLinear(TensorModule):
    """y = x @ dequant(W)^T + b with ggml-block-quantized W."""

    def __init__(self, input_size: int, output_size: int,
                 qtype: str = "sym_int4", with_bias: bool = False,
                 name: Optional[str] = None):
        super().__init__(name)
        self.input_size = input_size
        self.output_size = output_size
        self.qtype = qtype
        self.with_bias = with_bias

    @classmethod
    def from_linear(cls, linear, qtype: str = "sym_int4") -> "LowBitLinear":
        """Quantize an nn.Linear's weights (ref: FP4Params.quantize)."""
        w = np.asarray(linear._params["weight"], np.float32)
        mod = cls(linear.input_size, linear.output_size, qtype,
                  with_bias="bias" in linear._params,
                  name=getattr(linear, "name", None))
        mod.load_quantized(quantize(w, qtype))
        if mod.with_bias:
            mod.add_param("bias", jnp.asarray(linear._params["bias"]))
        return mod

    @classmethod
    def from_weight(cls, w: np.ndarray, qtype: str = "sym_int4",
                    bias: Optional[np.ndarray] = None) -> "LowBitLinear":
        out_f, in_f = w.shape
        mod = cls(in_f, out_f, qtype, with_bias=bias is not None)
        mod.load_quantized(quantize(np.asarray(w, np.float32), qtype))
        if bias is not None:
            mod.add_param("bias", jnp.asarray(bias))
        return mod

    _KERNEL_QTYPES = ("sym_int4", "asym_int4", "sym_int8")

    def load_quantized(self, qdict):
        if qdict.get("qtype", self.qtype) != self.qtype:
            raise ValueError((qdict.get("qtype"), self.qtype))
        if self.qtype in self._KERNEL_QTYPES:
            from bigdl_tpu.llm.kernels import to_tpu_layout
            qdict = to_tpu_layout(qdict)
        for k, v in qdict.items():
            if k == "qtype":
                continue
            # quantized planes are constants, not trainable: store as state
            self.add_state(k, v)

    def _apply(self, params, states, x, *, training, rng):
        orig_shape = x.shape
        x2 = x.reshape(-1, orig_shape[-1])
        qtype = self.qtype

        if qtype in self._KERNEL_QTYPES and _use_pallas():
            from bigdl_tpu.llm.kernels import (
                asym_int4_matmul, int4_matmul, int8_matmul)
            if qtype == "sym_int4":
                y = int4_matmul(x2, states["q"], states["scale"],
                                out_dtype=x.dtype)
            elif qtype == "asym_int4":
                y = asym_int4_matmul(x2, states["q"], states["scale"],
                                     states["zero"], out_dtype=x.dtype)
            else:
                y = int8_matmul(x2, states["q"], states["scale"],
                                out_dtype=x.dtype)
        else:
            y = (x2 @ self._dequant(states, x.dtype)).astype(x.dtype)
        if self.with_bias:
            y = y + params["bias"]
        return y.reshape(orig_shape[:-1] + (self.output_size,))

    def _dequant(self, states, dtype):
        """jnp dequant (XLA path) — always returns w (K, N) so forward is
        ``y = x @ w``. Kernel qtypes are stored k-major; the rest are
        row-major ggml and transposed here."""
        qtype = self.qtype
        n = self.output_size
        if qtype in ("bf16", "fp8"):
            return states["q"].astype(dtype).T
        scale = states["scale"].astype(jnp.float32)
        if qtype == "sym_int8":                       # k-major (K, N)
            q = states["q"].astype(jnp.float32)
            k = q.shape[0]
            w = (q.reshape(k // QK, QK, n) * scale[:, None, :])
            return w.reshape(k, n).astype(dtype)
        if qtype in ("sym_int4", "asym_int4"):        # k-major (K/2, N)
            packed = states["q"]
            half = packed.shape[0]
            lo = (packed & 0xF).astype(jnp.int32)
            hi = (packed >> 4).astype(jnp.int32)
            q = jnp.stack([lo, hi], axis=1).reshape(half * 2, n)
            g = scale.shape[0]
            if qtype == "sym_int4":
                w = (q - 8).astype(jnp.float32).reshape(g, QK, n) \
                    * scale[:, None, :]
            else:
                zero = states["zero"].astype(jnp.float32)
                w = q.astype(jnp.float32).reshape(g, QK, n) \
                    * scale[:, None, :] + zero[:, None, :]
            return w.reshape(half * 2, n).astype(dtype)
        # row-major ggml qtypes
        nb = scale.shape[1]
        if qtype == "sym_int5":
            q = states["q"].reshape(n, nb, QK).astype(jnp.float32) - 16.0
            return (q * scale[..., None]).reshape(n, -1).astype(dtype).T
        packed = states["q"]
        lo = (packed & 0xF).astype(jnp.int32)
        hi = (packed >> 4).astype(jnp.int32)
        q = jnp.stack([lo, hi], axis=-1).reshape(n, -1)
        if qtype in ("nf4", "fp4"):
            from bigdl_tpu.llm.ggml.quantize import FP4_CODE, NF4_CODE
            code = jnp.asarray(NF4_CODE if qtype == "nf4" else FP4_CODE)
            w = code[q].reshape(n, nb, QK) * scale[..., None]
        else:
            raise ValueError(f"unknown qtype {qtype!r}")
        return w.reshape(n, -1).astype(dtype).T

    def __repr__(self):
        return (f"LowBitLinear({self.input_size} -> {self.output_size}, "
                f"{self.qtype})")

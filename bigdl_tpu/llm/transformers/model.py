"""AutoModelForCausalLM facade (ref: P:llm/transformers/model.py — the
patched ``from_pretrained(load_in_4bit=True)`` entry that is bigdl-llm's
public API).

Loading paths:
- HF checkpoint dir with safetensors weights: read **directly** into the
  stacked jax layout (no torch model materialized), quantizing each layer
  on load when low-bit is requested — the memory-lean default.
- HF checkpoint dir / hub id without safetensors (requires the baked-in
  ``transformers``): weights read via torch on CPU, transposed into the
  jax layout, then ggml-quantized.
- ``LlamaConfig`` instance (or ``config=``): random-init weights —
  the test/benchmark path (the reference's tests use tiny dummy ckpts).
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any, Dict, Optional

import numpy as np

from bigdl_tpu.llm.models.llama import (
    LlamaConfig, LlamaForCausalLM, init_params, quantize_params)


# ---------------------------------------------------------------------------
# direct safetensors loading (no torch)
# ---------------------------------------------------------------------------

def _read_hf_config(path: str) -> LlamaConfig:
    """config.json → LlamaConfig (attribute-shim over the raw dict)."""
    with open(os.path.join(path, "config.json")) as f:
        raw = json.load(f)
    return LlamaConfig.from_hf(type("HFConfig", (), raw)())


def load_hf_llama_safetensors(path: str, cfg: Optional[LlamaConfig] = None,
                              qtype: Optional[str] = None,
                              dtype=None) -> Dict[str, Any]:
    """Read a HF Llama checkpoint (config.json + *.safetensors) straight
    into our stacked jax layout — per-layer streaming, so the fp32 torch
    model is never materialized; with ``qtype`` each linear is
    ggml-quantized the moment it is read (quantize-on-load)."""
    import jax.numpy as jnp

    from bigdl_tpu.llm.kernels import quantize_tpu
    from bigdl_tpu.llm.models.llama import _LAYER_LINEARS

    if qtype and qtype != "sym_int4":
        # same contract as quantize_params: the scanned decoder implements
        # q4_0 only; other qtypes go through LowBitLinear module surgery
        raise NotImplementedError(
            "the scanned decoder path implements q4_0 (sym_int4); other "
            "qtypes are available through LowBitLinear module surgery")
    dtype = dtype or jnp.bfloat16
    if cfg is None:
        cfg = _read_hf_config(path)
    from bigdl_tpu.llm.transformers.st_reader import SafetensorsReader
    reader = SafetensorsReader(path, prefix_fallbacks=("",))
    key_map = reader.key_map
    get = reader.get

    hf_linear = {
        "q_proj": "model.layers.{}.self_attn.q_proj.weight",
        "k_proj": "model.layers.{}.self_attn.k_proj.weight",
        "v_proj": "model.layers.{}.self_attn.v_proj.weight",
        "o_proj": "model.layers.{}.self_attn.o_proj.weight",
        "gate_proj": "model.layers.{}.mlp.gate_proj.weight",
        "up_proj": "model.layers.{}.mlp.up_proj.weight",
        "down_proj": "model.layers.{}.mlp.down_proj.weight",
    }
    L = cfg.num_hidden_layers
    layers: Dict[str, Any] = {}
    # GLM/ChatGLM checkpoints fuse gate/up as mlp.gate_up_proj
    # (gate = first intermediate_size rows); split back on read
    glm_fused = "model.layers.0.mlp.gate_up_proj.weight" in key_map
    if glm_fused:
        # read each fused tensor ONCE per layer, feeding both halves
        # (the name-outer loop below would otherwise read every 2I×H
        # tensor twice — ~double the checkpoint I/O at 9B scale)
        inter = cfg.intermediate_size
        acc = {"gate_proj": {"q": [], "scale": [], "w": []},
               "up_proj": {"q": [], "scale": [], "w": []}}
        for l in range(L):
            gu = np.asarray(
                get(f"model.layers.{l}.mlp.gate_up_proj.weight"),
                np.float32)
            for name, half in (("gate_proj", gu[:inter]),
                               ("up_proj", gu[inter:])):
                if qtype:
                    qd = quantize_tpu(half, qtype)
                    acc[name]["q"].append(qd["q"])
                    acc[name]["scale"].append(qd["scale"])
                else:
                    acc[name]["w"].append(half)
        for name, a in acc.items():
            if qtype:
                layers[name] = {"q": jnp.asarray(np.stack(a["q"])),
                                "scale": jnp.asarray(np.stack(a["scale"]))}
            else:
                layers[name] = {"w": jnp.asarray(np.stack(a["w"]), dtype)}

    for name in _LAYER_LINEARS:
        fmt = hf_linear[name]
        if glm_fused and name in ("gate_proj", "up_proj"):
            continue                       # built above in one pass
        if qtype:
            qs, ss = [], []
            for l in range(L):
                qd = quantize_tpu(
                    np.asarray(get(fmt.format(l)), np.float32), qtype)
                qs.append(qd["q"])
                ss.append(qd["scale"])
            layers[name] = {"q": jnp.asarray(np.stack(qs)),
                            "scale": jnp.asarray(np.stack(ss))}
        else:
            layers[name] = {"w": jnp.asarray(np.stack(
                [np.asarray(get(fmt.format(l)), np.float32)
                 for l in range(L)]), dtype)}
    for name in ("q_proj", "k_proj", "v_proj"):
        bias_key = f"model.layers.0.self_attn.{name}.bias"
        if bias_key in key_map:
            layers[name]["b"] = jnp.asarray(np.stack(
                [np.asarray(get(
                    f"model.layers.{l}.self_attn.{name}.bias"),
                    np.float32) for l in range(L)]))
    for norm in ("input_layernorm", "post_attention_layernorm"):
        layers[norm] = jnp.asarray(np.stack(
            [np.asarray(get(f"model.layers.{l}.{norm}.weight"), np.float32)
             for l in range(L)]), dtype)
    params: Dict[str, Any] = {
        "embed_tokens": jnp.asarray(
            np.asarray(get("model.embed_tokens.weight"), np.float32), dtype),
        "norm": jnp.asarray(
            np.asarray(get("model.norm.weight"), np.float32), dtype),
        "layers": layers,
    }
    if not cfg.tie_word_embeddings and "lm_head.weight" in key_map:
        params["lm_head"] = {"w": jnp.asarray(
            np.asarray(get("lm_head.weight"), np.float32), dtype)}
    if qtype:
        from bigdl_tpu.llm.models.llama import fuse_decoder_params
        params = fuse_decoder_params(params)
    return params


def _hf_to_params(model, cfg: LlamaConfig) -> Dict[str, Any]:
    """torch LlamaForCausalLM state_dict → our stacked jax layout."""
    import jax.numpy as jnp

    sd = {k: v.detach().cpu().float().numpy()
          for k, v in model.state_dict().items()}
    L = cfg.num_hidden_layers

    def stack(fmt: str) -> np.ndarray:
        return np.stack([sd[fmt.format(l)] for l in range(L)])

    layers = {
        "q_proj": {"w": jnp.asarray(
            stack("model.layers.{}.self_attn.q_proj.weight"),
            jnp.bfloat16)},
        "k_proj": {"w": jnp.asarray(
            stack("model.layers.{}.self_attn.k_proj.weight"),
            jnp.bfloat16)},
        "v_proj": {"w": jnp.asarray(
            stack("model.layers.{}.self_attn.v_proj.weight"),
            jnp.bfloat16)},
        "o_proj": {"w": jnp.asarray(
            stack("model.layers.{}.self_attn.o_proj.weight"),
            jnp.bfloat16)},
        "gate_proj": {"w": jnp.asarray(
            stack("model.layers.{}.mlp.gate_proj.weight"), jnp.bfloat16)},
        "up_proj": {"w": jnp.asarray(
            stack("model.layers.{}.mlp.up_proj.weight"), jnp.bfloat16)},
        "down_proj": {"w": jnp.asarray(
            stack("model.layers.{}.mlp.down_proj.weight"), jnp.bfloat16)},
        "input_layernorm": jnp.asarray(
            stack("model.layers.{}.input_layernorm.weight"), jnp.bfloat16),
        "post_attention_layernorm": jnp.asarray(
            stack("model.layers.{}.post_attention_layernorm.weight"),
            jnp.bfloat16),
    }
    # Qwen2-family attention biases ride along when present
    for name in ("q_proj", "k_proj", "v_proj"):
        key = "model.layers.{}.self_attn." + name + ".bias"
        if key.format(0) in sd:
            layers[name]["b"] = jnp.asarray(stack(key), jnp.float32)
    params = {
        "embed_tokens": jnp.asarray(sd["model.embed_tokens.weight"],
                                    jnp.bfloat16),
        "norm": jnp.asarray(sd["model.norm.weight"], jnp.bfloat16),
        "layers": layers,
    }
    if "lm_head.weight" in sd and not cfg.tie_word_embeddings:
        params["lm_head"] = {"w": jnp.asarray(sd["lm_head.weight"],
                                              jnp.bfloat16)}
    return params


class AutoModelForCausalLM:
    """ref API: AutoModelForCausalLM.from_pretrained(path,
    load_in_4bit=True | load_in_low_bit="sym_int4")."""

    @staticmethod
    def from_pretrained(pretrained_model_name_or_path=None,
                        load_in_4bit: bool = False,
                        load_in_low_bit: Optional[str] = None,
                        config: Optional[LlamaConfig] = None,
                        max_cache_len: int = 512,
                        seed: int = 0,
                        **kwargs) -> LlamaForCausalLM:
        qtype = load_in_low_bit or ("sym_int4" if load_in_4bit else None)

        if isinstance(pretrained_model_name_or_path, LlamaConfig):
            config = pretrained_model_name_or_path
            pretrained_model_name_or_path = None

        if pretrained_model_name_or_path is None:
            cfg = config or LlamaConfig.tiny()
            params = init_params(cfg, seed)
        elif (os.path.isdir(pretrained_model_name_or_path)
              and glob.glob(os.path.join(pretrained_model_name_or_path,
                                         "*.safetensors"))):
            # direct safetensors path: stream per-layer, quantize on load;
            # family dispatched on config.json model_type
            path = pretrained_model_name_or_path
            with open(os.path.join(path, "config.json")) as f:
                raw = json.load(f)
            hf_shim = type("HFConfig", (), raw)()
            if raw.get("model_type") == "gpt_neox":
                from bigdl_tpu.llm.models.gptneox import (
                    GptNeoXConfig, GptNeoXForCausalLM,
                    load_hf_gptneox_safetensors)
                ncfg = GptNeoXConfig.from_hf(hf_shim)
                nparams = load_hf_gptneox_safetensors(path, ncfg,
                                                      qtype=qtype)
                return GptNeoXForCausalLM(ncfg, nparams,
                                          max_cache_len=max_cache_len)
            if raw.get("model_type") == "bloom":
                from bigdl_tpu.llm.models.bloom import (
                    BloomConfig, BloomForCausalLM,
                    load_hf_bloom_safetensors)
                bcfg = BloomConfig.from_hf(hf_shim)
                bparams = load_hf_bloom_safetensors(path, bcfg,
                                                    qtype=qtype)
                return BloomForCausalLM(bcfg, bparams,
                                        max_cache_len=max_cache_len)
            if raw.get("model_type") == "gpt_bigcode":
                from bigdl_tpu.llm.models.starcoder import (
                    StarCoderConfig, StarCoderForCausalLM,
                    load_hf_starcoder_safetensors)
                scfg = StarCoderConfig.from_hf(hf_shim)
                sparams = load_hf_starcoder_safetensors(path, scfg,
                                                        qtype=qtype)
                return StarCoderForCausalLM(scfg, sparams,
                                            max_cache_len=max_cache_len)
            cfg = LlamaConfig.from_hf(hf_shim)
            params = load_hf_llama_safetensors(path, cfg, qtype=qtype)
            return LlamaForCausalLM(cfg, params,
                                    max_cache_len=max_cache_len)
        else:
            import transformers

            hf_cfg = transformers.AutoConfig.from_pretrained(
                pretrained_model_name_or_path)
            cfg = LlamaConfig.from_hf(hf_cfg)
            hf_model = transformers.AutoModelForCausalLM.from_pretrained(
                pretrained_model_name_or_path, torch_dtype="float32",
                **kwargs)
            params = _hf_to_params(hf_model, cfg)
            del hf_model

        if qtype:
            params = quantize_params(params, qtype)
        return LlamaForCausalLM(cfg, params, max_cache_len=max_cache_len)

"""AutoModelForCausalLM facade (ref: P:llm/transformers/model.py — the
patched ``from_pretrained(load_in_4bit=True)`` entry that is bigdl-llm's
public API).

Loading paths:
- HF checkpoint dir / hub id (requires the baked-in ``transformers``):
  config + weights are read via torch on CPU, transposed into the jax
  Llama layout, then ggml-quantized.
- ``LlamaConfig`` instance (or ``config=``): random-init weights —
  the test/benchmark path (the reference's tests use tiny dummy ckpts).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from bigdl_tpu.llm.models.llama import (
    LlamaConfig, LlamaForCausalLM, init_params, quantize_params)


def _hf_to_params(model, cfg: LlamaConfig) -> Dict[str, Any]:
    """torch LlamaForCausalLM state_dict → our stacked jax layout."""
    import jax.numpy as jnp

    sd = {k: v.detach().cpu().float().numpy()
          for k, v in model.state_dict().items()}
    L = cfg.num_hidden_layers

    def stack(fmt: str) -> np.ndarray:
        return np.stack([sd[fmt.format(l)] for l in range(L)])

    layers = {
        "q_proj": {"w": jnp.asarray(
            stack("model.layers.{}.self_attn.q_proj.weight"),
            jnp.bfloat16)},
        "k_proj": {"w": jnp.asarray(
            stack("model.layers.{}.self_attn.k_proj.weight"),
            jnp.bfloat16)},
        "v_proj": {"w": jnp.asarray(
            stack("model.layers.{}.self_attn.v_proj.weight"),
            jnp.bfloat16)},
        "o_proj": {"w": jnp.asarray(
            stack("model.layers.{}.self_attn.o_proj.weight"),
            jnp.bfloat16)},
        "gate_proj": {"w": jnp.asarray(
            stack("model.layers.{}.mlp.gate_proj.weight"), jnp.bfloat16)},
        "up_proj": {"w": jnp.asarray(
            stack("model.layers.{}.mlp.up_proj.weight"), jnp.bfloat16)},
        "down_proj": {"w": jnp.asarray(
            stack("model.layers.{}.mlp.down_proj.weight"), jnp.bfloat16)},
        "input_layernorm": jnp.asarray(
            stack("model.layers.{}.input_layernorm.weight"), jnp.bfloat16),
        "post_attention_layernorm": jnp.asarray(
            stack("model.layers.{}.post_attention_layernorm.weight"),
            jnp.bfloat16),
    }
    params = {
        "embed_tokens": jnp.asarray(sd["model.embed_tokens.weight"],
                                    jnp.bfloat16),
        "norm": jnp.asarray(sd["model.norm.weight"], jnp.bfloat16),
        "layers": layers,
    }
    if "lm_head.weight" in sd and not cfg.tie_word_embeddings:
        params["lm_head"] = {"w": jnp.asarray(sd["lm_head.weight"],
                                              jnp.bfloat16)}
    return params


class AutoModelForCausalLM:
    """ref API: AutoModelForCausalLM.from_pretrained(path,
    load_in_4bit=True | load_in_low_bit="sym_int4")."""

    @staticmethod
    def from_pretrained(pretrained_model_name_or_path=None,
                        load_in_4bit: bool = False,
                        load_in_low_bit: Optional[str] = None,
                        config: Optional[LlamaConfig] = None,
                        max_cache_len: int = 512,
                        seed: int = 0,
                        **kwargs) -> LlamaForCausalLM:
        qtype = load_in_low_bit or ("sym_int4" if load_in_4bit else None)

        if isinstance(pretrained_model_name_or_path, LlamaConfig):
            config = pretrained_model_name_or_path
            pretrained_model_name_or_path = None

        if pretrained_model_name_or_path is None:
            cfg = config or LlamaConfig.tiny()
            params = init_params(cfg, seed)
        else:
            import transformers

            hf_cfg = transformers.AutoConfig.from_pretrained(
                pretrained_model_name_or_path)
            cfg = LlamaConfig.from_hf(hf_cfg)
            hf_model = transformers.AutoModelForCausalLM.from_pretrained(
                pretrained_model_name_or_path, torch_dtype="float32",
                **kwargs)
            params = _hf_to_params(hf_model, cfg)
            del hf_model

        if qtype:
            params = quantize_params(params, qtype)
        return LlamaForCausalLM(cfg, params, max_cache_len=max_cache_len)

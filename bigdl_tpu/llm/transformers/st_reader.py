"""Shared safetensors checkpoint reader for the HF model loaders.

Every family loader (llama/glm in model.py, gptneox, bloom, starcoder)
needs the same machinery: map tensor name → containing file (single
file, glob, or sharded index.json), cache open handles so a layer's
tensors stream from one file, tolerate an optional name prefix
(``transformer.`` on bloom/gpt_bigcode checkpoints), and return fp32
numpy. One implementation keeps the four loaders in lockstep (review
r5 finding #2 — three drifting copies of ~40 lines)."""

from __future__ import annotations

import glob
import json
import os
from typing import Any, Dict, Optional

import numpy as np


class SafetensorsReader:
    def __init__(self, path: str, prefix_fallbacks: tuple = ("",
                                                             "transformer.")):
        from safetensors import safe_open  # noqa: F401 (availability)

        self._path = path
        self._prefixes = prefix_fallbacks
        self._handles: Dict[str, Any] = {}
        index = os.path.join(path, "model.safetensors.index.json")
        if os.path.exists(index):
            with open(index) as f:
                weight_map = json.load(f)["weight_map"]
            self.key_map = {k: os.path.join(path, v)
                            for k, v in weight_map.items()}
        else:
            self.key_map = {}
            from safetensors import safe_open
            for fname in sorted(glob.glob(os.path.join(path,
                                                       "*.safetensors"))):
                with safe_open(fname, framework="numpy") as f:
                    for k in f.keys():
                        self.key_map[k] = fname

    def resolve(self, name: str) -> Optional[str]:
        for p in self._prefixes:
            if p + name in self.key_map:
                return p + name
        return None

    def __contains__(self, name: str) -> bool:
        return self.resolve(name) is not None

    def get(self, name: str) -> np.ndarray:
        """fp32 numpy tensor by (possibly prefix-less) HF name."""
        from safetensors import safe_open

        resolved = self.resolve(name)
        if resolved is None:
            raise KeyError(name)
        fname = self.key_map[resolved]
        if fname not in self._handles:
            self._handles[fname] = safe_open(fname, framework="numpy")
        return np.asarray(self._handles[fname].get_tensor(resolved),
                          np.float32)

    def close(self):
        for h in self._handles.values():
            close = getattr(h, "close", None)
            if close:
                close()
        self._handles.clear()

    def __enter__(self) -> "SafetensorsReader":
        return self

    def __exit__(self, *exc):
        self.close()

"""HF-style facade + low-bit module surgery (ref: P:llm/transformers)."""

from bigdl_tpu.llm.transformers.low_bit_linear import LowBitLinear
from bigdl_tpu.llm.transformers.convert import (
    ggml_convert_low_bit, optimize_model)
from bigdl_tpu.llm.transformers.model import AutoModelForCausalLM

__all__ = ["LowBitLinear", "ggml_convert_low_bit", "optimize_model",
           "AutoModelForCausalLM"]

"""Module surgery: replace Linear with LowBitLinear (ref:
P:llm/transformers/convert.py — ``ggml_convert_low_bit`` recursive
replacement + ``optimize_model``)."""

from __future__ import annotations

from typing import Optional, Sequence

from bigdl_tpu.llm.transformers.low_bit_linear import LowBitLinear
from bigdl_tpu.nn.layers.linear import Linear
from bigdl_tpu.nn.module import Module


def ggml_convert_low_bit(model: Module, qtype: str = "sym_int4",
                         modules_to_not_convert:
                         Optional[Sequence[str]] = None) -> Module:
    """Recursively swap every nn.Linear for a quantized LowBitLinear.

    ``modules_to_not_convert``: names to skip (the reference skips lm_head
    by default for quality; pass e.g. ``["lm_head"]``)."""
    skip = set(modules_to_not_convert or ())

    from bigdl_tpu.llm.ggml.quantize import QK

    def walk(mod: Module):
        for key, child in list(mod._modules.items()):
            if isinstance(child, Linear) and not \
                    isinstance(child, LowBitLinear):
                if child.name in skip or key in skip:
                    continue
                if qtype not in ("bf16", "fp8") and \
                        child.input_size % QK != 0:
                    # block formats need K % 32 == 0 (the reference keeps
                    # such layers fp too); bf16/fp8 have no block shape
                    continue
                low = LowBitLinear.from_linear(child, qtype)
                mod._modules[key] = low
                if getattr(mod, key, None) is child:
                    object.__setattr__(mod, key, low)
            else:
                walk(child)

    walk(model)
    return model


def optimize_model(model: Module, low_bit: str = "sym_int4",
                   **kwargs) -> Module:
    """Public entry (ref: bigdl.llm.optimize_model) — quantize an arbitrary
    model built on our nn."""
    return ggml_convert_low_bit(model, low_bit, **kwargs)

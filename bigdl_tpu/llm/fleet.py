"""Elastic serving fleet control plane (ISSUE 15 tentpole).

PR 7 gave the router live pool membership (``POST /backends``) and PR 12
gave it a fleet-wide metric view — but nothing *decided* membership, and
removing a backend simply abandoned its queue, its warm KV chains and
its in-flight streams to the failover path. This module closes that
loop, all behind ``bigdl.llm.fleet.enabled`` (default off, structurally
absent):

- :class:`DrainCoordinator` — the worker-side graceful drain.
  ``POST /worker_drain`` flips the engine to DRAINING (``/healthz``
  answers 503 ``"draining"``; the router's prober stops routing new
  work there while in-flight streams keep draining), waits for every
  accepted request to finish, then migrates the warm KV chains (radix
  leaves + host-arena entries) to surviving replicas through the PR 6
  ``export_chain``/``import_chain`` handoff blobs — scale-in deletes no
  cached prefixes and loses zero requests. Cancellable at any point
  (``stop()`` during an active drain must leave no orphaned migration
  jobs and no pinned arena slots).
- :class:`FleetController` — the router-embedded autoscaler daemon. It
  reads queue depth, shed-rate deltas and pages-free signals off the
  PR 12 federation snapshots (falling back to direct ``/healthz``
  scrapes when federation is off), and drives a pluggable
  :class:`WorkerProvider` through the router's live membership:
  scale-out on sustained queue/shed pressure, drain-then-remove on
  sustained idleness, with cooldowns, min/max bounds and flap damping
  (pressure must SUSTAIN for ``bigdl.llm.fleet.sustain`` consecutive
  ticks; every action re-arms the cooldown).
- :class:`WorkerProvider` — the two-call launcher interface
  (``launch() -> (host, port)``, ``terminate(addr)``) a real deployment
  implements over its process manager / k8s API.
  :class:`LocalWorkerProvider` is the in-process implementation the
  tests and ``chaos_check --fleet`` use: each launch builds an
  ``LLMServer`` over the SHARED model plus an ``LLMWorker`` surface on
  a fresh port (the compiled-step cache is keyed on the model config,
  so a scaled-out worker never recompiles). Its ``kill()`` is the chaos
  hook: the HTTP surface dies abruptly, exactly like a crashed process.

Observability: ``bigdl_fleet_workers`` / ``bigdl_fleet_scale_events_
total`` / ``bigdl_fleet_drains_total`` / ``bigdl_fleet_chains_migrated_
total`` series, ``fleet/scale`` + ``worker/drain`` spans, and the
``fleet.scale`` / ``worker.drain`` fault sites (``chaos_check --fleet``
arms them). Disabled mode constructs none of it: no controller thread,
no drain coordinator, no ``bigdl_fleet_*`` series, and the
``/worker_drain`` / ``/fleet/autoscaler`` endpoints answer 404.

See docs/RELIABILITY.md ("Elastic serving fleet") for the drain state
machine, the autoscaler signals/knobs and the provider contract.
"""

from __future__ import annotations

import base64
import http.client
import json
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from bigdl_tpu import observability as obs
from bigdl_tpu import reliability
from bigdl_tpu.observability import flight
from bigdl_tpu.observability import timeseries


def fleet_enabled(override: Optional[bool] = None) -> bool:
    """The one gate every fleet surface checks
    (``bigdl.llm.fleet.enabled``, default off)."""
    if override is not None:
        return bool(override)
    from bigdl_tpu.utils.conf import conf
    return conf.get_bool("bigdl.llm.fleet.enabled", False)


def _post_json(addr, path: str, body: dict, timeout: float = 10.0):
    """One JSON POST → (status, parsed body). Thin wrapper over the
    worker module's shared HTTP helper (one client implementation to
    maintain, not four). Raises on transport errors — drain/scale
    callers decide whether that is fatal."""
    from bigdl_tpu.llm.worker import _post_json as post
    status, parsed, _hdrs = post(addr, path, body, timeout=timeout)
    return status, parsed


def _get_json(addr, path: str, timeout: float = 5.0):
    conn = http.client.HTTPConnection(addr[0], addr[1], timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read().decode())
    finally:
        conn.close()


# ---------------------------------------------------------------------------
# graceful drain (worker side)
# ---------------------------------------------------------------------------

class DrainCoordinator:
    """Worker-side drain state machine (constructed by
    :class:`~bigdl_tpu.llm.worker.LLMWorker` only when the fleet gate is
    on). States::

        idle -> draining -> migrating -> drained
                   |             |
                   +---cancel----+--> cancelled   (engine resumes)
                   |
                   +--> failed   (in-flight never finished in time)

    ``begin`` flips the engine to DRAINING (submit sheds 503
    ``"draining"``; ``/healthz`` follows) and starts one daemon thread:
    phase 1 waits for every accepted request — queued, slotted,
    fetch-parked — to finish; phase 2 exports each warm KV chain
    (:meth:`LLMServer.warm_chains`) and lands it on a surviving peer
    via ``POST /worker_import_chain``, round-robin, skipping peers that
    refuse. Chain migration is best-effort by contract: a failed export
    or a dead peer costs a re-prefill on the survivor, never a lost
    request. The ``worker.drain`` fault site fires once per chain so
    ``chaos_check --fleet`` can kill a drain mid-migration.

    ``cancel`` stops the thread at its next checkpoint, un-drains the
    engine (unless the worker is shutting down for good), and joins —
    after it returns there are no orphaned migration posts in flight
    and no arena slots pinned by the drain (exports use the pin-less
    ``read_keyed`` copy path, so the only drain-held state is the
    thread itself)."""

    def __init__(self, server, poll_interval: float = 0.01):
        self.server = server
        self.poll_interval = poll_interval
        self._lock = threading.Lock()
        self._cancel = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.state = "idle"
        self.error: Optional[str] = None
        self.migrated_chains = 0
        self.migrated_pages = 0
        self.failed_chains = 0
        self._t0 = 0.0

    # -- lifecycle -----------------------------------------------------------
    def begin(self, peers: List[Tuple[str, int]],
              timeout: float = 60.0) -> bool:
        """Start a drain toward ``peers`` (the surviving replicas warm
        chains migrate to; empty = finish in-flight, migrate nothing).
        False if a drain is already active."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return False
            self._cancel.clear()
            self.state = "draining"
            self.error = None
            self.migrated_chains = 0
            self.migrated_pages = 0
            self.failed_chains = 0
            self._t0 = time.time()
            self.server.begin_drain()
            self._thread = threading.Thread(
                target=self._run,
                args=([tuple(p) for p in peers], float(timeout)),
                name="bigdl-fleet-drain", daemon=True)
            self._thread.start()
        return True

    def cancel(self, resume: bool = True, timeout: float = 10.0):
        """Abandon a drain: stop the thread (if still running), join
        it, and — with ``resume`` — clear the engine's draining flag so
        it accepts work again. Cancelling an already-DRAINED worker
        with ``resume`` also re-opens admission (the controller
        abandoning a scale-in after the drain finished but before the
        removal). ``resume=False`` is the shutdown path — the engine is
        about to stop for good and must not briefly re-open
        admission."""
        with self._lock:
            t = self._thread
        if t is not None and t.is_alive():
            self._cancel.set()
            t.join(timeout)
        with self._lock:
            if self.state in ("draining", "migrating"):
                self.state = "cancelled"
            if resume and self.state in ("cancelled", "drained",
                                         "failed"):
                self.server.cancel_drain()
                self.state = "cancelled"

    def active(self) -> bool:
        with self._lock:
            t = self._thread
        return t is not None and t.is_alive()

    def status(self) -> Dict[str, Any]:
        """The ``GET /worker_drain`` body (the controller's poll)."""
        with self._lock:
            return {
                "state": self.state,
                "error": self.error,
                "migrated_chains": self.migrated_chains,
                "migrated_pages": self.migrated_pages,
                "failed_chains": self.failed_chains,
                "age_s": (round(time.time() - self._t0, 3)
                          if self._t0 else 0.0),
            }

    # -- the drain thread ----------------------------------------------------
    def _run(self, peers: List[Tuple[str, int]], timeout: float):
        t0 = time.time()
        deadline = t0 + timeout
        try:
            # phase 1: every accepted request finishes (the router keeps
            # draining the in-flight streams; submit already sheds)
            while not self._cancel.is_set():
                if self.server.engine_idle():
                    break
                if time.time() > deadline:
                    with self._lock:
                        self.state = "failed"
                        self.error = (
                            f"in-flight requests did not finish within "
                            f"{timeout:g}s")
                    return
                time.sleep(self.poll_interval)
            if self._cancel.is_set():
                with self._lock:
                    self.state = "cancelled"
                return
            # phase 2: migrate warm KV chains to the survivors
            with self._lock:
                self.state = "migrating"
            self._migrate(peers)
            if self._cancel.is_set():
                with self._lock:
                    self.state = "cancelled"
                return
            with self._lock:
                self.state = "drained"
        finally:
            wall = time.time() - t0
            if obs.enabled():
                obs.add_complete(
                    "worker/drain", t0, wall, stage="llm_worker",
                    state=self.state, chains=self.migrated_chains,
                    pages=self.migrated_pages,
                    failed=self.failed_chains)

    def _migrate(self, peers: List[Tuple[str, int]]):
        chains = self.server.warm_chains()
        if not chains or not peers:
            return
        ins = _fleet_instruments()
        rr = 0
        for chain in chains:
            if self._cancel.is_set():
                return
            try:
                # the mid-drain fault site: a raise here abandons THIS
                # chain (survivors re-prefill it) — never the drain
                reliability.inject("worker.drain")
                blob = self.server.export_chain(chain)
            except Exception as e:  # noqa: BLE001 — best-effort
                with self._lock:
                    self.failed_chains += 1
                    self.error = f"export failed: {e}"
                continue
            b64 = base64.b64encode(blob).decode()
            landed = 0
            for k in range(len(peers)):
                peer = peers[(rr + k) % len(peers)]
                try:
                    status, parsed = _post_json(
                        peer, "/worker_import_chain", {"handoff": b64})
                except Exception:   # noqa: BLE001 — dead peer: next
                    continue
                if status == 200:
                    landed = int(parsed.get("imported_pages", 0))
                    rr = (rr + k + 1) % len(peers)
                    break
            if landed:
                with self._lock:
                    self.migrated_chains += 1
                    self.migrated_pages += landed
                flight.record("drain_migrate", pages=landed,
                              peer=f"{peer[0]}:{peer[1]}")
                if ins is not None:
                    ins["chains"].inc()
            else:
                with self._lock:
                    self.failed_chains += 1


# ---------------------------------------------------------------------------
# worker providers
# ---------------------------------------------------------------------------

class WorkerProvider:
    """What the autoscaler drives — the entire launcher contract:

    - ``launch() -> (host, port)``: bring up one decode-role worker
      (fleet-enabled, same model/config as the pool) and return its
      address once it serves ``/healthz``. Raise on failure — the
      controller counts it and backs off.
    - ``terminate(addr)``: tear one down for good (it has already been
      drained and removed from the router pool). Must tolerate unknown
      addresses (a worker the provider never launched, or one that
      crashed meanwhile).

    Real deployments implement these two calls over their process
    manager (subprocess + ``python -m``, k8s Deployments, GCE MIGs —
    docs/RELIABILITY.md sketches the subprocess shape). The in-process
    :class:`LocalWorkerProvider` below is the test/chaos
    implementation."""

    def launch(self) -> Tuple[str, int]:
        raise NotImplementedError

    def terminate(self, addr) -> None:
        raise NotImplementedError


class LocalWorkerProvider(WorkerProvider):
    """In-process provider for tests and ``chaos_check --fleet``: each
    ``launch`` builds an ``LLMServer`` over the SHARED model object (the
    compiled-step cache is keyed on the model config, so no recompile)
    plus a decode-role, fleet-enabled ``LLMWorker`` on a fresh port.
    ``kill`` is the chaos hook — the HTTP surface and engine die without
    a drain, exactly like a crashed process."""

    def __init__(self, model, server_kwargs: Optional[dict] = None,
                 worker_kwargs: Optional[dict] = None):
        self.model = model
        self.server_kwargs = dict(server_kwargs or {})
        self.worker_kwargs = dict(worker_kwargs or {})
        self._lock = threading.Lock()
        self._pairs: Dict[Tuple[str, int], tuple] = {}
        self.launches = 0
        self.terminations = 0

    def launch(self) -> Tuple[str, int]:
        from bigdl_tpu.llm.serving import LLMServer
        from bigdl_tpu.llm.worker import LLMWorker
        srv = LLMServer(self.model, **self.server_kwargs).start()
        try:
            w = LLMWorker(srv, role="decode", fleet=True,
                          **self.worker_kwargs).start()
        except BaseException:
            srv.stop(drain=False)
            raise
        addr = tuple(w.address)
        with self._lock:
            self._pairs[addr] = (srv, w)
            self.launches += 1
        return addr

    def servers(self) -> Dict[Tuple[str, int], Any]:
        """Live ``{addr: LLMServer}`` — the chaos harness's window into
        engine state (prefix hits, ledger idleness)."""
        with self._lock:
            return {a: p[0] for a, p in self._pairs.items()}

    def terminate(self, addr) -> None:
        with self._lock:
            pair = self._pairs.pop(tuple(addr), None)
            if pair is not None:
                self.terminations += 1
        if pair is not None:
            srv, w = pair
            w.stop()
            srv.stop()

    def kill(self, addr) -> None:
        """Abrupt death (chaos): no drain, no graceful engine stop."""
        with self._lock:
            pair = self._pairs.pop(tuple(addr), None)
        if pair is not None:
            srv, w = pair
            w.stop()
            srv.stop(drain=False)

    def stop_all(self):
        with self._lock:
            pairs = list(self._pairs.values())
            self._pairs.clear()
        for srv, w in pairs:
            w.stop()
            srv.stop(drain=False)


# ---------------------------------------------------------------------------
# the autoscaler
# ---------------------------------------------------------------------------

def _fleet_instruments() -> Optional[Dict[str, Any]]:
    """The ``bigdl_fleet_*`` series — declared only when observability
    records AND a fleet object is calling (this module is only imported
    behind the gate, so disabled mode mints nothing)."""
    if not obs.enabled():
        return None
    return {
        "workers": obs.gauge(
            "bigdl_fleet_workers",
            "Decode-pool size the autoscaler currently maintains"),
        "scale_events": obs.counter(
            "bigdl_fleet_scale_events_total",
            "Autoscaler pool changes by direction",
            labelnames=("direction",)),
        "drains": obs.counter(
            "bigdl_fleet_drains_total",
            "Graceful worker drains by outcome",
            labelnames=("outcome",)),
        "chains": obs.counter(
            "bigdl_fleet_chains_migrated_total",
            "Warm KV chains migrated to survivors during drains"),
    }


class FleetController:
    """Router-embedded autoscaler (constructed by
    :class:`~bigdl_tpu.llm.worker.LLMRouter` only when the fleet gate is
    on; requires failover mode for the prober + live ``POST /backends``
    membership).

    One ``tick`` per ``bigdl.llm.fleet.interval`` seconds:

    1. read :meth:`signals` — per-worker queue depth and active slots,
       the cumulative shed counter, and the worst pool occupancy,
       preferring the PR 12 federation snapshots (``bigdl_llm_queue_
       depth`` / ``bigdl_llm_active_slots`` / ``bigdl_llm_kv_pool_
       occupancy`` / ``bigdl_reliability_shed_total`` per instance)
       and falling back to direct ``/healthz`` scrapes when federation
       is off or a member has no snapshot yet;
    2. classify: **pressure** when total queue depth exceeds
       ``queue.high`` × workers, sheds grew since the last tick, or
       every worker's page pool is above 90% occupancy; **idle** when
       queue + active work sits at or below ``idle.low`` (absolute);
    3. act only on SUSTAINED signals (``sustain`` consecutive ticks —
       the flap damper) outside the ``cooldown`` window and inside the
       ``[min, max]`` bounds: scale-out = ``provider.launch()`` + pool
       add; scale-in = pick the newest backend, mark it draining at the
       prober (no new dispatch from the next ``_pick`` on), ``POST
       /worker_drain`` with the survivors as migration peers, poll
       until drained, then pool-remove + ``provider.terminate``. A
       drain that fails or times out is cancelled (the worker resumes);
       a worker that DIES mid-drain is removed anyway — its in-flight
       streams already failed over, its chains re-prefill.

    Every scale action runs under the ``fleet.scale`` fault site and a
    ``fleet/scale`` span. With no provider the controller still drains
    and removes (scale-in works on externally-launched workers) but
    counts scale-out decisions as ``no_provider`` events instead of
    acting."""

    THREAD_NAME = "bigdl-fleet-controller"

    def __init__(self, router, provider: Optional[WorkerProvider] = None,
                 min_workers: Optional[int] = None,
                 max_workers: Optional[int] = None,
                 interval: Optional[float] = None,
                 cooldown: Optional[float] = None,
                 sustain: Optional[int] = None,
                 queue_high: Optional[float] = None,
                 idle_low: Optional[float] = None,
                 drain_timeout: Optional[float] = None):
        from bigdl_tpu.utils.conf import conf
        self.router = router
        self.provider = provider
        self.min_workers = max(1, int(
            min_workers if min_workers is not None
            else conf.get_int("bigdl.llm.fleet.min", 1)))
        self.max_workers = max(self.min_workers, int(
            max_workers if max_workers is not None
            else conf.get_int("bigdl.llm.fleet.max", 4)))
        self.interval = float(
            interval if interval is not None
            else conf.get_float("bigdl.llm.fleet.interval", 1.0))
        self.cooldown = float(
            cooldown if cooldown is not None
            else conf.get_float("bigdl.llm.fleet.cooldown", 5.0))
        self.sustain = max(1, int(
            sustain if sustain is not None
            else conf.get_int("bigdl.llm.fleet.sustain", 2)))
        self.queue_high = float(
            queue_high if queue_high is not None
            else conf.get_float("bigdl.llm.fleet.queue.high", 2.0))
        self.idle_low = float(
            idle_low if idle_low is not None
            else conf.get_float("bigdl.llm.fleet.idle.low", 0.0))
        self.drain_timeout = float(
            drain_timeout if drain_timeout is not None
            else conf.get_float("bigdl.llm.fleet.drain.timeout", 30.0))
        # class-split pressure (ISSUE 17): an interactive backlog above
        # queue_high on ANY single worker's share is pressure even when
        # the fleet-wide total looks fine — batch depth must not hide
        # interactive starvation. Inert unless workers report class
        # depths (bigdl.llm.priority.enabled on the engines).
        self.pressure_interactive = conf.get_bool(
            "bigdl.llm.fleet.pressure.interactive", True)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._hot = 0                 # consecutive pressured ticks
        self._cold = 0                # consecutive idle ticks
        self._last_action = 0.0       # monotonic stamp of the last act
        # per-member reset-aware shed deltas (ISSUE 18): the window
        # primitive replaces the old summed _last_sheds bookkeeping —
        # a restarted member's counter drop is a reset for that member
        # only, never a clamp that swallows the others' sheds
        self._sheds = timeseries.WindowedCounter()
        self.decisions: List[dict] = []   # bounded per-tick trace
        self._draining: Optional[dict] = None   # {"addr", "t0"}
        self.scale_outs = 0
        self.scale_ins = 0
        self.drains_lost = 0          # workers that died mid-drain
        self.ticks = 0
        self.events: List[dict] = []  # bounded action log
        self._ins: Optional[Dict[str, Any]] = None

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "FleetController":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name=self.THREAD_NAME, daemon=True)
            self._thread.start()
        return self

    def stop(self):
        """Stop the control loop; an in-progress drain is CANCELLED
        (satellite: router shutdown mid-drain must not orphan the
        worker in a draining state it would never leave)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval + 5.0)
            self._thread = None
        with self._lock:
            dr = self._draining
            self._draining = None
        if dr is not None:
            try:
                _post_json(dr["addr"], "/worker_drain",
                           {"action": "cancel"}, timeout=5.0)
                self._record_drain("cancelled")
            except Exception:   # noqa: BLE001 — it may already be dead
                pass

    def _loop(self):
        while not self._stop.wait(self.interval):
            try:
                self.tick()
            except Exception:   # noqa: BLE001 — the controller never dies
                pass

    # -- signals -------------------------------------------------------------
    def _pool(self) -> List[Tuple[str, int]]:
        with self.router._pool_lock:
            return list(self.router.decode_workers)

    def signals(self) -> Dict[str, Any]:
        """The autoscaler's inputs this tick. Federation snapshots are
        the primary source; members without one (federation off, first
        sweep pending) are filled in from a direct ``/healthz``
        scrape."""
        pool = self._pool()
        per: Dict[str, dict] = {}
        source = "healthz"
        collector = getattr(self.router, "_collector", None)
        if collector is not None:
            source = "federation"
            for inst, snap in collector.snapshots().items():
                if snap is None or inst == "router":
                    continue
                per[inst] = self._from_snapshot(snap)
        queue = active = 0.0
        sheds = 0.0
        sheds_by: Dict[str, float] = {}
        occ_max = 0.0
        q_interactive = 0.0
        parked_by: Dict[Tuple[str, int], float] = {}
        for addr in pool:
            name = f"{addr[0]}:{addr[1]}"
            vals = per.get(name)
            if vals is None:
                vals = self._from_healthz(addr)
            queue += vals.get("queue", 0.0)
            active += vals.get("active", 0.0)
            sheds += vals.get("sheds", 0.0)
            if "sheds" in vals:
                sheds_by[name] = float(vals["sheds"])
            occ_max = max(occ_max, vals.get("occupancy", 0.0))
            q_interactive += vals.get("queue_interactive", 0.0)
            parked_by[tuple(addr)] = vals.get("parked", 0.0)
        journal = getattr(self.router, "_journal", None)
        return {
            "workers": len(pool),
            "queue": queue,
            "active": active,
            "inflight": journal.inflight() if journal else 0,
            "sheds": sheds,
            # per-member cumulative sheds: the WindowedCounter's keys,
            # so each member's counter resets independently
            "sheds_by": sheds_by,
            "occupancy_max": occ_max,
            # ISSUE 17: zero everywhere unless engines run the
            # priority scheduler — the class-pressure term and the
            # scale-in parked filter are then inert
            "queue_interactive": q_interactive,
            "parked_by": parked_by,
            "source": source,
        }

    @staticmethod
    def _from_snapshot(snap: dict) -> dict:
        out = {"queue": 0.0, "active": 0.0, "sheds": 0.0,
               "occupancy": 0.0}
        for m in snap.get("metrics", []):
            name = m.get("name")
            if name == "bigdl_llm_queue_depth":
                for s in m.get("series", []):
                    out["queue"] += float(s.get("value", 0.0))
            elif name == "bigdl_llm_active_slots":
                for s in m.get("series", []):
                    out["active"] += float(s.get("value", 0.0))
            elif name == "bigdl_reliability_shed_total":
                for s in m.get("series", []):
                    out["sheds"] += float(s.get("value", 0.0))
            elif name == "bigdl_llm_kv_pool_occupancy":
                for s in m.get("series", []):
                    out["occupancy"] = max(out["occupancy"],
                                           float(s.get("value", 0.0)))
            elif name == "bigdl_llm_queue_depth_class":
                # ISSUE 17: series labels are the label-value tuple in
                # labelnames order — ("class",) here
                for s in m.get("series", []):
                    if list(s.get("labels", [])) == ["interactive"]:
                        out["queue_interactive"] = \
                            out.get("queue_interactive", 0.0) \
                            + float(s.get("value", 0.0))
            elif name == "bigdl_llm_preempt_parked":
                for s in m.get("series", []):
                    out["parked"] = out.get("parked", 0.0) \
                        + float(s.get("value", 0.0))
        return out

    @staticmethod
    def _from_healthz(addr) -> dict:
        try:
            _status, body = _get_json(addr, "/healthz", timeout=2.0)
        except Exception:   # noqa: BLE001 — dead member contributes 0
            return {}
        out = {"queue": float(body.get("queue_length", 0) or 0)}
        by_class = body.get("queue_by_class")
        if isinstance(by_class, dict):
            out["queue_interactive"] = \
                float(by_class.get("interactive", 0) or 0)
        if "preempt_parked" in body:
            out["parked"] = float(body.get("preempt_parked", 0) or 0)
        return out

    # -- the control loop ----------------------------------------------------
    def tick(self):
        """One control decision (also the tests' and chaos harness's
        fake clock — no sleeping)."""
        self.ticks += 1
        if self._draining is not None:
            self._poll_drain()
            self._record_gauges()
            return
        sig = self.signals()
        n = sig["workers"]
        # a signals() override that predates the per-member contract
        # (or a healthz-only scrape) may carry just the aggregate —
        # feed it as a single-key observation so delta math still runs
        sheds_by = sig.get("sheds_by")
        if not sheds_by and "sheds" in sig:
            sheds_by = {"__total__": float(sig["sheds"])}
        shed_delta = self._sheds.observe(sheds_by or {})
        pressure = (sig["queue"] > self.queue_high * max(n, 1)
                    or shed_delta > 0
                    or (n > 0 and sig["occupancy_max"] > 0.9)
                    or (self.pressure_interactive
                        and sig.get("queue_interactive", 0.0)
                        > self.queue_high))
        load = sig["queue"] + sig["active"] + sig["inflight"]
        idle = load <= self.idle_low
        if pressure:
            self._hot += 1
            self._cold = 0
        elif idle:
            self._cold += 1
            self._hot = 0
        else:
            self._hot = 0
            self._cold = 0
        now = time.monotonic()
        cool = now - self._last_action < self.cooldown \
            and self._last_action > 0
        action = "none"
        if pressure and self._hot >= self.sustain and not cool \
                and n < self.max_workers:
            action = "scale_out"
            self._scale_out(sig)
        elif idle and self._cold >= self.sustain and not cool \
                and n > self.min_workers:
            action = "scale_in"
            self._begin_scale_in(sig)
        # bounded decision trace: chaos_check --alerts replays the old
        # summed-delta formula over sheds_by and asserts the identical
        # pressure/idle/action sequence
        self.decisions.append({
            "tick": self.ticks, "workers": n, "queue": sig["queue"],
            "sheds_by": dict(sig.get("sheds_by") or {}),
            "shed_delta": shed_delta, "pressure": pressure,
            "idle": idle, "action": action})
        if len(self.decisions) > 512:
            del self.decisions[:-512]
        self._record_gauges()

    def _scale_out(self, sig: dict):
        self._hot = 0
        self._last_action = time.monotonic()
        if self.provider is None:
            self._event("no_provider", None, sig)
            return
        t0 = time.time()
        try:
            reliability.inject("fleet.scale")
            addr = tuple(self.provider.launch())
            self.router._admin_backends(
                {"action": "add", "role": "decode",
                 "host": addr[0], "port": addr[1]})
        except Exception as e:  # noqa: BLE001 — count, back off
            self._event("scale_out_failed", None, sig, error=str(e))
            return
        self.scale_outs += 1
        self._event("scale_out", addr, sig)
        ins = self._instruments()
        if ins is not None:
            ins["scale_events"].labels(direction="out").inc()
        if obs.enabled():
            obs.add_complete(
                "fleet/scale", t0, time.time() - t0, stage="llm_router",
                direction="out", backend=f"{addr[0]}:{addr[1]}",
                workers=sig["workers"] + 1)

    def _begin_scale_in(self, sig: dict):
        self._cold = 0
        self._last_action = time.monotonic()
        pool = self._pool()
        if len(pool) <= self.min_workers:
            return
        # newest first: LIFO scale-in — but never the worker holding
        # preempted-parked chains (ISSUE 17 satellite): draining it
        # would force every parked request through a full re-prefill
        # on a peer, exactly the latency the preemption tried to save
        parked_by = sig.get("parked_by", {})
        victim = None
        for cand in reversed(pool):
            if parked_by.get(tuple(cand), 0.0) <= 0:
                victim = cand
                break
        if victim is None:
            victim = pool[-1]        # every worker holds parked chains:
            # fall back to plain LIFO rather than wedging scale-in
        peers = [list(a) for a in pool if a != victim]
        try:
            reliability.inject("fleet.scale")
            # stop new dispatch IMMEDIATELY (the prober would take one
            # sweep to observe the draining healthz)
            prober = getattr(self.router, "_prober", None)
            if prober is not None:
                prober.mark(victim, "draining")
            status, body = _post_json(
                victim, "/worker_drain",
                {"action": "begin", "peers": peers,
                 "timeout": self.drain_timeout})
            if status != 200:
                raise RuntimeError(
                    f"worker_drain answered {status}: "
                    f"{body.get('error', '')}")
        except Exception as e:  # noqa: BLE001
            self._event("scale_in_failed", victim, sig, error=str(e))
            self._unmark(victim)
            return
        with self._lock:
            self._draining = {"addr": victim, "t0": time.monotonic(),
                              "span_t0": time.time()}
        self._event("drain_begun", victim, sig)

    def _poll_drain(self):
        dr = self._draining
        victim = dr["addr"]
        try:
            _status, body = _get_json(victim, "/worker_drain")
            state = body.get("state", "")
        except Exception:   # noqa: BLE001 — the victim died mid-drain
            # its in-flight streams already failed over (journal), its
            # chains re-prefill on survivors: remove the corpse
            self._finish_scale_in(victim, outcome="lost",
                                  body={"state": "dead"})
            self.drains_lost += 1
            return
        if state == "drained":
            self._finish_scale_in(victim, outcome="drained", body=body)
        elif state in ("failed", "cancelled") or \
                time.monotonic() - dr["t0"] > self.drain_timeout + \
                2 * max(self.interval, 0.05):
            # abandon the scale-in: cancel (resumes admission) and put
            # the worker back into rotation
            try:
                _post_json(victim, "/worker_drain", {"action": "cancel"})
            except Exception:   # noqa: BLE001
                pass
            self._unmark(victim)
            with self._lock:
                self._draining = None
            self._last_action = time.monotonic()
            self._event("drain_abandoned", victim, {})
            self._record_drain("cancelled")

    def _finish_scale_in(self, victim, outcome: str, body: dict):
        with self._lock:
            dr = self._draining
            self._draining = None
        try:
            self.router._admin_backends(
                {"action": "remove", "role": "decode",
                 "host": victim[0], "port": victim[1]})
        except Exception as e:  # noqa: BLE001 — last-backend guard
            self._unmark(victim)
            self._event("scale_in_failed", victim, {}, error=str(e))
            return
        if self.provider is not None:
            try:
                self.provider.terminate(victim)
            except Exception:   # noqa: BLE001 — already dead is fine
                pass
        self.scale_ins += 1
        self._last_action = time.monotonic()
        self._event("scale_in", victim, {}, outcome=outcome,
                    chains=body.get("migrated_chains", 0))
        self._record_drain(outcome)
        ins = self._instruments()
        if ins is not None:
            ins["scale_events"].labels(direction="in").inc()
        if obs.enabled():
            t0 = dr.get("span_t0", time.time())
            obs.add_complete(
                "fleet/scale", t0, time.time() - t0, stage="llm_router",
                direction="in", backend=f"{victim[0]}:{victim[1]}",
                outcome=outcome,
                chains_migrated=body.get("migrated_chains", 0))

    def _unmark(self, addr):
        prober = getattr(self.router, "_prober", None)
        if prober is not None:
            prober.mark(addr, "ok")

    # -- accounting ----------------------------------------------------------
    def _event(self, action: str, addr, sig: dict, **extra):
        ev = {"ts": round(time.time(), 3), "action": action,
              "backend": f"{addr[0]}:{addr[1]}" if addr else None}
        if sig:
            ev["signals"] = {k: sig[k] for k in
                             ("workers", "queue", "active", "sheds")
                             if k in sig}
        ev.update(extra)
        with self._lock:
            self.events.append(ev)
            del self.events[:-64]
        if action in ("scale_out", "scale_in"):
            flight.record(action, backend=ev["backend"],
                          **{k: v for k, v in ev.items()
                               if k in ("signals", "outcome", "chains")})

    def _instruments(self):
        if not obs.enabled():
            return None
        if self._ins is None:
            self._ins = _fleet_instruments()
        return self._ins

    def _record_gauges(self):
        ins = self._instruments()
        if ins is not None:
            ins["workers"].set(len(self._pool()))

    def _record_drain(self, outcome: str):
        ins = self._instruments()
        if ins is not None:
            ins["drains"].labels(outcome=outcome).inc()

    def status(self) -> Dict[str, Any]:
        """The ``GET /fleet/autoscaler`` body."""
        with self._lock:
            events = list(self.events[-16:])
        dr = self._draining
        return {
            "min": self.min_workers, "max": self.max_workers,
            "workers": len(self._pool()),
            "interval_s": self.interval,
            "cooldown_s": self.cooldown,
            "sustain": self.sustain,
            "queue_high": self.queue_high,
            "idle_low": self.idle_low,
            "ticks": self.ticks,
            "scale_outs": self.scale_outs,
            "scale_ins": self.scale_ins,
            "drains_lost": self.drains_lost,
            "draining": (f"{dr['addr'][0]}:{dr['addr'][1]}"
                         if dr else None),
            "provider": (type(self.provider).__name__
                         if self.provider is not None else None),
            "events": events,
        }

"""Model-free self-speculative drafting (ISSUE 19).

The batch-1 decode wall is bandwidth: every engine tick streams the full
weight set to emit ONE token. Speculative decoding emits several per
tick — draft k candidate tokens cheaply, verify them all in one pass —
but the classic recipe needs a second (draft) model resident in HBM.
This module is the **model-free** variant (prompt-lookup / n-gram
decoding): the draft source is the request's OWN token history. Match
the most recent suffix of ``prompt + generated_so_far`` against an
earlier occurrence of the same n-gram, and propose the tokens that
followed it. Zero extra device memory, zero extra model bandwidth, and
on the repetitive workloads serving actually sees (code, templated
text, retrieval-augmented prompts quoting their own context) the match
rate is high exactly when the bandwidth win matters.

The proposer is pure host-side python over int token ids — drafting
runs in the dispatch gap while the device executes the previous step,
so it adds nothing to the device critical path. Verification is the
compiled chunk pass (``kvcache.prefill.make_spec_step``): the drafts
run as a ragged chunk at the row's position offset and a fused accept
kernel (``kernels.sampling.spec_accept``) keeps the longest prefix that
greedy decode would have produced anyway — acceptance is exactness, so
accepted output is bit-identical to the non-speculative engine.

Adaptive k: a per-request proposer tracks an EMA of its draft
acceptance rate. When it drops below ``bigdl.llm.spec.backoff`` the
draft length halves (floor 2: one real draft) — a request whose
history stops
predicting its future degrades toward plain decode instead of paying a
wide rejected verify chunk every tick; sustained acceptance grows k
back to the configured ceiling.
"""

from __future__ import annotations

from typing import List, Sequence

__all__ = ["NGramProposer"]


class NGramProposer:
    """Per-request prompt-lookup draft proposer with adaptive k.

    ``k`` is the ceiling on drafts per tick (``bigdl.llm.spec.k``),
    ``min_match`` the shortest suffix n-gram worth trusting
    (``bigdl.llm.spec.min_match``), ``backoff`` the acceptance-rate EMA
    floor below which the live draft length halves
    (``bigdl.llm.spec.backoff``). One instance per engine slot /
    request — the adaptive state is the request's, not the server's.
    """

    __slots__ = ("k", "min_match", "max_match", "backoff", "k_live",
                 "acc_ema", "proposed_total", "accepted_total",
                 "last_match")

    def __init__(self, k: int = 4, min_match: int = 2,
                 backoff: float = 0.5, max_match: int = 8):
        self.k = max(1, int(k))
        self.min_match = max(1, int(min_match))
        self.max_match = max(self.min_match, int(max_match))
        self.backoff = float(backoff)
        self.k_live = self.k          # adaptive draft length (<= k)
        self.acc_ema = 1.0            # optimistic start: first tick drafts
        self.proposed_total = 0
        self.accepted_total = 0
        self.last_match = 0           # n-gram length behind the last draft

    def propose(self, ids: Sequence[int],
                limit: int | None = None) -> List[int]:
        """Draft up to ``min(k_live, limit)`` continuation tokens for
        ``ids`` (= prompt + generated so far), or ``[]`` when no suffix
        n-gram of length >= ``min_match`` recurs earlier in ``ids`` —
        the engine then degrades this pass to plain decode.

        Longest-match-first, then the most recent occurrence that has a
        FULL ``kmax``-token continuation after it — the same tie-break
        prompt-lookup decoding uses, except occurrences too close to
        the end of ``ids`` (a constant-token run always matches at the
        second-to-last position, with nothing after it) lose to earlier
        ones that can actually supply drafts. A proposal shorter than 2
        tokens is worthless — the engine consumes it as
        ``proposal[1:]``, the first token targeting the position the
        verify step fills on device — so the floor is 2.
        """
        ids = list(ids)
        n = len(ids)
        kmax = self.k_live if limit is None else min(self.k_live,
                                                    int(limit))
        if kmax < 1 or n < self.min_match + 1:
            return []
        for m in range(min(self.max_match, n - 1),
                       self.min_match - 1, -1):
            tail = ids[n - m:]
            last = tail[-1]
            best: List[int] = []
            # j = end index of a candidate EARLIER occurrence; right to
            # left so the most recent context wins the tie
            for j in range(n - 2, m - 2, -1):
                if ids[j] != last or ids[j - m + 1:j + 1] != tail:
                    continue
                drafts = ids[j + 1:j + 1 + kmax]
                if len(drafts) == kmax:
                    self.last_match = m
                    return drafts
                if len(drafts) > len(best):
                    best = drafts
            if len(best) >= 2:
                self.last_match = m
                return best
        return []

    def observe(self, proposed: int, accepted: int) -> None:
        """Fold one verify outcome (``accepted`` of ``proposed`` draft
        tokens survived) into the acceptance EMA and adapt ``k_live``:
        below ``backoff`` the draft length halves; an EMA back above
        the midpoint between ``backoff`` and 1.0 regrows it one step
        per tick toward ``k``.

        The floor is 2, not 1: the engine consumes a proposal as
        ``proposal[1:]`` (the first token targets the position the
        verify step fills with the on-device greedy token), so a
        1-token proposal carries zero drafts — speculation would shut
        off permanently, and with no verifies this EMA could never
        observe a recovery. Floor 2 keeps one real draft in play so a
        history that turns repetitive again regrows k."""
        if proposed <= 0:
            return
        self.proposed_total += proposed
        self.accepted_total += accepted
        rate = accepted / proposed
        self.acc_ema = 0.5 * self.acc_ema + 0.5 * rate
        if self.acc_ema < self.backoff:
            self.k_live = max(min(2, self.k), self.k_live // 2)
        elif self.acc_ema > (1.0 + self.backoff) / 2.0 and \
                self.k_live < self.k:
            self.k_live += 1

    @property
    def accept_rate(self) -> float:
        """Lifetime draft acceptance rate (1.0 before any verify)."""
        if self.proposed_total <= 0:
            return 1.0
        return self.accepted_total / self.proposed_total

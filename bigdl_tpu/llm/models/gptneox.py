"""GPT-NeoX family on TPU (ref: P:llm/ggml/model/gptneox — the reference
ships five ggml model families; round 1 shipped Llama only. GPT-NeoX is
architecturally distinct from Llama: LayerNorm with bias (not RMSNorm),
biased linears, **parallel residual** (x + attn(ln1 x) + mlp(ln2 x)),
partial rotary embedding (``rotary_pct`` of head dims), GELU MLP, no GQA).

Same TPU-first skeleton as llama.py: scan-stacked decoder layers, static
ring kv cache updated in-program, q4_0 quantized linears dispatching to
the Pallas kernel on TPU, TP PartitionSpecs over ``model``.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from bigdl_tpu.llm.models._facade import CausalLMFacade
from bigdl_tpu.llm.models.llama import _attention, _linear


@dataclasses.dataclass
class GptNeoXConfig:
    vocab_size: int = 50432
    hidden_size: int = 6144
    intermediate_size: int = 24576
    num_hidden_layers: int = 44
    num_attention_heads: int = 64
    rotary_pct: float = 0.25
    rotary_emb_base: float = 10000.0
    max_position_embeddings: int = 2048
    layer_norm_eps: float = 1e-5
    use_parallel_residual: bool = True
    attn_block_size: int = 1024
    sliding_window = None          # read by the shared _attention
    # GQA-free family
    @property
    def num_key_value_heads(self) -> int:
        return self.num_attention_heads

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @classmethod
    def pythia_70m(cls) -> "GptNeoXConfig":
        return cls(vocab_size=50304, hidden_size=512, intermediate_size=2048,
                   num_hidden_layers=6, num_attention_heads=8)

    @classmethod
    def tiny(cls, vocab: int = 256) -> "GptNeoXConfig":
        return cls(vocab_size=vocab, hidden_size=64, intermediate_size=128,
                   num_hidden_layers=2, num_attention_heads=4,
                   max_position_embeddings=128)

    @classmethod
    def from_hf(cls, hf) -> "GptNeoXConfig":
        g = (lambda k, d: getattr(hf, k, d))
        return cls(
            vocab_size=g("vocab_size", 50432),
            hidden_size=g("hidden_size", 6144),
            intermediate_size=g("intermediate_size", 24576),
            num_hidden_layers=g("num_hidden_layers", 44),
            num_attention_heads=g("num_attention_heads", 64),
            rotary_pct=g("rotary_pct", 0.25),
            rotary_emb_base=g("rotary_emb_base", 10000.0),
            max_position_embeddings=g("max_position_embeddings", 2048),
            layer_norm_eps=g("layer_norm_eps", 1e-5),
            use_parallel_residual=g("use_parallel_residual", True))


_LAYER_LINEARS = ("q_proj", "k_proj", "v_proj", "o_proj",
                  "fc_in", "fc_out")


def linear_shapes(cfg: GptNeoXConfig) -> Dict[str, Tuple[int, int]]:
    h = cfg.hidden_size
    return {
        "q_proj": (h, h), "k_proj": (h, h), "v_proj": (h, h),
        "o_proj": (h, h),
        "fc_in": (cfg.intermediate_size, h),
        "fc_out": (h, cfg.intermediate_size),
    }


def init_params(cfg: GptNeoXConfig, seed: int = 0,
                dtype=jnp.bfloat16) -> Dict[str, Any]:
    key = jax.random.PRNGKey(seed)
    h = cfg.hidden_size
    L = cfg.num_hidden_layers
    shapes = linear_shapes(cfg)

    def mk(key, shape, scale=None):
        scale = scale or (1.0 / np.sqrt(shape[-1]))
        return (jax.random.normal(key, shape, jnp.float32)
                * scale).astype(dtype)

    keys = jax.random.split(key, 4 + len(shapes))
    layers: Dict[str, Any] = {}
    for i, (name, shape) in enumerate(shapes.items()):
        layers[name] = {"w": mk(keys[i], (L,) + shape),
                        "b": jnp.zeros((L, shape[0]), dtype)}
    for norm in ("input_layernorm", "post_attention_layernorm"):
        layers[norm] = {"w": jnp.ones((L, h), dtype),
                        "b": jnp.zeros((L, h), dtype)}
    return {
        "embed_in": mk(keys[-3], (cfg.vocab_size, h), 0.02),
        "final_norm": {"w": jnp.ones((h,), dtype),
                       "b": jnp.zeros((h,), dtype)},
        "embed_out": {"w": mk(keys[-2], (cfg.vocab_size, h))},
        "layers": layers,
    }


def quantize_params(params: Dict[str, Any], qtype: str = "sym_int4"
                    ) -> Dict[str, Any]:
    """ggml-quantize the decoder linears into the k-major TPU kernel
    layout (weights only; biases stay bf16)."""
    from bigdl_tpu.llm.kernels import quantize_tpu

    if qtype != "sym_int4":
        raise NotImplementedError(
            "the scanned decoder path implements q4_0 (sym_int4)")
    out = dict(params)
    layers = dict(params["layers"])
    for name in _LAYER_LINEARS:
        w = np.asarray(layers[name]["w"], np.float32)
        qs, ss = [], []
        for l in range(w.shape[0]):
            qd = quantize_tpu(w[l], qtype)
            qs.append(qd["q"])
            ss.append(qd["scale"])
        layers[name] = {"q": jnp.asarray(np.stack(qs)),
                        "scale": jnp.asarray(np.stack(ss)),
                        "b": layers[name]["b"]}
    out["layers"] = layers
    return out


def param_pspecs(params: Dict[str, Any]) -> Dict[str, Any]:
    """Megatron TP rules over ``model``: q/k/v and fc_in row-sharded,
    o_proj/fc_out col-sharded, embeddings vocab-sharded, norms/biases of
    col-sharded layers replicated."""
    ROW = {"q_proj", "k_proj", "v_proj", "fc_in"}

    def spec_for(path, leaf):
        keys = [str(getattr(p, "key", "")) for p in path]
        stacked = "layers" in keys
        d0 = 1 if stacked else 0
        name = next((k for k in keys if k in ROW
                     or k in ("o_proj", "fc_out", "embed_in",
                              "embed_out")), None)
        if name is None or getattr(leaf, "ndim", 0) <= d0:
            return P()
        is_bias = keys[-1] == "b"
        kmajor = keys[-1] in ("q", "scale", "zero")   # TPU k-major layout
        spec = [None] * leaf.ndim
        if name in ROW or name in ("embed_in", "embed_out"):
            if kmajor:
                spec[-1] = "model"           # N is the last dim
            else:
                spec[d0] = "model"           # bias of a row-sharded linear
                # shards with it (dim d0 is the output dim for both)
        elif not is_bias:                    # o_proj / fc_out weights: K dim
            if kmajor:
                spec[d0] = "model"
            elif leaf.ndim > d0 + 1:
                spec[d0 + 1] = "model"
        return P(*spec)

    return jax.tree_util.tree_map_with_path(spec_for, params)


def _layer_norm(x, wd, eps):
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, -1, keepdims=True)
    var = jnp.var(x32, -1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return (y.astype(x.dtype) * wd["w"].astype(x.dtype)
            + wd["b"].astype(x.dtype))


def _linear_b(wd, x):
    y = _linear({k: v for k, v in wd.items() if k != "b"}, x)
    return y + wd["b"].astype(y.dtype)


def _partial_rope(x, positions, cfg: GptNeoXConfig):
    """Rotate only the first ``rotary_pct`` of head dims (HF convention:
    interleaved-free rotate_half on the rotary slice)."""
    d = x.shape[-1]
    rot = int(d * cfg.rotary_pct)
    if rot == 0:
        return x
    x_rot, x_pass = x[..., :rot], x[..., rot:]
    inv = 1.0 / (cfg.rotary_emb_base
                 ** (jnp.arange(0, rot, 2, dtype=jnp.float32) / rot))
    ang = positions[..., None].astype(jnp.float32) * inv     # (B,T,rot/2)
    cos = jnp.cos(ang)[:, :, None, :]
    sin = jnp.sin(ang)[:, :, None, :]
    x1, x2 = jnp.split(x_rot.astype(jnp.float32), 2, axis=-1)
    rotated = jnp.concatenate([x1 * cos - x2 * sin,
                               x2 * cos + x1 * sin], axis=-1)
    return jnp.concatenate([rotated.astype(x.dtype), x_pass], axis=-1)


def init_cache(cfg: GptNeoXConfig, batch: int, max_len: int,
               dtype=jnp.bfloat16) -> Dict[str, jnp.ndarray]:
    shape = (cfg.num_hidden_layers, batch, max_len,
             cfg.num_attention_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype),
            "pos": jnp.zeros((), jnp.int32)}


def forward(params: Dict[str, Any], cfg: GptNeoXConfig,
            tokens: jnp.ndarray, cache: Dict[str, jnp.ndarray],
            positions: jnp.ndarray) -> Tuple[jnp.ndarray, Dict]:
    x = params["embed_in"][tokens]
    start = cache["pos"]
    s_max = cache["k"].shape[2]
    valid = jnp.arange(s_max)[None, :] < (start + tokens.shape[1])
    nh, hd = cfg.num_attention_heads, cfg.head_dim

    def layer_step(carry, inputs):
        x, = carry
        lp, k_cache, v_cache = inputs
        b, t, _ = x.shape
        h1 = _layer_norm(x, lp["input_layernorm"], cfg.layer_norm_eps)
        q = _linear_b(lp["q_proj"], h1).reshape(b, t, nh, hd)
        k = _linear_b(lp["k_proj"], h1).reshape(b, t, nh, hd)
        v = _linear_b(lp["v_proj"], h1).reshape(b, t, nh, hd)
        q = _partial_rope(q, positions, cfg)
        k = _partial_rope(k, positions, cfg)
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype), (0, start, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype), (0, start, 0, 0))
        attn = _attention(q, k_cache, v_cache, positions, valid, cfg)
        attn = _linear_b(lp["o_proj"], attn)
        h2_in = x if cfg.use_parallel_residual else x + attn
        h2 = _layer_norm(h2_in, lp["post_attention_layernorm"],
                         cfg.layer_norm_eps)
        mlp = _linear_b(lp["fc_out"], jax.nn.gelu(
            _linear_b(lp["fc_in"], h2).astype(jnp.float32),
            approximate=False).astype(x.dtype))
        if cfg.use_parallel_residual:
            x = x + attn + mlp
        else:
            x = h2_in + mlp
        return (x,), (k_cache, v_cache)

    (x,), (k_new, v_new) = jax.lax.scan(
        layer_step, (x,), (params["layers"], cache["k"], cache["v"]))
    x = _layer_norm(x, params["final_norm"], cfg.layer_norm_eps)
    logits = _linear(params["embed_out"], x)
    return logits.astype(jnp.float32), {
        "k": k_new, "v": v_new, "pos": start + tokens.shape[1]}


def paged_decode_step(params, cfg, k_pages, v_pages, bt, lens, toks,
                      *, page: int):
    """GPT-NeoX paged-KV decode step — the family's layer math (LN with
    bias, biased linears, partial rotary, PARALLEL residual) in the
    same structure as serving.paged_decode_step: rolled layer scan,
    read-only pools (stats kernel + flash merge of the current token),
    one post-scan scatter into the donated pools. Lets the paged
    continuous-batching LLMServer serve the NeoX family."""
    from bigdl_tpu.llm.serving import paged_attend, scatter_new_kv
    b = toks.shape[0]
    L = cfg.num_hidden_layers
    nh, hd = cfg.num_attention_heads, cfg.head_dim
    x = params["embed_in"][toks][:, None]                     # (B, 1, H)
    positions = lens[:, None].astype(jnp.int32)
    attend = paged_attend(k_pages, v_pages, bt, lens, page=page)

    def layer_step(carry, inputs):
        x, = carry
        lp, l = inputs
        h1 = _layer_norm(x, lp["input_layernorm"], cfg.layer_norm_eps)
        q = _linear_b(lp["q_proj"], h1).reshape(b, 1, nh, hd)
        k = _linear_b(lp["k_proj"], h1).reshape(b, 1, nh, hd)
        v = _linear_b(lp["v_proj"], h1).reshape(b, 1, nh, hd)
        q = _partial_rope(q, positions, cfg)
        k = _partial_rope(k, positions, cfg)
        attn = attend(l, q, k, v).astype(x.dtype)
        attn = _linear_b(lp["o_proj"], attn.reshape(b, 1, -1))
        h2_in = x if cfg.use_parallel_residual else x + attn
        h2 = _layer_norm(h2_in, lp["post_attention_layernorm"],
                         cfg.layer_norm_eps)
        mlp = _linear_b(lp["fc_out"], jax.nn.gelu(
            _linear_b(lp["fc_in"], h2).astype(jnp.float32),
            approximate=False).astype(x.dtype))
        if cfg.use_parallel_residual:
            x = x + attn + mlp
        else:
            x = h2_in + mlp
        return (x,), (k[:, 0], v[:, 0])

    (x,), (k_new, v_new) = jax.lax.scan(
        layer_step, (x,), (params["layers"], jnp.arange(L)))
    x = _layer_norm(x, params["final_norm"], cfg.layer_norm_eps)
    logits = _linear(params["embed_out"], x)
    k_pages, v_pages = scatter_new_kv(k_pages, v_pages, bt, lens,
                                      k_new, v_new, page=page)
    return logits[:, 0].astype(jnp.float32), k_pages, v_pages


# pipelined-engine step shape (ISSUE 4): sampling folded on device,
# device-resident lens carry, fence element — see kernels.sampling
from bigdl_tpu.llm.kernels.sampling import make_sampled_step  # noqa: E402

paged_decode_step_sampled = make_sampled_step(paged_decode_step)

# prefix-cache partial prefill (ISSUE 5): suffix-only prefill over a
# pre-populated block-table prefix — see llm/kvcache/prefill.py
from bigdl_tpu.llm.kvcache.prefill import make_partial_prefill  # noqa: E402

paged_prefill_partial = make_partial_prefill(forward, init_cache)


def paged_prefill_ragged(params, cfg, k_pages, v_pages, toks, length,
                         offset, bt_row, phys, slots, fork_dst,
                         fork_src, *, page: int,
                         full_logits: bool = False):
    """Ragged in-place prefill (ISSUE 8) — the NeoX layer math (LN with
    bias, partial rotary, parallel residual) over the suffix tokens,
    attention reading the cached prefix in place via the ragged kernel;
    COW fork + one post-scan scatter fused into the same dispatch (see
    llama.paged_prefill_ragged for the structure and the
    ``full_logits`` speculative-verify variant)."""
    from bigdl_tpu.llm.kvcache.prefill import (fork_tail_pages,
                                               ragged_prefill_attend,
                                               scatter_suffix_kv)
    b, bucket = toks.shape                                  # b == 1
    L = cfg.num_hidden_layers
    nh, hd = cfg.num_attention_heads, cfg.head_dim
    k_pages, v_pages = fork_tail_pages(k_pages, v_pages, fork_dst,
                                       fork_src)
    positions = (offset
                 + jnp.arange(bucket, dtype=jnp.int32))[None]  # (1, Tq)
    x = params["embed_in"][toks]
    attend = ragged_prefill_attend(k_pages, v_pages, bt_row, offset,
                                   length, page=page)

    def layer_step(carry, inputs):
        x, = carry
        lp, l = inputs
        h1 = _layer_norm(x, lp["input_layernorm"], cfg.layer_norm_eps)
        q = _linear_b(lp["q_proj"], h1).reshape(b, bucket, nh, hd)
        k = _linear_b(lp["k_proj"], h1).reshape(b, bucket, nh, hd)
        v = _linear_b(lp["v_proj"], h1).reshape(b, bucket, nh, hd)
        q = _partial_rope(q, positions, cfg)
        k = _partial_rope(k, positions, cfg)
        # pool-precision K/V before attention (bit-parity with the
        # dense temp-cache path — see llama.paged_prefill_ragged)
        k = k.astype(k_pages.dtype)
        v = v.astype(v_pages.dtype)
        attn = attend(l, q, k, v).astype(x.dtype)
        attn = _linear_b(lp["o_proj"], attn.reshape(b, bucket, -1))
        h2_in = x if cfg.use_parallel_residual else x + attn
        h2 = _layer_norm(h2_in, lp["post_attention_layernorm"],
                         cfg.layer_norm_eps)
        mlp = _linear_b(lp["fc_out"], jax.nn.gelu(
            _linear_b(lp["fc_in"], h2).astype(jnp.float32),
            approximate=False).astype(x.dtype))
        if cfg.use_parallel_residual:
            x = x + attn + mlp
        else:
            x = h2_in + mlp
        return (x,), (k[0], v[0])

    (x,), (k_new, v_new) = jax.lax.scan(
        layer_step, (x,), (params["layers"], jnp.arange(L)))
    x = _layer_norm(x, params["final_norm"], cfg.layer_norm_eps)
    logits = _linear(params["embed_out"], x)
    k_pages, v_pages = scatter_suffix_kv(k_pages, v_pages, phys, slots,
                                         k_new, v_new)
    if full_logits:
        return k_pages, v_pages, logits[0].astype(jnp.float32)
    last = jax.lax.dynamic_index_in_dim(logits[0], length - 1, 0,
                                        keepdims=False)
    return k_pages, v_pages, last.astype(jnp.float32)


def paged_step_mixed(params, cfg, k_pages, v_pages, bt, lens, last,
                     active, temperature, key, ctoks, clen, coff,
                     cbt_row, cphys, cslots, fork_dst, fork_src, *,
                     page: int, do_sample: bool = False,
                     top_k: int = 0):
    """Unified mixed prefill+decode step (ISSUE 14) — the NeoX decode
    and ragged-chunk legs fused into one program (see
    :func:`bigdl_tpu.llm.kvcache.prefill.make_mixed_step`)."""
    from bigdl_tpu.llm.kvcache.prefill import make_mixed_step
    return make_mixed_step(paged_decode_step, paged_prefill_ragged)(
        params, cfg, k_pages, v_pages, bt, lens, last, active,
        temperature, key, ctoks, clen, coff, cbt_row, cphys, cslots,
        fork_dst, fork_src, page=page, do_sample=do_sample, top_k=top_k)


def paged_step_spec(params, cfg, k_pages, v_pages, bt, lens, last,
                    active, temperature, key, srow, ctoks, n_draft,
                    cbt_row, cphys, cslots, *, page: int,
                    do_sample: bool = False, top_k: int = 0):
    """Speculative verify step (ISSUE 19) — the NeoX decode and
    full-logits ragged-chunk legs fused with the greedy accept kernel
    (see :func:`bigdl_tpu.llm.kvcache.prefill.make_spec_step`)."""
    from bigdl_tpu.llm.kvcache.prefill import make_spec_step
    return make_spec_step(paged_decode_step, paged_prefill_ragged)(
        params, cfg, k_pages, v_pages, bt, lens, last, active,
        temperature, key, srow, ctoks, n_draft, cbt_row, cphys, cslots,
        page=page, do_sample=do_sample, top_k=top_k)


class GptNeoXForCausalLM(CausalLMFacade):
    """Generation facade — shared driver (see models._facade)."""

    _forward = staticmethod(forward)
    _init_cache = staticmethod(init_cache)
    _init_params = staticmethod(init_params)
    _quantize_params = staticmethod(quantize_params)

    def shard(self, mesh) -> "GptNeoXForCausalLM":
        """Place params on a mesh with TP PartitionSpecs."""
        from jax.sharding import NamedSharding

        specs = param_pspecs(self.params)
        self.params = jax.tree_util.tree_map(
            lambda a, s: jax.device_put(a, NamedSharding(mesh, s)),
            self.params, specs)
        return self


# ---------------------------------------------------------------------------
# HF interop (safetensors, no torch)
# ---------------------------------------------------------------------------

def load_hf_gptneox_safetensors(path: str,
                                cfg: Optional[GptNeoXConfig] = None,
                                qtype: Optional[str] = None,
                                dtype=jnp.bfloat16) -> Dict[str, Any]:
    """HF GPTNeoXForCausalLM checkpoint → our stacked layout. The HF
    layer fuses qkv as ``query_key_value`` with per-head interleaving
    [q1 k1 v1 q2 k2 v2 ...]; we split it back into separate projections."""
    import json as _json
    import os as _os

    from bigdl_tpu.llm.kernels import quantize_tpu

    if qtype and qtype != "sym_int4":
        raise NotImplementedError("q4_0 only on the scanned path")
    if cfg is None:
        with open(_os.path.join(path, "config.json")) as f:
            raw = _json.load(f)
        cfg = GptNeoXConfig.from_hf(type("HFConfig", (), raw)())

    # lazy per-tensor reads (same stream-per-layer pattern as the llama
    # loader): only one layer's tensors are resident at a time
    from bigdl_tpu.llm.transformers.st_reader import SafetensorsReader
    get = SafetensorsReader(path).get

    L = cfg.num_hidden_layers
    nh, hd, h = cfg.num_attention_heads, cfg.head_dim, cfg.hidden_size
    _HF_LIN = {"o_proj": "attention.dense", "fc_in": "mlp.dense_h_to_4h",
               "fc_out": "mlp.dense_4h_to_h"}
    # per-layer accumulators: only one layer's fp32 tensors live at a time
    acc: Dict[str, Dict[str, list]] = {
        n: {"w": [], "q": [], "scale": [], "b": []} for n in _LAYER_LINEARS}

    def put_linear(name, w, b):
        a = acc[name]
        a["b"].append(b)
        if qtype:
            qd = quantize_tpu(w, qtype)
            a["q"].append(qd["q"])
            a["scale"].append(qd["scale"])
        else:
            a["w"].append(w.astype(np.float32))

    for l in range(L):
        # fused qkv: (nh*(3*hd), h) output dim laid out [q k v] per head
        w = get(f"gpt_neox.layers.{l}.attention.query_key_value.weight")
        b = get(f"gpt_neox.layers.{l}.attention.query_key_value.bias")
        w = w.reshape(nh, 3, hd, h)
        b = b.reshape(nh, 3, hd)
        for i, name in enumerate(("q_proj", "k_proj", "v_proj")):
            put_linear(name, w[:, i].reshape(h, h), b[:, i].reshape(h))
        for name, hf in _HF_LIN.items():
            put_linear(name, get(f"gpt_neox.layers.{l}.{hf}.weight"),
                       get(f"gpt_neox.layers.{l}.{hf}.bias"))

    layers: Dict[str, Any] = {}
    for name, a in acc.items():
        entry: Dict[str, Any] = {"b": jnp.asarray(np.stack(a["b"]), dtype)}
        if qtype:
            entry["q"] = jnp.asarray(np.stack(a["q"]))
            entry["scale"] = jnp.asarray(np.stack(a["scale"]))
        else:
            entry["w"] = jnp.asarray(np.stack(a["w"]), dtype)
        layers[name] = entry
    for ours, hf in (("input_layernorm", "input_layernorm"),
                     ("post_attention_layernorm",
                      "post_attention_layernorm")):
        layers[ours] = {
            "w": jnp.asarray(np.stack(
                [get(f"gpt_neox.layers.{l}.{hf}.weight")
                 for l in range(L)]), dtype),
            "b": jnp.asarray(np.stack(
                [get(f"gpt_neox.layers.{l}.{hf}.bias")
                 for l in range(L)]), dtype)}
    return {
        "embed_in": jnp.asarray(get("gpt_neox.embed_in.weight"), dtype),
        "final_norm": {
            "w": jnp.asarray(get("gpt_neox.final_layer_norm.weight"),
                             dtype),
            "b": jnp.asarray(get("gpt_neox.final_layer_norm.bias"),
                             dtype)},
        "embed_out": {"w": jnp.asarray(get("embed_out.weight"), dtype)},
        "layers": layers,
    }

"""jax LLM implementations (ref: the per-arch forward rewrites under
P:llm/transformers/models/ — here full TPU-native models). Five ggml
families (P:llm/ggml/model/): Llama (also covering Mistral, Mixtral,
Qwen2 and the GLM/ChatGLM rotary variant), GPT-NeoX, Bloom, StarCoder."""

from bigdl_tpu.llm.models.bloom import BloomConfig, BloomForCausalLM
from bigdl_tpu.llm.models.gptneox import (
    GptNeoXConfig, GptNeoXForCausalLM)
from bigdl_tpu.llm.models.llama import (
    LlamaConfig, LlamaForCausalLM)
from bigdl_tpu.llm.models.starcoder import (
    StarCoderConfig, StarCoderForCausalLM)

__all__ = ["BloomConfig", "BloomForCausalLM",
           "GptNeoXConfig", "GptNeoXForCausalLM",
           "LlamaConfig", "LlamaForCausalLM",
           "StarCoderConfig", "StarCoderForCausalLM"]

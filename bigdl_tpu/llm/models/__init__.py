"""jax LLM implementations (ref: the per-arch forward rewrites under
P:llm/transformers/models/ — here full TPU-native models)."""

from bigdl_tpu.llm.models.gptneox import (
    GptNeoXConfig, GptNeoXForCausalLM)
from bigdl_tpu.llm.models.llama import (
    LlamaConfig, LlamaForCausalLM)

__all__ = ["GptNeoXConfig", "GptNeoXForCausalLM",
           "LlamaConfig", "LlamaForCausalLM"]

"""Shared generation facade for the simple model families.

GPT-NeoX, Bloom and StarCoder drive the exact same loop: jitted
prefill step + one-jit greedy ``decode_scan`` with a donated cache and
EOS-chunked early exit. One base class keeps the four families'
decode-loop semantics in lockstep (review r5: the copy-pasted facades
could silently diverge on a one-file fix). Llama keeps its richer
facade (sampling knobs, ring prefill, TP shard) in llama.py.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.llm.models.llama import decode_scan


class CausalLMFacade:
    """Greedy generation driver over a family's ``forward``/``init_cache``.

    Subclasses set ``_forward`` and ``_init_cache`` (module functions)
    as class attributes via ``staticmethod``."""

    _forward = None
    _init_cache = None

    def __init__(self, cfg, params: Dict[str, Any],
                 max_cache_len: int = 512, cache_dtype=jnp.bfloat16):
        self.config = cfg
        self.params = params
        self.cache_dtype = cache_dtype
        self.max_cache_len = min(max_cache_len,
                                 cfg.max_position_embeddings)
        fwd = type(self)._forward
        self._step = jax.jit(functools.partial(fwd, cfg=cfg))
        self._decode_scan = jax.jit(
            functools.partial(decode_scan, cfg=cfg, forward_fn=fwd),
            static_argnames=("num_tokens", "do_sample", "top_k",
                             "eos_token_id"),
            donate_argnames=("cache",))

    @classmethod
    def from_config(cls, cfg, seed: int = 0,
                    load_in_low_bit: Optional[str] = None,
                    max_cache_len: int = 512):
        params = cls._init_params(cfg, seed)
        if load_in_low_bit:
            params = cls._quantize_params(params, load_in_low_bit)
        return cls(cfg, params, max_cache_len)

    def __call__(self, tokens, cache=None, positions=None):
        b, t = tokens.shape
        if cache is None:
            cache = type(self)._init_cache(self.config, b,
                                           self.max_cache_len,
                                           dtype=self.cache_dtype)
        if positions is None:
            base = jnp.asarray(cache["pos"])
            positions = base + jnp.broadcast_to(jnp.arange(t), (b, t))
        return self._step(self.params, tokens=jnp.asarray(tokens),
                          cache=cache, positions=positions)

    def generate(self, input_ids, max_new_tokens: int = 32,
                 eos_token_id: Optional[int] = None,
                 decode_chunk: int = 32):
        """Greedy decode via the one-jit scan loop (llama.decode_scan)."""
        tokens = jnp.asarray(np.asarray(input_ids), jnp.int32)
        b, t0 = tokens.shape
        if t0 + max_new_tokens > self.max_cache_len:
            raise ValueError(f"sequence {t0}+{max_new_tokens} exceeds "
                             f"cache {self.max_cache_len}")
        logits, cache = self(tokens)
        key = jax.random.PRNGKey(0)
        last = logits[:, -1]
        pieces = [np.asarray(tokens)]
        remaining = max_new_tokens
        chunk = max_new_tokens if eos_token_id is None else decode_chunk
        finished = jnp.zeros((b,), bool)
        while remaining > 0:
            n = min(chunk, remaining)
            toks, cache, last, key, finished = self._decode_scan(
                self.params, cache, last, key, jnp.float32(1.0), finished,
                num_tokens=n, eos_token_id=eos_token_id)
            pieces.append(np.asarray(toks))
            remaining -= n
            if (eos_token_id is not None
                    and np.asarray(finished).all()):
                break
        return np.concatenate(pieces, axis=1)

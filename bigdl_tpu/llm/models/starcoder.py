"""StarCoder / GPTBigCode family on TPU (ref: P:llm/ggml/model/starcoder
— the fourth of the reference's five ggml model families; SURVEY.md
§2.8 row 65). Distinct from the other stacks: **multi-query attention**
(ONE shared K/V head), learned absolute position embeddings (wpe, no
rotary), GPT-2-style LayerNorm+bias blocks, tanh-GELU MLP, tied head.

Same TPU-first skeleton: scan-stacked decoder layers, static ring kv
cache updated in-program, q4_0 quantized linears on the Pallas kernel.
MQA needs no special kernel — the shared :func:`llama._attention`
groups all ``Hq`` query heads onto the single kv head (GQA with
``g = Hq``), so repeated K/V never materializes."""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.llm.models._facade import CausalLMFacade
from bigdl_tpu.llm.models.gptneox import _layer_norm, _linear_b
from bigdl_tpu.llm.models.llama import _attention


@dataclasses.dataclass
class StarCoderConfig:
    vocab_size: int = 49152
    hidden_size: int = 6144
    intermediate_size: int = 24576
    num_hidden_layers: int = 40
    num_attention_heads: int = 48
    num_key_value_heads: int = 1           # multi-query
    max_position_embeddings: int = 8192
    layer_norm_epsilon: float = 1e-5
    attn_block_size: int = 1024
    sliding_window = None                  # read by the shared _attention
    num_experts = 0

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @classmethod
    def starcoder_15b(cls) -> "StarCoderConfig":
        return cls()

    @classmethod
    def tiny(cls, vocab: int = 256) -> "StarCoderConfig":
        return cls(vocab_size=vocab, hidden_size=64, intermediate_size=128,
                   num_hidden_layers=2, num_attention_heads=4,
                   max_position_embeddings=128)

    @classmethod
    def from_hf(cls, hf) -> "StarCoderConfig":
        g = (lambda k, d: getattr(hf, k, d))
        return cls(vocab_size=g("vocab_size", 49152),
                   hidden_size=g("n_embd", 6144),
                   intermediate_size=g("n_inner", None)
                   or 4 * g("n_embd", 6144),
                   num_hidden_layers=g("n_layer", 40),
                   num_attention_heads=g("n_head", 48),
                   num_key_value_heads=(1 if g("multi_query", True)
                                        else g("n_head", 48)),
                   max_position_embeddings=g("n_positions", 8192),
                   layer_norm_epsilon=g("layer_norm_epsilon", 1e-5))


_LAYER_LINEARS = ("q_proj", "k_proj", "v_proj", "o_proj",
                  "fc_in", "fc_out")


def linear_shapes(cfg: StarCoderConfig) -> Dict[str, Tuple[int, int]]:
    h = cfg.hidden_size
    kv = cfg.num_key_value_heads * cfg.head_dim
    return {"q_proj": (h, h), "k_proj": (kv, h), "v_proj": (kv, h),
            "o_proj": (h, h), "fc_in": (cfg.intermediate_size, h),
            "fc_out": (h, cfg.intermediate_size)}


def init_params(cfg: StarCoderConfig, seed: int = 0,
                dtype=jnp.bfloat16) -> Dict[str, Any]:
    key = jax.random.PRNGKey(seed)
    h = cfg.hidden_size
    L = cfg.num_hidden_layers
    shapes = linear_shapes(cfg)

    def mk(key, shape, scale=None):
        scale = scale or (1.0 / np.sqrt(shape[-1]))
        return (jax.random.normal(key, shape, jnp.float32)
                * scale).astype(dtype)

    keys = jax.random.split(key, 5 + len(shapes))
    layers: Dict[str, Any] = {}
    for i, (name, shape) in enumerate(shapes.items()):
        layers[name] = {"w": mk(keys[i], (L,) + shape),
                        "b": jnp.zeros((L, shape[0]), dtype)}
    for norm in ("input_layernorm", "post_attention_layernorm"):
        layers[norm] = {"w": jnp.ones((L, h), dtype),
                        "b": jnp.zeros((L, h), dtype)}
    return {
        "wte": mk(keys[-3], (cfg.vocab_size, h), 0.02),
        "wpe": mk(keys[-4], (cfg.max_position_embeddings, h), 0.02),
        "ln_f": {"w": jnp.ones((h,), dtype), "b": jnp.zeros((h,), dtype)},
        "layers": layers,
    }


def quantize_params(params: Dict[str, Any], qtype: str = "sym_int4"
                    ) -> Dict[str, Any]:
    from bigdl_tpu.llm.kernels import quantize_tpu

    if qtype != "sym_int4":
        raise NotImplementedError(
            "the scanned decoder path implements q4_0 (sym_int4)")
    out = dict(params)
    layers = dict(params["layers"])
    for name in _LAYER_LINEARS:
        w = np.asarray(layers[name]["w"], np.float32)
        if w.shape[1] % 128:
            # the MQA k/v projections are (head_dim, h) = (128, h) at
            # production size but smaller in test configs — tiny N stays
            # dense (the kernel tiles N at 128)
            continue
        qs, ss = [], []
        for l in range(w.shape[0]):
            qd = quantize_tpu(w[l], qtype)
            qs.append(qd["q"])
            ss.append(qd["scale"])
        layers[name] = {"q": jnp.asarray(np.stack(qs)),
                        "scale": jnp.asarray(np.stack(ss)),
                        "b": layers[name]["b"]}
    out["layers"] = layers
    return out


def init_cache(cfg: StarCoderConfig, batch: int, max_len: int,
               dtype=jnp.bfloat16) -> Dict[str, jnp.ndarray]:
    shape = (cfg.num_hidden_layers, batch, max_len,
             cfg.num_key_value_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype),
            "pos": jnp.zeros((), jnp.int32)}


def forward(params: Dict[str, Any], cfg: StarCoderConfig,
            tokens: jnp.ndarray, cache: Dict[str, jnp.ndarray],
            positions: jnp.ndarray) -> Tuple[jnp.ndarray, Dict]:
    # learned absolute position embeddings — the family's position story
    x = params["wte"][tokens] + params["wpe"][positions].astype(
        params["wte"].dtype)
    start = cache["pos"]
    s_max = cache["k"].shape[2]
    valid = jnp.arange(s_max)[None, :] < (start + tokens.shape[1])
    nh, hd = cfg.num_attention_heads, cfg.head_dim
    kvh = cfg.num_key_value_heads

    def layer_step(carry, inputs):
        x, = carry
        lp, k_cache, v_cache = inputs
        b, t, _ = x.shape
        h1 = _layer_norm(x, lp["input_layernorm"], cfg.layer_norm_epsilon)
        q = _linear_b(lp["q_proj"], h1).reshape(b, t, nh, hd)
        k = _linear_b(lp["k_proj"], h1).reshape(b, t, kvh, hd)
        v = _linear_b(lp["v_proj"], h1).reshape(b, t, kvh, hd)
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype), (0, start, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype), (0, start, 0, 0))
        attn = _attention(q, k_cache, v_cache, positions, valid, cfg)
        x = x + _linear_b(lp["o_proj"], attn)
        h2 = _layer_norm(x, lp["post_attention_layernorm"],
                         cfg.layer_norm_epsilon)
        mlp = _linear_b(lp["fc_out"], jax.nn.gelu(
            _linear_b(lp["fc_in"], h2).astype(jnp.float32),
            approximate=True).astype(x.dtype))   # gelu_pytorch_tanh
        x = x + mlp
        return (x,), (k_cache, v_cache)

    (x,), (k_new, v_new) = jax.lax.scan(
        layer_step, (x,), (params["layers"], cache["k"], cache["v"]))
    x = _layer_norm(x, params["ln_f"], cfg.layer_norm_epsilon)
    logits = x @ params["wte"].T.astype(x.dtype)   # tied head
    return logits.astype(jnp.float32), {
        "k": k_new, "v": v_new, "pos": start + tokens.shape[1]}


def paged_decode_step(params, cfg, k_pages, v_pages, bt, lens, toks,
                      *, page: int):
    """StarCoder paged-KV decode step — learned position embeddings,
    MQA (the paged stats kernel's GQA grouping handles Hkv=1), LN with
    bias, sequential residual, tied head; same structure as
    serving.paged_decode_step (rolled scan, read-only pools, one
    post-scan scatter). Lets the paged LLMServer serve GPTBigCode."""
    from bigdl_tpu.llm.serving import paged_attend, scatter_new_kv
    b = toks.shape[0]
    L = cfg.num_hidden_layers
    nh, hd = cfg.num_attention_heads, cfg.head_dim
    kvh = cfg.num_key_value_heads
    positions = lens[:, None].astype(jnp.int32)
    x = (params["wte"][toks][:, None]
         + params["wpe"][positions].astype(params["wte"].dtype))
    attend = paged_attend(k_pages, v_pages, bt, lens, page=page)

    def layer_step(carry, inputs):
        x, = carry
        lp, l = inputs
        h1 = _layer_norm(x, lp["input_layernorm"], cfg.layer_norm_epsilon)
        q = _linear_b(lp["q_proj"], h1).reshape(b, 1, nh, hd)
        k = _linear_b(lp["k_proj"], h1).reshape(b, 1, kvh, hd)
        v = _linear_b(lp["v_proj"], h1).reshape(b, 1, kvh, hd)
        attn = attend(l, q, k, v).astype(x.dtype)
        x = x + _linear_b(lp["o_proj"], attn.reshape(b, 1, -1))
        h2 = _layer_norm(x, lp["post_attention_layernorm"],
                         cfg.layer_norm_epsilon)
        mlp = _linear_b(lp["fc_out"], jax.nn.gelu(
            _linear_b(lp["fc_in"], h2).astype(jnp.float32),
            approximate=True).astype(x.dtype))
        x = x + mlp
        return (x,), (k[:, 0], v[:, 0])

    (x,), (k_new, v_new) = jax.lax.scan(
        layer_step, (x,), (params["layers"], jnp.arange(L)))
    x = _layer_norm(x, params["ln_f"], cfg.layer_norm_epsilon)
    logits = x @ params["wte"].T.astype(x.dtype)
    k_pages, v_pages = scatter_new_kv(k_pages, v_pages, bt, lens,
                                      k_new, v_new, page=page)
    return logits[:, 0].astype(jnp.float32), k_pages, v_pages


# pipelined-engine step shape (ISSUE 4): sampling folded on device,
# device-resident lens carry, fence element — see kernels.sampling
from bigdl_tpu.llm.kernels.sampling import make_sampled_step  # noqa: E402

paged_decode_step_sampled = make_sampled_step(paged_decode_step)

# prefix-cache partial prefill (ISSUE 5): suffix-only prefill over a
# pre-populated block-table prefix — see llm/kvcache/prefill.py
from bigdl_tpu.llm.kvcache.prefill import make_partial_prefill  # noqa: E402

paged_prefill_partial = make_partial_prefill(forward, init_cache)


def paged_prefill_ragged(params, cfg, k_pages, v_pages, toks, length,
                         offset, bt_row, phys, slots, fork_dst,
                         fork_src, *, page: int,
                         full_logits: bool = False):
    """Ragged in-place prefill (ISSUE 8) — StarCoder's layer math
    (learned position embeddings, MQA via the kernel's GQA grouping,
    sequential residual, tied head) over the suffix tokens, attention
    reading the cached prefix in place; COW fork + one post-scan
    scatter fused into the same dispatch (see llama.paged_prefill_ragged
    for the structure and the ``full_logits`` speculative-verify
    variant)."""
    from bigdl_tpu.llm.kvcache.prefill import (fork_tail_pages,
                                               ragged_prefill_attend,
                                               scatter_suffix_kv)
    b, bucket = toks.shape                                  # b == 1
    L = cfg.num_hidden_layers
    nh, hd = cfg.num_attention_heads, cfg.head_dim
    kvh = cfg.num_key_value_heads
    k_pages, v_pages = fork_tail_pages(k_pages, v_pages, fork_dst,
                                       fork_src)
    positions = (offset
                 + jnp.arange(bucket, dtype=jnp.int32))[None]  # (1, Tq)
    x = (params["wte"][toks]
         + params["wpe"][positions].astype(params["wte"].dtype))
    attend = ragged_prefill_attend(k_pages, v_pages, bt_row, offset,
                                   length, page=page)

    def layer_step(carry, inputs):
        x, = carry
        lp, l = inputs
        h1 = _layer_norm(x, lp["input_layernorm"], cfg.layer_norm_epsilon)
        q = _linear_b(lp["q_proj"], h1).reshape(b, bucket, nh, hd)
        k = _linear_b(lp["k_proj"], h1).reshape(b, bucket, kvh, hd)
        v = _linear_b(lp["v_proj"], h1).reshape(b, bucket, kvh, hd)
        # pool-precision K/V before attention (bit-parity with the
        # dense temp-cache path — see llama.paged_prefill_ragged)
        k = k.astype(k_pages.dtype)
        v = v.astype(v_pages.dtype)
        attn = attend(l, q, k, v).astype(x.dtype)
        x = x + _linear_b(lp["o_proj"], attn.reshape(b, bucket, -1))
        h2 = _layer_norm(x, lp["post_attention_layernorm"],
                         cfg.layer_norm_epsilon)
        mlp = _linear_b(lp["fc_out"], jax.nn.gelu(
            _linear_b(lp["fc_in"], h2).astype(jnp.float32),
            approximate=True).astype(x.dtype))
        x = x + mlp
        return (x,), (k[0], v[0])

    (x,), (k_new, v_new) = jax.lax.scan(
        layer_step, (x,), (params["layers"], jnp.arange(L)))
    x = _layer_norm(x, params["ln_f"], cfg.layer_norm_epsilon)
    logits = x @ params["wte"].T.astype(x.dtype)
    k_pages, v_pages = scatter_suffix_kv(k_pages, v_pages, phys, slots,
                                         k_new, v_new)
    if full_logits:
        return k_pages, v_pages, logits[0].astype(jnp.float32)
    last = jax.lax.dynamic_index_in_dim(logits[0], length - 1, 0,
                                        keepdims=False)
    return k_pages, v_pages, last.astype(jnp.float32)


def paged_step_mixed(params, cfg, k_pages, v_pages, bt, lens, last,
                     active, temperature, key, ctoks, clen, coff,
                     cbt_row, cphys, cslots, fork_dst, fork_src, *,
                     page: int, do_sample: bool = False,
                     top_k: int = 0):
    """Unified mixed prefill+decode step (ISSUE 14) — the StarCoder
    decode and ragged-chunk legs fused into one program (see
    :func:`bigdl_tpu.llm.kvcache.prefill.make_mixed_step`)."""
    from bigdl_tpu.llm.kvcache.prefill import make_mixed_step
    return make_mixed_step(paged_decode_step, paged_prefill_ragged)(
        params, cfg, k_pages, v_pages, bt, lens, last, active,
        temperature, key, ctoks, clen, coff, cbt_row, cphys, cslots,
        fork_dst, fork_src, page=page, do_sample=do_sample, top_k=top_k)


def paged_step_spec(params, cfg, k_pages, v_pages, bt, lens, last,
                    active, temperature, key, srow, ctoks, n_draft,
                    cbt_row, cphys, cslots, *, page: int,
                    do_sample: bool = False, top_k: int = 0):
    """Speculative verify step (ISSUE 19) — the StarCoder decode and
    full-logits ragged-chunk legs fused with the greedy accept kernel
    (see :func:`bigdl_tpu.llm.kvcache.prefill.make_spec_step`)."""
    from bigdl_tpu.llm.kvcache.prefill import make_spec_step
    return make_spec_step(paged_decode_step, paged_prefill_ragged)(
        params, cfg, k_pages, v_pages, bt, lens, last, active,
        temperature, key, srow, ctoks, n_draft, cbt_row, cphys, cslots,
        page=page, do_sample=do_sample, top_k=top_k)


class StarCoderForCausalLM(CausalLMFacade):
    """Generation facade — shared driver (see models._facade)."""

    _forward = staticmethod(forward)
    _init_cache = staticmethod(init_cache)
    _init_params = staticmethod(init_params)
    _quantize_params = staticmethod(quantize_params)


# ---------------------------------------------------------------------------
# HF interop (safetensors, no torch)
# ---------------------------------------------------------------------------

def load_hf_starcoder_safetensors(path: str,
                                  cfg: Optional[StarCoderConfig] = None,
                                  qtype: Optional[str] = None,
                                  dtype=jnp.bfloat16) -> Dict[str, Any]:
    """HF GPTBigCodeForCausalLM checkpoint → our stacked layout. HF's
    ``attn.c_attn`` is a plain concat [q (h); k (kv); v (kv)] along the
    output dim (nn.Linear, NOT gpt2's transposed Conv1D)."""
    import json as _json
    import os as _os

    from bigdl_tpu.llm.kernels import quantize_tpu

    if qtype and qtype != "sym_int4":
        raise NotImplementedError("q4_0 only on the scanned path")
    if cfg is None:
        with open(_os.path.join(path, "config.json")) as f:
            raw = _json.load(f)
        cfg = StarCoderConfig.from_hf(type("HFConfig", (), raw)())

    from bigdl_tpu.llm.transformers.st_reader import SafetensorsReader
    reader = SafetensorsReader(path)   # handles the optional
    get = reader.get                   # "transformer." name prefix

    L = cfg.num_hidden_layers
    h = cfg.hidden_size
    kv = cfg.num_key_value_heads * cfg.head_dim
    _HF_LIN = {"o_proj": "attn.c_proj", "fc_in": "mlp.c_fc",
               "fc_out": "mlp.c_proj"}
    acc: Dict[str, Dict[str, list]] = {
        n: {"w": [], "q": [], "scale": [], "b": []} for n in _LAYER_LINEARS}

    def put_linear(name, w, b):
        a = acc[name]
        a["b"].append(b)
        if qtype and w.shape[0] % 128 == 0:
            qd = quantize_tpu(w, qtype)
            a["q"].append(qd["q"])
            a["scale"].append(qd["scale"])
        else:
            a["w"].append(w.astype(np.float32))

    for l in range(L):
        w = get(f"h.{l}.attn.c_attn.weight")
        b = get(f"h.{l}.attn.c_attn.bias")
        put_linear("q_proj", w[:h], b[:h])
        put_linear("k_proj", w[h:h + kv], b[h:h + kv])
        put_linear("v_proj", w[h + kv:], b[h + kv:])
        for name, hf in _HF_LIN.items():
            put_linear(name, get(f"h.{l}.{hf}.weight"),
                       get(f"h.{l}.{hf}.bias"))

    layers: Dict[str, Any] = {}
    for name, a in acc.items():
        entry: Dict[str, Any] = {"b": jnp.asarray(np.stack(a["b"]), dtype)}
        if a["q"]:
            entry["q"] = jnp.asarray(np.stack(a["q"]))
            entry["scale"] = jnp.asarray(np.stack(a["scale"]))
        else:
            entry["w"] = jnp.asarray(np.stack(a["w"]), dtype)
        layers[name] = entry
    for ours, hf in (("input_layernorm", "ln_1"),
                     ("post_attention_layernorm", "ln_2")):
        layers[ours] = {
            "w": jnp.asarray(np.stack(
                [get(f"h.{l}.{hf}.weight") for l in range(L)]), dtype),
            "b": jnp.asarray(np.stack(
                [get(f"h.{l}.{hf}.bias") for l in range(L)]), dtype)}
    return {
        "wte": jnp.asarray(get("wte.weight"), dtype),
        "wpe": jnp.asarray(get("wpe.weight"), dtype),
        "ln_f": {"w": jnp.asarray(get("ln_f.weight"), dtype),
                 "b": jnp.asarray(get("ln_f.bias"), dtype)},
        "layers": layers,
    }

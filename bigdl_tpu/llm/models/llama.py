"""Llama family on TPU (ref: P:llm/transformers/models/llama.py — the
reference rewrites HF LlamaAttention.forward for fused rope + kv cache on
CPU; BASELINE config 5 = Llama-2-7B INT4 decode).

TPU-first design decisions:
- decoder layers are a **stacked pytree scanned with lax.scan** (compile
  time O(1) in depth, weights stream per layer);
- kv cache is a static-shape ring ``(L, B, S_max, H_kv, D)`` updated with
  dynamic_update_slice inside the jitted step — the whole decode step is
  ONE compiled program (the reference's python-per-layer loop becomes a
  single XLA launch);
- weights may be ggml-quantized (llm.ggml): each linear is a dict with
  either ``{"w"}`` (dense bf16) or ``{"q", "scale"}`` (q4_0 planes), and
  matmuls dispatch to the Pallas kernel on TPU;
- tensor parallelism via PartitionSpec rules (:func:`param_pspecs`):
  attention heads and MLP intermediate sharded over ``model``, sequence
  shardable over ``seq`` for long prompts (ring attention available in
  bigdl_tpu.parallel for the prefill path).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    # cache windows larger than this use blockwise online-softmax attention
    # (the (Tq, S) score matrix never materializes beyond one block column)
    attn_block_size: int = 1024
    # Mistral-style sliding-window attention: position p attends only to
    # [p - sliding_window + 1, p]. None = full causal (Llama).
    sliding_window: Optional[int] = None
    # Qwen2-style attention bias: q/k/v projections carry biases
    # (o_proj and the MLP stay bias-free, matching HF Qwen2)
    attention_bias: bool = False
    # RoPE layout: "half" = Llama rotate-half over the full head dim;
    # "glm" = ChatGLM/GLM-4 lineage — INTERLEAVED pairs (2i, 2i+1) over
    # the first ``head_dim * partial_rotary_factor`` dims, rest passed
    # through (ref: P:llm/ggml/model/chatglm — the fifth ggml family;
    # HF transformers GlmModel is the same rotary/residual layout)
    rope_mode: str = "half"
    partial_rotary_factor: float = 1.0
    # Mixture-of-experts FFN (Mixtral-style): 0 = dense FFN. With
    # num_experts > 0 every decoder MLP becomes num_experts switch-FFN
    # experts with top-k routing and static expert capacity
    # ceil(S*k/E * capacity_factor) — einsum dispatch, so the expert
    # dimension shards cleanly over an "ep" mesh axis (param_pspecs).
    num_experts: int = 0
    num_experts_per_tok: int = 2
    expert_capacity_factor: float = 1.25

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @classmethod
    def llama2_7b(cls) -> "LlamaConfig":
        return cls()

    @classmethod
    def llama3_8b(cls) -> "LlamaConfig":
        return cls(vocab_size=128256, intermediate_size=14336,
                   num_key_value_heads=8, rope_theta=500000.0,
                   max_position_embeddings=8192)

    @classmethod
    def mistral_7b(cls) -> "LlamaConfig":
        """Mistral-7B-v0.1: Llama block structure + GQA(8) + 4k sliding
        window (ref: P:llm/ggml/model — second model family)."""
        return cls(intermediate_size=14336, num_key_value_heads=8,
                   max_position_embeddings=8192, sliding_window=4096,
                   rms_norm_eps=1e-5, rope_theta=10000.0)

    @classmethod
    def qwen2_7b(cls) -> "LlamaConfig":
        """Qwen2-7B: Llama block + GQA(4) + q/k/v biases (ref:
        P:llm/transformers model families — qwen lineage)."""
        return cls(vocab_size=152064, hidden_size=3584,
                   intermediate_size=18944, num_hidden_layers=28,
                   num_attention_heads=28, num_key_value_heads=4,
                   max_position_embeddings=32768, rope_theta=1e6,
                   rms_norm_eps=1e-6, attention_bias=True)

    @classmethod
    def tiny_qwen2(cls, vocab: int = 256) -> "LlamaConfig":
        return cls(vocab_size=vocab, hidden_size=64, intermediate_size=128,
                   num_hidden_layers=2, num_attention_heads=4,
                   num_key_value_heads=2, max_position_embeddings=128,
                   attention_bias=True)

    @classmethod
    def glm4_9b(cls) -> "LlamaConfig":
        """GLM-4-9B (the ChatGLM lineage): Llama-shaped block +
        INTERLEAVED partial rotary (first half of head dims), GQA(2),
        qkv biases, fused gate_up MLP (ref: P:llm/ggml/model/chatglm —
        fifth ggml family; HF ``GlmForCausalLM`` is this layout)."""
        return cls(vocab_size=151552, hidden_size=4096,
                   intermediate_size=13696, num_hidden_layers=40,
                   num_attention_heads=32, num_key_value_heads=2,
                   max_position_embeddings=8192, rms_norm_eps=1.5625e-07,
                   rope_theta=10000.0, attention_bias=True,
                   rope_mode="glm", partial_rotary_factor=0.5)

    @classmethod
    def tiny_glm(cls, vocab: int = 256) -> "LlamaConfig":
        return cls(vocab_size=vocab, hidden_size=64, intermediate_size=128,
                   num_hidden_layers=2, num_attention_heads=4,
                   num_key_value_heads=2, max_position_embeddings=128,
                   attention_bias=True, rope_mode="glm",
                   partial_rotary_factor=0.5)

    @classmethod
    def mixtral_8x7b(cls) -> "LlamaConfig":
        """Mixtral-8x7B: Mistral block + 8-expert top-2 MoE FFN."""
        return cls(intermediate_size=14336, num_key_value_heads=8,
                   max_position_embeddings=8192, rope_theta=1e6,
                   num_experts=8, num_experts_per_tok=2)

    @classmethod
    def tiny_moe(cls, vocab: int = 256) -> "LlamaConfig":
        """Test-size MoE config (4 experts, top-2)."""
        return cls(vocab_size=vocab, hidden_size=64, intermediate_size=128,
                   num_hidden_layers=2, num_attention_heads=4,
                   num_key_value_heads=2, max_position_embeddings=128,
                   num_experts=4, num_experts_per_tok=2)

    @classmethod
    def tiny(cls, vocab: int = 256) -> "LlamaConfig":
        """Test-size config (the reference's tests use tiny dummy ckpts)."""
        return cls(vocab_size=vocab, hidden_size=64, intermediate_size=128,
                   num_hidden_layers=2, num_attention_heads=4,
                   num_key_value_heads=2, max_position_embeddings=128)

    @classmethod
    def from_hf(cls, hf_config) -> "LlamaConfig":
        g = (lambda k, d: getattr(hf_config, k, d))
        return cls(
            vocab_size=g("vocab_size", 32000),
            hidden_size=g("hidden_size", 4096),
            intermediate_size=g("intermediate_size", 11008),
            num_hidden_layers=g("num_hidden_layers", 32),
            num_attention_heads=g("num_attention_heads", 32),
            num_key_value_heads=g("num_key_value_heads",
                                  g("num_attention_heads", 32)),
            max_position_embeddings=g("max_position_embeddings", 4096),
            rms_norm_eps=g("rms_norm_eps", 1e-5),
            rope_theta=g("rope_theta", 10000.0),
            tie_word_embeddings=g("tie_word_embeddings", False),
            # Qwen2 configs carry sliding_window=4096 but apply it only
            # when use_sliding_window is set (HF default False) — an
            # unconditional read would window-mask every layer
            sliding_window=(g("sliding_window", None)
                            if g("use_sliding_window", True) else None),
            attention_bias=bool(g("attention_bias",
                                  g("model_type", "") == "qwen2")),
            # GLM/ChatGLM lineage: interleaved partial rotary
            rope_mode=("glm" if g("model_type", "") == "glm" else "half"),
            partial_rotary_factor=g("partial_rotary_factor", 1.0) or 1.0,
            num_experts=g("num_local_experts", 0) or 0,
            num_experts_per_tok=g("num_experts_per_tok", 2) or 2)


# ---------------------------------------------------------------------------
# params
# ---------------------------------------------------------------------------

_LAYER_LINEARS = ("q_proj", "k_proj", "v_proj", "o_proj",
                  "gate_proj", "up_proj", "down_proj")

# fused-projection layout: q/k/v and gate/up are concatenated along the
# output (N) axis so each decode step runs 4 weight-streaming matmuls per
# layer instead of 7 (VERDICT r3: ~0.3 ms/layer of the b1 decode step was
# kernel dispatch across the 7 separate quantized matvecs)
_FUSED_LINEARS = {"qkv_proj": ("q_proj", "k_proj", "v_proj"),
                  "gate_up_proj": ("gate_proj", "up_proj")}


def fuse_decoder_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """Concatenate per-layer q/k/v → ``qkv_proj`` and gate/up →
    ``gate_up_proj`` along the output dim. Works on both dense stacked
    weights (``w`` (L, N, K) — concat axis 1) and k-major quantized
    planes (``q`` (L, K/2, N) / ``scale`` (L, G, N) — concat axis -1;
    q4_0 groups run along K, so N-concat never mixes scale groups).
    MoE expert-stacked FFN weights (L, E, N, K) are left unfused (the
    MoE path dispatches per expert). Idempotent."""
    layers = dict(params["layers"])
    for fused, parts in _FUSED_LINEARS.items():
        if fused in layers or not all(p in layers for p in parts):
            continue
        ds = [layers[p] for p in parts]
        if "w" in ds[0]:
            if any("w" not in d or d["w"].ndim != 3 for d in ds):
                continue                      # MoE expert-stacked: skip
            fd = {"w": jnp.concatenate([d["w"] for d in ds], axis=1)}
        else:
            if any("q" not in d for d in ds):
                continue
            fd = {k: jnp.concatenate([d[k] for d in ds], axis=-1)
                  for k in ("q", "scale", "zero") if k in ds[0]}
        if any("b" in d for d in ds):
            # bias rides along (zeros where a part has none, e.g. a
            # hypothetical mixed layout — lazily built, normal Qwen2
            # layouts have all three)
            ref_b = next(d["b"] for d in ds if "b" in d)
            n_of = (lambda d: d["w"].shape[1] if "w" in d
                    else d["q"].shape[-1])
            fd["b"] = jnp.concatenate(
                [d["b"] if "b" in d
                 else jnp.zeros((ref_b.shape[0], n_of(d)), ref_b.dtype)
                 for d in ds], axis=-1)
        layers[fused] = fd
        for p in parts:
            del layers[p]
    out = dict(params)
    out["layers"] = layers
    return out


def linear_shapes(cfg: LlamaConfig) -> Dict[str, Tuple[int, int]]:
    """(out, in) shapes of every per-layer linear — single source of truth
    shared by init_params and the synthetic benchmark params."""
    hd, h = cfg.head_dim, cfg.hidden_size
    kvh = cfg.num_key_value_heads * hd
    qh = cfg.num_attention_heads * hd
    return {
        "q_proj": (qh, h), "k_proj": (kvh, h), "v_proj": (kvh, h),
        "o_proj": (h, qh),
        "gate_proj": (cfg.intermediate_size, h),
        "up_proj": (cfg.intermediate_size, h),
        "down_proj": (h, cfg.intermediate_size),
    }


def init_params(cfg: LlamaConfig, seed: int = 0,
                dtype=jnp.bfloat16) -> Dict[str, Any]:
    """Random-init params (tests / benchmarks without checkpoints)."""
    key = jax.random.PRNGKey(seed)
    h = cfg.hidden_size
    shapes = linear_shapes(cfg)
    L = cfg.num_hidden_layers

    def mk(key, shape, scale=None):
        scale = scale or (1.0 / np.sqrt(shape[-1]))
        return (jax.random.normal(key, shape, jnp.float32)
                * scale).astype(dtype)

    keys = jax.random.split(key, 4 + len(shapes))
    layers = {}
    moe = ("gate_proj", "up_proj", "down_proj") if cfg.num_experts else ()
    for i, (name, shape) in enumerate(shapes.items()):
        if name in moe:
            # expert-stacked MLP weights (L, E, N, K)
            layers[name] = {"w": mk(keys[i],
                                    (L, cfg.num_experts) + shape)}
        else:
            layers[name] = {"w": mk(keys[i], (L,) + shape)}
    if cfg.attention_bias:
        for name in ("q_proj", "k_proj", "v_proj"):
            n_out = shapes[name][0]
            layers[name]["b"] = jnp.zeros((L, n_out), dtype)
    if cfg.num_experts:
        layers["router"] = {"w": mk(keys[-4], (L, cfg.num_experts, h))}
    layers["input_layernorm"] = jnp.ones((L, h), dtype)
    layers["post_attention_layernorm"] = jnp.ones((L, h), dtype)
    params = {
        "embed_tokens": mk(keys[-3], (cfg.vocab_size, h), 0.02),
        "norm": jnp.ones((h,), dtype),
        "layers": layers,
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = {"w": mk(keys[-2], (cfg.vocab_size, h))}
    return params


def quantize_params(params: Dict[str, Any], qtype: str = "sym_int4",
                    quantize_lm_head: bool = False,
                    fuse: bool = True) -> Dict[str, Any]:
    """ggml-quantize every decoder linear (stacked per layer) into the
    k-major TPU kernel layout (q (L, K/2, N) uint8, scale (L, K/QK, N)
    f32 — see llm.kernels.int4_matmul), keeping norms/embeddings in bf16
    (matching the reference's default)."""
    from bigdl_tpu.llm.kernels import quantize_tpu

    if qtype != "sym_int4":
        raise NotImplementedError(
            "the scanned decoder path implements q4_0 (sym_int4); other "
            "qtypes are available through LowBitLinear module surgery")
    if any(layers_w.get("w") is not None and layers_w["w"].ndim == 4
           for name, layers_w in params["layers"].items()
           if isinstance(layers_w, dict)):
        raise NotImplementedError(
            "MoE expert-stacked FFN weights are not ggml-quantized yet "
            "(experts stay bf16; attention linears of an MoE model can "
            "be quantized through LowBitLinear module surgery)")
    out = dict(params)
    layers = dict(params["layers"])
    # accept both layouts: unfused q/k/v..., or params already through
    # fuse_decoder_params (fused dense weights quantize just as well —
    # q4_0 groups run along K, which fusion leaves untouched)
    names = [n for n in _LAYER_LINEARS + tuple(_FUSED_LINEARS)
             if n in layers and "w" in layers[n]]
    for name in names:
        w = np.asarray(layers[name]["w"], np.float32)   # (L, N, K)
        qs, ss = [], []
        for l in range(w.shape[0]):
            td = quantize_tpu(w[l], qtype)
            qs.append(td["q"])
            ss.append(td["scale"])
        # NOTE: no "qtype" string key here — the stacked layer pytree is
        # scanned, so every leaf must be an L-leading array
        nd = {"q": jnp.asarray(np.stack(qs)),
              "scale": jnp.asarray(np.stack(ss))}
        if "b" in layers[name]:
            nd["b"] = layers[name]["b"]      # biases stay dense
        layers[name] = nd
    out["layers"] = layers
    if fuse:
        out = fuse_decoder_params(out)
    if quantize_lm_head and "lm_head" in out:
        td = quantize_tpu(np.asarray(out["lm_head"]["w"], np.float32),
                          qtype)
        out["lm_head"] = {"q": jnp.asarray(td["q"]),
                          "scale": jnp.asarray(td["scale"]), "qtype": qtype}
    return out


def param_pspecs(params: Dict[str, Any],
                 ep_axis: Optional[str] = None) -> Dict[str, Any]:
    """Tensor-parallel PartitionSpecs over the ``model`` axis.

    Row-sharded (output dim): q/k/v, gate/up (+ their q4 planes & scales).
    Col-sharded (input dim): o_proj, down_proj. Embed/lm_head row-sharded
    over vocab. Norms replicated. XLA inserts the two allreduces per layer
    (after o_proj and down_proj) — the standard Megatron TP pattern.

    MoE: expert-stacked MLP weights (L, E, N, K) and the router
    (L, E, H) shard their expert dim over ``ep_axis`` (expert
    parallelism) when given; expert weights also shard N/K over
    ``model`` as usual. Without ``ep_axis`` the router is replicated.
    """
    ROW = {"q_proj", "k_proj", "v_proj", "gate_proj", "up_proj",
           "qkv_proj", "gate_up_proj"}

    def spec_for(path, leaf):
        keys = [str(getattr(p, "key", "")) for p in path]
        stacked = "layers" in keys
        d0 = 1 if stacked else 0            # skip the layer-stack dim
        if "router" in keys:
            nd = getattr(leaf, "ndim", 0)
            if ep_axis and nd > d0:         # (L, E, H): shard experts
                spec = [None] * nd
                spec[d0] = ep_axis
                return P(*spec)
            return P()
        name = next((k for k in keys if k in ROW
                     or k in ("o_proj", "down_proj", "lm_head",
                              "embed_tokens")), None)
        if name is None or getattr(leaf, "ndim", 0) <= d0:
            return P()
        # expert-stacked dense MLP weight (L, E, N, K)
        if (name in ("gate_proj", "up_proj", "down_proj")
                and keys[-1] == "w" and leaf.ndim == d0 + 3):
            spec = [None] * leaf.ndim
            spec[d0] = ep_axis
            if name == "down_proj":
                spec[d0 + 2] = "model"      # shard K (input) dim
            else:
                spec[d0 + 1] = "model"      # shard N (output) dim
            return P(*spec)
        # quantized leaves are k-major TPU layout (…, K-ish, N); dense
        # "w" leaves are row-major (…, N, K)
        kmajor = keys[-1] in ("q", "scale", "zero")
        spec = [None] * leaf.ndim
        if name in ROW or name in ("lm_head", "embed_tokens"):
            if kmajor:
                spec[-1] = "model"           # N is the last dim
            else:
                spec[d0] = "model"           # shard N/vocab dim
        else:
            # o/down: shard the K dim
            if kmajor:
                spec[d0] = "model"           # K/2 (or G) right after stack
            elif leaf.ndim > d0 + 1:
                spec[d0 + 1] = "model"
        return P(*spec)

    return jax.tree_util.tree_map_with_path(spec_for, params)


# ---------------------------------------------------------------------------
# compute
# ---------------------------------------------------------------------------

def _linear(wd: Dict[str, Any], x: jnp.ndarray) -> jnp.ndarray:
    """Dense or quantized matmul: x (..., K) → (..., N), plus an
    optional bias ``b`` (N,) — Qwen2's q/k/v carry one; biases stay
    dense even when weights are ggml-quantized (reference behavior).
    Quantized weights are the k-major TPU layout (q (K/2, N),
    scale (G, N))."""
    if "w" in wd:
        y = x @ wd["w"].T.astype(x.dtype)
        if "b" in wd:
            y = y + wd["b"].astype(y.dtype)
        return y
    shape = x.shape
    x2 = x.reshape(-1, shape[-1])
    if jax.default_backend() == "tpu":
        from bigdl_tpu.llm.kernels import int4_matmul
        y = int4_matmul(x2, wd["q"], wd["scale"], out_dtype=x.dtype)
    else:
        y = (x2 @ _dequant_q4(wd, x.dtype)).astype(x.dtype)
    if "b" in wd:
        y = y + wd["b"].astype(y.dtype)
    return y.reshape(shape[:-1] + (y.shape[-1],))


def _dequant_q4(wd, dtype):
    """k-major XLA dequant: returns w (K, N) so y = x @ w."""
    from bigdl_tpu.llm.ggml.quantize import QK
    packed, scale = wd["q"], wd["scale"].astype(jnp.float32)
    half, n = packed.shape
    lo = (packed & 0xF).astype(jnp.int32)
    hi = (packed >> 4).astype(jnp.int32)
    q = jnp.stack([lo, hi], axis=1).reshape(half * 2, n)
    g = scale.shape[0]
    w = ((q - 8).astype(jnp.float32).reshape(g, QK, n)
         * scale[:, None, :])
    return w.reshape(half * 2, n).astype(dtype)


def _moe_ffn(lp: Dict[str, Any], h: jnp.ndarray,
             cfg: LlamaConfig) -> jnp.ndarray:
    """Switch-FFN mixture of experts (ref scope: beyond the upstream —
    VERDICT r2 named EP the one empty parallelism axis; Mixtral-style
    top-k routing with renormalized gates).

    Static-shape einsum dispatch: every token picks top-k experts; each
    expert processes at most C = ceil(S*k/E * capacity_factor) tokens
    (overflow tokens silently drop that expert slot — standard switch
    behaviour). All tensors keep the expert axis explicit, so sharding
    expert weights over an ``ep`` mesh axis turns the dispatch/combine
    einsums into XLA all-to-alls.
    """
    b, t, hd = h.shape
    S = b * t
    E, K = cfg.num_experts, cfg.num_experts_per_tok
    x = h.reshape(S, hd)
    router = lp["router"]["w"]                              # (E, H)
    logits = x.astype(jnp.float32) @ router.T.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)                 # (S, E)
    gate_vals, gate_idx = jax.lax.top_k(probs, K)           # (S, K)
    gate_vals = gate_vals / jnp.sum(gate_vals, -1, keepdims=True)

    if not cfg.expert_capacity_factor or cfg.expert_capacity_factor <= 0:
        # no-drop dense mode (capacity_factor <= 0): every expert runs on
        # every token, outputs weighted by the scattered top-k gates.
        # Exact (batch-composition independent — prefill == step-wise
        # decode) at E/K x the FFN compute; the right choice for
        # correctness tests and small-batch inference.
        w_full = jnp.einsum("ske,sk->se",
                            jax.nn.one_hot(gate_idx, E,
                                           dtype=jnp.float32),
                            gate_vals)                      # (S, E)
        xb = x.astype(jnp.bfloat16)
        wg = lp["gate_proj"]["w"].astype(jnp.bfloat16)      # (E, I, H)
        wu = lp["up_proj"]["w"].astype(jnp.bfloat16)
        wd = lp["down_proj"]["w"].astype(jnp.bfloat16)      # (E, H, I)
        gate = jnp.einsum("sh,eih->esi", xb, wg)
        up = jnp.einsum("sh,eih->esi", xb, wu)
        act = (jax.nn.silu(gate.astype(jnp.float32))
               * up.astype(jnp.float32)).astype(jnp.bfloat16)
        out = jnp.einsum("esi,ehi->esh", act, wd)           # (E, S, H)
        y = jnp.einsum("se,esh->sh", w_full.astype(jnp.bfloat16), out)
        return y.reshape(b, t, hd).astype(h.dtype)

    C = max(int(np.ceil(S * K / E * cfg.expert_capacity_factor)), 1)
    # slot-major flattening: slot 0 of every token first (priority to
    # each token's best expert when capacity runs out)
    expert_of = gate_idx.T.reshape(-1)                      # (K*S,)
    gates = gate_vals.T.reshape(-1)
    sel = jax.nn.one_hot(expert_of, E, dtype=jnp.float32)   # (K*S, E)
    pos = jnp.einsum("te,te->t", jnp.cumsum(sel, axis=0) - sel, sel)
    keep = pos < C
    disp = (sel[:, :, None]
            * jax.nn.one_hot(pos.astype(jnp.int32), C)[:, None, :]
            * keep[:, None, None])                          # (K*S, E, C)

    x_rep = jnp.tile(x, (K, 1)).astype(jnp.bfloat16)        # (K*S, H)
    xin = jnp.einsum("tec,th->ech", disp.astype(jnp.bfloat16), x_rep)
    wg = lp["gate_proj"]["w"].astype(jnp.bfloat16)          # (E, I, H)
    wu = lp["up_proj"]["w"].astype(jnp.bfloat16)
    wd = lp["down_proj"]["w"].astype(jnp.bfloat16)          # (E, H, I)
    gate = jnp.einsum("ech,eih->eci", xin, wg)
    up = jnp.einsum("ech,eih->eci", xin, wu)
    act = (jax.nn.silu(gate.astype(jnp.float32))
           * up.astype(jnp.float32)).astype(jnp.bfloat16)
    out = jnp.einsum("eci,ehi->ech", act, wd)               # (E, C, H)
    comb = (disp * gates[:, None, None]).astype(jnp.bfloat16)
    y = jnp.einsum("tec,ech->th", comb, out)                # (K*S, H)
    y = y.reshape(K, S, hd).sum(axis=0)
    return y.reshape(b, t, hd).astype(h.dtype)


def rms_norm(x, w, eps: float):
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w


def rope(x, positions, theta: float, mode: str = "half",
         partial: float = 1.0):
    """RoPE. x: (B, T, H, D); positions: (B, T) int32.

    ``mode="half"``: Llama rotate-half over the full head dim.
    ``mode="glm"``: ChatGLM/GLM-4 layout — INTERLEAVED pairs (2i, 2i+1)
    over the first ``D * partial`` dims, remainder passed through."""
    d = x.shape[-1]
    if mode == "glm":
        rot = int(d * partial)
        x_rot, x_pass = x[..., :rot], x[..., rot:]
        inv_freq = 1.0 / (theta ** (jnp.arange(0, rot, 2,
                                               dtype=jnp.float32) / rot))
        ang = positions[..., None].astype(jnp.float32) * inv_freq
        cos = jnp.cos(ang)[:, :, None, :]                  # (B,T,1,rot/2)
        sin = jnp.sin(ang)[:, :, None, :]
        xr = x_rot.astype(jnp.float32).reshape(x.shape[:-1] + (rot // 2, 2))
        x1, x2 = xr[..., 0], xr[..., 1]
        out = jnp.stack([x1 * cos - x2 * sin,
                         x2 * cos + x1 * sin], axis=-1).reshape(
                             x.shape[:-1] + (rot,))
        return jnp.concatenate([out.astype(x.dtype), x_pass], axis=-1)
    inv_freq = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    ang = positions[..., None].astype(jnp.float32) * inv_freq  # (B,T,D/2)
    cos = jnp.cos(ang)[:, :, None, :]
    sin = jnp.sin(ang)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin,
                           x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def rope_cfg(x, positions, cfg: "LlamaConfig"):
    """cfg-driven dispatch shared by every Llama-stack call site (the
    prefill scan, the paged serving step, the slot-static decode)."""
    return rope(x, positions, cfg.rope_theta, cfg.rope_mode,
                cfg.partial_rotary_factor)


def init_cache(cfg: LlamaConfig, batch: int, max_len: int,
               dtype=jnp.bfloat16) -> Dict[str, jnp.ndarray]:
    shape = (cfg.num_hidden_layers, batch, max_len,
             cfg.num_key_value_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype),
            "pos": jnp.zeros((), jnp.int32)}


def _attention(q, k_all, v_all, q_positions, kv_len_mask, cfg,
               alibi_slopes=None):
    """q: (B, Tq, Hq, D); k_all/v_all: (B, S, Hkv, D) (full cache window).
    kv_len_mask: (B, S) True where the cache slot is valid.
    Causal: slot position s attends iff s <= q_position.
    ``alibi_slopes`` (Hq,) adds Bloom-style per-head linear position
    biases to the scores (single-block path only).

    GQA-aware: query heads are grouped onto their kv head inside the
    einsum (q head h uses kv head ``h // (Hq//Hkv)``) — repeated K/V is
    never materialized. When the cache window exceeds
    ``cfg.attn_block_size`` the computation goes blockwise over the cache
    axis with flash-style online softmax, so peak memory per layer is one
    (Tq × block) score column instead of the full (Tq × S) matrix — this
    is what lets 4k+ prefill fit (VERDICT r1 weak #6).
    """
    b, tq, hq, d = q.shape
    s, hkv = k_all.shape[1], k_all.shape[2]
    g = hq // hkv
    qg = q.reshape(b, tq, hkv, g, d)
    scale = 1.0 / np.sqrt(d)
    qpos = q_positions                                     # (B, Tq)

    def _causal(slot_idx):
        """(B, Tq, S') causal (+ sliding window) mask for slot indices."""
        m = slot_idx[None, None, :] <= qpos[..., None]
        if cfg.sliding_window is not None:
            m &= slot_idx[None, None, :] > (qpos[..., None]
                                            - cfg.sliding_window)
        return m

    if s <= cfg.attn_block_size:
        logits = jnp.einsum("bthgd,bshd->bhgts", qg, k_all,
                            preferred_element_type=jnp.float32) * scale
        if alibi_slopes is not None:
            # ALiBi (Bloom): score += slope_h * key_position. HF adds
            # slopes * key_index — row-shift-invariant under softmax, so
            # the relative -(i-j)*slope form and this agree exactly
            sl = alibi_slopes.astype(jnp.float32).reshape(hkv, g)
            logits = logits + (sl[None, :, :, None, None]
                               * jnp.arange(s, dtype=jnp.float32))
        mask = _causal(jnp.arange(s)) & kv_len_mask[:, None, :]  # (B,Tq,S)
        logits = jnp.where(mask[:, None, None], logits, -1e30)
        p = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bhgts,bshd->bthgd", p, v_all.astype(jnp.float32),
                         preferred_element_type=jnp.float32)
        return out.astype(q.dtype).reshape(b, tq, hq * d)

    if alibi_slopes is not None:
        raise NotImplementedError(
            "ALiBi rides the single-block path: set attn_block_size >= "
            "max_position_embeddings on ALiBi configs (Bloom does)")
    blk = cfg.attn_block_size
    kv_len_mask = jnp.broadcast_to(kv_len_mask, (b, s))
    nblk = -(-s // blk)
    pad = nblk * blk - s
    if pad:
        k_all = jnp.pad(k_all, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v_all = jnp.pad(v_all, ((0, 0), (0, pad), (0, 0), (0, 0)))
        kv_len_mask = jnp.pad(kv_len_mask, ((0, 0), (0, pad)))
    kb = k_all.reshape(b, nblk, blk, hkv, d).transpose(1, 0, 2, 3, 4)
    vb = v_all.reshape(b, nblk, blk, hkv, d).transpose(1, 0, 2, 3, 4)
    mb = kv_len_mask.reshape(b, nblk, blk).transpose(1, 0, 2)
    sb = jnp.arange(nblk * blk).reshape(nblk, blk)

    acc0 = jnp.zeros((b, hkv, g, tq, d), jnp.float32)
    max0 = jnp.full((b, hkv, g, tq), -1e30, jnp.float32)
    sum0 = jnp.zeros((b, hkv, g, tq), jnp.float32)

    def step(carry, inputs):
        from bigdl_tpu.parallel.ring_attention import online_block_update
        acc, rmax, rsum = carry
        k_blk, v_blk, m_blk, slot_blk = inputs
        mask = _causal(slot_blk) & m_blk[:, None, :]       # (B, Tq, blk)
        acc, nmax, rsum = online_block_update(
            qg, k_blk, v_blk, mask, acc, rmax, rsum, scale=scale)
        return (acc, nmax, rsum), None

    (acc, _, rsum), _ = jax.lax.scan(step, (acc0, max0, sum0),
                                     (kb, vb, mb, sb))
    out = (acc / jnp.maximum(rsum, 1e-30)[..., None]).astype(q.dtype)
    return out.transpose(0, 3, 1, 2, 4).reshape(b, tq, hq * d)


def attention_qkv(lp: Dict[str, Any], h: jnp.ndarray,
                  cfg: LlamaConfig) -> Tuple[jnp.ndarray, jnp.ndarray,
                                             jnp.ndarray]:
    """q/k/v projections for one decoder layer, handling both the fused
    (``qkv_proj``, one weight stream) and unfused per-layer layouts.
    Returns head-shaped (B, T, H*, D) arrays, pre-RoPE."""
    b, t, _ = h.shape
    hd = cfg.head_dim
    qh = cfg.num_attention_heads * hd
    kvh = cfg.num_key_value_heads * hd
    if "qkv_proj" in lp:
        qkv = _linear(lp["qkv_proj"], h)
        q, k, v = (qkv[..., :qh], qkv[..., qh:qh + kvh],
                   qkv[..., qh + kvh:])
    else:
        q = _linear(lp["q_proj"], h)
        k = _linear(lp["k_proj"], h)
        v = _linear(lp["v_proj"], h)
    return (q.reshape(b, t, cfg.num_attention_heads, hd),
            k.reshape(b, t, cfg.num_key_value_heads, hd),
            v.reshape(b, t, cfg.num_key_value_heads, hd))


def mlp(lp: Dict[str, Any], h2: jnp.ndarray, dtype) -> jnp.ndarray:
    """SwiGLU FFN for one decoder layer (fused gate_up or unfused)."""
    if "gate_up_proj" in lp:
        gu = _linear(lp["gate_up_proj"], h2).astype(jnp.float32)
        gate, up = jnp.split(gu, 2, axis=-1)
        gate = jax.nn.silu(gate)
    else:
        gate = jax.nn.silu(_linear(lp["gate_proj"], h2).astype(jnp.float32))
        up = _linear(lp["up_proj"], h2).astype(jnp.float32)
    return _linear(lp["down_proj"], (gate * up).astype(dtype))


def forward(params: Dict[str, Any], cfg: LlamaConfig,
            tokens: jnp.ndarray, cache: Dict[str, jnp.ndarray],
            positions: jnp.ndarray,
            ring: Optional[tuple] = None,
            unroll: int = 1) -> Tuple[jnp.ndarray, Dict]:
    """One forward pass over ``tokens`` (B, T) writing kv at
    ``positions`` (B, T); returns (logits (B, T, V), new_cache).

    Works for both prefill (T = prompt len) and decode (T = 1); the whole
    body jits once per T.

    ``ring=(mesh, axis)`` switches attention to the sequence-parallel ring
    kernel (bigdl_tpu.parallel.ring_attention): the sequence axis of the
    current tokens is sharded over ``axis`` and K/V chunks rotate around
    the ICI ring. Only valid for prefill from an empty cache (positions
    must be 0..T-1; attention is over the current tokens, not the cache
    window) — the generation facade enforces this.
    """
    x = params["embed_tokens"][tokens]                     # (B, T, H)
    start = cache["pos"]
    s_max = cache["k"].shape[2]
    valid = jnp.arange(s_max)[None, :] < (start + tokens.shape[1])

    def layer_step(carry, inputs):
        x, = carry
        lp, k_cache, v_cache = inputs
        h = rms_norm(x, lp["input_layernorm"], cfg.rms_norm_eps)
        b, t, _ = h.shape
        q, k, v = attention_qkv(lp, h, cfg)
        q = rope_cfg(q, positions, cfg)
        k = rope_cfg(k, positions, cfg)
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype), (0, start, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype), (0, start, 0, 0))
        if ring is not None:
            from bigdl_tpu.parallel import ring_attention as _ring
            mesh, axis = ring
            attn = _ring(q, k, v, mesh, axis=axis, causal=True,
                         batch_axis=None).reshape(b, t, -1)
        else:
            attn = _attention(q, k_cache, v_cache, positions, valid, cfg)
        x = x + _linear(lp["o_proj"], attn)
        h2 = rms_norm(x, lp["post_attention_layernorm"], cfg.rms_norm_eps)
        if cfg.num_experts:
            x = x + _moe_ffn(lp, h2, cfg)
        else:
            x = x + mlp(lp, h2, x.dtype)
        return (x,), (k_cache, v_cache)

    (x,), (k_new, v_new) = jax.lax.scan(
        layer_step, (x,), (params["layers"], cache["k"], cache["v"]),
        unroll=min(unroll, cfg.num_hidden_layers) if unroll else 1)
    x = rms_norm(x, params["norm"], cfg.rms_norm_eps)
    head = params.get("lm_head")
    if head is None:
        logits = x @ params["embed_tokens"].T.astype(x.dtype)
    else:
        logits = _linear(head, x)
    new_cache = {"k": k_new, "v": v_new,
                 "pos": start + tokens.shape[1]}
    return logits.astype(jnp.float32), new_cache


# ---------------------------------------------------------------------------
# fused decode loop
# ---------------------------------------------------------------------------

def _pick_token(logits, key, do_sample: bool, temperature, top_k: int):
    """logits (B, V) → (B,) int32 next tokens (shared on-device
    sampling: the serving engine's pipelined decode step folds the same
    primitive into its compiled program, ISSUE 4)."""
    from bigdl_tpu.llm.kernels.sampling import sample_tokens
    return sample_tokens(logits, key, do_sample=do_sample,
                         temperature=temperature, top_k=top_k)


def decode_scan(params, cache, last_logits, key, temperature,
                finished=None,
                *, cfg, forward_fn, num_tokens: int, do_sample: bool = False,
                top_k: int = 0, eos_token_id: Optional[int] = None):
    """``num_tokens`` autoregressive steps as ONE compiled program.

    The reference decodes with a host-side python loop (stock HF
    ``generate``, SURVEY.md §3.4) — one dispatch per token. On this
    runtime a device roundtrip costs ~100 ms (BENCH_r02's 110 ms "sync
    overhead" was exactly this), which would dominate a ~10 ms/token
    model. Here the whole token loop is a ``lax.scan`` inside one jit
    with a **donated** kv cache, so decode throughput tracks the HBM
    weight-stream roofline instead of the dispatch rate.

    Returns (tokens (B, num_tokens), cache, last_logits, key, finished).
    After an EOS hit a row keeps emitting ``eos_token_id`` (HF padding
    semantics); compute continues but outputs are frozen. ``finished``
    (B,) bool carries that state ACROSS windows — callers decoding in
    chunks must pass the returned mask back in, otherwise a row that hit
    EOS would resume emitting arbitrary tokens at the next chunk
    boundary.
    """
    b = last_logits.shape[0]
    if finished is None:
        finished = jnp.zeros((b,), bool)

    def step(carry, _):
        cache, last, key, finished = carry
        key, sub = jax.random.split(key)
        nxt = _pick_token(last, sub, do_sample, temperature, top_k)
        if eos_token_id is not None:
            nxt = jnp.where(finished, eos_token_id, nxt)
            finished = finished | (nxt == eos_token_id)
        pos = jnp.full((b, 1), cache["pos"], jnp.int32)
        logits, cache = forward_fn(params, cfg, nxt[:, None], cache, pos)
        return (cache, logits[:, -1], key, finished), nxt

    init = (cache, last_logits, key, finished)
    (cache, last, key, finished), toks = jax.lax.scan(step, init, None,
                                                      length=num_tokens)
    return toks.T, cache, last, key, finished


def pageify_cache(cache: Dict[str, jnp.ndarray], page: int = 16
                  ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Dense prefill cache (L, B, S, H, D) → page pools + block tables.

    Each batch row gets the contiguous page run ``1 + i·maxp ..`` (page
    0 is the trash page, matching the serving allocator's invariant);
    ``maxp`` is padded to the kernel's ``LANE // page`` block multiple.
    Static-shape and jit-friendly — this is the bridge from the dense
    prefill to the paged token loop (:func:`decode_scan_paged`)."""
    from bigdl_tpu.llm.kernels.paged_attention import LANE
    if page <= 0 or LANE % page:
        raise ValueError(
            f"page_size {page} must divide the kernel lane width "
            f"{LANE} (8/16/32/64/128)")
    k, v = cache["k"], cache["v"]
    L, B, S, H, D = k.shape
    ppb = LANE // page
    cap = -(-S // page)                      # ceil(S / page)
    maxp = -(-cap // ppb) * ppb              # .. to the kernel block mult
    s_pad = maxp * page - S
    if s_pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, s_pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, s_pad), (0, 0), (0, 0)))

    def pageify(a):
        # (L, B, maxp*page, H, D) -> (L, B*maxp, H, page, D)
        a = a.reshape(L, B * maxp, page, H, D).transpose(0, 1, 3, 2, 4)
        trash = jnp.zeros((L, 1) + a.shape[2:], a.dtype)
        return jnp.concatenate([trash, a], axis=1)

    bt = 1 + (jnp.arange(B)[:, None] * maxp
              + jnp.arange(maxp)[None, :]).astype(jnp.int32)
    return pageify(k), pageify(v), bt


def decode_scan_paged(params, k_pages, v_pages, bt, pos, last_logits, key,
                      temperature, finished=None, *, cfg, page: int,
                      num_tokens: int, do_sample: bool = False,
                      top_k: int = 0, eos_token_id: Optional[int] = None):
    """The :func:`decode_scan` token loop over a PAGED kv pool.

    Why this exists: the dense decode reads the full ``max_cache_len``
    window every token; the paged kernel reads only the pages below the
    live length, so generate() inherits the serving path's measured win
    (b8/7B: 216 vs 180 tok/s — the pool is carried through the token
    scan and updated in place by the post-scan scatter each step).
    ``pos`` is the shared position scalar (generate is rectangular);
    returns ``(tokens (B, T), k_pages, v_pages, pos, last, key,
    finished)``."""
    from bigdl_tpu.llm.serving import paged_decode_step
    b = last_logits.shape[0]
    if finished is None:
        finished = jnp.zeros((b,), bool)

    def step(carry, _):
        kp, vp, pos, last, key, finished = carry
        key, sub = jax.random.split(key)
        nxt = _pick_token(last, sub, do_sample, temperature, top_k)
        if eos_token_id is not None:
            nxt = jnp.where(finished, eos_token_id, nxt)
            finished = finished | (nxt == eos_token_id)
        lens = jnp.full((b,), pos, jnp.int32)
        logits, kp, vp = paged_decode_step(params, cfg, kp, vp, bt, lens,
                                           nxt, page=page)
        return (kp, vp, pos + 1, logits, key, finished), nxt

    (k_pages, v_pages, pos, last, key, finished), toks = jax.lax.scan(
        step, (k_pages, v_pages, jnp.asarray(pos, jnp.int32),
               last_logits, key, finished), None, length=num_tokens)
    return toks.T, k_pages, v_pages, pos, last, key, finished


# prefix-cache partial prefill (ISSUE 5): run only the uncached suffix
# at a position offset over a pre-populated block-table prefix, with the
# COW tail fork fused into the write-back — see llm/kvcache/prefill.py
from bigdl_tpu.llm.kvcache.prefill import make_partial_prefill  # noqa: E402

paged_prefill_partial = make_partial_prefill(forward, init_cache)


def paged_prefill_ragged(params, cfg, k_pages, v_pages, toks, length,
                         offset, bt_row, phys, slots, fork_dst,
                         fork_src, *, page: int,
                         full_logits: bool = False):
    """Ragged in-place prefill (ISSUE 8): the suffix tokens run through
    the llama layer math while attention reads the cached prefix
    DIRECTLY from the page pool (llm/kernels/ragged_prefill.py) — no
    dense temp cache, no prefix gather. Same structure as
    :func:`serving.paged_decode_step`: rolled layer scan, read-only
    pools inside the scan, one post-scan scatter into the donated
    pools; the COW tail fork is a single page copy fused ahead of the
    scan. ``bt_row`` (pages_cap,), ``offset``/``length`` and the
    ``phys``/``slots`` scatter targets are all runtime data — the only
    compile-relevant shape is the suffix bucket ``toks.shape[1]``.
    Returns ``(k_pages, v_pages, last_logits (V,) f32)``; with
    ``full_logits=True`` (the speculative verify leg, ISSUE 19) the
    logits for ALL bucket positions come back as ``(bucket, V)`` f32
    instead — a trace-time branch, so the default trace is unchanged."""
    from bigdl_tpu.llm.kvcache.prefill import (fork_tail_pages,
                                               ragged_prefill_attend,
                                               scatter_suffix_kv)
    b, bucket = toks.shape                                  # b == 1
    L = cfg.num_hidden_layers
    k_pages, v_pages = fork_tail_pages(k_pages, v_pages, fork_dst,
                                       fork_src)
    positions = (offset
                 + jnp.arange(bucket, dtype=jnp.int32))[None]  # (1, Tq)
    x = params["embed_tokens"][toks]                        # (1, Tq, H)
    attend = ragged_prefill_attend(k_pages, v_pages, bt_row, offset,
                                   length, page=page,
                                   sliding_window=cfg.sliding_window)

    def layer_step(carry, inputs):
        x, = carry
        lp, l = inputs
        h = rms_norm(x, lp["input_layernorm"], cfg.rms_norm_eps)
        q, k, v = attention_qkv(lp, h, cfg)
        q = rope_cfg(q, positions, cfg)
        k = rope_cfg(k, positions, cfg)
        # attend the suffix K/V at POOL precision — the dense sandwich
        # attends them from the cache_dtype temp cache, and a later
        # suffix re-prefill reads them back from the pages, so greedy
        # bit-parity needs the cast BEFORE attention, not just at the
        # scatter
        k = k.astype(k_pages.dtype)
        v = v.astype(v_pages.dtype)
        attn = attend(l, q, k, v).astype(x.dtype)
        x = x + _linear(lp["o_proj"], attn.reshape(b, bucket, -1))
        h2 = rms_norm(x, lp["post_attention_layernorm"], cfg.rms_norm_eps)
        if cfg.num_experts:
            x = x + _moe_ffn(lp, h2, cfg)
        else:
            x = x + mlp(lp, h2, x.dtype)
        return (x,), (k[0], v[0])

    (x,), (k_new, v_new) = jax.lax.scan(
        layer_step, (x,), (params["layers"], jnp.arange(L)))
    x = rms_norm(x, params["norm"], cfg.rms_norm_eps)
    head = params.get("lm_head")
    if head is None:
        logits = x @ params["embed_tokens"].T.astype(x.dtype)
    else:
        logits = _linear(head, x)
    k_pages, v_pages = scatter_suffix_kv(k_pages, v_pages, phys, slots,
                                         k_new, v_new)
    if full_logits:
        return k_pages, v_pages, logits[0].astype(jnp.float32)
    last = jax.lax.dynamic_index_in_dim(logits[0], length - 1, 0,
                                        keepdims=False)
    return k_pages, v_pages, last.astype(jnp.float32)


def paged_step_mixed(params, cfg, k_pages, v_pages, bt, lens, last,
                     active, temperature, key, ctoks, clen, coff,
                     cbt_row, cphys, cslots, fork_dst, fork_src, *,
                     page: int, do_sample: bool = False,
                     top_k: int = 0):
    """Unified mixed prefill+decode engine step (ISSUE 14): one
    compiled program whose batch carries every active decode row PLUS
    one suffix-prefill chunk — the composition of
    :func:`serving.paged_decode_step` (sampled) and
    :func:`paged_prefill_ragged`, see
    :func:`bigdl_tpu.llm.kvcache.prefill.make_mixed_step`."""
    from bigdl_tpu.llm.kvcache.prefill import make_mixed_step
    from bigdl_tpu.llm.serving import paged_decode_step
    return make_mixed_step(paged_decode_step, paged_prefill_ragged)(
        params, cfg, k_pages, v_pages, bt, lens, last, active,
        temperature, key, ctoks, clen, coff, cbt_row, cphys, cslots,
        fork_dst, fork_src, page=page, do_sample=do_sample, top_k=top_k)


def paged_step_spec(params, cfg, k_pages, v_pages, bt, lens, last,
                    active, temperature, key, srow, ctoks, n_draft,
                    cbt_row, cphys, cslots, *, page: int,
                    do_sample: bool = False, top_k: int = 0):
    """Speculative verify engine step (ISSUE 19): one compiled program
    whose batch carries every active decode row PLUS one row's draft
    tokens run as a verify chunk with fused greedy accept — the
    composition of :func:`serving.paged_decode_step` (sampled) and
    :func:`paged_prefill_ragged` (``full_logits=True``), see
    :func:`bigdl_tpu.llm.kvcache.prefill.make_spec_step`."""
    from bigdl_tpu.llm.kvcache.prefill import make_spec_step
    from bigdl_tpu.llm.serving import paged_decode_step
    return make_spec_step(paged_decode_step, paged_prefill_ragged)(
        params, cfg, k_pages, v_pages, bt, lens, last, active,
        temperature, key, srow, ctoks, n_draft, cbt_row, cphys, cslots,
        page=page, do_sample=do_sample, top_k=top_k)


# ---------------------------------------------------------------------------
# generation facade
# ---------------------------------------------------------------------------

class LlamaForCausalLM:
    """Generation driver (ref: the stock HF generate loop the reference
    keeps, with our compiled prefill/decode steps underneath)."""

    def __init__(self, cfg: LlamaConfig, params: Dict[str, Any],
                 max_cache_len: int = 512, cache_dtype=jnp.bfloat16,
                 decode_unroll: int = 1, paged_decode: bool = True,
                 page_size: int = 16):
        self.config = cfg
        self.params = params
        self.cache_dtype = cache_dtype
        self.max_cache_len = min(max_cache_len, cfg.max_position_embeddings)
        # paged_decode (DEFAULT) routes generate()'s token loop over a
        # page pool (decode_scan_paged): attention reads only live
        # pages instead of the full max_cache_len window each token.
        # Measured on chip at 7B/q4_0 vs the dense scan: b8 212.6 vs
        # 179.7 tok/s, b1 32.0 vs ~30; greedy/sampled/EOS-chunked
        # outputs are bit-identical (tests). paged_decode=False keeps
        # the dense ring-cache loop.
        self.paged_decode = paged_decode
        self.page_size = page_size
        self._prefill = jax.jit(functools.partial(forward, cfg=cfg))
        self._decode = jax.jit(functools.partial(forward, cfg=cfg))
        self._decode_scan_paged = jax.jit(
            functools.partial(decode_scan_paged, cfg=cfg),
            static_argnames=("num_tokens", "do_sample", "top_k",
                             "eos_token_id", "page"),
            donate_argnames=("k_pages", "v_pages"))
        # one-jit multi-token decode (donated cache, see decode_scan).
        # decode_unroll unrolls the LAYER scan inside each decode step.
        # Measured on v5e (7B q4_0, b1): unroll=1 31.7 tok/s, unroll=8
        # 23.1 (-27%), full python-loop unroll 28.8 — the rolled scan
        # pipelines the per-layer weight stream best, so 1 is the
        # default and the knob exists for future toolchains.
        self._decode_scan = jax.jit(
            functools.partial(decode_scan, cfg=cfg,
                              forward_fn=functools.partial(
                                  forward, unroll=max(decode_unroll, 1))),
            static_argnames=("num_tokens", "do_sample", "top_k",
                             "eos_token_id"),
            donate_argnames=("cache",))
        self._ring = None          # (mesh, axis) once sequence_parallel()
        self._prefill_ring = None

    @classmethod
    def from_config(cls, cfg: LlamaConfig, seed: int = 0,
                    load_in_low_bit: Optional[str] = None,
                    max_cache_len: int = 512) -> "LlamaForCausalLM":
        params = init_params(cfg, seed)
        if load_in_low_bit:
            params = quantize_params(params, load_in_low_bit)
        return cls(cfg, params, max_cache_len)

    def quantize(self, qtype: str = "sym_int4") -> "LlamaForCausalLM":
        self.params = quantize_params(self.params, qtype)
        return self

    def shard(self, mesh) -> "LlamaForCausalLM":
        """Place params on a mesh with TP PartitionSpecs."""
        from jax.sharding import NamedSharding

        specs = param_pspecs(self.params)
        self.params = jax.tree_util.tree_map(
            lambda a, s: jax.device_put(a, NamedSharding(mesh, s)),
            self.params, specs)
        return self

    def sequence_parallel(self, mesh, axis: str = "seq"
                          ) -> "LlamaForCausalLM":
        """Enable ring-attention sequence parallelism for the prefill of
        fresh sequences: long prompts shard over ``axis`` and K/V chunks
        ride the ICI ring (decode keeps the cache-window path)."""
        self._ring = (mesh, axis)
        self._prefill_ring = jax.jit(functools.partial(
            forward, cfg=self.config, ring=self._ring))
        return self

    def __call__(self, tokens, cache=None, positions=None):
        b, t = tokens.shape
        # ring prefill is only valid from an empty cache with the default
        # contiguous positions 0..T-1 (caller-supplied positions may be
        # packed/offset, which the ring mask does not model)
        use_ring = (cache is None and positions is None and t > 1
                    and self._prefill_ring is not None
                    and self.config.sliding_window is None  # ring mask is
                    # plain causal; window models use the blockwise path
                    and t % self._ring[0].shape[self._ring[1]] == 0)
        if cache is None:
            cache = init_cache(self.config, b, self.max_cache_len,
                               dtype=self.cache_dtype)
        if positions is None:
            base = jnp.asarray(cache["pos"])
            positions = base + jnp.broadcast_to(jnp.arange(t), (b, t))
        step = self._prefill_ring if use_ring else self._prefill
        return step(self.params, tokens=jnp.asarray(tokens),
                    cache=cache, positions=positions)

    def generate(self, input_ids, max_new_tokens: int = 32,
                 do_sample: bool = False, temperature: float = 1.0,
                 top_k: int = 0, eos_token_id: Optional[int] = None,
                 seed: int = 0, decode_chunk: int = 32):
        """Greedy/sampled autoregressive decode. input_ids: (B, T0).

        The token loop runs on-device via :func:`decode_scan` — one
        compiled program for all ``max_new_tokens`` (or per
        ``decode_chunk`` when ``eos_token_id`` is set, so the host can
        stop early once every row finished)."""
        tokens = jnp.asarray(np.asarray(input_ids), jnp.int32)
        b, t0 = tokens.shape
        if t0 + max_new_tokens > self.max_cache_len:
            raise ValueError(
                f"sequence {t0}+{max_new_tokens} exceeds cache "
                f"{self.max_cache_len}")
        # let __call__ create the cache: it applies cache_dtype and routes
        # the fresh-prompt prefill through ring attention when enabled
        logits, cache = self(tokens)
        key = jax.random.PRNGKey(seed)
        last = logits[:, -1]
        temp = jnp.float32(temperature)
        pieces = [np.asarray(tokens)]
        remaining = max_new_tokens
        chunk = max_new_tokens if eos_token_id is None else decode_chunk
        finished = jnp.zeros((b,), bool)
        if self.paged_decode:
            # bridge the dense prefill into the paged token loop: the
            # pool is carried (and scatter-updated in place) through
            # the token scan, and attention reads only live pages
            k_pages, v_pages, bt = pageify_cache(cache,
                                                 page=self.page_size)
            pos = cache["pos"]
            del cache
            while remaining > 0:
                n = min(chunk, remaining)
                toks, k_pages, v_pages, pos, last, key, finished = \
                    self._decode_scan_paged(
                        self.params, k_pages, v_pages, bt, pos, last,
                        key, temp, finished, page=self.page_size,
                        num_tokens=n, do_sample=do_sample, top_k=top_k,
                        eos_token_id=eos_token_id)
                pieces.append(np.asarray(toks))
                remaining -= n
                if (eos_token_id is not None
                        and np.asarray(finished).all()):
                    break
            return np.concatenate(pieces, axis=1)
        while remaining > 0:
            n = min(chunk, remaining)
            toks, cache, last, key, finished = self._decode_scan(
                self.params, cache, last, key, temp, finished,
                num_tokens=n, do_sample=do_sample, top_k=top_k,
                eos_token_id=eos_token_id)
            t_np = np.asarray(toks)
            pieces.append(t_np)
            remaining -= n
            if (eos_token_id is not None
                    and np.asarray(finished).all()):
                break
        return np.concatenate(pieces, axis=1)

"""Bloom family on TPU (ref: P:llm/ggml/model/bloom — the third of the
reference's five ggml model families; SURVEY.md §2.8 row 65). Bloom is
architecturally distinct from Llama AND GPT-NeoX: **ALiBi** linear
position biases instead of rotary, an extra LayerNorm directly after the
word embeddings, sequential residuals, tanh-GELU MLP, fused per-head
qkv, tied lm_head, no GQA.

Same TPU-first skeleton as llama.py/gptneox.py: scan-stacked decoder
layers, static ring kv cache updated in-program, q4_0 quantized linears
dispatching to the Pallas kernel on TPU. ALiBi biases enter through the
shared :func:`llama._attention` (single-block score path — Bloom's 2k
context fits one block)."""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.llm.models._facade import CausalLMFacade
from bigdl_tpu.llm.models.gptneox import _layer_norm, _linear_b
from bigdl_tpu.llm.models.llama import _attention


@dataclasses.dataclass
class BloomConfig:
    vocab_size: int = 250880
    hidden_size: int = 4096
    num_hidden_layers: int = 30
    num_attention_heads: int = 32
    layer_norm_epsilon: float = 1e-5
    max_position_embeddings: int = 2048
    sliding_window = None              # read by the shared _attention
    num_experts = 0

    @property
    def intermediate_size(self) -> int:
        return 4 * self.hidden_size

    @property
    def num_key_value_heads(self) -> int:
        return self.num_attention_heads

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def attn_block_size(self) -> int:
        # ALiBi rides the single-block attention path (llama._attention)
        return max(self.max_position_embeddings, 1024)

    @classmethod
    def bloom_7b1(cls) -> "BloomConfig":
        return cls()

    @classmethod
    def tiny(cls, vocab: int = 256) -> "BloomConfig":
        return cls(vocab_size=vocab, hidden_size=64, num_hidden_layers=2,
                   num_attention_heads=4, max_position_embeddings=128)

    @classmethod
    def from_hf(cls, hf) -> "BloomConfig":
        g = (lambda k, d: getattr(hf, k, d))
        return cls(vocab_size=g("vocab_size", 250880),
                   hidden_size=g("hidden_size", g("n_embed", 4096)),
                   num_hidden_layers=g("num_hidden_layers",
                                       g("n_layer", 30)),
                   num_attention_heads=g("num_attention_heads",
                                         g("n_head", 32)),
                   layer_norm_epsilon=g("layer_norm_epsilon", 1e-5))


def alibi_slopes(n_heads: int) -> np.ndarray:
    """Per-head ALiBi slopes — the closest-power-of-2 recipe of the
    ALiBi paper that HF's ``build_alibi_tensor`` implements: for
    ``p = 2^floor(log2 n)`` heads, slope_i = 2^(-8(i+1)/p); remaining
    heads interleave the odd steps of the 2p schedule."""
    p = 2 ** int(np.floor(np.log2(n_heads)))
    base = 2.0 ** (-(2.0 ** -(np.log2(p) - 3)))
    slopes = base ** np.arange(1, p + 1)
    if p < n_heads:
        base2 = 2.0 ** (-(2.0 ** -(np.log2(2 * p) - 3)))
        extra = base2 ** np.arange(1, 2 * (n_heads - p) + 1, 2)
        slopes = np.concatenate([slopes, extra])
    return slopes.astype(np.float32)


_LAYER_LINEARS = ("q_proj", "k_proj", "v_proj", "o_proj",
                  "fc_in", "fc_out")


def linear_shapes(cfg: BloomConfig) -> Dict[str, Tuple[int, int]]:
    h = cfg.hidden_size
    return {"q_proj": (h, h), "k_proj": (h, h), "v_proj": (h, h),
            "o_proj": (h, h), "fc_in": (cfg.intermediate_size, h),
            "fc_out": (h, cfg.intermediate_size)}


def init_params(cfg: BloomConfig, seed: int = 0,
                dtype=jnp.bfloat16) -> Dict[str, Any]:
    key = jax.random.PRNGKey(seed)
    h = cfg.hidden_size
    L = cfg.num_hidden_layers
    shapes = linear_shapes(cfg)

    def mk(key, shape, scale=None):
        scale = scale or (1.0 / np.sqrt(shape[-1]))
        return (jax.random.normal(key, shape, jnp.float32)
                * scale).astype(dtype)

    keys = jax.random.split(key, 4 + len(shapes))
    layers: Dict[str, Any] = {}
    for i, (name, shape) in enumerate(shapes.items()):
        layers[name] = {"w": mk(keys[i], (L,) + shape),
                        "b": jnp.zeros((L, shape[0]), dtype)}
    for norm in ("input_layernorm", "post_attention_layernorm"):
        layers[norm] = {"w": jnp.ones((L, h), dtype),
                        "b": jnp.zeros((L, h), dtype)}
    return {
        "word_embeddings": mk(keys[-3], (cfg.vocab_size, h), 0.02),
        "word_embeddings_layernorm": {"w": jnp.ones((h,), dtype),
                                      "b": jnp.zeros((h,), dtype)},
        "ln_f": {"w": jnp.ones((h,), dtype), "b": jnp.zeros((h,), dtype)},
        "layers": layers,
    }


def quantize_params(params: Dict[str, Any], qtype: str = "sym_int4"
                    ) -> Dict[str, Any]:
    """ggml-quantize the decoder linears into the k-major TPU layout
    (weights only; biases/norms stay bf16)."""
    from bigdl_tpu.llm.kernels import quantize_tpu

    if qtype != "sym_int4":
        raise NotImplementedError(
            "the scanned decoder path implements q4_0 (sym_int4)")
    out = dict(params)
    layers = dict(params["layers"])
    for name in _LAYER_LINEARS:
        w = np.asarray(layers[name]["w"], np.float32)
        qs, ss = [], []
        for l in range(w.shape[0]):
            qd = quantize_tpu(w[l], qtype)
            qs.append(qd["q"])
            ss.append(qd["scale"])
        layers[name] = {"q": jnp.asarray(np.stack(qs)),
                        "scale": jnp.asarray(np.stack(ss)),
                        "b": layers[name]["b"]}
    out["layers"] = layers
    return out


def init_cache(cfg: BloomConfig, batch: int, max_len: int,
               dtype=jnp.bfloat16) -> Dict[str, jnp.ndarray]:
    shape = (cfg.num_hidden_layers, batch, max_len,
             cfg.num_attention_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype),
            "pos": jnp.zeros((), jnp.int32)}


def forward(params: Dict[str, Any], cfg: BloomConfig,
            tokens: jnp.ndarray, cache: Dict[str, jnp.ndarray],
            positions: jnp.ndarray) -> Tuple[jnp.ndarray, Dict]:
    x = _layer_norm(params["word_embeddings"][tokens],
                    params["word_embeddings_layernorm"],
                    cfg.layer_norm_epsilon)
    start = cache["pos"]
    s_max = cache["k"].shape[2]
    valid = jnp.arange(s_max)[None, :] < (start + tokens.shape[1])
    nh, hd = cfg.num_attention_heads, cfg.head_dim
    slopes = jnp.asarray(alibi_slopes(nh))

    def layer_step(carry, inputs):
        x, = carry
        lp, k_cache, v_cache = inputs
        b, t, _ = x.shape
        h1 = _layer_norm(x, lp["input_layernorm"], cfg.layer_norm_epsilon)
        q = _linear_b(lp["q_proj"], h1).reshape(b, t, nh, hd)
        k = _linear_b(lp["k_proj"], h1).reshape(b, t, nh, hd)
        v = _linear_b(lp["v_proj"], h1).reshape(b, t, nh, hd)
        # no rotary: ALiBi carries all position information
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype), (0, start, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype), (0, start, 0, 0))
        attn = _attention(q, k_cache, v_cache, positions, valid, cfg,
                          alibi_slopes=slopes)
        x = x + _linear_b(lp["o_proj"], attn)
        h2 = _layer_norm(x, lp["post_attention_layernorm"],
                         cfg.layer_norm_epsilon)
        mlp = _linear_b(lp["fc_out"], jax.nn.gelu(
            _linear_b(lp["fc_in"], h2).astype(jnp.float32),
            approximate=True).astype(x.dtype))   # Bloom's tanh GELU
        x = x + mlp
        return (x,), (k_cache, v_cache)

    (x,), (k_new, v_new) = jax.lax.scan(
        layer_step, (x,), (params["layers"], cache["k"], cache["v"]))
    x = _layer_norm(x, params["ln_f"], cfg.layer_norm_epsilon)
    # tied head: logits through the word embedding matrix
    logits = x @ params["word_embeddings"].T.astype(x.dtype)
    return logits.astype(jnp.float32), {
        "k": k_new, "v": v_new, "pos": start + tokens.shape[1]}


class BloomForCausalLM(CausalLMFacade):
    """Generation facade — shared driver (see models._facade)."""

    _forward = staticmethod(forward)
    _init_cache = staticmethod(init_cache)
    _init_params = staticmethod(init_params)
    _quantize_params = staticmethod(quantize_params)


# ---------------------------------------------------------------------------
# HF interop (safetensors, no torch)
# ---------------------------------------------------------------------------

def load_hf_bloom_safetensors(path: str, cfg: Optional[BloomConfig] = None,
                              qtype: Optional[str] = None,
                              dtype=jnp.bfloat16) -> Dict[str, Any]:
    """HF BloomForCausalLM checkpoint → our stacked layout. HF fuses qkv
    as ``self_attention.query_key_value`` with per-head [q; k; v]
    interleaving — split back into separate projections here."""
    import json as _json
    import os as _os

    from bigdl_tpu.llm.kernels import quantize_tpu

    if qtype and qtype != "sym_int4":
        raise NotImplementedError("q4_0 only on the scanned path")
    if cfg is None:
        with open(_os.path.join(path, "config.json")) as f:
            raw = _json.load(f)
        cfg = BloomConfig.from_hf(type("HFConfig", (), raw)())

    from bigdl_tpu.llm.transformers.st_reader import SafetensorsReader
    reader = SafetensorsReader(path)   # handles the optional
    get = reader.get                   # "transformer." name prefix

    L = cfg.num_hidden_layers
    nh, hd, h = cfg.num_attention_heads, cfg.head_dim, cfg.hidden_size
    _HF_LIN = {"o_proj": "self_attention.dense",
               "fc_in": "mlp.dense_h_to_4h", "fc_out": "mlp.dense_4h_to_h"}
    acc: Dict[str, Dict[str, list]] = {
        n: {"w": [], "q": [], "scale": [], "b": []} for n in _LAYER_LINEARS}

    def put_linear(name, w, b):
        a = acc[name]
        a["b"].append(b)
        if qtype:
            qd = quantize_tpu(w, qtype)
            a["q"].append(qd["q"])
            a["scale"].append(qd["scale"])
        else:
            a["w"].append(w.astype(np.float32))

    for l in range(L):
        w = get(f"h.{l}.self_attention.query_key_value.weight")
        b = get(f"h.{l}.self_attention.query_key_value.bias")
        w = w.reshape(nh, 3, hd, h)
        b = b.reshape(nh, 3, hd)
        for i, name in enumerate(("q_proj", "k_proj", "v_proj")):
            put_linear(name, w[:, i].reshape(h, h), b[:, i].reshape(h))
        for name, hf in _HF_LIN.items():
            put_linear(name, get(f"h.{l}.{hf}.weight"),
                       get(f"h.{l}.{hf}.bias"))

    layers: Dict[str, Any] = {}
    for name, a in acc.items():
        entry: Dict[str, Any] = {"b": jnp.asarray(np.stack(a["b"]), dtype)}
        if qtype:
            entry["q"] = jnp.asarray(np.stack(a["q"]))
            entry["scale"] = jnp.asarray(np.stack(a["scale"]))
        else:
            entry["w"] = jnp.asarray(np.stack(a["w"]), dtype)
        layers[name] = entry
    for norm in ("input_layernorm", "post_attention_layernorm"):
        layers[norm] = {
            "w": jnp.asarray(np.stack(
                [get(f"h.{l}.{norm}.weight") for l in range(L)]), dtype),
            "b": jnp.asarray(np.stack(
                [get(f"h.{l}.{norm}.bias") for l in range(L)]), dtype)}
    return {
        "word_embeddings": jnp.asarray(get("word_embeddings.weight"),
                                       dtype),
        "word_embeddings_layernorm": {
            "w": jnp.asarray(get("word_embeddings_layernorm.weight"),
                             dtype),
            "b": jnp.asarray(get("word_embeddings_layernorm.bias"),
                             dtype)},
        "ln_f": {"w": jnp.asarray(get("ln_f.weight"), dtype),
                 "b": jnp.asarray(get("ln_f.bias"), dtype)},
        "layers": layers,
    }

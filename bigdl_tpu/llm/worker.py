"""FastChat-style model worker over LLMServer (ref: ``P:llm/serving``'s
bigdl-llm FastChat worker — VERDICT r3 missing #4's second half). The
reference registers a worker process with a FastChat controller and
serves ``/worker_generate``-family endpoints; this is that HTTP surface
(stdlib-only) over our continuous-batching paged-KV engine.

Endpoints:
- ``POST /worker_generate``        {"prompt_ids": [...], "max_new_tokens"?}
  → blocks → {"output_ids": [...], "finish_reason": "stop"|"length"}
- ``POST /worker_generate_stream`` same body → chunked JSON lines, one
  per newly decoded token batch: {"output_ids": [...so far], "done": bool}
  (the FastChat worker streams exactly such JSON deltas)
- ``GET  /worker_get_status``      {"model": ..., "queue_length": ...,
  "speed": tokens/s since start}
- ``GET  /healthz``                200/503 + engine-thread liveness and
  the reliability health-check registry (ISSUE 2)
- ``GET  /debug/trace/<trace_id>`` stitched per-request trace;
  ``GET /debug/traces`` slowest-N latency exemplars (ISSUE 3)

Distributed tracing (ISSUE 3): the generate endpoints read the
case-insensitive ``X-BigDL-Trace-Id``/``X-BigDL-Parent-Span`` headers
(minting a fresh trace when absent), activate the context so the
engine's queue-wait/prefill/decode spans stitch under the request, and
echo ``X-BigDL-Trace-Id`` on the response. Disabled observability emits
no trace headers at all.

Backpressure (ISSUE 2): when the engine's bounded queue rejects a
submit (``OverloadError``) the worker sheds with **503 + Retry-After**
instead of queueing unboundedly; per-request deadlines propagate via
``X-BigDL-Deadline-Ms`` and cap the blocking wait.

Disaggregated serving (ISSUE 6): ``role`` (``bigdl.llm.role``) splits
workers into **prefill** and **decode** pools with KV handoff through
the host tier:

- ``POST /worker_prefill``       {"prompt_ids": [...]} → runs the
  prompt once (one decoded token), exports the KV chain as a
  base64 handoff blob (prefill role; decode-role workers answer 403)
- ``POST /worker_import_chain``  {"handoff": "<b64>"} → lands the
  blob's pages in this worker's host arena (decode role; prefill-role
  workers answer 403)
- :class:`LLMRouter` — the thin placement scheduler over both pools:
  per-backend circuit breakers, 503 + Retry-After shed when no decode
  backend is admittable, trace-header propagation so
  ``GET /debug/trace/<id>`` stitches the request across router →
  prefill worker → decode worker, and graceful degradation (a failed
  prefill stage routes the request to the decode pool without a blob
  — it simply prefills itself).

Request-level failover (ISSUE 7, ``bigdl.llm.failover.enabled`` /
``bigdl.llm.hedge.enabled``, both default off — see
docs/RELIABILITY.md "Request-level failover"): the router journals
in-flight requests and resumes ``prompt + generated_so_far`` on
another backend after a decode failure, a background prober feeds
live pool membership (``POST /backends`` joins/leaves members), slow
calls hedge to a twin backend after a p95-based delay, and ``GET
/metrics`` exports per-backend breaker-state gauges. The worker side
grows a watchdog-aware ``/healthz`` (a stalled engine answers 503
``"stalled"``) and terminal stream chunks that carry the engine's
error + ``retriable`` flag so the router can fail over with the
tokens drained so far.

Token-level API by design: tokenization happens client-side (the
environment ships no tokenizer assets; the reference worker accepts text
because it bundles the HF tokenizer).
"""

from __future__ import annotations

import base64
import http.client
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional, Tuple

import numpy as np

from bigdl_tpu import observability as obs
from bigdl_tpu import reliability
from bigdl_tpu.observability import alerts
from bigdl_tpu.observability import flight
from bigdl_tpu.observability import request_context as rc
from bigdl_tpu.observability import timeseries
from bigdl_tpu.observability import tracing
from bigdl_tpu.observability.federation import (
    federation_enabled, registry_snapshot)

ROLES = ("", "prefill", "decode")

# SLO-class header (ISSUE 17): case-insensitive (HTTPMessage lookups
# already are), propagated router→worker alongside the trace/deadline
# headers so the journal's failover re-dispatch keeps the class
PRIORITY_HEADER = "X-BigDL-Priority"


class _QuietHTTPServer(ThreadingHTTPServer):
    """Abandoned client connections are ROUTINE on these surfaces
    (ISSUE 7): the loser of a hedge race is cancelled mid-stream, and a
    failover re-dispatch closes the dead attempt's socket — the default
    stderr traceback for a peer reset is pure noise. Real handler
    errors still print."""

    def handle_error(self, request, client_address):
        import sys
        exc = sys.exc_info()[1]
        if isinstance(exc, (ConnectionResetError, BrokenPipeError,
                            ConnectionAbortedError)):
            return
        super().handle_error(request, client_address)


def _send_json(handler, code: int, obj, headers=()):
    """Shared JSON response for the worker and router handlers: body,
    custom headers, and the request's trace-id echo (absent in disabled
    mode). Keep-alive reuses handlers — ``_trace`` is reset at the top
    of every do_GET/do_POST, so no cross-request leak."""
    body = json.dumps(obj).encode()
    handler.send_response(code)
    handler.send_header("Content-Type", "application/json")
    for k, v in headers:
        handler.send_header(k, v)
    trace_id = getattr(handler, "_trace", None)
    if trace_id:
        handler.send_header(rc.TRACE_HEADER, trace_id)
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)


class LLMWorker:
    def __init__(self, server, model_name: str = "bigdl-tpu-llm",
                 host: str = "127.0.0.1", port: int = 0,
                 request_timeout: float = 600.0,
                 role: Optional[str] = None,
                 federation: Optional[bool] = None,
                 fleet: Optional[bool] = None,
                 api: Optional[bool] = None,
                 tokenizer=None):
        from bigdl_tpu.utils.conf import conf
        self.server = server
        self.model_name = model_name
        self.request_timeout = request_timeout
        self.role = (role if role is not None
                     else conf.get("bigdl.llm.role", "") or "")
        if self.role not in ROLES:
            raise ValueError(f"bigdl.llm.role must be one of {ROLES}, "
                             f"got {self.role!r}")
        # fleet federation member surface (ISSUE 12): /metrics/snapshot
        # exists only when the federation plane is on — a disabled
        # worker keeps the endpoint structurally absent (404)
        self.federation = federation_enabled(federation)
        # graceful drain (ISSUE 15): the coordinator exists only when
        # bigdl.llm.fleet.enabled — disabled mode has no drain state
        # and /worker_drain answers 404 (structural absence)
        fleet_on = (fleet if fleet is not None else
                    conf.get_bool("bigdl.llm.fleet.enabled", False))
        self._drain = None
        if fleet_on:
            from bigdl_tpu.llm.fleet import DrainCoordinator
            self._drain = DrainCoordinator(server)
        # OpenAI-compatible gateway (ISSUE 20): constructed ONLY when
        # bigdl.llm.api.enabled — disabled mode keeps /v1/* answering
        # 404 naming the gate and mints no bigdl_api_* series
        api_on = (api if api is not None else
                  conf.get_bool("bigdl.llm.api.enabled", False))
        self._api = None
        if api_on:
            from bigdl_tpu.llm.api.gateway import (EngineBackend,
                                                   OpenAIGateway)
            self._api = OpenAIGateway(
                EngineBackend(server, model_name,
                              request_timeout=request_timeout),
                tokenizer=tokenizer, scope="worker")
        self._t0 = time.time()
        self._tokens_out = 0
        worker = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _json(self, code: int, obj, headers=()):
                _send_json(self, code, obj, headers)

            def _read_req(self):
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n))
                ids = np.asarray(req["prompt_ids"], np.int32)
                return ids, int(req.get("max_new_tokens", 32))

            def _submit(self, ids, mnt):
                """submit with the 422/503/500 split: invalid requests
                are the client's fault, overload is shed with
                Retry-After, and any other failure (including an
                injected one — InjectedFault is deliberately NOT
                special-cased, per the faults.py contract) answers 500
                instead of killing the handler's connection."""
                pri = self.headers.get(PRIORITY_HEADER)
                try:
                    # the kwarg is passed only when the header is
                    # present: stub servers in tests (and any
                    # priority-unaware engine) keep working unchanged
                    kw = {"priority": pri} if pri is not None else {}
                    return worker.server.submit(ids, max_new_tokens=mnt,
                                                **kw)
                except reliability.OverloadError as e:
                    # page accounting rides the Retry-After diagnostics
                    # (ISSUE 5 satellite): pages_needed is the POST-
                    # LOOKUP suffix cost, so clients see how far the
                    # prefix cache already got them
                    body = {"error": str(e)}
                    for key in ("pages_needed", "pages_free"):
                        val = getattr(e, key, None)
                        if val is not None:
                            body[key] = int(val)
                    if getattr(e, "draining", False):
                        # drain shed (ISSUE 15): a structured field the
                        # router's bounce keys on — never the wording
                        body["draining"] = True
                    # Retry-After derived from observed queue depth
                    # (ISSUE 7 satellite) — a deep backlog tells
                    # clients to back off longer, jitter decorrelates
                    # the retry herd. With the priority scheduler the
                    # depth is class-weighted (ISSUE 17 satellite):
                    # batch clients back off harder than interactive
                    # ones under the SAME backlog.
                    rd = getattr(worker.server, "retry_depth", None)
                    if rd is not None:
                        depth = rd(pri)
                    else:
                        q = getattr(worker.server, "_queue", None)
                        depth = q.qsize() if q is not None else 0
                    self._json(503, body, headers=(
                        ("Retry-After",
                         reliability.retry_after_seconds(depth)),))
                    return None
                except ValueError as e:
                    self._json(422, {"error": str(e)})
                    return None
                except Exception as e:  # noqa: BLE001 — real or injected
                    self._json(500, {"error": f"submit failed: {e}"})
                    return None

            def _wait_timeout(self) -> float:
                deadline = reliability.Deadline.from_header(
                    self.headers.get(reliability.DEADLINE_HEADER))
                if deadline is None:
                    return worker.request_timeout
                return max(min(worker.request_timeout,
                               deadline.remaining()), 0.0)

            def do_GET(self):
                self._trace = None
                debug = tracing.debug_endpoint(self.path)
                if debug is None:
                    # flight recorder + per-request explain (ISSUE 16):
                    # same shared-helper idiom, 404 arms included
                    debug = flight.debug_endpoint(self.path)
                if debug is None:
                    # time-series plane (ISSUE 18): /metrics/query +
                    # /fleet/timeline + /alerts, 404 arms included
                    debug = timeseries.debug_endpoint(self.path)
                if debug is None:
                    debug = alerts.debug_endpoint(self.path)
                if debug is not None:
                    self._json(*debug)
                elif self.path == "/debug/kvcache":
                    # prefix-cache state (ISSUE 5): pool refcounts,
                    # radix index size, hit/miss/evict tallies. 404
                    # when the cache is disabled — the surface is
                    # structurally absent, not empty
                    kv = getattr(worker.server, "_kv", None)
                    if kv is None or not kv.enabled:
                        self._json(404, {"error": "kvcache disabled"})
                    else:
                        self._json(200, kv.debug_stats())
                elif self.path == "/worker_drain":
                    # drain status poll (ISSUE 15): 404 when the fleet
                    # plane is off — structurally absent, not idle
                    if worker._drain is None:
                        self._json(404, {"error": "fleet disabled"})
                    else:
                        self._json(200, worker._drain.status())
                elif self.path == "/v1/models":
                    # OpenAI surface (ISSUE 20): 404 when the gateway
                    # is off — structurally absent, naming the gate
                    if worker._api is None:
                        self._json(404, {"error": "api disabled "
                                         "(bigdl.llm.api.enabled)"})
                    else:
                        worker._api.handle_models(self)
                elif self.path == "/worker_get_status":
                    dt = max(time.time() - worker._t0, 1e-9)
                    status = {
                        "model": worker.model_name,
                        "role": worker.role,
                        "queue_length": worker.server._queue.qsize(),
                        "steps": worker.server.steps,
                        "speed": round(worker._tokens_out / dt, 2)}
                    cd = getattr(worker.server, "class_depths", None)
                    depths = cd() if cd is not None else None
                    if depths is not None:
                        # ISSUE 17: absent when the scheduler is off
                        status["queue_by_class"] = depths
                        status["preempt_parked"] = \
                            worker.server.preempt_parked
                    self._json(200, status)
                elif self.path == "/metrics":
                    # same Prometheus surface as the cluster-serving
                    # frontend: prefill/decode tokens, KV occupancy, …
                    from bigdl_tpu import observability as obs
                    body = obs.render().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", obs.CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path == "/metrics/snapshot":
                    # federation member surface (ISSUE 12): the full
                    # registry as JSON incl. sketch state, for the
                    # fleet collector's label-aware merge. 404 when
                    # the federation plane is off — structurally
                    # absent, not empty.
                    if not worker.federation:
                        self._json(404,
                                   {"error": "federation disabled"})
                    else:
                        addr = worker.address
                        self._json(200, registry_snapshot(
                            instance=f"{addr[0]}:{addr[1]}"))
                elif self.path == "/healthz":
                    ok, report = reliability.health_report()
                    engine = worker.server._thread
                    alive = engine is not None and engine.is_alive()
                    draining = worker.server._draining.is_set() \
                        if hasattr(worker.server, "_draining") else False
                    # watchdog (ISSUE 7): a stalled engine answers 503
                    # so the router's prober drains this worker; the
                    # key is structurally absent when the watchdog is
                    # off (disabled-mode byte-compat)
                    tripped = bool(getattr(worker.server,
                                           "watchdog_tripped", False))
                    healthy = ok and alive and not draining \
                        and not tripped
                    body = {
                        "status": ("ok" if healthy else
                                   "draining" if draining else
                                   "stalled" if tripped else
                                   "unhealthy"),
                        "role": worker.role,
                        "engine_alive": alive,
                        "queue_length": worker.server._queue.qsize(),
                        "checks": report}
                    if getattr(worker.server, "watchdog_enabled",
                               False):
                        body["watchdog"] = {
                            "tripped": tripped,
                            "trips": worker.server.watchdog_trips,
                            "step_timeout_s":
                                worker.server.watchdog_timeout}
                    # rolling SLO burn rate (ISSUE 12): absent when
                    # bigdl.slo.enabled is off
                    slo = getattr(worker.server, "_slo", None)
                    if slo is not None:
                        body["slo"] = slo.status()
                    # priority scheduler (ISSUE 17): per-class backlog
                    # and preempted-parked count, keys structurally
                    # absent when bigdl.llm.priority.enabled is off —
                    # the fleet's scale-in victim filter and class-
                    # pressure signal read these without federation
                    cd = getattr(worker.server, "class_depths", None)
                    depths = cd() if cd is not None else None
                    if depths is not None:
                        body["queue_by_class"] = depths
                        body["preempt_parked"] = \
                            worker.server.preempt_parked
                    self._json(200 if healthy else 503, body)
                else:
                    self._json(404, {"error": "unknown path"})

            def do_POST(self):
                self._trace = None
                ctx = None
                if self.path in ("/worker_generate",
                                 "/worker_generate_stream",
                                 "/worker_prefill",
                                 "/worker_import_chain",
                                 "/v1/completions",
                                 "/v1/chat/completions"):
                    # case-insensitive trace extraction (or a fresh
                    # root); None in disabled mode — no headers emitted
                    ctx = rc.server_context(self.headers)
                    if ctx is not None:
                        self._trace = ctx.trace_id
                # role gating (ISSUE 6): a prefill-pool worker never
                # decodes full requests, a decode-pool worker never
                # serves the prefill/export side — misrouted calls are
                # the router's bug and answer 403, not a silent detour
                if worker.role == "prefill" and self.path in (
                        "/worker_generate", "/worker_generate_stream",
                        "/v1/completions", "/v1/chat/completions"):
                    self._json(403, {"error": "prefill-role worker: "
                                     "use /worker_prefill"})
                    return
                if worker.role == "decode" and \
                        self.path == "/worker_prefill":
                    self._json(403, {"error": "decode-role worker "
                                     "does not prefill"})
                    return
                if worker.role == "prefill" and \
                        self.path == "/worker_import_chain":
                    self._json(403, {"error": "prefill-role worker "
                                     "does not import chains"})
                    return
                if self.path in ("/v1/completions",
                                 "/v1/chat/completions"):
                    # OpenAI surface (ISSUE 20): direct engine drain
                    # on the single-node worker; 404 naming the gate
                    # when off — structurally absent
                    if worker._api is None:
                        self._json(404, {"error": "api disabled "
                                         "(bigdl.llm.api.enabled)"})
                        return
                    with rc.activate(ctx):
                        worker._api.handle_post(self, self.path)
                    return
                if self.path == "/worker_drain":
                    # graceful drain control (ISSUE 15): begin flips
                    # the engine to DRAINING and starts the finish-
                    # then-migrate thread; cancel resumes admission.
                    # 404 when the fleet plane is off.
                    if worker._drain is None:
                        self._json(404, {"error": "fleet disabled"})
                        return
                    try:
                        n = int(self.headers.get("Content-Length", 0))
                        body = json.loads(self.rfile.read(n)) if n \
                            else {}
                        action = body.get("action", "begin")
                        if action not in ("begin", "cancel"):
                            raise ValueError(
                                "action must be begin|cancel")
                        # coerce peers/timeout HERE: malformed values
                        # are the client's 400, not a torn connection
                        peers = [(str(p[0]), int(p[1]))
                                 for p in body.get("peers", [])]
                        drain_timeout = float(body.get("timeout", 60.0))
                    except Exception as e:  # noqa: BLE001
                        self._json(400, {"error": f"bad request: {e}"})
                        return
                    if action == "cancel":
                        worker._drain.cancel()
                        self._json(200, worker._drain.status())
                        return
                    started = worker._drain.begin(
                        peers, timeout=drain_timeout)
                    if not started:
                        self._json(409, {
                            "error": "drain already active",
                            **worker._drain.status()})
                        return
                    self._json(200, worker._drain.status())
                    return
                if self.path == "/worker_prefill":
                    # run the prompt once (one decoded token pins the
                    # chain in the index), then export its KV pages as
                    # the handoff blob (ISSUE 6 disaggregation)
                    try:
                        ids, _ = self._read_req()
                    except Exception as e:  # noqa: BLE001
                        self._json(400, {"error": f"bad request: {e}"})
                        return
                    with rc.activate(ctx), \
                            obs.span("llm/handoff_export",
                                     stage="llm_worker",
                                     tokens=len(ids)):
                        req = self._submit(ids, 1)
                        if req is None:
                            return
                        try:
                            toks = req.get(timeout=self._wait_timeout())
                        except TimeoutError:
                            self._json(504,
                                       {"error": "prefill timed out"})
                            return
                        except RuntimeError as e:
                            self._json(500, {"error": str(e)})
                            return
                        try:
                            blob = worker.server.export_chain(ids)
                        except RuntimeError as e:   # tier disabled
                            self._json(501, {"error": str(e)})
                            return
                    worker._tokens_out += len(toks)
                    self._json(200, {
                        "handoff": base64.b64encode(blob).decode(),
                        "handoff_bytes": len(blob),
                        "output_ids": list(map(int, toks))})
                    return
                if self.path == "/worker_import_chain":
                    try:
                        n = int(self.headers.get("Content-Length", 0))
                        body = json.loads(self.rfile.read(n))
                        blob = base64.b64decode(body["handoff"])
                    except Exception as e:  # noqa: BLE001
                        self._json(400, {"error": f"bad request: {e}"})
                        return
                    with rc.activate(ctx), \
                            obs.span("llm/handoff_import",
                                     stage="llm_worker",
                                     bytes=len(blob)):
                        try:
                            pages = worker.server.import_chain(blob)
                        except RuntimeError as e:   # tier disabled
                            self._json(501, {"error": str(e)})
                            return
                        except ValueError as e:     # malformed blob
                            self._json(422, {"error": str(e)})
                            return
                    self._json(200, {"imported_pages": pages})
                    return
                if self.path == "/worker_generate":
                    try:
                        ids, mnt = self._read_req()
                    except Exception as e:  # noqa: BLE001
                        self._json(400, {"error": f"bad request: {e}"})
                        return
                    t_req = time.perf_counter()
                    with rc.activate(ctx), \
                            obs.span("llm/request", stage="llm_worker",
                                     max_new_tokens=mnt):
                        req = self._submit(ids, mnt)
                        if req is None:
                            return
                        try:
                            toks = req.get(timeout=self._wait_timeout())
                        except TimeoutError:
                            # timed-out requests are by definition the
                            # slowest — excluding them would make the
                            # exemplar store lie about the tail
                            if ctx is not None:
                                obs.EXEMPLARS.offer(
                                    ctx.trace_id,
                                    time.perf_counter() - t_req,
                                    name="llm/request", request=req.id,
                                    status="timeout")
                            self._json(504,
                                       {"error": "generation timed out"})
                            return
                        except RuntimeError as e:  # engine failed it
                            self._json(500, {"error": str(e)})
                            return
                    if ctx is not None:
                        obs.EXEMPLARS.offer(
                            ctx.trace_id, time.perf_counter() - t_req,
                            name="llm/request", request=req.id,
                            status="ok", tokens=len(toks))
                    worker._tokens_out += len(toks)
                    eos = worker.server.eos_token_id
                    reason = ("stop" if eos is not None and toks
                              and toks[-1] == eos else "length")
                    self._json(200, {"output_ids": list(map(int, toks)),
                                     "finish_reason": reason})
                elif self.path == "/worker_generate_stream":
                    try:
                        ids, mnt = self._read_req()
                    except Exception as e:  # noqa: BLE001
                        self._json(400, {"error": f"bad request: {e}"})
                        return
                    with rc.activate(ctx):
                        req = self._submit(ids, mnt)
                    if req is None:
                        return
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/json-lines")
                    self.send_header("Transfer-Encoding", "chunked")
                    if ctx is not None:
                        self.send_header(rc.TRACE_HEADER, ctx.trace_id)
                    self.end_headers()

                    def chunk(obj):
                        data = (json.dumps(obj) + "\n").encode()
                        self.wfile.write(
                            f"{len(data):x}\r\n".encode() + data
                            + b"\r\n")
                        self.wfile.flush()

                    seen = 0
                    done = False
                    deadline = time.time() + self._wait_timeout()
                    try:
                        while time.time() < deadline:
                            done = req.done.wait(0.02)
                            cur = list(req.tokens)
                            eos = worker.server.eos_token_id
                            if not done and req.error is None \
                                    and eos is not None and cur \
                                    and cur[-1] == eos:
                                # a chunk ending in EOS is ALWAYS
                                # terminal (ISSUE 7): the engine is
                                # about to finish this request with
                                # "stop". A done:false chunk carrying
                                # EOS would let a mid-stream failover
                                # journal it, resume past it on
                                # another backend, and generate
                                # spurious post-EOS tokens.
                                done = True
                            if len(cur) > seen or done:
                                seen = len(cur)
                                payload = {
                                    "output_ids": list(map(int, cur)),
                                    "done": bool(done)}
                                if done:
                                    # terminal chunk carries the same
                                    # verdict the blocking endpoint
                                    # returns (ISSUE 7): either the
                                    # finish reason, or the engine's
                                    # error so the router can fail
                                    # over with the tokens so far
                                    if req.error is not None:
                                        payload["error"] = req.error
                                        payload["retriable"] = True
                                    else:
                                        eos = worker.server.eos_token_id
                                        payload["finish_reason"] = (
                                            "stop" if eos is not None
                                            and cur and cur[-1] == eos
                                            else "length")
                                chunk(payload)
                            if done:
                                break
                        if not done:
                            # timed out: a stream must never end with
                            # done:false — clients reading until
                            # done:true would see a silent truncation
                            # (ADVICE r4)
                            cur = list(req.tokens)
                            eos = worker.server.eos_token_id
                            if eos is not None and cur \
                                    and cur[-1] == eos:
                                # the engine appended EOS in the
                                # window between the last wait-loop
                                # snapshot and deadline expiry: this
                                # is a FINISHED answer, not a stall.
                                # Labeling it "timeout" (retriable)
                                # would let failover resume past EOS
                                # and append spurious tokens — the
                                # same corruption the in-loop EOS
                                # guard exists to prevent.
                                chunk({"output_ids":
                                       list(map(int, cur)),
                                       "done": True,
                                       "finish_reason": "stop"})
                            else:
                                chunk({"output_ids":
                                       list(map(int, cur)),
                                       "done": True,
                                       "finish_reason": "timeout"})
                                # the router treats "timeout" as
                                # retriable and resumes elsewhere —
                                # abort the orphan so a merely-slow
                                # engine frees its slot and KV pages
                                # instead of double-computing tokens
                                # nobody will read
                                abort = getattr(worker.server,
                                                "abort", None)
                                if abort is not None:
                                    abort(req, reason="stream wait "
                                          "expired")
                        worker._tokens_out += seen
                        self.wfile.write(b"0\r\n\r\n")
                        self.wfile.flush()
                    except OSError:
                        # client gone mid-stream — the loser of a hedge
                        # race, cancelled (ISSUE 7): abort the request
                        # so its slot and KV pages free instead of
                        # decoding tokens nobody will read
                        abort = getattr(worker.server, "abort", None)
                        if abort is not None:
                            abort(req, reason="client disconnected "
                                  "mid-stream")
                        worker._tokens_out += seen
                        self.close_connection = True
                else:
                    self._json(404, {"error": "unknown path"})

        self._httpd = _QuietHTTPServer((host, port), Handler)
        self.address = self._httpd.server_address
        self._thread: Optional[object] = None

    def start(self) -> "LLMWorker":
        import threading
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        # time-series plane (ISSUE 18): refcounted — released on stop
        self._timeseries = timeseries.acquire()
        return self

    def stop(self):
        # shutdown during an active drain (ISSUE 15 satellite): cancel
        # and JOIN the drain thread first — after this there are no
        # orphaned migration posts and no drain-held state; resume=False
        # keeps admission closed (the engine is about to stop for good)
        if self._drain is not None:
            self._drain.cancel(resume=False)
        if getattr(self, "_timeseries", None) is not None:
            timeseries.release()
            self._timeseries = None
        if self._thread is not None:
            # shutdown() handshakes with serve_forever — calling it on
            # a never-started server would wait forever
            self._httpd.shutdown()
            self._thread.join(timeout=5.0)
        self._httpd.server_close()


def _post_json(addr: Tuple[str, int], path: str, body: dict,
               headers=(), timeout: float = 600.0, canceller=None):
    """One JSON POST to a backend worker → (status, parsed body,
    response headers dict). Connection errors raise — the router's
    breaker accounting wants them loud. ``canceller`` (ISSUE 7) lets a
    hedge race close this connection from another thread."""
    conn = http.client.HTTPConnection(addr[0], addr[1], timeout=timeout)
    if canceller is not None:
        canceller.attach(conn)
    try:
        payload = json.dumps(body)
        hdrs = {"Content-Type": "application/json"}
        for k, v in headers:
            hdrs[k] = v
        conn.request("POST", path, payload, hdrs)
        resp = conn.getresponse()
        data = resp.read()
        try:
            parsed = json.loads(data.decode())
        except ValueError:
            parsed = {"error": data.decode(errors="replace")[:200]}
        # resp.msg is the parsed HTTPMessage: case-insensitive .get,
        # still readable after the connection closes
        return resp.status, parsed, resp.msg
    finally:
        conn.close()


class _BackendShed(Exception):
    """503 from a backend: alive, applying backpressure. Relayed with
    its own Retry-After — never retried, never a breaker failure."""

    def __init__(self, parsed, retry_after):
        super().__init__(parsed.get("error", "backend shedding"))
        self.parsed = parsed
        self.retry_after = retry_after


class _BackendDraining(Exception):
    """503 whose body says the worker is DRAINING (ISSUE 15): alive,
    finishing its in-flight streams, taking no new work. NOT a breaker
    failure and NOT client-visible backpressure — the router marks the
    backend draining at the prober and re-routes the request to another
    backend instead of relaying the shed."""

    def __init__(self, parsed):
        super().__init__(parsed.get("error", "backend draining"))
        self.parsed = parsed


class _BackendFatal(Exception):
    """A 4xx from a backend: the *request* is bad (422 infeasible, 403
    misroute), not the backend — relayed as-is, never failed over."""

    def __init__(self, status, parsed):
        super().__init__(parsed.get("error", f"backend answered {status}"))
        self.status = status
        self.parsed = parsed


class _RouteError(Exception):
    """Typed carrier for a failover-routing outcome that must surface
    as an HTTP error. ``_route_failover`` renders it through
    ``handler._json`` exactly as before the ISSUE 20 refactor; the
    OpenAI gateway's router backend maps it onto OpenAI error objects
    (503 → 429 ``rate_limit_exceeded`` keeping the Retry-After)."""

    def __init__(self, status, body, headers=()):
        super().__init__(body.get("error", f"status {status}"))
        self.status = status
        self.body = body
        self.headers = tuple(headers)


class _ApiRouterBackend:
    """OpenAI-gateway backend over the router's failover dispatch
    (ISSUE 20): ``generate`` runs the same journal + resume loop as
    ``POST /worker_generate``, with the gateway's per-delta callback
    installed as the journal entry's drain listener — the SSE chunk
    emission and the router SLO arrival stamps happen at the same
    drain event, so client-visible TTFT/ITL and the
    ``bigdl_router_{ttft,itl}_seconds`` sketches are one accounting.
    Routed pools run greedy decode (the failover bit-parity contract
    requires determinism), so ``sampling()`` reports greedy."""

    def __init__(self, router, model_name: str):
        self.router = router
        self.model_name = model_name
        self.request_timeout = router.request_timeout

    def sampling(self):
        return (0.0, 0)

    def generate(self, prompt_ids, max_new_tokens, priority, deadline,
                 on_delta):
        from bigdl_tpu.llm.api.errors import error_for_status
        body = {"prompt_ids": [int(t) for t in prompt_ids],
                "max_new_tokens": int(max_new_tokens)}
        ctx = rc.current()

        def fwd_headers():
            hdrs = list(rc.to_headers(ctx))
            if deadline is not None:
                hdrs.append((reliability.DEADLINE_HEADER,
                             deadline.to_header()))
            if priority is not None:
                hdrs.append((PRIORITY_HEADER, priority))
            return hdrs

        try:
            ent = self.router._dispatch_failover(
                body, fwd_headers, deadline, priority=priority,
                listener=on_delta)
        except _RouteError as e:
            raise error_for_status(
                e.status,
                e.body.get("error", f"routing failed ({e.status})"),
                retry_after=dict(e.headers).get("Retry-After"))
        return [int(t) for t in ent.tokens], \
            ent.finish_reason or "length"


#: Prometheus encoding of breaker states (ISSUE 7 satellite):
#: closed=0, half_open=1, open=2 — so an alerting rule is `> 1`.
BREAKER_STATE_VALUES = {"closed": 0, "half_open": 1, "open": 2}


class LLMRouter:
    """Placement scheduler over disaggregated worker pools (ISSUE 6),
    grown into the reliability boundary of the serving stack (ISSUE 7).

    ``POST /worker_generate`` routes one request end-to-end:

    1. pick a prefill backend (round-robin over the pool, skipping open
       circuit breakers and prober-unhealthy backends) →
       ``/worker_prefill`` → handoff blob;
    2. pick a decode backend the same way → ``/worker_import_chain``
       (best-effort) then decode → relay the answer.

    **Request-level failover (ISSUE 7 tentpole,
    ``bigdl.llm.failover.enabled`` / ``failover=`` ctor arg; default
    off).** When enabled the router drains decode through the worker's
    *streaming* endpoint and journals every token as it arrives
    (:class:`~bigdl_tpu.llm.failover.RequestJournal`). A connection
    failure / 5xx / mid-generation engine error re-dispatches
    ``prompt + generated_so_far`` to another backend with the remaining
    token budget — greedy decoding is deterministic, so the spliced
    output is bit-identical to an unfailed run, and the backend's radix
    cache / host tier make the resume a cheap suffix re-prefill. Worker
    loss costs latency, not answers (the Spark-lineage story, arXiv
    1804.05839 §3). Alongside it:

    - an active :class:`~bigdl_tpu.llm.failover.HealthProber` polls
      worker ``/healthz`` so ``_pick`` routes on observed health, and
      ``POST /backends`` joins/leaves pool members without a restart;
    - **hedged dispatch** (``bigdl.llm.hedge.enabled``): a prefill or
      decode call slower than the stage's observed p95 is duplicated to
      a second backend — first success wins, the loser's connection is
      closed and the worker aborts it, releasing its KV. Bounded by
      ``bigdl.llm.hedge.budget``;
    - every outgoing backend call re-derives the remaining
      ``X-BigDL-Deadline-Ms`` from elapsed time, so retries and hedges
      never overstate the budget (ISSUE 7 satellite).

    Disabled (both knobs false, the default) the router is the PR 6
    object byte-for-byte: blocking dispatch, no journal, no prober
    thread, no failover/hedge metric series.

    Reused machinery, not re-invented: per-backend
    :class:`~bigdl_tpu.reliability.CircuitBreaker` trips on connection
    failures/5xx, overload sheds with **503 + Retry-After** (derived
    from ``bigdl.llm.retry_after.*``; a backend's own Retry-After is
    relayed unchanged), and the trace context rides
    ``X-BigDL-Trace-Id`` into every backend so ``GET
    /debug/trace/<id>`` shows the stitched router → prefill → decode
    waterfall, with ``router/failover``/``router/hedge`` spans marking
    the recovery path. A failed prefill stage degrades gracefully: the
    decode backend prefills itself.
    """

    def __init__(self, prefill_workers: List[Tuple[str, int]],
                 decode_workers: List[Tuple[str, int]],
                 host: str = "127.0.0.1", port: int = 0,
                 request_timeout: float = 600.0,
                 breaker_threshold: int = 3,
                 breaker_reset: float = 10.0,
                 failover: Optional[bool] = None,
                 hedge: Optional[bool] = None,
                 failover_attempts: Optional[int] = None,
                 hedge_delay_ms: Optional[float] = None,
                 prober_interval: Optional[float] = None,
                 start_prober: bool = True,
                 slo: Optional[bool] = None,
                 federation: Optional[bool] = None,
                 fleet: Optional[bool] = None,
                 provider=None,
                 fleet_opts: Optional[dict] = None,
                 start_fleet: bool = True,
                 api: Optional[bool] = None,
                 model_name: str = "bigdl-tpu-llm",
                 tokenizer=None):
        from bigdl_tpu.utils.conf import conf
        if not decode_workers:
            raise ValueError("the router needs at least one "
                             "decode-role backend")
        self.prefill_workers = [tuple(a) for a in prefill_workers]
        self.decode_workers = [tuple(a) for a in decode_workers]
        self.request_timeout = request_timeout
        self._breaker_threshold = breaker_threshold
        self._breaker_reset = breaker_reset
        self._pool_lock = threading.RLock()
        self._rr = {"prefill": 0, "decode": 0}
        self._breakers = {}
        for addr in self.prefill_workers + self.decode_workers:
            self._breaker_for(addr)   # the one get-or-create path
        self.requests_routed = 0
        self.handoffs_routed = 0
        self.prefill_degraded = 0
        # ISSUE 7: failover + hedging are constructed ONLY when enabled
        # — the disabled router must be structurally the PR 6 object
        self.failover_enabled = (
            failover if failover is not None else
            conf.get_bool("bigdl.llm.failover.enabled", False))
        hedge_on = (hedge if hedge is not None else
                    conf.get_bool("bigdl.llm.hedge.enabled", False))
        self._active = self.failover_enabled or hedge_on
        self.max_attempts = max(1, (
            failover_attempts if failover_attempts is not None else
            conf.get_int("bigdl.llm.failover.max.attempts", 3)))
        self._journal = None
        self._prober = None
        self._hedge = None
        self._latency = None
        self._start_prober = False
        if self._active:
            from bigdl_tpu.llm.failover import (HealthProber, HedgePolicy,
                                                LatencyTracker,
                                                RequestJournal)
            self._journal = RequestJournal()
            self._hedge = HedgePolicy(
                enabled=hedge_on,
                delay_ms=(hedge_delay_ms if hedge_delay_ms is not None
                          else conf.get_float("bigdl.llm.hedge.delay.ms",
                                              0.0)),
                min_delay_ms=conf.get_float(
                    "bigdl.llm.hedge.min.delay.ms", 50.0),
                budget=conf.get_float("bigdl.llm.hedge.budget", 0.1))
            self._latency = {"prefill": LatencyTracker(),
                             "decode": LatencyTracker()}
            if self.failover_enabled:
                self._prober = HealthProber(
                    self._prober_targets,
                    interval=(prober_interval if prober_interval
                              is not None else
                              conf.get_float("bigdl.llm.prober.interval",
                                             0.5)),
                    on_probe=self._on_probe)
                self._start_prober = start_prober
        # client-visible SLO accounting (ISSUE 12): TTFT/ITL from the
        # journal's streamed-token timestamps — only meaningful in
        # failover mode (the blocking PR 6 path streams nothing), and
        # only constructed when bigdl.slo.enabled says so
        self._slo = None
        if self._active:
            from bigdl_tpu.observability.slo import SLOAccount
            self._slo = SLOAccount.if_enabled("router", enabled=slo)
        # fleet metric federation (ISSUE 12): a background collector
        # scraping every pool member's /metrics/snapshot; constructed
        # ONLY when bigdl.observability.federation is on — disabled
        # mode has no collector thread and the fleet endpoints 404
        self._collector = None
        if federation_enabled(federation):
            from bigdl_tpu.observability.federation import (
                FederationCollector)
            self._collector = FederationCollector(
                self._federation_targets, include_self="router")
        # elastic fleet autoscaler (ISSUE 15): constructed ONLY when
        # bigdl.llm.fleet.enabled — disabled mode has no controller
        # thread, no bigdl_fleet_* series, and /fleet/autoscaler 404s
        fleet_on = (fleet if fleet is not None else
                    conf.get_bool("bigdl.llm.fleet.enabled", False))
        self._fleet = None
        self._start_fleet = False
        if fleet_on:
            if not self.failover_enabled:
                raise ValueError(
                    "bigdl.llm.fleet needs bigdl.llm.failover.enabled: "
                    "the autoscaler drives the prober and the live "
                    "POST /backends membership")
            from bigdl_tpu.llm.fleet import FleetController
            self._fleet = FleetController(self, provider=provider,
                                          **(fleet_opts or {}))
            self._start_fleet = start_fleet
        # OpenAI-compatible gateway (ISSUE 20): constructed ONLY when
        # bigdl.llm.api.enabled. On the router it REQUIRES failover
        # mode — the SSE relay streams from the failover journal's
        # drain (the per-token listener), and the blocking PR 6 path
        # streams nothing to relay.
        self.model_name = model_name
        api_on = (api if api is not None else
                  conf.get_bool("bigdl.llm.api.enabled", False))
        self._api = None
        if api_on:
            if not self.failover_enabled:
                raise ValueError(
                    "bigdl.llm.api needs bigdl.llm.failover.enabled "
                    "on the router: the SSE relay drains the failover "
                    "journal")
            from bigdl_tpu.llm.api.gateway import OpenAIGateway
            self._api = OpenAIGateway(
                _ApiRouterBackend(self, model_name),
                tokenizer=tokenizer, scope="router")
        self._ins = None
        router = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _json(self, code: int, obj, headers=()):
                _send_json(self, code, obj, headers)

            def do_GET(self):
                self._trace = None
                debug = tracing.debug_endpoint(self.path)
                if debug is None:
                    # router surface of the flight recorder (ISSUE 16):
                    # the journal's failover/hedge/shed events live in
                    # this process, so explain works here too
                    debug = flight.debug_endpoint(self.path)
                if debug is None:
                    # time-series plane (ISSUE 18): with the collector
                    # attached, /fleet/timeline serves per-member +
                    # merged series off the scrape cache
                    debug = timeseries.debug_endpoint(self.path)
                if debug is None:
                    debug = alerts.debug_endpoint(self.path)
                if debug is not None:
                    self._json(*debug)
                elif self.path == "/healthz":
                    self._json(*router._healthz())
                elif self.path == "/metrics":
                    router._record_breakers()
                    if router._collector is not None:
                        # fleet view (ISSUE 12): members' cached
                        # snapshots merged label-aware, the router's
                        # own registry riding along as instance
                        # "router". Render only reads the collector
                        # cache — a dead member can never stall this.
                        body = router._collector.render().encode()
                    else:
                        body = obs.render().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", obs.CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path == "/fleet/status":
                    if router._collector is None:
                        self._json(404,
                                   {"error": "federation disabled"})
                    else:
                        self._json(200, router._collector.status())
                elif self.path == "/fleet/autoscaler":
                    # autoscaler state (ISSUE 15): 404 when the fleet
                    # plane is off — structurally absent, not idle
                    if router._fleet is None:
                        self._json(404, {"error": "fleet disabled"})
                    else:
                        self._json(200, router._fleet.status())
                elif self.path == "/v1/models":
                    # OpenAI surface (ISSUE 20): 404 when the gateway
                    # is off — structurally absent, naming the gate
                    if router._api is None:
                        self._json(404, {"error": "api disabled "
                                         "(bigdl.llm.api.enabled)"})
                    else:
                        router._api.handle_models(self)
                elif self.path == "/worker_get_status":
                    self._json(200, router._status_body())
                else:
                    self._json(404, {"error": "unknown path"})

            def do_POST(self):
                self._trace = None
                if self.path == "/backends":
                    # live pool membership (ISSUE 7): part of the
                    # active-health layer, 404 when failover is off
                    # (the PR 6 router had no such surface)
                    if not router.failover_enabled:
                        self._json(404, {"error": "unknown path"})
                        return
                    try:
                        n = int(self.headers.get("Content-Length", 0))
                        body = json.loads(self.rfile.read(n))
                        code, out = router._admin_backends(body)
                    except Exception as e:  # noqa: BLE001
                        self._json(400, {"error": f"bad request: {e}"})
                        return
                    self._json(code, out)
                    return
                if self.path in ("/v1/completions",
                                 "/v1/chat/completions"):
                    # OpenAI surface (ISSUE 20): SSE relay from the
                    # failover journal drain; 404 naming the gate when
                    # off — structurally absent
                    if router._api is None:
                        self._json(404, {"error": "api disabled "
                                         "(bigdl.llm.api.enabled)"})
                        return
                    ctx = rc.server_context(self.headers)
                    if ctx is not None:
                        self._trace = ctx.trace_id
                    with rc.activate(ctx):
                        router._api.handle_post(self, self.path)
                    return
                if self.path != "/worker_generate":
                    self._json(404, {"error": "unknown path"})
                    return
                ctx = rc.server_context(self.headers)
                if ctx is not None:
                    self._trace = ctx.trace_id
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(n))
                    body["prompt_ids"] = [int(t)
                                          for t in body["prompt_ids"]]
                except Exception as e:  # noqa: BLE001
                    self._json(400, {"error": f"bad request: {e}"})
                    return
                # the deadline is parsed ONCE; every backend call
                # re-derives the remaining budget from it (ISSUE 7
                # satellite — a relayed original value would overstate
                # the budget on any retry or hedge)
                deadline = reliability.Deadline.from_header(
                    self.headers.get(reliability.DEADLINE_HEADER))
                # SLO class (ISSUE 17): relayed verbatim like the trace
                # headers — every backend attempt (including the
                # journal's failover resume on ANOTHER worker) carries
                # the submitter's class
                pri = self.headers.get(PRIORITY_HEADER)

                def fwd_headers():
                    hdrs = list(rc.to_headers(ctx))
                    if deadline is not None:
                        hdrs.append((reliability.DEADLINE_HEADER,
                                     deadline.to_header()))
                    if pri is not None:
                        hdrs.append((PRIORITY_HEADER, pri))
                    return hdrs

                with rc.activate(ctx), \
                        obs.span("llm/route", stage="llm_router",
                                 tokens=len(body["prompt_ids"])):
                    if router._active:
                        router._route_failover(self, body, fwd_headers,
                                               deadline, priority=pri)
                    else:
                        router._route(self, body, fwd_headers)

        self._httpd = _QuietHTTPServer((host, port), Handler)
        self.address = self._httpd.server_address
        self._thread = None

    # -- journal/prober views ------------------------------------------------
    @property
    def failovers(self) -> int:
        return self._journal.failovers if self._journal else 0

    @property
    def tokens_resumed(self) -> int:
        return self._journal.tokens_resumed if self._journal else 0

    @property
    def hedges_issued(self) -> int:
        return self._hedge.hedges if self._hedge else 0

    def _prober_targets(self):
        with self._pool_lock:
            return ([(a, "prefill") for a in self.prefill_workers]
                    + [(a, "decode") for a in self.decode_workers])

    def _federation_targets(self):
        """Live pool membership for the fleet collector (ISSUE 12):
        one member per distinct backend address — a worker in both
        pools is scraped once."""
        with self._pool_lock:
            seen = {}
            for a in self.prefill_workers + self.decode_workers:
                seen.setdefault(f"{a[0]}:{a[1]}", a)
        return sorted(seen.items())

    def _on_probe(self, addr, role, healthy, body):
        ins = self._instruments()
        if ins is not None and "healthy" in ins:
            ins["healthy"].labels(
                backend=f"{addr[0]}:{addr[1]}", role=role).set(
                    1 if healthy else 0)

    # -- metrics -------------------------------------------------------------
    def _instruments(self):
        if not obs.enabled():
            return None
        if self._ins is None:
            ins = {
                "breaker_state": obs.gauge(
                    "bigdl_router_breaker_state",
                    "Per-backend circuit-breaker state "
                    "(0=closed, 1=half_open, 2=open)",
                    labelnames=("backend",)),
            }
            if self._active:
                ins.update({
                    "failovers": obs.counter(
                        "bigdl_router_failovers_total",
                        "Requests re-dispatched to another backend "
                        "after a failure", labelnames=("stage",)),
                    "hedges": obs.counter(
                        "bigdl_router_hedges_total",
                        "Hedged backend calls by outcome",
                        labelnames=("stage", "outcome")),
                    "journal": obs.gauge(
                        "bigdl_router_journal_inflight",
                        "Routed requests currently in the failover "
                        "journal"),
                    "healthy": obs.gauge(
                        "bigdl_router_backend_healthy",
                        "Prober verdict per backend (1 healthy)",
                        labelnames=("backend", "role")),
                })
            self._ins = ins
        return self._ins

    def _record_breakers(self):
        ins = self._instruments()
        if ins is None:
            return
        with self._pool_lock:
            items = list(self._breakers.items())
        for addr, b in items:
            ins["breaker_state"].labels(
                backend=f"{addr[0]}:{addr[1]}").set(
                    BREAKER_STATE_VALUES.get(b.state, 2))

    # -- surfaces ------------------------------------------------------------
    def _healthz(self):
        ok, report = reliability.health_report()
        with self._pool_lock:
            states = {f"{a[0]}:{a[1]}": self._breakers[a].state
                      for a in self._breakers}
            decode_up = any(
                self._breakers[a].state != "open"
                and (self._prober is None or self._prober.healthy(a))
                for a in self.decode_workers)
        self._record_breakers()
        healthy = ok and decode_up
        body = {
            "status": "ok" if healthy else "unhealthy",
            "role": "router",
            "backends": states,
            "checks": report}
        if self._active:
            body["journal_inflight"] = self._journal.inflight()
            body["failovers"] = self.failovers
            body["hedges_issued"] = self.hedges_issued
        if self._prober is not None:
            body["prober"] = self._prober.status()
            # drain-aware verdicts (ISSUE 15): "draining" is visibly
            # distinct from "dead"/"stalled" in the fleet view
            body["backend_states"] = self._prober.states()
        if self._fleet is not None:
            body["fleet"] = {"workers": len(self.decode_workers),
                             "scale_outs": self._fleet.scale_outs,
                             "scale_ins": self._fleet.scale_ins}
        if self._slo is not None:
            # rolling burn rate (ISSUE 12): one number an autoscaler
            # or alert reads instead of differencing counters
            body["slo"] = self._slo.status()
        return (200 if healthy else 503), body

    def _status_body(self):
        with self._pool_lock:
            body = {
                "role": "router",
                "prefill_workers": len(self.prefill_workers),
                "decode_workers": len(self.decode_workers),
                "requests_routed": self.requests_routed,
                "handoffs_routed": self.handoffs_routed,
                "prefill_degraded": self.prefill_degraded}
            if self._active:
                body.update({
                    "prefill_pool": [f"{a[0]}:{a[1]}"
                                     for a in self.prefill_workers],
                    "decode_pool": [f"{a[0]}:{a[1]}"
                                    for a in self.decode_workers],
                    "failover_enabled": self.failover_enabled,
                    "journal_inflight": self._journal.inflight(),
                    "journal": self._journal.snapshot(),
                    "failovers": self.failovers,
                    "tokens_resumed": self.tokens_resumed,
                    "hedges_issued": self.hedges_issued})
        return body

    def _admin_backends(self, body: dict):
        """``POST /backends``: join/leave pool members without a
        restart. {"action": "add"|"remove", "role": "prefill"|"decode",
        "host": ..., "port": ...}"""
        action = body.get("action")
        role = body.get("role")
        if action not in ("add", "remove") or \
                role not in ("prefill", "decode"):
            raise ValueError("need action add|remove and role "
                             "prefill|decode")
        addr = (str(body["host"]), int(body["port"]))
        with self._pool_lock:
            pool = (self.prefill_workers if role == "prefill"
                    else self.decode_workers)
            if action == "add":
                if addr not in pool:
                    pool.append(addr)
                    self._breaker_for(addr)
            else:
                if role == "decode" and len(pool) == 1 \
                        and addr in pool:
                    raise ValueError("refusing to remove the last "
                                     "decode backend")
                if addr in pool:
                    pool.remove(addr)
                other = (self.decode_workers if role == "prefill"
                         else self.prefill_workers)
                if addr not in other:
                    self._breakers.pop(addr, None)
                if self._prober is not None:
                    self._prober.forget(addr)
            out = {"prefill_workers": [list(a) for a in
                                       self.prefill_workers],
                   "decode_workers": [list(a) for a in
                                      self.decode_workers]}
        return 200, out

    # -- placement -----------------------------------------------------------
    def _pick(self, kind: str, exclude=frozenset()
              ) -> Optional[Tuple[str, int]]:
        """Round-robin over the pool, skipping open breakers (the
        half-open probe slot is granted like any call) and — with the
        prober running — backends whose last ``/healthz`` failed.
        ``exclude`` softly avoids backends that already failed this
        request: if excluding them empties the pool, they are retried
        rather than failing the request outright."""
        with self._pool_lock:
            pool = list(self.prefill_workers if kind == "prefill"
                        else self.decode_workers)
            if not pool:
                return None
            for skip_excluded in (True, False) if exclude else (False,):
                for off in range(len(pool)):
                    addr = pool[(self._rr[kind] + off) % len(pool)]
                    if skip_excluded and addr in exclude:
                        continue
                    if not self._breakers[addr].allow():
                        continue
                    if self._prober is not None and \
                            not self._prober.healthy(addr):
                        continue
                    self._rr[kind] = \
                        (self._rr[kind] + off + 1) % len(pool)
                    return addr
        return None

    def _breaker_for(self, addr):
        with self._pool_lock:
            b = self._breakers.get(addr)
            if b is None:
                b = self._breakers[addr] = reliability.CircuitBreaker(
                    f"llm_router:{addr[0]}:{addr[1]}",
                    failure_threshold=self._breaker_threshold,
                    reset_timeout=self._breaker_reset)
            return b

    def _call(self, addr, path, body, headers, canceller=None):
        """Backend call under its breaker; raises on transport errors
        and 5xx so the breaker sees them. A 503 shed is NOT a failure:
        the backend is alive and applying backpressure — it is relayed
        to the caller (with its own Retry-After, unchanged) instead of
        tripping the breaker, else transient overload on a healthy
        worker would escalate to the whole backend being circuit-broken
        out."""
        breaker = self._breaker_for(addr)
        try:
            reliability.inject("router.dispatch")
            status, parsed, hdrs = _post_json(
                addr, path, body, headers, self.request_timeout,
                canceller=canceller)
        except Exception:
            # a cancelled hedge loser died because WE closed its
            # socket, not because the backend failed — recording it
            # would circuit-break the consistently-slower (but
            # healthy) twin out of the pool
            if canceller is None or not canceller.cancelled:
                breaker.record_failure()
                self._record_breakers()
            raise
        if status >= 500 and status != 503:
            breaker.record_failure()
            self._record_breakers()
            raise RuntimeError(
                f"{addr[0]}:{addr[1]}{path} answered {status}: "
                f"{parsed.get('error', '')}")
        breaker.record_success()
        return status, parsed, hdrs

    # -- legacy (PR 6) routing: failover + hedging disabled ------------------
    def _route(self, handler, body, fwd_headers):
        prompt_ids = body["prompt_ids"]
        # stage 1: prefill + export (optional — losing it only costs
        # the decode worker a full prefill)
        handoff = None
        addr = self._pick("prefill")
        if addr is not None:
            try:
                status, parsed, _ = self._call(
                    addr, "/worker_prefill",
                    {"prompt_ids": prompt_ids}, fwd_headers())
                if status == 200:
                    handoff = parsed.get("handoff")
            except Exception:
                pass
        if handoff is None and self.prefill_workers:
            self.prefill_degraded += 1
        # stage 2: import + decode
        addr = self._pick("decode")
        if addr is None:
            reliability.count_shed("llm_router")
            handler._json(503, {"error": "no decode backend available "
                                "(breakers open)"},
                          headers=(("Retry-After",
                                    reliability.retry_after_seconds(0)),))
            return
        try:
            if handoff:
                try:
                    self._call(addr, "/worker_import_chain",
                               {"handoff": handoff}, fwd_headers())
                    self.handoffs_routed += 1
                except Exception:
                    pass   # decode still works, just re-prefills
            status, parsed, hdrs = self._call(addr, "/worker_generate",
                                              body, fwd_headers())
        except Exception as e:  # noqa: BLE001
            handler._json(502, {"error": f"decode backend failed: {e}"})
            return
        if status == 503:
            reliability.count_shed("llm_router")
            # the backend's own Retry-After rides through unchanged
            # (ISSUE 7 satellite)
            ra = hdrs.get("Retry-After") or \
                reliability.retry_after_seconds(0)
            handler._json(503, parsed, headers=(("Retry-After", ra),))
            return
        self.requests_routed += 1
        handler._json(status, parsed)

    # -- failover routing (ISSUE 7) ------------------------------------------
    def _prefill_stage(self, prompt_ids, fwd_headers):
        """Hedged, best-effort prefill+export: returns the handoff blob
        or None (the decode backend then prefills itself)."""
        from bigdl_tpu.llm import failover as fo
        addr = self._pick("prefill")
        if addr is None:
            return None

        def attempt(a):
            def run(canceller):
                status, parsed, _ = self._call(
                    a, "/worker_prefill", {"prompt_ids": prompt_ids},
                    fwd_headers(), canceller=canceller)
                if status != 200:
                    raise RuntimeError(
                        f"prefill backend answered {status}")
                return parsed.get("handoff")
            return run

        hedge_fn = None
        hedge_addr = None
        if self._hedge.allow():
            hedge_addr = self._pick("prefill", exclude={addr})
            if hedge_addr is not None and hedge_addr != addr:
                hedge_fn = attempt(hedge_addr)
        delay = self._hedge.delay_for(self._latency["prefill"])
        t0 = time.perf_counter()

        def on_hedge():
            self._hedge.note_hedge()
            flight.record("hedge", stage="prefill",
                          backend=f"{hedge_addr[0]}:{hedge_addr[1]}")
            ins = self._instruments()
            if ins is not None and "hedges" in ins:
                ins["hedges"].labels(stage="prefill",
                                     outcome="issued").inc()

        try:
            blob, outcome = fo.run_hedged(attempt(addr), hedge_fn,
                                          delay, on_hedge)
        except Exception:
            return None
        self._latency["prefill"].record(time.perf_counter() - t0)
        if outcome != "primary":
            self._note_hedge_outcome("prefill", outcome)
        return blob

    def _note_hedge_outcome(self, stage, outcome):
        ins = self._instruments()
        if ins is not None and "hedges" in ins:
            ins["hedges"].labels(stage=stage, outcome=outcome).inc()

    def _stream_decode(self, addr, body, headers, canceller, on_tokens):
        """One decode attempt over ``/worker_generate_stream``: every
        chunk's cumulative token list feeds ``on_tokens`` (the journal
        update — tokens survive the attempt failing). Returns the
        finish reason. Raises :class:`_BackendShed` (503),
        :class:`_BackendFatal` (other 4xx) or a failover-eligible error
        (transport / 5xx / mid-generation engine failure — the breaker
        records those). A :class:`~bigdl_tpu.llm.failover.StreamAbort`
        raised out of ``on_tokens`` (the SSE relay tearing the stream
        down, ISSUE 20) propagates without blaming the breaker — the
        backend did nothing wrong."""
        from bigdl_tpu.llm import failover as fo
        breaker = self._breaker_for(addr)
        conn = http.client.HTTPConnection(addr[0], addr[1],
                                          timeout=self.request_timeout)
        if canceller is not None:
            canceller.attach(conn)
        try:
            try:
                reliability.inject("router.dispatch")
                hdrs = {"Content-Type": "application/json"}
                for k, v in headers:
                    hdrs[k] = v
                conn.request("POST", "/worker_generate_stream",
                             json.dumps(body), hdrs)
                resp = conn.getresponse()
                if resp.status != 200:
                    data = resp.read()
                    try:
                        parsed = json.loads(data.decode())
                    except ValueError:
                        parsed = {"error":
                                  data.decode(errors="replace")[:200]}
                    if resp.status == 503:
                        breaker.record_success()
                        if parsed.get("draining"):
                            # drain shed (ISSUE 15): alive, no new
                            # work — re-route, don't relay, and never
                            # a breaker failure (regression-tested)
                            raise _BackendDraining(parsed)
                        raise _BackendShed(
                            parsed, resp.getheader("Retry-After"))
                    if resp.status >= 500:
                        raise RuntimeError(
                            f"{addr[0]}:{addr[1]} answered "
                            f"{resp.status}: {parsed.get('error', '')}")
                    breaker.record_success()
                    raise _BackendFatal(resp.status, parsed)
                last = None
                while True:
                    # mid-stream fault site: a raise here is a torn
                    # connection AFTER tokens drained — exactly the
                    # suffix-resume case the journal exists for
                    reliability.inject("router.dispatch")
                    line = resp.readline()
                    if not line:
                        break
                    line = line.strip()
                    if not line:
                        continue
                    obj = json.loads(line.decode())
                    on_tokens(obj.get("output_ids", []))
                    last = obj
                    if obj.get("done"):
                        break
                if last is None or not last.get("done"):
                    raise RuntimeError(
                        f"{addr[0]}:{addr[1]} stream ended before "
                        "done:true")
                if last.get("error"):
                    raise RuntimeError(
                        f"{addr[0]}:{addr[1]} failed mid-generation: "
                        f"{last['error']}")
                if last.get("finish_reason") == "timeout":
                    # the worker's stream wait expired with the request
                    # still parked on a wedged engine (watchdog off, or
                    # the request raced in after the trip sweep) — a
                    # silent truncation, not an answer. Retriable: the
                    # journal resumes the drained tokens elsewhere.
                    raise RuntimeError(
                        f"{addr[0]}:{addr[1]} timed out mid-generation "
                        f"({len(last.get('output_ids', []))} tokens "
                        "drained)")
            except (_BackendShed, _BackendFatal, _BackendDraining,
                    fo.StreamAbort):
                raise
            except Exception:
                # same hedge-loser carve-out as _call: a socket we
                # cancelled is not a backend failure
                if canceller is None or not canceller.cancelled:
                    breaker.record_failure()
                    self._record_breakers()
                raise
            breaker.record_success()
            return last.get("finish_reason") or "length"
        finally:
            conn.close()

    def _decode_attempt(self, addr, ent, fwd_headers, tried=None):
        """One (possibly hedged) decode dispatch resuming from the
        journal entry's current state. Tokens land in the entry AS THEY
        DRAIN; hedge twins run the same greedy resume so the longest
        cumulative list is always a consistent prefix of the answer.
        A launched hedge twin is added to ``tried`` so that when BOTH
        attempts fail, the failover loop excludes it too instead of
        burning the next attempt re-picking a known-bad backend."""
        from bigdl_tpu.llm import failover as fo
        body = {"prompt_ids": ent.resume_prompt(),
                "max_new_tokens": ent.remaining}
        base = len(ent.tokens)
        lock = threading.Lock()

        def absorb(cur):
            with lock:
                ent.drained(cur, base)

        def attempt(a):
            def run(canceller):
                return self._stream_decode(a, body, fwd_headers(),
                                           canceller, absorb)
            return run

        hedge_fn = None
        hedge_addr = None
        # SSE-relayed requests never hedge: the drain listener fires
        # from whichever twin extends the journal, and a StreamAbort it
        # raises must unwind ONE attempt, not a race of two
        if self._hedge.allow() and ent.listener is None:
            hedge_addr = self._pick(
                "decode", exclude={addr} | (tried or set()))
            if hedge_addr is not None and hedge_addr != addr:
                hedge_fn = attempt(hedge_addr)
        delay = self._hedge.delay_for(self._latency["decode"])

        def on_hedge():
            self._hedge.note_hedge()
            ent.hedges += 1
            flight.record("hedge", stage="decode", entry=ent.id,
                          backend=f"{hedge_addr[0]}:{hedge_addr[1]}")
            if tried is not None:
                tried.add(hedge_addr)
            ins = self._instruments()
            if ins is not None and "hedges" in ins:
                ins["hedges"].labels(stage="decode",
                                     outcome="issued").inc()

        t0 = time.perf_counter()
        if hedge_fn is not None:
            with obs.span("router/hedge", stage="llm_router",
                          backend=f"{addr[0]}:{addr[1]}"):
                # prefer= keeps a backend's 4xx/shed verdict from
                # being masked by the twin's later transport error —
                # those must relay, not burn failover attempts
                reason, outcome = fo.run_hedged(
                    attempt(addr), hedge_fn, delay, on_hedge,
                    prefer=(_BackendShed, _BackendFatal,
                            _BackendDraining))
        else:
            reason, outcome = fo.run_hedged(attempt(addr), None, delay)
        self._latency["decode"].record(time.perf_counter() - t0)
        if outcome != "primary":
            self._note_hedge_outcome("decode", outcome)
        return reason

    def _route_failover(self, handler, body, fwd_headers, deadline,
                        priority=None):
        """The native JSON surface over :meth:`_dispatch_failover`:
        typed routing errors render through ``handler._json`` exactly
        as they did before the ISSUE 20 refactor."""
        try:
            ent = self._dispatch_failover(body, fwd_headers, deadline,
                                          priority=priority)
        except _RouteError as e:
            handler._json(e.status, e.body, headers=e.headers)
            return
        handler._json(200, {
            "output_ids": [int(t) for t in ent.tokens],
            "finish_reason": ent.finish_reason or "length"})

    def _observe_slo(self, ent):
        """Client-visible SLO verdict from the journal's token arrival
        stamps (ISSUE 12): resumed/hedged tokens were stamped exactly
        once by ``JournalEntry.drained``, so a mid-stream failover
        contributes its recovery gap as ONE inter-token sample instead
        of replayed duplicates. Shared by the native JSON path and the
        OpenAI SSE relay (ISSUE 20) — the gateway's chunks fire from
        the same drain events, so there is one accounting, not two."""
        if self._slo is None:
            return
        from bigdl_tpu.observability.slo import itl_samples
        times = list(ent.token_times)
        if times:
            ttft = times[0] - ent.created_at
            self._slo.observe_ttft(ttft)
            gaps = itl_samples(times)
            for g in gaps:
                self._slo.observe_itl(g)
            self._slo.finish(ttft, max(gaps) if gaps else None)
        else:
            self._slo.finish(None, None)

    def _dispatch_failover(self, body, fwd_headers, deadline,
                           priority=None, listener=None):
        """Journal + resume dispatch loop (ISSUE 7), decoupled from the
        HTTP handler (ISSUE 20): returns the completed journal entry or
        raises :class:`_RouteError`. ``listener`` (the OpenAI gateway's
        per-delta callback) is installed as the entry's drain listener;
        a :class:`~bigdl_tpu.llm.failover.StreamAbort` it raises tears
        down the attempt without a failover retry and propagates after
        the delivered tokens are SLO-observed."""
        from bigdl_tpu.llm import failover as fo
        prompt_ids = body["prompt_ids"]
        try:
            mnt = int(body.get("max_new_tokens", 32))
        except (TypeError, ValueError):
            raise _RouteError(400, {"error": "bad max_new_tokens"})
        ent = self._journal.add(prompt_ids, mnt, priority=priority)
        ent.listener = listener
        self._hedge.note_request()
        ins = self._instruments()
        if ins is not None and "journal" in ins:
            ins["journal"].set(self._journal.inflight())
        try:
            handoff = self._prefill_stage(prompt_ids, fwd_headers)
            if handoff is None and self.prefill_workers:
                self.prefill_degraded += 1
            imported = set()
            tried = set()
            drain_bounces = 0
            while True:
                if deadline is not None and deadline.expired():
                    raise _RouteError(504, {
                        "error": "deadline exceeded while routing",
                        "tokens_drained": len(ent.tokens)})
                addr = self._pick("decode", exclude=tried)
                if addr is None:
                    reliability.count_shed("llm_router")
                    raise _RouteError(
                        503, {"error": "no decode backend available "
                              "(breakers open or unhealthy)"},
                        headers=(("Retry-After",
                                  reliability.retry_after_seconds(
                                      self._journal.inflight())),))
                if handoff and addr not in imported:
                    try:
                        self._call(addr, "/worker_import_chain",
                                   {"handoff": handoff}, fwd_headers())
                        self.handoffs_routed += 1
                    except Exception:
                        pass   # decode still works, just re-prefills
                    imported.add(addr)
                ent.attempts += 1
                try:
                    ent.finish_reason = self._decode_attempt(
                        addr, ent, fwd_headers, tried)
                    break
                except fo.StreamAbort:
                    # the SSE relay tore the stream down (client gone,
                    # or stop satisfied): no retry, no breaker blame —
                    # observe what was delivered, let the gateway
                    # decide how the request ends
                    self._observe_slo(ent)
                    raise
                except _BackendDraining:
                    # drain bounce (ISSUE 15): the backend is healthy
                    # but winding down — route elsewhere without
                    # consuming a failover attempt or tripping
                    # anything. The prober mark makes _pick skip it
                    # outright from here on (a fully-draining pool then
                    # sheds through the addr-is-None arm above).
                    ent.attempts -= 1
                    tried.add(addr)
                    if self._prober is not None:
                        self._prober.mark(addr, "draining")
                    drain_bounces = drain_bounces + 1
                    if drain_bounces > 2 * max(
                            len(self.decode_workers), 1):
                        reliability.count_shed("llm_router")
                        raise _RouteError(
                            503, {"error": "every decode backend is "
                                  "draining"},
                            headers=(("Retry-After",
                                      reliability.retry_after_seconds(
                                          self._journal.inflight())),))
                    continue
                except _BackendShed as e:
                    reliability.count_shed("llm_router")
                    ra = e.retry_after or \
                        reliability.retry_after_seconds(0)
                    raise _RouteError(503, e.parsed,
                                      headers=(("Retry-After", ra),))
                except _BackendFatal as e:
                    raise _RouteError(e.status, e.parsed)
                except Exception as e:  # noqa: BLE001 — failover
                    tried.add(addr)
                    if ent.remaining == 0:
                        # the connection died delivering the final
                        # token: the budget is already fulfilled
                        ent.finish_reason = ent.finish_reason or "length"
                        break
                    if not self.failover_enabled or \
                            ent.attempts >= self.max_attempts:
                        raise _RouteError(502, {
                            "error": f"decode backend failed after "
                                     f"{ent.attempts} attempt(s): {e}",
                            "tokens_drained": len(ent.tokens)})
                    # journal → resume: re-dispatch prompt + generated
                    # so far to another backend (the tentpole)
                    self._journal.record_failover(ent)
                    if ins is not None and "failovers" in ins:
                        ins["failovers"].labels(stage="decode").inc()
                    obs.add_complete(
                        "router/failover", time.time(), 0.0,
                        stage="llm_router",
                        backend=f"{addr[0]}:{addr[1]}",
                        tokens_resumed=len(ent.tokens),
                        attempt=ent.attempts,
                        **({"trace": rc.current().trace_id}
                           if rc.current() is not None else {}))
                    continue
            self.requests_routed += 1
            self._observe_slo(ent)
            return ent
        finally:
            self._journal.complete(ent)
            if ins is not None and "journal" in ins:
                ins["journal"].set(self._journal.inflight())

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "LLMRouter":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        if self._prober is not None and self._start_prober:
            self._prober.start()
        if self._collector is not None:
            self._collector.start()
        # time-series plane (ISSUE 18): the router's store rides the
        # federation collector's scrape cache when there is one
        self._timeseries = timeseries.acquire()
        if self._timeseries is not None and self._collector is not None:
            timeseries.attach_collector(self._collector)
        if self._fleet is not None and self._start_fleet:
            self._fleet.start()
        return self

    def stop(self):
        # the fleet controller stops FIRST (ISSUE 15 satellite): it may
        # hold an in-progress drain, which must be cancelled before the
        # prober/membership surfaces it depends on go away
        if self._fleet is not None:
            self._fleet.stop()
        if getattr(self, "_timeseries", None) is not None:
            if self._collector is not None:
                timeseries.detach_collector(self._collector)
            timeseries.release()
            self._timeseries = None
        if self._collector is not None:
            self._collector.stop()
        if self._prober is not None:
            self._prober.stop()
        if self._thread is not None:
            # shutdown() handshakes with serve_forever — calling it on
            # a never-started router would wait forever
            self._httpd.shutdown()
            self._thread.join(timeout=5.0)
        self._httpd.server_close()

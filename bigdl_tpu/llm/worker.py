"""FastChat-style model worker over LLMServer (ref: ``P:llm/serving``'s
bigdl-llm FastChat worker — VERDICT r3 missing #4's second half). The
reference registers a worker process with a FastChat controller and
serves ``/worker_generate``-family endpoints; this is that HTTP surface
(stdlib-only) over our continuous-batching paged-KV engine.

Endpoints:
- ``POST /worker_generate``        {"prompt_ids": [...], "max_new_tokens"?}
  → blocks → {"output_ids": [...], "finish_reason": "stop"|"length"}
- ``POST /worker_generate_stream`` same body → chunked JSON lines, one
  per newly decoded token batch: {"output_ids": [...so far], "done": bool}
  (the FastChat worker streams exactly such JSON deltas)
- ``GET  /worker_get_status``      {"model": ..., "queue_length": ...,
  "speed": tokens/s since start}
- ``GET  /healthz``                200/503 + engine-thread liveness and
  the reliability health-check registry (ISSUE 2)
- ``GET  /debug/trace/<trace_id>`` stitched per-request trace;
  ``GET /debug/traces`` slowest-N latency exemplars (ISSUE 3)

Distributed tracing (ISSUE 3): the generate endpoints read the
case-insensitive ``X-BigDL-Trace-Id``/``X-BigDL-Parent-Span`` headers
(minting a fresh trace when absent), activate the context so the
engine's queue-wait/prefill/decode spans stitch under the request, and
echo ``X-BigDL-Trace-Id`` on the response. Disabled observability emits
no trace headers at all.

Backpressure (ISSUE 2): when the engine's bounded queue rejects a
submit (``OverloadError``) the worker sheds with **503 + Retry-After**
instead of queueing unboundedly; per-request deadlines propagate via
``X-BigDL-Deadline-Ms`` and cap the blocking wait.

Token-level API by design: tokenization happens client-side (the
environment ships no tokenizer assets; the reference worker accepts text
because it bundles the HF tokenizer).
"""

from __future__ import annotations

import json
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from bigdl_tpu import observability as obs
from bigdl_tpu import reliability
from bigdl_tpu.observability import request_context as rc
from bigdl_tpu.observability import tracing


class LLMWorker:
    def __init__(self, server, model_name: str = "bigdl-tpu-llm",
                 host: str = "127.0.0.1", port: int = 0,
                 request_timeout: float = 600.0):
        self.server = server
        self.model_name = model_name
        self.request_timeout = request_timeout
        self._t0 = time.time()
        self._tokens_out = 0
        worker = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _json(self, code: int, obj, headers=()):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                for k, v in headers:
                    self.send_header(k, v)
                # echo the request's trace id (absent in disabled mode).
                # keep-alive reuses this handler: _trace is reset at the
                # top of every do_GET/do_POST, so no cross-request leak
                trace_id = getattr(self, "_trace", None)
                if trace_id:
                    self.send_header(rc.TRACE_HEADER, trace_id)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _read_req(self):
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n))
                ids = np.asarray(req["prompt_ids"], np.int32)
                return ids, int(req.get("max_new_tokens", 32))

            def _submit(self, ids, mnt):
                """submit with the 422/503/500 split: invalid requests
                are the client's fault, overload is shed with
                Retry-After, and any other failure (including an
                injected one — InjectedFault is deliberately NOT
                special-cased, per the faults.py contract) answers 500
                instead of killing the handler's connection."""
                try:
                    return worker.server.submit(ids, max_new_tokens=mnt)
                except reliability.OverloadError as e:
                    # page accounting rides the Retry-After diagnostics
                    # (ISSUE 5 satellite): pages_needed is the POST-
                    # LOOKUP suffix cost, so clients see how far the
                    # prefix cache already got them
                    body = {"error": str(e)}
                    for key in ("pages_needed", "pages_free"):
                        val = getattr(e, key, None)
                        if val is not None:
                            body[key] = int(val)
                    self._json(503, body,
                               headers=(("Retry-After", "1"),))
                    return None
                except ValueError as e:
                    self._json(422, {"error": str(e)})
                    return None
                except Exception as e:  # noqa: BLE001 — real or injected
                    self._json(500, {"error": f"submit failed: {e}"})
                    return None

            def _wait_timeout(self) -> float:
                deadline = reliability.Deadline.from_header(
                    self.headers.get(reliability.DEADLINE_HEADER))
                if deadline is None:
                    return worker.request_timeout
                return max(min(worker.request_timeout,
                               deadline.remaining()), 0.0)

            def do_GET(self):
                self._trace = None
                debug = tracing.debug_endpoint(self.path)
                if debug is not None:
                    self._json(*debug)
                elif self.path == "/debug/kvcache":
                    # prefix-cache state (ISSUE 5): pool refcounts,
                    # radix index size, hit/miss/evict tallies. 404
                    # when the cache is disabled — the surface is
                    # structurally absent, not empty
                    kv = getattr(worker.server, "_kv", None)
                    if kv is None or not kv.enabled:
                        self._json(404, {"error": "kvcache disabled"})
                    else:
                        self._json(200, kv.debug_stats())
                elif self.path == "/worker_get_status":
                    dt = max(time.time() - worker._t0, 1e-9)
                    self._json(200, {
                        "model": worker.model_name,
                        "queue_length": worker.server._queue.qsize(),
                        "steps": worker.server.steps,
                        "speed": round(worker._tokens_out / dt, 2)})
                elif self.path == "/metrics":
                    # same Prometheus surface as the cluster-serving
                    # frontend: prefill/decode tokens, KV occupancy, …
                    from bigdl_tpu import observability as obs
                    body = obs.render().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", obs.CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path == "/healthz":
                    ok, report = reliability.health_report()
                    engine = worker.server._thread
                    alive = engine is not None and engine.is_alive()
                    draining = worker.server._draining.is_set() \
                        if hasattr(worker.server, "_draining") else False
                    healthy = ok and alive and not draining
                    self._json(200 if healthy else 503, {
                        "status": ("ok" if healthy else
                                   "draining" if draining else
                                   "unhealthy"),
                        "engine_alive": alive,
                        "queue_length": worker.server._queue.qsize(),
                        "checks": report})
                else:
                    self._json(404, {"error": "unknown path"})

            def do_POST(self):
                self._trace = None
                ctx = None
                if self.path in ("/worker_generate",
                                 "/worker_generate_stream"):
                    # case-insensitive trace extraction (or a fresh
                    # root); None in disabled mode — no headers emitted
                    ctx = rc.server_context(self.headers)
                    if ctx is not None:
                        self._trace = ctx.trace_id
                if self.path == "/worker_generate":
                    try:
                        ids, mnt = self._read_req()
                    except Exception as e:  # noqa: BLE001
                        self._json(400, {"error": f"bad request: {e}"})
                        return
                    t_req = time.perf_counter()
                    with rc.activate(ctx), \
                            obs.span("llm/request", stage="llm_worker",
                                     max_new_tokens=mnt):
                        req = self._submit(ids, mnt)
                        if req is None:
                            return
                        try:
                            toks = req.get(timeout=self._wait_timeout())
                        except TimeoutError:
                            # timed-out requests are by definition the
                            # slowest — excluding them would make the
                            # exemplar store lie about the tail
                            if ctx is not None:
                                obs.EXEMPLARS.offer(
                                    ctx.trace_id,
                                    time.perf_counter() - t_req,
                                    name="llm/request", request=req.id,
                                    status="timeout")
                            self._json(504,
                                       {"error": "generation timed out"})
                            return
                        except RuntimeError as e:  # engine failed it
                            self._json(500, {"error": str(e)})
                            return
                    if ctx is not None:
                        obs.EXEMPLARS.offer(
                            ctx.trace_id, time.perf_counter() - t_req,
                            name="llm/request", request=req.id,
                            status="ok", tokens=len(toks))
                    worker._tokens_out += len(toks)
                    eos = worker.server.eos_token_id
                    reason = ("stop" if eos is not None and toks
                              and toks[-1] == eos else "length")
                    self._json(200, {"output_ids": list(map(int, toks)),
                                     "finish_reason": reason})
                elif self.path == "/worker_generate_stream":
                    try:
                        ids, mnt = self._read_req()
                    except Exception as e:  # noqa: BLE001
                        self._json(400, {"error": f"bad request: {e}"})
                        return
                    with rc.activate(ctx):
                        req = self._submit(ids, mnt)
                    if req is None:
                        return
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/json-lines")
                    self.send_header("Transfer-Encoding", "chunked")
                    if ctx is not None:
                        self.send_header(rc.TRACE_HEADER, ctx.trace_id)
                    self.end_headers()

                    def chunk(obj):
                        data = (json.dumps(obj) + "\n").encode()
                        self.wfile.write(
                            f"{len(data):x}\r\n".encode() + data
                            + b"\r\n")
                        self.wfile.flush()

                    seen = 0
                    done = False
                    deadline = time.time() + self._wait_timeout()
                    while time.time() < deadline:
                        done = req.done.wait(0.02)
                        cur = list(req.tokens)
                        if len(cur) > seen or done:
                            seen = len(cur)
                            chunk({"output_ids": list(map(int, cur)),
                                   "done": bool(done)})
                        if done:
                            break
                    if not done:
                        # timed out: a stream must never end with
                        # done:false — clients reading until done:true
                        # would see a silent truncation (ADVICE r4)
                        chunk({"output_ids": list(map(int, req.tokens)),
                               "done": True, "finish_reason": "timeout"})
                    worker._tokens_out += seen
                    self.wfile.write(b"0\r\n\r\n")
                    self.wfile.flush()
                else:
                    self._json(404, {"error": "unknown path"})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.address = self._httpd.server_address
        self._thread: Optional[object] = None

    def start(self) -> "LLMWorker":
        import threading
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()

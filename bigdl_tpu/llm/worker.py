"""FastChat-style model worker over LLMServer (ref: ``P:llm/serving``'s
bigdl-llm FastChat worker — VERDICT r3 missing #4's second half). The
reference registers a worker process with a FastChat controller and
serves ``/worker_generate``-family endpoints; this is that HTTP surface
(stdlib-only) over our continuous-batching paged-KV engine.

Endpoints:
- ``POST /worker_generate``        {"prompt_ids": [...], "max_new_tokens"?}
  → blocks → {"output_ids": [...], "finish_reason": "stop"|"length"}
- ``POST /worker_generate_stream`` same body → chunked JSON lines, one
  per newly decoded token batch: {"output_ids": [...so far], "done": bool}
  (the FastChat worker streams exactly such JSON deltas)
- ``GET  /worker_get_status``      {"model": ..., "queue_length": ...,
  "speed": tokens/s since start}
- ``GET  /healthz``                200/503 + engine-thread liveness and
  the reliability health-check registry (ISSUE 2)
- ``GET  /debug/trace/<trace_id>`` stitched per-request trace;
  ``GET /debug/traces`` slowest-N latency exemplars (ISSUE 3)

Distributed tracing (ISSUE 3): the generate endpoints read the
case-insensitive ``X-BigDL-Trace-Id``/``X-BigDL-Parent-Span`` headers
(minting a fresh trace when absent), activate the context so the
engine's queue-wait/prefill/decode spans stitch under the request, and
echo ``X-BigDL-Trace-Id`` on the response. Disabled observability emits
no trace headers at all.

Backpressure (ISSUE 2): when the engine's bounded queue rejects a
submit (``OverloadError``) the worker sheds with **503 + Retry-After**
instead of queueing unboundedly; per-request deadlines propagate via
``X-BigDL-Deadline-Ms`` and cap the blocking wait.

Disaggregated serving (ISSUE 6): ``role`` (``bigdl.llm.role``) splits
workers into **prefill** and **decode** pools with KV handoff through
the host tier:

- ``POST /worker_prefill``       {"prompt_ids": [...]} → runs the
  prompt once (one decoded token), exports the KV chain as a
  base64 handoff blob (prefill role; decode-role workers answer 403)
- ``POST /worker_import_chain``  {"handoff": "<b64>"} → lands the
  blob's pages in this worker's host arena (decode role; prefill-role
  workers answer 403)
- :class:`LLMRouter` — the thin placement scheduler over both pools:
  per-backend circuit breakers, 503 + Retry-After shed when no decode
  backend is admittable, trace-header propagation so
  ``GET /debug/trace/<id>`` stitches the request across router →
  prefill worker → decode worker, and graceful degradation (a failed
  prefill stage routes the request to the decode pool without a blob
  — it simply prefills itself).

Token-level API by design: tokenization happens client-side (the
environment ships no tokenizer assets; the reference worker accepts text
because it bundles the HF tokenizer).
"""

from __future__ import annotations

import base64
import http.client
import json
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional, Tuple

import numpy as np

from bigdl_tpu import observability as obs
from bigdl_tpu import reliability
from bigdl_tpu.observability import request_context as rc
from bigdl_tpu.observability import tracing

ROLES = ("", "prefill", "decode")


def _send_json(handler, code: int, obj, headers=()):
    """Shared JSON response for the worker and router handlers: body,
    custom headers, and the request's trace-id echo (absent in disabled
    mode). Keep-alive reuses handlers — ``_trace`` is reset at the top
    of every do_GET/do_POST, so no cross-request leak."""
    body = json.dumps(obj).encode()
    handler.send_response(code)
    handler.send_header("Content-Type", "application/json")
    for k, v in headers:
        handler.send_header(k, v)
    trace_id = getattr(handler, "_trace", None)
    if trace_id:
        handler.send_header(rc.TRACE_HEADER, trace_id)
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)


class LLMWorker:
    def __init__(self, server, model_name: str = "bigdl-tpu-llm",
                 host: str = "127.0.0.1", port: int = 0,
                 request_timeout: float = 600.0,
                 role: Optional[str] = None):
        from bigdl_tpu.utils.conf import conf
        self.server = server
        self.model_name = model_name
        self.request_timeout = request_timeout
        self.role = (role if role is not None
                     else conf.get("bigdl.llm.role", "") or "")
        if self.role not in ROLES:
            raise ValueError(f"bigdl.llm.role must be one of {ROLES}, "
                             f"got {self.role!r}")
        self._t0 = time.time()
        self._tokens_out = 0
        worker = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _json(self, code: int, obj, headers=()):
                _send_json(self, code, obj, headers)

            def _read_req(self):
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n))
                ids = np.asarray(req["prompt_ids"], np.int32)
                return ids, int(req.get("max_new_tokens", 32))

            def _submit(self, ids, mnt):
                """submit with the 422/503/500 split: invalid requests
                are the client's fault, overload is shed with
                Retry-After, and any other failure (including an
                injected one — InjectedFault is deliberately NOT
                special-cased, per the faults.py contract) answers 500
                instead of killing the handler's connection."""
                try:
                    return worker.server.submit(ids, max_new_tokens=mnt)
                except reliability.OverloadError as e:
                    # page accounting rides the Retry-After diagnostics
                    # (ISSUE 5 satellite): pages_needed is the POST-
                    # LOOKUP suffix cost, so clients see how far the
                    # prefix cache already got them
                    body = {"error": str(e)}
                    for key in ("pages_needed", "pages_free"):
                        val = getattr(e, key, None)
                        if val is not None:
                            body[key] = int(val)
                    self._json(503, body,
                               headers=(("Retry-After", "1"),))
                    return None
                except ValueError as e:
                    self._json(422, {"error": str(e)})
                    return None
                except Exception as e:  # noqa: BLE001 — real or injected
                    self._json(500, {"error": f"submit failed: {e}"})
                    return None

            def _wait_timeout(self) -> float:
                deadline = reliability.Deadline.from_header(
                    self.headers.get(reliability.DEADLINE_HEADER))
                if deadline is None:
                    return worker.request_timeout
                return max(min(worker.request_timeout,
                               deadline.remaining()), 0.0)

            def do_GET(self):
                self._trace = None
                debug = tracing.debug_endpoint(self.path)
                if debug is not None:
                    self._json(*debug)
                elif self.path == "/debug/kvcache":
                    # prefix-cache state (ISSUE 5): pool refcounts,
                    # radix index size, hit/miss/evict tallies. 404
                    # when the cache is disabled — the surface is
                    # structurally absent, not empty
                    kv = getattr(worker.server, "_kv", None)
                    if kv is None or not kv.enabled:
                        self._json(404, {"error": "kvcache disabled"})
                    else:
                        self._json(200, kv.debug_stats())
                elif self.path == "/worker_get_status":
                    dt = max(time.time() - worker._t0, 1e-9)
                    self._json(200, {
                        "model": worker.model_name,
                        "role": worker.role,
                        "queue_length": worker.server._queue.qsize(),
                        "steps": worker.server.steps,
                        "speed": round(worker._tokens_out / dt, 2)})
                elif self.path == "/metrics":
                    # same Prometheus surface as the cluster-serving
                    # frontend: prefill/decode tokens, KV occupancy, …
                    from bigdl_tpu import observability as obs
                    body = obs.render().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", obs.CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path == "/healthz":
                    ok, report = reliability.health_report()
                    engine = worker.server._thread
                    alive = engine is not None and engine.is_alive()
                    draining = worker.server._draining.is_set() \
                        if hasattr(worker.server, "_draining") else False
                    healthy = ok and alive and not draining
                    self._json(200 if healthy else 503, {
                        "status": ("ok" if healthy else
                                   "draining" if draining else
                                   "unhealthy"),
                        "role": worker.role,
                        "engine_alive": alive,
                        "queue_length": worker.server._queue.qsize(),
                        "checks": report})
                else:
                    self._json(404, {"error": "unknown path"})

            def do_POST(self):
                self._trace = None
                ctx = None
                if self.path in ("/worker_generate",
                                 "/worker_generate_stream",
                                 "/worker_prefill",
                                 "/worker_import_chain"):
                    # case-insensitive trace extraction (or a fresh
                    # root); None in disabled mode — no headers emitted
                    ctx = rc.server_context(self.headers)
                    if ctx is not None:
                        self._trace = ctx.trace_id
                # role gating (ISSUE 6): a prefill-pool worker never
                # decodes full requests, a decode-pool worker never
                # serves the prefill/export side — misrouted calls are
                # the router's bug and answer 403, not a silent detour
                if worker.role == "prefill" and self.path in (
                        "/worker_generate", "/worker_generate_stream"):
                    self._json(403, {"error": "prefill-role worker: "
                                     "use /worker_prefill"})
                    return
                if worker.role == "decode" and \
                        self.path == "/worker_prefill":
                    self._json(403, {"error": "decode-role worker "
                                     "does not prefill"})
                    return
                if worker.role == "prefill" and \
                        self.path == "/worker_import_chain":
                    self._json(403, {"error": "prefill-role worker "
                                     "does not import chains"})
                    return
                if self.path == "/worker_prefill":
                    # run the prompt once (one decoded token pins the
                    # chain in the index), then export its KV pages as
                    # the handoff blob (ISSUE 6 disaggregation)
                    try:
                        ids, _ = self._read_req()
                    except Exception as e:  # noqa: BLE001
                        self._json(400, {"error": f"bad request: {e}"})
                        return
                    with rc.activate(ctx), \
                            obs.span("llm/handoff_export",
                                     stage="llm_worker",
                                     tokens=len(ids)):
                        req = self._submit(ids, 1)
                        if req is None:
                            return
                        try:
                            toks = req.get(timeout=self._wait_timeout())
                        except TimeoutError:
                            self._json(504,
                                       {"error": "prefill timed out"})
                            return
                        except RuntimeError as e:
                            self._json(500, {"error": str(e)})
                            return
                        try:
                            blob = worker.server.export_chain(ids)
                        except RuntimeError as e:   # tier disabled
                            self._json(501, {"error": str(e)})
                            return
                    worker._tokens_out += len(toks)
                    self._json(200, {
                        "handoff": base64.b64encode(blob).decode(),
                        "handoff_bytes": len(blob),
                        "output_ids": list(map(int, toks))})
                    return
                if self.path == "/worker_import_chain":
                    try:
                        n = int(self.headers.get("Content-Length", 0))
                        body = json.loads(self.rfile.read(n))
                        blob = base64.b64decode(body["handoff"])
                    except Exception as e:  # noqa: BLE001
                        self._json(400, {"error": f"bad request: {e}"})
                        return
                    with rc.activate(ctx), \
                            obs.span("llm/handoff_import",
                                     stage="llm_worker",
                                     bytes=len(blob)):
                        try:
                            pages = worker.server.import_chain(blob)
                        except RuntimeError as e:   # tier disabled
                            self._json(501, {"error": str(e)})
                            return
                        except ValueError as e:     # malformed blob
                            self._json(422, {"error": str(e)})
                            return
                    self._json(200, {"imported_pages": pages})
                    return
                if self.path == "/worker_generate":
                    try:
                        ids, mnt = self._read_req()
                    except Exception as e:  # noqa: BLE001
                        self._json(400, {"error": f"bad request: {e}"})
                        return
                    t_req = time.perf_counter()
                    with rc.activate(ctx), \
                            obs.span("llm/request", stage="llm_worker",
                                     max_new_tokens=mnt):
                        req = self._submit(ids, mnt)
                        if req is None:
                            return
                        try:
                            toks = req.get(timeout=self._wait_timeout())
                        except TimeoutError:
                            # timed-out requests are by definition the
                            # slowest — excluding them would make the
                            # exemplar store lie about the tail
                            if ctx is not None:
                                obs.EXEMPLARS.offer(
                                    ctx.trace_id,
                                    time.perf_counter() - t_req,
                                    name="llm/request", request=req.id,
                                    status="timeout")
                            self._json(504,
                                       {"error": "generation timed out"})
                            return
                        except RuntimeError as e:  # engine failed it
                            self._json(500, {"error": str(e)})
                            return
                    if ctx is not None:
                        obs.EXEMPLARS.offer(
                            ctx.trace_id, time.perf_counter() - t_req,
                            name="llm/request", request=req.id,
                            status="ok", tokens=len(toks))
                    worker._tokens_out += len(toks)
                    eos = worker.server.eos_token_id
                    reason = ("stop" if eos is not None and toks
                              and toks[-1] == eos else "length")
                    self._json(200, {"output_ids": list(map(int, toks)),
                                     "finish_reason": reason})
                elif self.path == "/worker_generate_stream":
                    try:
                        ids, mnt = self._read_req()
                    except Exception as e:  # noqa: BLE001
                        self._json(400, {"error": f"bad request: {e}"})
                        return
                    with rc.activate(ctx):
                        req = self._submit(ids, mnt)
                    if req is None:
                        return
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/json-lines")
                    self.send_header("Transfer-Encoding", "chunked")
                    if ctx is not None:
                        self.send_header(rc.TRACE_HEADER, ctx.trace_id)
                    self.end_headers()

                    def chunk(obj):
                        data = (json.dumps(obj) + "\n").encode()
                        self.wfile.write(
                            f"{len(data):x}\r\n".encode() + data
                            + b"\r\n")
                        self.wfile.flush()

                    seen = 0
                    done = False
                    deadline = time.time() + self._wait_timeout()
                    while time.time() < deadline:
                        done = req.done.wait(0.02)
                        cur = list(req.tokens)
                        if len(cur) > seen or done:
                            seen = len(cur)
                            chunk({"output_ids": list(map(int, cur)),
                                   "done": bool(done)})
                        if done:
                            break
                    if not done:
                        # timed out: a stream must never end with
                        # done:false — clients reading until done:true
                        # would see a silent truncation (ADVICE r4)
                        chunk({"output_ids": list(map(int, req.tokens)),
                               "done": True, "finish_reason": "timeout"})
                    worker._tokens_out += seen
                    self.wfile.write(b"0\r\n\r\n")
                    self.wfile.flush()
                else:
                    self._json(404, {"error": "unknown path"})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.address = self._httpd.server_address
        self._thread: Optional[object] = None

    def start(self) -> "LLMWorker":
        import threading
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()


def _post_json(addr: Tuple[str, int], path: str, body: dict,
               headers=(), timeout: float = 600.0):
    """One JSON POST to a backend worker → (status, parsed body,
    response trace header). Connection errors raise — the router's
    breaker accounting wants them loud."""
    conn = http.client.HTTPConnection(addr[0], addr[1], timeout=timeout)
    try:
        payload = json.dumps(body)
        hdrs = {"Content-Type": "application/json"}
        for k, v in headers:
            hdrs[k] = v
        conn.request("POST", path, payload, hdrs)
        resp = conn.getresponse()
        data = resp.read()
        try:
            parsed = json.loads(data.decode())
        except ValueError:
            parsed = {"error": data.decode(errors="replace")[:200]}
        return resp.status, parsed, resp.getheader(rc.TRACE_HEADER)
    finally:
        conn.close()


class LLMRouter:
    """Thin placement scheduler over disaggregated worker pools
    (ISSUE 6): prefill-role workers compute prompt KV once, decode-role
    workers stream tokens, and the request's chain crosses between them
    as a handoff blob through the host tier.

    ``POST /worker_generate`` routes one request end-to-end:

    1. pick a prefill backend (round-robin over the pool, skipping
       open circuit breakers) → ``/worker_prefill`` → handoff blob;
    2. pick a decode backend the same way → ``/worker_import_chain``
       (best-effort) then ``/worker_generate`` → relay the answer.

    Reused machinery, not re-invented (ISSUE 6 contract): per-backend
    :class:`~bigdl_tpu.reliability.CircuitBreaker` trips on connection
    failures/5xx, overload sheds with **503 + Retry-After** through
    ``reliability.count_shed``, deadlines propagate via
    ``X-BigDL-Deadline-Ms``, and the trace context rides
    ``X-BigDL-Trace-Id`` into both backends so ``GET
    /debug/trace/<id>`` shows the stitched router → prefill → decode
    waterfall. A failed prefill stage degrades gracefully: the decode
    backend gets the request without a blob and prefills it itself.
    """

    def __init__(self, prefill_workers: List[Tuple[str, int]],
                 decode_workers: List[Tuple[str, int]],
                 host: str = "127.0.0.1", port: int = 0,
                 request_timeout: float = 600.0,
                 breaker_threshold: int = 3,
                 breaker_reset: float = 10.0):
        if not decode_workers:
            raise ValueError("the router needs at least one "
                             "decode-role backend")
        self.prefill_workers = [tuple(a) for a in prefill_workers]
        self.decode_workers = [tuple(a) for a in decode_workers]
        self.request_timeout = request_timeout
        self._rr = {"prefill": 0, "decode": 0}
        self._breakers = {
            addr: reliability.CircuitBreaker(
                f"llm_router:{addr[0]}:{addr[1]}",
                failure_threshold=breaker_threshold,
                reset_timeout=breaker_reset)
            for addr in self.prefill_workers + self.decode_workers}
        self.requests_routed = 0
        self.handoffs_routed = 0
        self.prefill_degraded = 0
        router = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _json(self, code: int, obj, headers=()):
                _send_json(self, code, obj, headers)

            def do_GET(self):
                self._trace = None
                debug = tracing.debug_endpoint(self.path)
                if debug is not None:
                    self._json(*debug)
                elif self.path == "/healthz":
                    ok, report = reliability.health_report()
                    states = {f"{a[0]}:{a[1]}": router._breakers[a].state
                              for a in router._breakers}
                    decode_up = any(
                        router._breakers[a].state != "open"
                        for a in router.decode_workers)
                    healthy = ok and decode_up
                    self._json(200 if healthy else 503, {
                        "status": "ok" if healthy else "unhealthy",
                        "role": "router",
                        "backends": states,
                        "checks": report})
                elif self.path == "/worker_get_status":
                    self._json(200, {
                        "role": "router",
                        "prefill_workers": len(router.prefill_workers),
                        "decode_workers": len(router.decode_workers),
                        "requests_routed": router.requests_routed,
                        "handoffs_routed": router.handoffs_routed,
                        "prefill_degraded": router.prefill_degraded})
                else:
                    self._json(404, {"error": "unknown path"})

            def do_POST(self):
                self._trace = None
                if self.path != "/worker_generate":
                    self._json(404, {"error": "unknown path"})
                    return
                ctx = rc.server_context(self.headers)
                if ctx is not None:
                    self._trace = ctx.trace_id
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(n))
                    body["prompt_ids"] = [int(t)
                                          for t in body["prompt_ids"]]
                except Exception as e:  # noqa: BLE001
                    self._json(400, {"error": f"bad request: {e}"})
                    return
                fwd = list(rc.to_headers(ctx))
                deadline = self.headers.get(reliability.DEADLINE_HEADER)
                if deadline:
                    fwd.append((reliability.DEADLINE_HEADER, deadline))
                with rc.activate(ctx), \
                        obs.span("llm/route", stage="llm_router",
                                 tokens=len(body["prompt_ids"])):
                    router._route(self, body, fwd)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.address = self._httpd.server_address
        self._thread = None

    # -- placement -----------------------------------------------------------
    def _pick(self, kind: str) -> Optional[Tuple[str, int]]:
        """Round-robin over the pool, skipping open breakers (the
        half-open probe slot is granted like any call)."""
        pool = (self.prefill_workers if kind == "prefill"
                else self.decode_workers)
        for off in range(len(pool)):
            addr = pool[(self._rr[kind] + off) % len(pool)]
            if self._breakers[addr].allow():
                self._rr[kind] = (self._rr[kind] + off + 1) % len(pool)
                return addr
        return None

    def _call(self, addr, path, body, headers):
        """Backend call under its breaker; raises on transport errors
        and 5xx so the breaker sees them. A 503 shed is NOT a failure:
        the backend is alive and applying backpressure — it is relayed
        to the caller (with Retry-After) instead of tripping the
        breaker, else transient overload on a healthy worker would
        escalate to the whole backend being circuit-broken out."""
        breaker = self._breakers[addr]
        try:
            status, parsed, trace = _post_json(
                addr, path, body, headers, self.request_timeout)
        except Exception:
            breaker.record_failure()
            raise
        if status >= 500 and status != 503:
            breaker.record_failure()
            raise RuntimeError(
                f"{addr[0]}:{addr[1]}{path} answered {status}: "
                f"{parsed.get('error', '')}")
        breaker.record_success()
        return status, parsed

    def _route(self, handler, body, fwd_headers):
        prompt_ids = body["prompt_ids"]
        # stage 1: prefill + export (optional — losing it only costs
        # the decode worker a full prefill)
        handoff = None
        addr = self._pick("prefill")
        if addr is not None:
            try:
                status, parsed = self._call(
                    addr, "/worker_prefill",
                    {"prompt_ids": prompt_ids}, fwd_headers)
                if status == 200:
                    handoff = parsed.get("handoff")
            except Exception:
                pass
        if handoff is None and self.prefill_workers:
            self.prefill_degraded += 1
        # stage 2: import + decode
        addr = self._pick("decode")
        if addr is None:
            reliability.count_shed("llm_router")
            handler._json(503, {"error": "no decode backend available "
                                "(breakers open)"},
                          headers=(("Retry-After", "1"),))
            return
        try:
            if handoff:
                try:
                    self._call(addr, "/worker_import_chain",
                               {"handoff": handoff}, fwd_headers)
                    self.handoffs_routed += 1
                except Exception:
                    pass   # decode still works, just re-prefills
            status, parsed = self._call(addr, "/worker_generate", body,
                                        fwd_headers)
        except Exception as e:  # noqa: BLE001
            handler._json(502, {"error": f"decode backend failed: {e}"})
            return
        if status == 503:
            reliability.count_shed("llm_router")
            handler._json(503, parsed,
                          headers=(("Retry-After", "1"),))
            return
        self.requests_routed += 1
        handler._json(status, parsed)

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "LLMRouter":
        import threading
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()

"""KV-chain handoff blobs for disaggregated prefill/decode (ISSUE 6).

A prefill-role worker computes a prompt's KV once, then ships the full
pages of that chain to a decode-role worker as one self-describing
binary blob. The decode worker lands the pages in its **host arena**
(never directly in HBM): the next admission of that prompt hits the
host tier and the normal async fetch path uploads the pages behind
in-flight decode steps — import is control-plane-only and the
migration machinery stays the single door into the device pool.

Wire format (version 1)::

    magic  b"BDKV1\\n"
    header u32 length + UTF-8 JSON {tokens, page_size, shape, dtype,
                                    pages}
    body   pages × (k_page ‖ v_page) raw bytes, C-order

Raw bytes + a JSON header instead of ``np.savez``: the pools are often
``bfloat16`` (an ml_dtypes extension type NpzFile round-trips
unreliably across numpy versions), and bit-exactness is the whole
point — the decode worker must produce the same greedy tokens the
prefill worker's own decode would have.
"""

from __future__ import annotations

import json
import struct
from typing import Dict, List, Tuple

MAGIC = b"BDKV1\n"


class HandoffError(ValueError):
    """Malformed or incompatible handoff blob."""


def serialize_chain(tokens, k_pages: List, v_pages: List,
                    page_size: int) -> bytes:
    """Pack ``len(k_pages)`` full pages covering ``tokens`` (page ``j``
    holds tokens ``[j*page, (j+1)*page)``) into a handoff blob.
    ``k_pages[j]``/``v_pages[j]`` are same-shape/dtype numpy arrays
    (the per-page ``(L, H, page, D)`` layout the arena holds)."""
    import numpy as np
    if len(k_pages) != len(v_pages):
        raise HandoffError("k/v page count mismatch")
    if len(tokens) < len(k_pages) * page_size:
        raise HandoffError("fewer tokens than the pages cover")
    header = {
        "tokens": [int(t) for t in tokens[:len(k_pages) * page_size]],
        "page_size": int(page_size),
        "pages": len(k_pages),
        "shape": [],
        "dtype": "",
    }
    body = bytearray()
    for k, v in zip(k_pages, v_pages):
        k = np.ascontiguousarray(k)
        v = np.ascontiguousarray(v)
        if not header["dtype"]:
            header["shape"] = list(k.shape)
            header["dtype"] = str(k.dtype)
        if list(k.shape) != header["shape"] or \
                list(v.shape) != header["shape"] or \
                str(v.dtype) != header["dtype"]:
            raise HandoffError("inconsistent page shapes in chain")
        body += k.tobytes()
        body += v.tobytes()
    hdr = json.dumps(header).encode()
    return MAGIC + struct.pack("<I", len(hdr)) + hdr + bytes(body)


def deserialize_chain(blob: bytes) -> Tuple[List[int], List, List, Dict]:
    """Unpack a blob into ``(tokens, k_pages, v_pages, header)``. The
    importer validates ``page_size``/``shape``/``dtype`` against its own
    pool before landing anything."""
    import numpy as np
    if not blob.startswith(MAGIC):
        raise HandoffError("not a KV handoff blob (bad magic)")
    off = len(MAGIC)
    if len(blob) < off + 4:
        raise HandoffError("truncated handoff header")
    (hlen,) = struct.unpack_from("<I", blob, off)
    off += 4
    try:
        header = json.loads(blob[off:off + hlen].decode())
    except Exception as e:
        raise HandoffError(f"unreadable handoff header: {e}") from None
    off += hlen
    if not int(header["pages"]):
        # a fully-evicted chain exports as an empty blob: the importer
        # simply has nothing to land and the decode side re-prefills
        return list(map(int, header["tokens"])), [], [], header
    shape = tuple(header["shape"])
    dtype = np.dtype(_resolve_dtype(header["dtype"]))
    per = int(np.prod(shape)) * dtype.itemsize
    n = int(header["pages"])
    if len(blob) - off != 2 * per * n:
        raise HandoffError(
            f"handoff body holds {len(blob) - off} bytes, expected "
            f"{2 * per * n} for {n} pages of {shape} {dtype}")
    k_pages, v_pages = [], []
    for _ in range(n):
        k_pages.append(np.frombuffer(blob, dtype, count=per
                                     // dtype.itemsize,
                                     offset=off).reshape(shape))
        off += per
        v_pages.append(np.frombuffer(blob, dtype, count=per
                                     // dtype.itemsize,
                                     offset=off).reshape(shape))
        off += per
    return list(map(int, header["tokens"])), k_pages, v_pages, header


def _resolve_dtype(name: str):
    """Numpy dtype from its string name, including the ml_dtypes
    extension types jax pools use (``bfloat16``)."""
    import numpy as np
    try:
        return np.dtype(name)
    except TypeError:
        pass
    import jax.numpy as jnp
    return jnp.dtype(name)

"""Pinned host-RAM page arena (ISSUE 6 tentpole part 1).

The PR 5 radix index drops evicted prefix chains on the floor, so the
reusable KV working set is capped by a single chip's HBM. The arena is
the capacity tier behind it: page-granularity K/V copies live in
preallocated host buffers (one contiguous ``(capacity, L, H, page, D)``
block per side — allocated once, so the host allocator never fragments
the way per-page ``np.array`` churn would; on runtimes that pin
transfer staging this is the pinned region DMA reads/writes).

Entries are keyed by the **full token prefix** through the chunk
(``tuple(tokens[:end])``, the same identity the radix tree encodes
path-wise). An exact-match dict instead of a second tree keeps the
host tier robust to arbitrary insertion order: device eviction is
leaf-first, so chains spill back-to-front and the deepest chunk
arrives *first* — a tree would need phantom interior nodes, the dict
does not care. A dropped middle chunk merely truncates the usable
prefix at lookup time (the walk stops at the first missing key);
nothing structural can corrupt.

Only FULL pages are admitted: a partially-filled tail page is still
private to a live request's decode when it evicts, and its token key
would collide with the full page that position range eventually
holds. Tails simply re-prefill on a later miss (cheap: < one page of
tokens).

Thread-safe (its own lock): the engine thread reserves/looks up while
the migration thread commits/aborts. **Pins** keep a slot's bytes
immovable while a migration is in flight — a pinned slot is never
LRU-evicted and its buffers are never handed to another key.

Host-side only; no jax imports. Unit-testable with bare numpy.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple


class HostArenaError(RuntimeError):
    """Internal-invariant violation (double commit, unpin underflow)."""


class HostArena:
    """Slot allocator + token-prefix index over the host page buffers.

    ``capacity`` is the number of host page slots
    (``bigdl.llm.kvtier.host_pages``). Buffer shape/dtype are fixed by
    the first :meth:`reserve` caller's page shape — the arena is owned
    by one engine (one model config), so all pages are alike.
    """

    def __init__(self, capacity: int, page_size: int):
        if capacity < 1:
            raise ValueError("host arena needs at least one page slot")
        self.capacity = capacity
        self.page = page_size
        self._lock = threading.Lock()
        # slot ids pop low-first like the device pool (no parity
        # requirement here — just the same debuggable convention)
        self._free: List[int] = list(range(capacity - 1, -1, -1))
        self._index: Dict[Tuple[int, ...], int] = {}   # key -> slot
        self._slots: Dict[int, dict] = {}   # slot -> {key, ready, tick}
        self._pins: Dict[int, int] = {}
        self._tick = 0
        self._k = None     # (capacity, L, H, page, D), lazily shaped
        self._v = None
        # plain tallies (debug endpoint + microbench)
        self.host_evictions = 0
        self.bytes_per_page = 0

    # -- buffers -------------------------------------------------------------
    def _ensure_buffers(self, page_shape, dtype):
        import numpy as np
        if self._k is None:
            shape = (self.capacity,) + tuple(page_shape)
            self._k = np.zeros(shape, dtype)
            self._v = np.zeros(shape, dtype)
            self.bytes_per_page = 2 * self._k[0].nbytes
        elif self._k.shape[1:] != tuple(page_shape) or \
                self._k.dtype != dtype:
            raise HostArenaError(
                f"arena shaped {self._k.shape[1:]}/{self._k.dtype} "
                f"cannot hold a {tuple(page_shape)}/{dtype} page")

    # -- allocation ----------------------------------------------------------
    def reserve(self, key: Tuple[int, ...]) -> Optional[int]:
        """Claim a slot for ``key`` (pinned, not yet readable) — the
        spill/import side. An existing entry for the key is reused
        (same tokens at the same positions hold identical KV — the
        re-spill just refreshes it). Returns None when every slot is
        pinned (arena saturated by in-flight migrations): the caller
        drops the spill, which degrades to a plain eviction."""
        with self._lock:
            if len(key) % self.page:
                raise HostArenaError(
                    "arena holds full pages only (partial tails "
                    "re-prefill on miss)")
            slot = self._index.get(key)
            if slot is None:
                slot = self._take_slot_locked()
                if slot is None:
                    return None
                self._index[key] = slot
                self._slots[slot] = {"key": key, "ready": False,
                                     "tick": self._bump()}
            else:
                self._slots[slot]["ready"] = False
            self._pins[slot] = self._pins.get(slot, 0) + 1
            return slot

    def _take_slot_locked(self) -> Optional[int]:
        if self._free:
            return self._free.pop()
        victim = None
        for slot, meta in self._slots.items():
            if slot in self._pins or not meta["ready"]:
                continue
            if victim is None or meta["tick"] < \
                    self._slots[victim]["tick"]:
                victim = slot
        if victim is None:
            return None
        self._drop_locked(victim)
        self.host_evictions += 1
        return self._free.pop()

    def _drop_locked(self, slot: int):
        meta = self._slots.pop(slot)
        self._index.pop(meta["key"], None)
        self._free.append(slot)

    def _bump(self) -> int:
        self._tick += 1
        return self._tick

    # -- migration-side writes -----------------------------------------------
    def commit(self, slot: int, k_page, v_page):
        """Publish a reserved slot's bytes (migration/import thread):
        write, mark ready, drop the reserve pin."""
        with self._lock:
            meta = self._slots.get(slot)
            if meta is None:
                raise HostArenaError(f"commit of unreserved slot {slot}")
            self._ensure_buffers(k_page.shape, k_page.dtype)
            self._k[slot] = k_page
            self._v[slot] = v_page
            meta["ready"] = True
            meta["tick"] = self._bump()
            self._unpin_locked(slot)

    def abort(self, slot: int):
        """A reserved slot whose bytes never arrived (failed/injected
        spill): remove the entry entirely so lookups can never serve a
        zero-filled page."""
        with self._lock:
            if slot in self._slots and not self._slots[slot]["ready"]:
                self._unpin_locked(slot)
                if slot not in self._pins:
                    self._drop_locked(slot)
            elif slot in self._slots:
                self._unpin_locked(slot)

    # -- lookup / fetch side -------------------------------------------------
    def lookup_chunks(self, tokens, start: int, limit: int,
                      *, touch: bool = True
                      ) -> List[Tuple[Tuple[int, ...], int]]:
        """Consecutive READY full-page chunks of ``tokens`` resident in
        the arena, beginning at position ``start`` (a page multiple) and
        never reaching past ``limit`` tokens (the caller passes
        ``len(prompt) - 1`` so at least one suffix token always
        prefills). Returns ``[(key, slot), ...]`` in chain order."""
        toks = tuple(int(t) for t in tokens)
        out: List[Tuple[Tuple[int, ...], int]] = []
        with self._lock:
            end = start + self.page
            while end <= limit:
                slot = self._index.get(toks[:end])
                if slot is None or not self._slots[slot]["ready"]:
                    break
                out.append((toks[:end], slot))
                if touch:
                    self._slots[slot]["tick"] = self._bump()
                end += self.page
        return out

    def pin(self, slot: int):
        with self._lock:
            if slot not in self._slots:
                raise HostArenaError(f"pin of unknown slot {slot}")
            self._pins[slot] = self._pins.get(slot, 0) + 1

    def unpin(self, slot: int):
        with self._lock:
            self._unpin_locked(slot)

    def _unpin_locked(self, slot: int):
        c = self._pins.get(slot, 0)
        if c <= 0:
            raise HostArenaError(f"unpin of unpinned slot {slot}")
        if c == 1:
            del self._pins[slot]
        else:
            self._pins[slot] = c - 1

    def read(self, slot: int):
        """The slot's (k, v) page views — caller must hold a pin so the
        slot cannot be evicted or rewritten mid-read."""
        with self._lock:
            meta = self._slots.get(slot)
            if meta is None or not meta["ready"]:
                raise HostArenaError(f"read of non-ready slot {slot}")
            return self._k[slot], self._v[slot]

    def read_keyed(self, slot: int, key: Tuple[int, ...]):
        """COPIES of a slot's pages, validated against the key the
        caller looked up — or None if the slot was re-keyed meanwhile
        (a lookup→read gap with the lock released lets LRU eviction
        hand the slot to another chain; a pin-less reader must not
        export the wrong chain's bytes). The copy happens under the
        lock, so no pin is needed at all."""
        with self._lock:
            meta = self._slots.get(slot)
            if meta is None or not meta["ready"] or meta["key"] != key:
                return None
            return self._k[slot].copy(), self._v[slot].copy()

    def keys(self) -> List[Tuple[int, ...]]:
        """READY entry keys (full token prefixes) — the host-resident
        warm chains the drain-time migration (ISSUE 15) exports."""
        with self._lock:
            return [meta["key"] for meta in self._slots.values()
                    if meta["ready"]]

    # -- introspection -------------------------------------------------------
    def used(self) -> int:
        with self._lock:
            return len(self._slots)

    def pinned(self) -> int:
        with self._lock:
            return len(self._pins)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            ready = sum(1 for m in self._slots.values() if m["ready"])
            return {
                "capacity": self.capacity,
                "used": len(self._slots),
                "ready": ready,
                "pinned": len(self._pins),
                "evictions": self.host_evictions,
                "bytes_used": ready * self.bytes_per_page,
            }

"""Tiered KV cache: host-RAM spill tier + disaggregated handoff
(ISSUE 6 tentpole).

``bigdl_tpu/llm/kvtier`` is the capacity tier behind the PR 5 prefix
cache. Radix-evicted page chains spill to a pinned host-RAM arena
instead of being freed, and an admission that hits a host-resident
prefix schedules an async fetch back into HBM — both transfers ride a
background migration thread so they hide behind in-flight decode
steps (the PR 4 pipeline):

- :mod:`~bigdl_tpu.llm.kvtier.arena` — the host page arena: slotted
  pinned buffers + an exact token-prefix index, LRU within the tier;
- :mod:`~bigdl_tpu.llm.kvtier.migrate` — the FIFO migration worker
  (spill = device→host, fetch = host→device) with the
  ``kvtier.{spill,fetch}`` fault sites; failures degrade to plain
  eviction / plain miss, never a stall or crash;
- :mod:`~bigdl_tpu.llm.kvtier.handoff` — serialized KV-chain blobs for
  the disaggregated prefill/decode split (``bigdl.llm.role``): a
  prefill worker exports a request's chain through the tier, a decode
  worker imports it into its own arena and decodes with a ~1-token
  prefill;
- :class:`KVTier` (here) — what the engine's
  :class:`~bigdl_tpu.llm.kvcache.KVCacheManager` holds: arena +
  migrator + the ``bigdl_kvtier_*`` accounting.

``bigdl.llm.kvtier.enabled=false`` (the default) constructs none of
this: no arena, no migration thread, no ``bigdl_kvtier_*`` series, no
``tier`` block on ``GET /debug/kvcache`` — and the engine is
bit-identical to the PR 5 engine (asserted in tests/test_kvtier.py).

See docs/KVCACHE.md ("Host tier") for the migration lifecycle and the
disaggregated topology.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from bigdl_tpu.llm.kvtier.arena import HostArena, HostArenaError
from bigdl_tpu.llm.kvtier.handoff import (HandoffError, deserialize_chain,
                                          serialize_chain)
from bigdl_tpu.llm.kvtier.migrate import MigrationJob, Migrator


class KVTier:
    """Arena + migrator + tier accounting, owned by the KVCacheManager
    when ``bigdl.llm.kvtier.enabled`` (or the ``kvtier=`` ctor arg) is
    on. Pure host-side object; every device touch goes through the
    engine-registered reader/writer callbacks on the manager."""

    def __init__(self, host_pages: int, page_size: int,
                 synchronous: bool = False,
                 fetch_timeout: float = 30.0):
        self.arena = HostArena(host_pages, page_size)
        self.migrator = Migrator(self.arena, synchronous=synchronous)
        self.fetch_timeout = fetch_timeout
        # always-on tallies (debug endpoint + microbench); the metric
        # series below mirror them only while observability is enabled
        self.spills = 0
        self.fetches = 0
        self.fetch_failures = 0
        self.handoffs_out = 0
        self.handoffs_in = 0
        self.handoff_bytes = 0
        self._ins: Optional[Dict[str, Any]] = None

    # -- observability -------------------------------------------------------
    def _instruments(self):
        from bigdl_tpu import observability as obs
        if not obs.enabled():
            return None
        if self._ins is None:
            self._ins = {
                "spills": obs.counter(
                    "bigdl_kvtier_spills_total",
                    "Pages spilled from HBM to the host arena"),
                "fetches": obs.counter(
                    "bigdl_kvtier_fetches_total",
                    "Pages fetched from the host arena back into HBM"),
                "fetch_failures": obs.counter(
                    "bigdl_kvtier_fetch_failures_total",
                    "Host-tier fetches that degraded to a cache miss"),
                "handoffs": obs.counter(
                    "bigdl_kvtier_handoffs_total",
                    "KV-chain handoffs across the prefill/decode split",
                    labelnames=("direction",)),
                "handoff_bytes": obs.counter(
                    "bigdl_kvtier_handoff_bytes_total",
                    "Serialized KV bytes moved by handoffs"),
                "host_used": obs.gauge(
                    "bigdl_kvtier_host_pages_used",
                    "Host arena slots currently holding a page"),
                "host_capacity": obs.gauge(
                    "bigdl_kvtier_host_pages",
                    "Host arena capacity in page slots"),
                "inflight": obs.gauge(
                    "bigdl_kvtier_inflight_migrations",
                    "Migration jobs queued or running"),
            }
        return self._ins

    def record_gauges(self):
        ins = self._instruments()
        if ins is None:
            return
        ins["host_used"].set(self.arena.used())
        ins["host_capacity"].set(self.arena.capacity)
        ins["inflight"].set(self.migrator.inflight())

    def count_spill(self, n: int = 1):
        self.spills += n
        from bigdl_tpu.observability import flight
        flight.record("spill", pages=n)
        ins = self._instruments()
        if ins is not None:
            ins["spills"].inc(n)
            self.record_gauges()

    def count_fetch(self, n: int):
        self.fetches += n
        ins = self._instruments()
        if ins is not None:
            ins["fetches"].inc(n)
            self.record_gauges()

    def count_fetch_failure(self, n: int = 1):
        self.fetch_failures += n
        ins = self._instruments()
        if ins is not None:
            ins["fetch_failures"].inc(n)

    def count_handoff(self, direction: str, nbytes: int):
        if direction == "export":
            self.handoffs_out += 1
        else:
            self.handoffs_in += 1
        self.handoff_bytes += nbytes
        ins = self._instruments()
        if ins is not None:
            ins["handoffs"].labels(direction=direction).inc()
            ins["handoff_bytes"].inc(nbytes)

    def cancel_fetch(self, job: Optional[MigrationJob]):
        """Flag an in-flight fetch cancelled from OUTSIDE the engine
        thread (ISSUE 7: the watchdog aborts parked fetches while the
        engine is wedged). Flag-only by design — the migration worker
        still resolves its arena pins, and the engine's next
        ``_poll_fetches`` pass degrades the admission to a plain miss
        under its own lock, so no budget bookkeeping happens here."""
        if job is not None:
            job.cancelled = True

    # -- introspection -------------------------------------------------------
    def debug_stats(self) -> Dict[str, Any]:
        """The ``tier`` block of ``GET /debug/kvcache``."""
        out = self.arena.stats()
        out.update({
            "spills": self.spills,
            "fetches": self.fetches,
            "fetch_failures": self.fetch_failures,
            "spill_failures": self.migrator.spill_failures,
            "inflight_migrations": self.migrator.inflight(),
            "handoffs_out": self.handoffs_out,
            "handoffs_in": self.handoffs_in,
            "handoff_bytes": self.handoff_bytes,
        })
        return out

    def close(self):
        self.migrator.stop()


__all__ = ["HandoffError", "HostArena", "HostArenaError", "KVTier",
           "MigrationJob", "Migrator", "deserialize_chain",
           "serialize_chain"]

"""Async HBM↔host page migration (ISSUE 6 tentpole part 2).

One background worker thread drains a FIFO of migration jobs so the
blocking halves of a migration — ``np.asarray`` (device→host) on a
spill, ``jax.device_put`` (host→device) on a fetch — never run on the
engine thread. The engine's half is dispatch-only:

- **spill**: the engine dispatches a per-page gather
  (``pool[:, pid]``) at eviction time, which materializes the page
  into its own device buffer *before* the page id can be reissued and
  overwritten (engine-thread program order, the same donated-pool
  dependency argument the partial prefill relies on). The worker then
  pulls those standalone buffers to the host and commits them into the
  arena — overlapped with whatever decode steps are in flight.
- **fetch**: the worker uploads arena pages to fresh device buffers;
  the engine polls ``job.done`` from its admission pass and scatters
  the uploaded pages into the pool only once the upload exists — so a
  host-tier hit hides its transfer behind the decode steps of the
  requests already running.

FIFO on a single worker also orders a fetch behind the spill that
produced its bytes, so a freshly-spilled chunk is fetchable with no
extra synchronization.

Failure contract (the ``kvtier.{spill,fetch}`` fault sites fire here):
a failed spill aborts its arena entry (the chunk is simply not
cached); a failed fetch marks the job failed and the engine degrades
the admission to a plain cache miss. Neither ever raises into the
engine loop or leaves an arena pin behind.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, List, Optional, Tuple

from bigdl_tpu.llm.kvtier.arena import HostArena


class MigrationJob:
    """One queued migration. ``done`` is set exactly once, after ``ok``
    and the payload are final. ``cancelled`` (engine-set, e.g. fetch
    timeout) tells the worker to skip the transfer; the arena pins are
    released either way."""

    __slots__ = ("kind", "done", "ok", "error", "cancelled",
                 "entries", "k_dev", "v_dev", "submitted_at")

    def __init__(self, kind: str, entries):
        self.kind = kind
        self.entries = entries        # [(key, slot, *payload)]
        self.done = threading.Event()
        self.ok = False
        self.error: Optional[str] = None
        self.cancelled = False
        self.k_dev: List[Any] = []    # fetch results (device arrays)
        self.v_dev: List[Any] = []
        self.submitted_at = time.monotonic()


class Migrator:
    """The worker thread + job queue. ``synchronous=True`` executes
    jobs inline at submit (no thread): deterministic unit tests and the
    tier-1 suite's fake-clock budget use it; production runs async."""

    def __init__(self, arena: HostArena, synchronous: bool = False):
        self.arena = arena
        self.synchronous = synchronous
        self._queue: "queue.Queue[Optional[MigrationJob]]" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._idle = threading.Event()
        self._idle.set()
        self._lock = threading.Lock()
        self._stopped = False
        # plain tallies (tier metrics mirror them when obs is on)
        self.spills_done = 0
        self.spill_failures = 0
        self.fetches_done = 0
        self.fetch_failures = 0

    # -- submission ----------------------------------------------------------
    def _submit(self, job: MigrationJob) -> MigrationJob:
        if self.synchronous:
            self._run(job)
            return job
        with self._lock:
            if self._stopped:
                # a stopped migrator fails jobs instead of leaking pins
                self._resolve_pins(job)
                job.error = "migrator stopped"
                job.done.set()
                return job
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._loop, name="bigdl-kvtier-migrate",
                    daemon=True)
                self._thread.start()
            self._idle.clear()
        self._queue.put(job)
        return job

    def submit_spill(self, key, slot: int, k_dev, v_dev) -> MigrationJob:
        """Device→host. ``k_dev``/``v_dev`` are the engine's standalone
        per-page gather outputs; the arena slot is reserve-pinned."""
        return self._submit(
            MigrationJob("spill", [(key, slot, k_dev, v_dev)]))

    def submit_fetch(self, entries: List[Tuple[Any, int]]) -> MigrationJob:
        """Host→device for a chain of ``(key, slot)`` arena chunks (the
        caller pinned each slot; the worker unpins when finished)."""
        return self._submit(MigrationJob("fetch", list(entries)))

    # -- worker --------------------------------------------------------------
    def _loop(self):
        while True:
            job = self._queue.get()
            if job is None:
                return
            try:
                self._run(job)
            finally:
                if self._queue.empty():
                    self._idle.set()

    def _run(self, job: MigrationJob):
        from bigdl_tpu import observability as obs
        from bigdl_tpu import reliability
        t0 = time.time()
        try:
            if job.cancelled:
                raise RuntimeError("cancelled before transfer")
            reliability.inject(f"kvtier.{job.kind}")
            if job.kind == "spill":
                self._run_spill(job)
            else:
                self._run_fetch(job)
            job.ok = True
        except BaseException as e:  # noqa: BLE001 — a migration must
            # degrade (miss / plain eviction), never crash the worker
            job.error = f"{type(e).__name__}: {e}"
            if job.kind == "spill":
                self.spill_failures += 1
                for _, slot, *_ in job.entries:
                    try:
                        self.arena.abort(slot)
                    except Exception:
                        pass
            else:
                self.fetch_failures += 1
                self._resolve_pins(job)
        finally:
            if job.ok:
                obs.add_complete(
                    "kvtier/migrate", t0, time.time() - t0,
                    direction=job.kind, pages=len(job.entries))
            job.done.set()

    def _run_spill(self, job: MigrationJob):
        import numpy as np
        for key, slot, k_dev, v_dev in job.entries:
            self.arena.commit(slot, np.asarray(k_dev), np.asarray(v_dev))
            self.spills_done += 1

    def _run_fetch(self, job: MigrationJob):
        import jax
        try:
            for key, slot in job.entries:
                k_np, v_np = self.arena.read(slot)
                job.k_dev.append(jax.device_put(k_np))
                job.v_dev.append(jax.device_put(v_np))
            self.fetches_done += len(job.entries)
        finally:
            self._resolve_pins(job)

    def _resolve_pins(self, job: MigrationJob):
        if job.kind != "fetch":
            return
        for key, slot in job.entries:
            try:
                self.arena.unpin(slot)
            except Exception:
                pass

    # -- lifecycle -----------------------------------------------------------
    def inflight(self) -> int:
        if self.synchronous:
            return 0
        return self._queue.qsize() + (0 if self._idle.is_set() else 1)

    def drain(self, timeout: float = 30.0) -> bool:
        """Wait for every queued job to finish (tests, stop())."""
        if self.synchronous:
            return True
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._queue.empty() and self._idle.is_set():
                return True
            time.sleep(0.002)
        return self._queue.empty() and self._idle.is_set()

    def stop(self, timeout: float = 5.0):
        self.drain(timeout)
        with self._lock:
            self._stopped = True
            thread = self._thread
        if thread is not None:
            self._queue.put(None)
            thread.join(timeout=timeout)

"""Tokenizer protocol + per-family chat templates (ISSUE 20).

The environment ships **no tokenizer assets** (``worker.py``: the
native surface is token-array in, token-array out, tokenization happens
client-side). The gateway therefore speaks token arrays natively —
``"prompt": [1, 2, 3]`` needs nothing — and treats *text* as an
optional capability behind a pluggable protocol:

- ``encode(text) -> List[int]`` and ``decode(ids) -> str``;
- any object with those two methods plugs in via the ``tokenizer=``
  ctor arg, or ``bigdl.llm.api.tokenizer=byte`` selects the
  deterministic :class:`ByteTokenizer` below (the test implementation:
  UTF-8 bytes as token ids, reversible, no assets).

Chat requests always go through a template: ``messages`` →
prompt text via the model family's conversation format, then the
tokenizer. The family formats mirror bigdl-llm's fastchat-style
per-family conversation templates (llama ``[INST]``, chatglm
``问/答``-free plain rounds, and a generic ``### Human/Assistant``
fallback) — deterministic string builders, not learned assets.
"""

from __future__ import annotations

from typing import List, Sequence

from bigdl_tpu.llm.api.errors import InvalidRequestError


class ByteTokenizer:
    """Deterministic, asset-free tokenizer: UTF-8 bytes are the token
    ids (0..255). Exactly the convention the langchain integration's
    fallback encoder has used since PR 9, now made reversible so text
    responses and ``stop`` strings work end-to-end in tests."""

    vocab_size = 256

    def encode(self, text: str) -> List[int]:
        return [b for b in text.encode("utf-8")]

    def decode(self, ids: Sequence[int]) -> str:
        return bytes(int(t) & 0xFF for t in ids).decode(
            "utf-8", errors="replace")


def build_tokenizer(name: str):
    """Resolve the ``bigdl.llm.api.tokenizer`` knob: ``""`` (default)
    means token-array-only — text prompts answer
    ``invalid_request_error`` — and ``"byte"`` is the deterministic
    test implementation. Anything else is a configuration error."""
    if not name:
        return None
    if name == "byte":
        return ByteTokenizer()
    raise ValueError(f"unknown bigdl.llm.api.tokenizer {name!r} "
                     "(expected '' or 'byte')")


#: role -> prefix line, per model family. Formats are intentionally
#: minimal and deterministic; the gateway's job is a faithful
#: ``messages`` -> prompt flattening, not prompt engineering.
CHAT_FAMILIES = ("plain", "llama", "chatglm")


def apply_chat_template(family: str, messages: List[dict]) -> str:
    """Flatten an OpenAI ``messages`` list into one prompt string using
    the family's conversation format. Validates shape: every message
    needs a known ``role`` and a string ``content``."""
    if family not in CHAT_FAMILIES:
        raise InvalidRequestError(
            f"unknown chat template family {family!r} "
            f"(expected one of {CHAT_FAMILIES})", param="model")
    if not isinstance(messages, list) or not messages:
        raise InvalidRequestError("messages must be a non-empty list",
                                  param="messages")
    system = []
    rounds = []   # (role, content) with role in user/assistant
    for i, msg in enumerate(messages):
        if not isinstance(msg, dict):
            raise InvalidRequestError(
                f"messages[{i}] must be an object", param="messages")
        role = msg.get("role")
        content = msg.get("content")
        if role not in ("system", "user", "assistant"):
            raise InvalidRequestError(
                f"messages[{i}].role must be system|user|assistant, "
                f"got {role!r}", param="messages")
        if not isinstance(content, str):
            raise InvalidRequestError(
                f"messages[{i}].content must be a string",
                param="messages")
        if role == "system":
            system.append(content)
        else:
            rounds.append((role, content))
    if not rounds or rounds[-1][0] != "user":
        raise InvalidRequestError(
            "the last non-system message must be from the user",
            param="messages")
    sys_text = "\n".join(system)
    if family == "llama":
        # [INST] <<SYS>> ... <</SYS>> user [/INST] answer ...
        parts = []
        first = True
        for role, content in rounds:
            if role == "user":
                block = content
                if first and sys_text:
                    block = f"<<SYS>>\n{sys_text}\n<</SYS>>\n\n{content}"
                parts.append(f"[INST] {block} [/INST]")
                first = False
            else:
                parts.append(f" {content} ")
        return "".join(parts)
    if family == "chatglm":
        parts = [sys_text] if sys_text else []
        turn = 0
        for role, content in rounds:
            if role == "user":
                parts.append(f"[Round {turn}]\n问：{content}")
                turn += 1
            else:
                parts.append(f"答：{content}")
        parts.append("答：")
        return "\n".join(parts)
    # plain: ### Human / ### Assistant rounds (the fastchat default)
    parts = [sys_text] if sys_text else []
    for role, content in rounds:
        tag = "### Human" if role == "user" else "### Assistant"
        parts.append(f"{tag}: {content}")
    parts.append("### Assistant:")
    return "\n".join(parts)

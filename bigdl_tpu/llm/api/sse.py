"""Server-Sent Events framing for the OpenAI gateway (ISSUE 20).

One chunk grammar, shared by the server side (the gateway's writer over
a chunked HTTP/1.1 response) and the client side (``tools/loadgen.py
--openai``, the chaos SSE client, the langchain helper, tests)::

    data: {json}\\n\\n      # one event per drained token group
    data: [DONE]\\n\\n      # terminal sentinel, always last

The writer frames each event as its own HTTP chunk and flushes — the
relay from the failover journal's drain to the client socket is
per-token-group, never buffered to the end. A client that went away
surfaces as :class:`StreamAbort` (``client_gone=True``) from
:meth:`SSEWriter.event`, which the dispatch layer turns into the
existing abort path (engine slot + KV pages freed).
"""

from __future__ import annotations

import json
from typing import Iterator, Optional

from bigdl_tpu.llm.failover import StreamAbort

#: terminal sentinel line, exactly as OpenAI emits it
DONE = "[DONE]"


def sse_event(obj) -> bytes:
    """One SSE event: ``data: {json}`` + blank-line terminator."""
    return b"data: " + json.dumps(obj).encode() + b"\n\n"


def sse_done() -> bytes:
    return b"data: " + DONE.encode() + b"\n\n"


class SSEWriter:
    """Streams SSE events over a ``BaseHTTPRequestHandler`` using
    chunked transfer encoding (the same wire idiom as the worker's
    ``/worker_generate_stream`` JSON-lines endpoint, different frame
    grammar). Headers are sent lazily on the first event so a request
    that fails during translation still gets a plain JSON error."""

    def __init__(self, handler, trace_id: Optional[str] = None):
        self._h = handler
        self._trace = trace_id
        self.started = False
        self.events = 0

    def _start(self):
        h = self._h
        h.send_response(200)
        h.send_header("Content-Type", "text/event-stream")
        h.send_header("Cache-Control", "no-cache")
        h.send_header("Transfer-Encoding", "chunked")
        if self._trace:
            from bigdl_tpu.observability import request_context as rc
            h.send_header(rc.TRACE_HEADER, self._trace)
        h.end_headers()
        self.started = True

    def _chunk(self, data: bytes):
        try:
            self._h.wfile.write(f"{len(data):x}\r\n".encode() + data
                                + b"\r\n")
            self._h.wfile.flush()
        except OSError as e:
            # client hung up mid-stream: the gateway aborts the engine
            # request (slot + KV pages free) instead of generating
            # tokens nobody will read
            raise StreamAbort("client disconnected mid-stream",
                              client_gone=True) from e

    def event(self, obj):
        if not self.started:
            self._start()
        self._chunk(sse_event(obj))
        self.events += 1

    def done(self):
        """Terminal ``data: [DONE]`` + the zero-length chunk that ends
        the HTTP response."""
        if not self.started:
            self._start()
        self._chunk(sse_done())
        try:
            self._h.wfile.write(b"0\r\n\r\n")
            self._h.wfile.flush()
        except OSError:
            # the payload was fully delivered — a reset racing the
            # trailer is not a client-visible failure
            pass


def parse_sse(resp) -> Iterator[dict]:
    """Client-side SSE reader over an ``http.client`` response (which
    undoes the chunked framing): yields one parsed JSON object per
    ``data:`` event, stopping at ``[DONE]``. Raises ``ValueError`` on
    grammar violations — the chaos/parity harnesses want framing bugs
    loud, not skipped."""
    for raw in resp:
        line = raw.strip()
        if not line:
            continue
        if not line.startswith(b"data:"):
            raise ValueError(f"not an SSE data line: {raw[:80]!r}")
        payload = line[len(b"data:"):].strip()
        if payload == DONE.encode():
            return
        yield json.loads(payload.decode())
    raise ValueError("SSE stream ended without data: [DONE]")

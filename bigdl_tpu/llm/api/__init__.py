"""OpenAI-compatible serving gateway (ISSUE 20).

Gated by ``bigdl.llm.api.enabled`` (default off): the worker/router
construct :class:`~bigdl_tpu.llm.api.gateway.OpenAIGateway` only when
the gate is on — off means ``/v1/*`` answers 404 naming the gate, no
``bigdl_api_*`` series exist, and nothing in this package runs.

Modules: :mod:`~bigdl_tpu.llm.api.gateway` (translation + dispatch),
:mod:`~bigdl_tpu.llm.api.sse` (SSE framing, both sides of the wire),
:mod:`~bigdl_tpu.llm.api.templates` (tokenizer protocol + per-family
chat templates), :mod:`~bigdl_tpu.llm.api.errors` (OpenAI error
objects). See ``docs/API.md`` for the wire contract.
"""

from bigdl_tpu.llm.api.errors import (ApiError, InvalidRequestError,
                                      RateLimitError, UpstreamError)
from bigdl_tpu.llm.api.gateway import (EngineBackend, OpenAIGateway,
                                       StopMatcher)
from bigdl_tpu.llm.api.sse import parse_sse, sse_done, sse_event
from bigdl_tpu.llm.api.templates import (ByteTokenizer,
                                         apply_chat_template,
                                         build_tokenizer)

__all__ = [
    "ApiError", "InvalidRequestError", "RateLimitError",
    "UpstreamError", "EngineBackend", "OpenAIGateway", "StopMatcher",
    "parse_sse", "sse_done", "sse_event", "ByteTokenizer",
    "apply_chat_template", "build_tokenizer",
]

"""OpenAI-style error objects for the serving gateway (ISSUE 20).

Every client-visible failure on the ``/v1/*`` surface is one JSON body
shaped exactly like the OpenAI API's::

    {"error": {"message": ..., "type": ..., "param": ..., "code": ...}}

The mapping is fixed by the tentpole contract:

- engine overload / backend shed (the 503 the native surface answers)
  → HTTP **429** with ``type=rate_limit_exceeded`` carrying the
  class-weighted ``Retry-After`` the native path already derives —
  OpenAI clients retry on 429, not 503, so the gateway translates the
  status while keeping the backoff signal byte-identical;
- any client-side schema problem (bad body, infeasible prompt, 422
  from the engine) → HTTP **400/422** with
  ``type=invalid_request_error`` and ``param`` naming the field;
- upstream failures the failover layer could not absorb → HTTP
  **502/504** with ``type=api_error``.

These are plain exceptions, not HTTP glue: the gateway raises them
from translation/dispatch and renders them once at the top of the
handler (or as a terminal SSE event when the stream already started).
"""

from __future__ import annotations

from typing import Optional


class ApiError(Exception):
    """A client-visible gateway failure carrying its OpenAI rendering."""

    #: OpenAI error ``type`` field.
    kind = "api_error"
    #: default HTTP status when not given explicitly
    status = 500

    def __init__(self, message: str, status: Optional[int] = None,
                 param: Optional[str] = None,
                 code: Optional[str] = None):
        super().__init__(message)
        if status is not None:
            self.status = int(status)
        self.param = param
        self.code = code

    def body(self) -> dict:
        err = {"message": str(self), "type": self.kind,
               "param": self.param, "code": self.code}
        return {"error": err}

    def headers(self):
        return ()


class InvalidRequestError(ApiError):
    """The request body is malformed or infeasible — the client's
    fault, never retried, never failed over (mirrors the native 400/422
    split; the gateway keeps the engine's status when it has one)."""

    kind = "invalid_request_error"
    status = 400


class RateLimitError(ApiError):
    """Overload shed translated for OpenAI clients: 429 +
    ``rate_limit_exceeded`` + the class-weighted Retry-After the native
    surface would have sent on its 503."""

    kind = "rate_limit_error"
    status = 429

    def __init__(self, message: str, retry_after: str = "1"):
        super().__init__(message, code="rate_limit_exceeded")
        self.retry_after = str(retry_after)

    def headers(self):
        return (("Retry-After", self.retry_after),)


class UpstreamError(ApiError):
    """The backend (engine or routed worker) failed in a way the
    failover layer could not absorb — 502, or 504 on deadline."""

    kind = "api_error"
    status = 502


def error_for_status(status: int, message: str,
                     retry_after: Optional[str] = None) -> ApiError:
    """Map a native-surface HTTP outcome onto the OpenAI vocabulary:
    503 shed → 429 ``rate_limit_exceeded`` (keeping the class-weighted
    Retry-After), other 4xx → ``invalid_request_error`` at the same
    status, 5xx → ``api_error``. The router's gateway backend uses
    this so the translation lives next to the error objects."""
    if status == 503:
        return RateLimitError(message, retry_after=retry_after or "1")
    if 400 <= status < 500:
        return InvalidRequestError(message, status=status)
    return UpstreamError(message, status=status)

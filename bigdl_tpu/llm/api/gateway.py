"""OpenAI-compatible serving gateway (ISSUE 20 tentpole).

The stack's native surface is bespoke (``/worker_generate*``, token
arrays, JSON-lines streaming). This module puts the ecosystem surface
in front of it — ``POST /v1/completions``, ``POST /v1/chat/completions``
and ``GET /v1/models``, the schema subset fastchat/langchain/OpenAI
clients already speak — **without a second serving path**: the gateway
is a translator over the same engine submit / failover dispatch the
native endpoints use, so SLO accounting, shed policy, failover
bit-parity and priority classes all come along for free.

Layering:

- :class:`OpenAIGateway` — schema translation + SSE relay + error
  mapping + the ``bigdl_api_requests_total{route,outcome}`` counter and
  the ``api/request`` span. One instance per surface, constructed ONLY
  when ``bigdl.llm.api.enabled`` (``LLMWorker``/``LLMRouter`` own the
  gate; off means /v1/* 404s and none of this exists).
- A *backend* adapter carries dispatch: :class:`EngineBackend` drains
  an in-process :class:`~bigdl_tpu.llm.serving.LLMServer` request
  (single-node worker), while the router passes its own adapter over
  the failover journal — there the per-token SSE relay IS the journal
  drain listener, so a mid-stream failover is invisible to the client
  and every token is stamped exactly once for the router SLO sketches
  (one accounting, not two).

Streaming contract (``stream=true``): one ``data:`` chunk per drained
token group, ``usage`` on the final chunk, ``data: [DONE]`` terminal.
A client disconnect surfaces as :class:`~bigdl_tpu.llm.failover.
StreamAbort` from the socket write and aborts the engine request via
the existing ``LLMServer.abort`` path — slot and KV pages free instead
of decoding tokens nobody will read.

Sampling is **server-configured** in this engine (``LLMServer(
temperature=, top_k=)`` — greedy by default, and the failover/parity
contracts depend on determinism). The gateway therefore validates
``temperature``/``top_k``/``top_p`` against the backend's configuration
instead of silently ignoring them: omit them, or match the server.
"""

from __future__ import annotations

import json
import time
import uuid
from typing import List, Optional, Sequence

from bigdl_tpu import observability as obs
from bigdl_tpu import reliability
from bigdl_tpu.llm.api.errors import (ApiError, InvalidRequestError,
                                      RateLimitError, UpstreamError)
from bigdl_tpu.llm.api.sse import SSEWriter
from bigdl_tpu.llm.api.templates import (apply_chat_template,
                                         build_tokenizer)
from bigdl_tpu.llm.failover import StreamAbort
from bigdl_tpu.observability import flight

#: mirrors worker.PRIORITY_HEADER / serving.PRIORITY_CLASSES without
#: importing the engine stack into the translation layer (the worker
#: module imports *this* package lazily from its gated ctor)
PRIORITY_HEADER = "X-BigDL-Priority"
PRIORITY_CLASSES = ("interactive", "standard", "batch")

GET_ROUTES = ("/v1/models",)
POST_ROUTES = ("/v1/completions", "/v1/chat/completions")


def _find(buf, pat) -> int:
    """``buf.find(pat)`` generalized to token-id lists."""
    if isinstance(buf, str):
        return buf.find(pat)
    n, m = len(buf), len(pat)
    for i in range(n - m + 1):
        if buf[i:i + m] == pat:
            return i
    return -1


class StopMatcher:
    """Incremental ``stop``-sequence matcher over a stream of pieces
    (text or token-id lists — the sequence type just has to slice and
    compare). :meth:`feed` returns the longest prefix that is safe to
    emit: anything that could still grow into a stop sequence is held
    back, so a stop split across two drained chunks is still cut
    exactly at the match, never leaked to the client."""

    def __init__(self, stops: Sequence):
        self.stops = list(stops)
        self.buf = None        # lazily typed from the first piece
        self.hit = False

    def feed(self, piece):
        """-> (emit, done). ``done`` means a stop matched; ``emit`` is
        everything up to (excluding) the match."""
        if not self.stops:
            return piece, False
        self.buf = piece if self.buf is None else self.buf + piece
        best = -1
        for s in self.stops:
            idx = _find(self.buf, s)
            if idx >= 0 and (best < 0 or idx < best):
                best = idx
        if best >= 0:
            emit = self.buf[:best]
            self.buf = self.buf[:0]
            self.hit = True
            return emit, True
        hold = 0
        for s in self.stops:
            top = min(len(s) - 1, len(self.buf))
            for k in range(top, hold, -1):
                if self.buf[len(self.buf) - k:] == s[:k]:
                    hold = k
                    break
        cut = len(self.buf) - hold
        emit = self.buf[:cut]
        self.buf = self.buf[cut:]
        return emit, False

    def flush(self):
        """Held-back remainder once the stream ends without a match."""
        if self.buf is None or self.hit:
            return None
        out, self.buf = self.buf, self.buf[:0]
        return out if len(out) else None


class TranslatedRequest:
    """The OpenAI request body mapped onto engine terms."""

    __slots__ = ("rid", "created", "chat", "prompt_ids", "max_tokens",
                 "n", "stream", "stops_text", "stops_tokens",
                 "priority", "deadline")

    def __init__(self, **kw):
        for k in self.__slots__:
            setattr(self, k, kw[k])


class EngineBackend:
    """Direct-engine dispatch for the single-node worker surface: the
    same submit / drain-loop / EOS-terminal / abort discipline as the
    native ``/worker_generate_stream`` handler, surfaced through the
    gateway's exception vocabulary."""

    def __init__(self, server, model_name: str,
                 request_timeout: float = 600.0):
        self.server = server
        self.model_name = model_name
        self.request_timeout = request_timeout

    def sampling(self):
        return (float(getattr(self.server, "temperature", 0.0) or 0.0),
                int(getattr(self.server, "top_k", 0) or 0))

    def _retry_after(self, priority) -> str:
        # class-weighted queue depth (ISSUE 17 satellite), same
        # derivation as the native 503 path
        rd = getattr(self.server, "retry_depth", None)
        if rd is not None:
            depth = rd(priority)
        else:
            q = getattr(self.server, "_queue", None)
            depth = q.qsize() if q is not None else 0
        return reliability.retry_after_seconds(depth)

    def generate(self, prompt_ids: List[int], max_new_tokens: int,
                 priority: Optional[str], deadline,
                 on_delta) -> tuple:
        import numpy as np
        ids = np.asarray(prompt_ids, np.int32)
        kw = {"priority": priority} if priority is not None else {}
        try:
            req = self.server.submit(ids, max_new_tokens=max_new_tokens,
                                     **kw)
        except reliability.OverloadError as e:
            raise RateLimitError(str(e),
                                 retry_after=self._retry_after(priority))
        except ValueError as e:
            raise InvalidRequestError(str(e), status=422)
        timeout = self.request_timeout if deadline is None else \
            max(min(self.request_timeout, deadline.remaining()), 0.0)
        end = time.time() + timeout
        abort = getattr(self.server, "abort", None)
        seen: List[int] = []
        try:
            while True:
                done = req.done.wait(0.02)
                cur = list(req.tokens)
                eos = self.server.eos_token_id
                if not done and req.error is None and eos is not None \
                        and cur and cur[-1] == eos:
                    # EOS-chunk-is-terminal, same rule as the native
                    # stream: never hand a resumable view that a
                    # failover could extend past EOS
                    done = True
                if len(cur) > len(seen):
                    new = cur[len(seen):]
                    seen[:] = cur
                    if on_delta is not None:
                        on_delta([int(t) for t in new])
                if done:
                    if req.error is not None:
                        raise UpstreamError(
                            f"engine failed: {req.error}", status=500)
                    finish = ("stop" if eos is not None and cur
                              and cur[-1] == eos else "length")
                    return [int(t) for t in seen], finish
                if time.time() >= end:
                    if abort is not None:
                        abort(req, reason="api request timed out")
                    raise UpstreamError("generation timed out",
                                        status=504)
        except StreamAbort as e:
            # client gone or stop satisfied: free the slot + KV pages
            # instead of decoding tokens nobody will read
            if abort is not None:
                abort(req, reason=str(e))
            raise


class OpenAIGateway:
    """Translate /v1/* requests onto a backend adapter and stream the
    answer back — see the module docstring for the contract."""

    def __init__(self, backend, tokenizer=None,
                 chat_family: Optional[str] = None,
                 scope: str = "worker"):
        from bigdl_tpu.utils.conf import conf
        self.backend = backend
        self.tokenizer = (tokenizer if tokenizer is not None else
                          build_tokenizer(
                              conf.get("bigdl.llm.api.tokenizer", "")))
        self.chat_family = (chat_family or
                            conf.get("bigdl.llm.api.chat_template",
                                     "plain"))
        self.scope = scope
        self._requests = None     # lazy bigdl_api_requests_total

    # -- observability -------------------------------------------------------
    def _count(self, route: str, outcome: str):
        if not obs.enabled():
            return
        if self._requests is None:
            self._requests = obs.counter(
                "bigdl_api_requests_total",
                "OpenAI gateway requests by route and outcome",
                labelnames=("route", "outcome"))
        self._requests.labels(route=route, outcome=outcome).inc()

    # -- GET /v1/models ------------------------------------------------------
    def handle_models(self, handler):
        handler._json(200, {
            "object": "list",
            "data": [{"id": self.backend.model_name, "object": "model",
                      "created": int(time.time()),
                      "owned_by": "bigdl-tpu"}]})
        self._count("/v1/models", "ok")

    # -- POST /v1/completions + /v1/chat/completions -------------------------
    def handle_post(self, handler, path: str):
        chat = path == "/v1/chat/completions"
        writer = None
        rid = None
        try:
            with obs.span("api/request", stage="api_gateway",
                          route=path):
                try:
                    n = int(handler.headers.get("Content-Length", 0))
                    raw = handler.rfile.read(n) if n else b""
                    body = json.loads(raw) if raw else {}
                except ValueError as e:
                    raise InvalidRequestError(f"body is not JSON: {e}")
                if not isinstance(body, dict):
                    raise InvalidRequestError(
                        "body must be a JSON object")
                treq = self._translate(body, handler.headers, chat=chat)
                if treq.stream:
                    writer = SSEWriter(
                        handler, trace_id=getattr(handler, "_trace",
                                                  None))
                    rid = treq.rid
                    self._dispatch_stream(handler, treq, path, writer)
                else:
                    self._dispatch_blocking(handler, treq, path)
            self._count(path, "ok")
        except StreamAbort as e:
            if not e.client_gone:   # defensive: stop aborts are
                raise               # consumed inside _run_choice
            # flight event at the abort site (ISSUE 20): the journaled
            # request id ties the explain timeline to the disconnect
            flight.record("client_abort", request_id=rid, route=path,
                          scope=self.scope)
            self._count(path, "disconnect")
            handler.close_connection = True
        except ApiError as e:
            outcome = ("shed" if isinstance(e, RateLimitError) else
                       "invalid" if isinstance(e, InvalidRequestError)
                       else "error")
            if isinstance(e, RateLimitError):
                # flight event at the shed site, next to the 429
                flight.record("shed", request_id=rid, route=path,
                              scope=self.scope, source="api")
            self._count(path, outcome)
            if writer is not None and writer.started:
                # the 200 + SSE headers are on the wire: the error
                # travels as a terminal event, then [DONE]
                writer.event(e.body())
                writer.done()
            else:
                handler._json(e.status, e.body(), headers=e.headers())

    # -- translation ---------------------------------------------------------
    def _translate(self, body: dict, headers,
                   chat: bool) -> TranslatedRequest:
        model = body.get("model")
        if model is not None and model != self.backend.model_name:
            raise InvalidRequestError(
                f"model {model!r} not found (serving "
                f"{self.backend.model_name!r})", status=404,
                param="model", code="model_not_found")
        prompt_ids = self._prompt_ids(body, chat)
        try:
            max_tokens = int(body.get("max_tokens", 16))
        except (TypeError, ValueError):
            raise InvalidRequestError("max_tokens must be an integer",
                                      param="max_tokens")
        if max_tokens < 1:
            raise InvalidRequestError("max_tokens must be >= 1",
                                      param="max_tokens")
        try:
            n = int(body.get("n", 1))
        except (TypeError, ValueError):
            raise InvalidRequestError("n must be an integer", param="n")
        if not 1 <= n <= 8:
            raise InvalidRequestError("n must be in 1..8", param="n")
        self._check_sampling(body)
        stops_text, stops_tokens = self._stops(body.get("stop"))
        pri = headers.get(PRIORITY_HEADER)
        if pri is None:
            # OpenAI-style passthrough: a `user` field naming an SLO
            # class rides into the scheduler like the native header
            user = body.get("user")
            if isinstance(user, str) and user in PRIORITY_CLASSES:
                pri = user
        deadline = reliability.Deadline.from_header(
            headers.get(reliability.DEADLINE_HEADER))
        prefix = "chatcmpl" if chat else "cmpl"
        return TranslatedRequest(
            rid=f"{prefix}-{uuid.uuid4().hex[:24]}",
            created=int(time.time()), chat=chat, prompt_ids=prompt_ids,
            max_tokens=max_tokens, n=n,
            stream=bool(body.get("stream", False)),
            stops_text=stops_text, stops_tokens=stops_tokens,
            priority=pri, deadline=deadline)

    def _prompt_ids(self, body: dict, chat: bool) -> List[int]:
        if chat:
            text = apply_chat_template(self.chat_family,
                                       body.get("messages"))
            if self.tokenizer is None:
                raise InvalidRequestError(
                    "chat needs a tokenizer: set "
                    "bigdl.llm.api.tokenizer (no tokenizer assets ship "
                    "with this environment; 'byte' is the "
                    "deterministic test implementation)",
                    param="messages")
            return [int(t) for t in self.tokenizer.encode(text)]
        prompt = body.get("prompt")
        if isinstance(prompt, str):
            if self.tokenizer is None:
                raise InvalidRequestError(
                    "text prompts need a tokenizer: send a token-id "
                    "array, or set bigdl.llm.api.tokenizer",
                    param="prompt")
            return [int(t) for t in self.tokenizer.encode(prompt)]
        if isinstance(prompt, list) and prompt and \
                all(isinstance(t, int) and not isinstance(t, bool)
                    for t in prompt):
            return list(prompt)
        raise InvalidRequestError(
            "prompt must be a string or a non-empty token-id array",
            param="prompt")

    def _check_sampling(self, body: dict):
        """Reject sampling params that contradict the server-side
        config instead of silently ignoring them (see module doc)."""
        temp, top_k = self.backend.sampling()
        t = body.get("temperature")
        if t is not None and abs(float(t) - temp) > 1e-9:
            raise InvalidRequestError(
                f"sampling is server-configured (engine "
                f"temperature={temp}): omit temperature or match it",
                param="temperature")
        k = body.get("top_k")
        if k is not None and int(k) != top_k:
            raise InvalidRequestError(
                f"sampling is server-configured (engine top_k={top_k})"
                f": omit top_k or match it", param="top_k")
        p = body.get("top_p")
        if p is not None and abs(float(p) - 1.0) > 1e-9:
            raise InvalidRequestError(
                "top_p sampling is not supported (server-configured "
                "greedy/top-k engine): omit top_p or send 1.0",
                param="top_p")

    def _stops(self, stop):
        """Normalize OpenAI ``stop`` → (text stops, token stops)."""
        if stop is None:
            return [], []
        if isinstance(stop, str):
            stop = [stop]
        if not isinstance(stop, list) or not stop:
            raise InvalidRequestError(
                "stop must be a string, an array of strings, or an "
                "array of token-id arrays", param="stop")
        if all(isinstance(s, int) and not isinstance(s, bool)
               for s in stop):
            stop = [stop]          # one token-id sequence
        if all(isinstance(s, str) for s in stop):
            if len(stop) > 4:
                raise InvalidRequestError("at most 4 stop sequences",
                                          param="stop")
            if self.tokenizer is None:
                raise InvalidRequestError(
                    "string stop sequences need a tokenizer: send "
                    "token-id arrays, or set bigdl.llm.api.tokenizer",
                    param="stop")
            return list(stop), []
        if all(isinstance(s, list) and s and
               all(isinstance(t, int) and not isinstance(t, bool)
                   for t in s) for s in stop):
            if len(stop) > 4:
                raise InvalidRequestError("at most 4 stop sequences",
                                          param="stop")
            return [], [list(s) for s in stop]
        raise InvalidRequestError(
            "stop must be a string, an array of strings, or an "
            "array of token-id arrays", param="stop")

    # -- dispatch ------------------------------------------------------------
    def _run_choice(self, treq: TranslatedRequest, emit=None):
        """One engine generation: stop matching + incremental emission.
        ``emit(delta_ids, delta_text)`` fires once per drained token
        group (either side may be None depending on tokenizer/stop
        mode). Returns ``(tokens_generated, finish_reason)``."""
        text_mode = bool(treq.stops_text)
        matcher = StopMatcher(treq.stops_text if text_mode
                              else treq.stops_tokens)
        generated: List[int] = []

        def on_delta(new_ids):
            generated.extend(new_ids)
            if text_mode:
                piece = self.tokenizer.decode(new_ids)
                out, done = matcher.feed(piece)
                if emit is not None and out:
                    emit(None, out)
            else:
                out, done = matcher.feed(list(new_ids))
                if emit is not None and len(out):
                    txt = (self.tokenizer.decode(out)
                           if self.tokenizer is not None else None)
                    emit(list(out), txt)
            if done:
                raise StreamAbort("stop sequence matched")

        stream_needed = emit is not None or bool(
            treq.stops_text or treq.stops_tokens)
        try:
            toks, finish = self.backend.generate(
                treq.prompt_ids, treq.max_tokens, treq.priority,
                treq.deadline, on_delta if stream_needed else None)
            if not stream_needed:
                generated[:] = toks
        except StreamAbort as e:
            if e.client_gone:
                raise
            finish = "stop"
        if not matcher.hit:
            tail = matcher.flush()
            if emit is not None and tail is not None:
                if text_mode:
                    emit(None, tail)
                else:
                    txt = (self.tokenizer.decode(tail)
                           if self.tokenizer is not None else None)
                    emit(list(tail), txt)
        return generated, finish

    def _collect_choice(self, treq: TranslatedRequest, index: int):
        """Blocking variant: accumulate what streaming would emit."""
        ids: List[int] = []
        texts: List[str] = []

        def emit(delta_ids, delta_text):
            if delta_ids is not None:
                ids.extend(delta_ids)
            if delta_text is not None:
                texts.append(delta_text)

        generated, finish = self._run_choice(treq, emit)
        text_mode = bool(treq.stops_text)
        choice = {"index": index, "finish_reason": finish}
        if text_mode:
            choice["text"] = "".join(texts)
        else:
            choice["text"] = ("".join(texts)
                              if self.tokenizer is not None else "")
            choice["token_ids"] = ids
        return choice, len(generated)

    def _usage(self, treq: TranslatedRequest, completion: int) -> dict:
        return {"prompt_tokens": len(treq.prompt_ids),
                "completion_tokens": completion,
                "total_tokens": len(treq.prompt_ids) + completion}

    def _dispatch_blocking(self, handler, treq, path: str):
        choices = []
        completion = 0
        for i in range(treq.n):
            choice, ntok = self._collect_choice(treq, i)
            completion += ntok
            if treq.chat:
                choice["message"] = {"role": "assistant",
                                     "content": choice.pop("text")}
            choices.append(choice)
        handler._json(200, {
            "id": treq.rid,
            "object": "chat.completion" if treq.chat
                      else "text_completion",
            "created": treq.created,
            "model": self.backend.model_name,
            "choices": choices,
            "usage": self._usage(treq, completion)})

    def _dispatch_stream(self, handler, treq, path: str,
                         writer: SSEWriter):
        obj = ("chat.completion.chunk" if treq.chat
               else "text_completion")

        def chunk(choice):
            return {"id": treq.rid, "object": obj,
                    "created": treq.created,
                    "model": self.backend.model_name,
                    "choices": [choice]}

        completion = 0
        for i in range(treq.n):
            first = [True]

            def emit(delta_ids, delta_text, _i=i, _first=first):
                choice = {"index": _i, "finish_reason": None}
                if treq.chat:
                    delta = {"content": delta_text or ""}
                    if _first[0]:
                        delta["role"] = "assistant"
                        _first[0] = False
                    choice["delta"] = delta
                else:
                    choice["text"] = (delta_text if delta_text
                                      is not None else "")
                if delta_ids is not None:
                    choice["token_ids"] = list(delta_ids)
                writer.event(chunk(choice))

            generated, finish = self._run_choice(treq, emit)
            completion += len(generated)
            final = {"index": i, "finish_reason": finish}
            if treq.chat:
                final["delta"] = {}
            else:
                final["text"] = ""
            payload = chunk(final)
            if i == treq.n - 1:
                # usage rides the FINAL chunk (the tentpole contract)
                payload["usage"] = self._usage(treq, completion)
            writer.event(payload)
        writer.done()

"""Request-level failover primitives for the disaggregated router
(ISSUE 7 tentpole).

The original BigDL inherited Spark's task-retry/lineage story: a lost
worker cost latency, never answers (arXiv 1804.05839 §3). This module
is that layer for the TPU serving stack — the pieces
:class:`~bigdl_tpu.llm.worker.LLMRouter` composes when
``bigdl.llm.failover.enabled`` is on:

- :class:`RequestJournal` — the in-flight ledger: each routed request's
  prompt plus every token drained so far. On a decode-backend failure
  the router re-dispatches ``prompt + generated_so_far`` to another
  backend; greedy decoding is deterministic, so the resumed suffix is
  bit-identical to the tokens the dead worker would have produced, and
  the PR 5 radix cache / PR 6 host tier turn the resume into a cheap
  suffix re-prefill.
- :class:`HealthProber` — a background thread polling each backend's
  ``/healthz`` so ``_pick`` routes on *observed* health (a watchdog-
  tripped worker answers 503 and is drained before a request has to
  die on it), and pool membership can change without a restart.
- :class:`LatencyTracker` / :class:`HedgePolicy` — the p95 estimator
  and the hedge budget behind hedged dispatch: a prefill/decode call
  slower than the stage's observed p95 is duplicated to a second
  backend, first success wins, the loser is cancelled
  (:class:`Canceller` closes its connection; the worker aborts the
  request and releases its KV).
- :func:`run_hedged` — the generic first-success-wins runner.

Everything here is pure host-side plumbing: no jax, no engine state.
With failover disabled none of it is constructed (the structurally-
absent contract the disabled-mode tests assert).
"""

from __future__ import annotations

import collections
import itertools
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple


# ---------------------------------------------------------------------------
# Request journal
# ---------------------------------------------------------------------------

class StreamAbort(Exception):
    """Raised *through* a decode stream to tear it down without a
    failover retry (ISSUE 20): the OpenAI gateway's journal listener
    raises it when the SSE client hung up (``client_gone=True`` — the
    request is aborted, slot + KV pages free) or when a ``stop``
    sequence is satisfied mid-stream (the answer is complete; the rest
    of the token budget would be wasted work). Never a breaker failure
    and never a failover attempt — the backend did nothing wrong."""

    def __init__(self, reason: str, client_gone: bool = False):
        super().__init__(reason)
        self.client_gone = client_gone


class JournalEntry:
    """One in-flight routed request: the resume state failover needs."""

    __slots__ = ("id", "prompt_ids", "max_new_tokens", "tokens",
                 "attempts", "hedges", "created_at", "finish_reason",
                 "token_times", "priority", "listener")

    def __init__(self, entry_id: int, prompt_ids: List[int],
                 max_new_tokens: int, priority: Optional[str] = None):
        self.id = entry_id
        self.prompt_ids = list(prompt_ids)
        self.max_new_tokens = int(max_new_tokens)
        # SLO class as received on the wire (ISSUE 17); None when the
        # client sent no X-BigDL-Priority header — the journal never
        # normalizes, the engine does
        self.priority = priority
        self.tokens: List[int] = []       # drained so far (all attempts)
        self.attempts = 0                 # decode dispatches issued
        self.hedges = 0
        self.created_at = time.monotonic()
        self.finish_reason: Optional[str] = None
        # client-visible arrival stamp per token (ISSUE 12 SLO
        # accounting): aligned with ``tokens``, written by ``drained``
        # only for the indices an update actually extends — so a
        # resume's replayed prefix and a hedge twin's echo never
        # re-stamp a token, and the failover recovery gap shows up as
        # one honest inter-token sample
        self.token_times: List[float] = []
        # journal→SSE relay (ISSUE 20): an optional callable fired
        # from ``drained`` with exactly the newly-extended token slice.
        # Because it sits INSIDE the exactly-once growth guard, the
        # gateway's SSE chunks and the SLO arrival stamps are the same
        # accounting — a hedge twin's echo or a resume's replayed
        # prefix can no more double-emit a chunk than double-stamp a
        # token. May raise :class:`StreamAbort` to tear down the
        # attempt (client disconnect / stop satisfied).
        self.listener: Optional[Callable[[List[int]], None]] = None

    @property
    def remaining(self) -> int:
        return max(self.max_new_tokens - len(self.tokens), 0)

    def resume_prompt(self) -> List[int]:
        """What a re-dispatch sends: the original prompt plus every
        token already delivered — the radix cache on the new backend
        sees it as one long cached prefix."""
        return self.prompt_ids + self.tokens

    def drained(self, cumulative: List[int], base: int = 0):
        """Record a stream chunk's CUMULATIVE token list for the
        attempt that started at ``base`` tokens. Idempotent: stream
        chunks repeat everything drained so far, so shorter/equal
        updates (a hedge twin behind the winner) are no-ops — a plain
        ``extend`` here would duplicate tokens and corrupt
        :meth:`resume_prompt` on the next failover (and double-stamp
        ITL samples, ISSUE 12)."""
        if base + len(cumulative) > len(self.tokens):
            # the guard means tokens only ever GROW, so stamping the
            # tail up to the new length covers exactly the indices
            # this update added
            prev = len(self.tokens)
            self.tokens[base:] = [int(t) for t in cumulative]
            now = time.monotonic()
            while len(self.token_times) < len(self.tokens):
                self.token_times.append(now)
            if self.listener is not None:
                # one relay call per drained token group (ISSUE 20);
                # the slice is exactly what this update added
                self.listener(self.tokens[prev:])


class RequestJournal:
    """Thread-safe ledger of in-flight routed requests. The router adds
    an entry at admission, updates it as tokens drain, and removes it on
    completion — ``inflight()`` is what ``/healthz`` and the journal
    gauge report. Only constructed when failover is enabled."""

    def __init__(self):
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._entries: Dict[int, JournalEntry] = {}
        self.completed = 0
        self.failovers = 0                # re-dispatches after failure
        self.tokens_resumed = 0           # tokens carried across them

    def add(self, prompt_ids, max_new_tokens: int,
            priority: Optional[str] = None) -> JournalEntry:
        ent = JournalEntry(next(self._ids), prompt_ids, max_new_tokens,
                           priority=priority)
        with self._lock:
            self._entries[ent.id] = ent
        return ent

    def record_failover(self, ent: JournalEntry):
        with self._lock:
            self.failovers += 1
            self.tokens_resumed += len(ent.tokens)
        # same site as the ledger: the flight cross-check asserts
        # failover events reconcile exactly with the router counter
        # (trace id picked up from the routing thread's ambient context)
        from bigdl_tpu.observability import flight
        flight.record("failover", entry=ent.id,
                      tokens_resumed=len(ent.tokens),
                      attempt=ent.attempts)

    def complete(self, ent: JournalEntry):
        with self._lock:
            self._entries.pop(ent.id, None)
            self.completed += 1

    def inflight(self) -> int:
        with self._lock:
            return len(self._entries)

    def snapshot(self) -> List[dict]:
        with self._lock:
            return [dict(
                {"id": e.id, "prompt_tokens": len(e.prompt_ids),
                 "tokens_drained": len(e.tokens),
                 "attempts": e.attempts, "hedges": e.hedges,
                 "age_s": round(time.monotonic() - e.created_at, 3)},
                **({"priority": e.priority}
                   if e.priority is not None else {}))
                    for e in self._entries.values()]


# ---------------------------------------------------------------------------
# Active health model
# ---------------------------------------------------------------------------

class HealthProber:
    """Background ``/healthz`` poller feeding live pool membership.

    ``targets_fn`` returns the current ``[(addr, role), ...]`` snapshot
    (pools are mutable via the router's admin endpoint, so the prober
    re-reads them every sweep). A backend is healthy until a probe says
    otherwise — a freshly added backend is immediately routable, and a
    worker whose watchdog tripped (``/healthz`` 503) leaves the pool
    within one interval instead of eating a live request first.
    ``on_probe(addr, role, healthy, body)`` is the router's gauge hook.
    """

    def __init__(self, targets_fn: Callable[[], List[Tuple[Any, str]]],
                 interval: float = 0.5, timeout: float = 2.0,
                 on_probe: Optional[Callable] = None):
        self._targets_fn = targets_fn
        self.interval = interval
        self.timeout = timeout
        self._on_probe = on_probe
        self._lock = threading.Lock()
        self._status: Dict[Any, bool] = {}
        # last observed healthz verdict string (ISSUE 15 satellite):
        # DRAINING is not DEAD — a draining backend finishes its
        # in-flight streams and must never trip a breaker or trigger
        # failover; it just takes no new work. "dead" = the probe
        # itself failed (connection refused / timeout).
        self._states: Dict[Any, str] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.probes = 0

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "HealthProber":
        self._thread = threading.Thread(target=self._loop,
                                        name="bigdl-router-prober",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.timeout + 1.0)

    def _loop(self):
        while not self._stop.wait(self.interval):
            try:
                self.probe_now()
            except Exception:   # noqa: BLE001 — the prober never dies
                pass

    # -- probing -------------------------------------------------------------
    def _probe_one(self, addr) -> Tuple[bool, dict]:
        import http.client
        import json
        conn = http.client.HTTPConnection(addr[0], addr[1],
                                          timeout=self.timeout)
        try:
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            raw = resp.read()
            try:
                body = json.loads(raw.decode())
            except ValueError:
                body = {}
            return resp.status == 200, body
        finally:
            conn.close()

    def probe_now(self):
        """One synchronous sweep over the current targets (also the
        tests' fake clock: no sleeping on the poll interval)."""
        for addr, role in list(self._targets_fn()):
            if self._stop.is_set():
                return
            try:
                healthy, body = self._probe_one(addr)
                state = str(body.get("status") or
                            ("ok" if healthy else "unhealthy"))
            except Exception:   # noqa: BLE001 — dead = unhealthy
                healthy, body, state = False, {}, "dead"
            with self._lock:
                self._status[addr] = healthy
                self._states[addr] = state
            self.probes += 1
            if self._on_probe is not None:
                try:
                    self._on_probe(addr, role, healthy, body)
                except Exception:   # noqa: BLE001
                    pass

    def healthy(self, addr) -> bool:
        """Unprobed backends default healthy: a just-added backend must
        be routable before the first sweep reaches it."""
        with self._lock:
            return self._status.get(addr, True)

    def state(self, addr) -> str:
        """Last observed verdict: ``"ok"`` / ``"draining"`` /
        ``"stalled"`` / ``"unhealthy"`` / ``"dead"`` (unprobed backends
        are ``"ok"`` — same default as :meth:`healthy`). The router's
        drain handling branches on this: DRAINING backends finish
        their in-flight work and are simply not picked; only the other
        non-ok states mean failover-now."""
        with self._lock:
            return self._states.get(
                addr, "ok" if self._status.get(addr, True)
                else "unhealthy")

    def mark(self, addr, state: str):
        """Out-of-band verdict between sweeps (ISSUE 15): the router
        marks a backend ``"draining"`` the moment it sees the drain
        503 (or initiates the drain itself) instead of waiting an
        interval for the next probe; ``"ok"`` puts an
        abandoned-drain backend straight back into rotation. The next
        real probe overwrites either."""
        with self._lock:
            self._states[addr] = state
            self._status[addr] = state == "ok"

    def forget(self, addr):
        with self._lock:
            self._status.pop(addr, None)
            self._states.pop(addr, None)

    def status(self) -> Dict[str, bool]:
        with self._lock:
            return {f"{a[0]}:{a[1]}": h for a, h in self._status.items()}

    def states(self) -> Dict[str, str]:
        """Per-backend verdict strings (the ``/healthz`` prober block's
        drain-aware view)."""
        with self._lock:
            return {f"{a[0]}:{a[1]}": self._states.get(a, "ok")
                    for a in set(self._status) | set(self._states)}


# ---------------------------------------------------------------------------
# Hedging
# ---------------------------------------------------------------------------

class LatencyTracker:
    """Sliding window of call durations → the p95 the hedge delay is
    derived from. Plain insertion-sort quantile over ≤ ``maxlen``
    samples — this runs once per request, not per token."""

    def __init__(self, maxlen: int = 64):
        self._samples: "collections.deque[float]" = collections.deque(
            maxlen=maxlen)
        self._lock = threading.Lock()

    def record(self, seconds: float):
        with self._lock:
            self._samples.append(float(seconds))

    def quantile(self, q: float = 0.95) -> Optional[float]:
        with self._lock:
            if not self._samples:
                return None
            s = sorted(self._samples)
        idx = min(int(q * len(s)), len(s) - 1)
        return s[idx]

    def __len__(self):
        with self._lock:
            return len(self._samples)


class HedgePolicy:
    """When and whether to hedge. The delay is the stage's observed p95
    (floored at ``min.delay.ms``) unless ``delay.ms`` pins it; the
    budget caps issued hedges at ``budget`` × routed requests (+1 so a
    cold router can still hedge its first straggler)."""

    def __init__(self, enabled: bool, delay_ms: float = 0.0,
                 min_delay_ms: float = 50.0, budget: float = 0.1):
        self.enabled = enabled
        self.delay_ms = delay_ms
        self.min_delay_ms = min_delay_ms
        self.budget = budget
        self._lock = threading.Lock()
        self.requests = 0
        self.hedges = 0

    def note_request(self):
        with self._lock:
            self.requests += 1

    def allow(self) -> bool:
        if not self.enabled:
            return False
        with self._lock:
            return self.hedges < self.budget * max(self.requests, 1) + 1

    def note_hedge(self):
        with self._lock:
            self.hedges += 1

    def delay_for(self, tracker: LatencyTracker) -> float:
        """Seconds to wait before duplicating the call."""
        if self.delay_ms and self.delay_ms > 0:
            return self.delay_ms / 1000.0
        p95 = tracker.quantile(0.95)
        floor = self.min_delay_ms / 1000.0
        return max(p95 if p95 is not None else floor, floor)


class Canceller:
    """Cancellation handle an attempt registers its live connection
    with. ``cancel()`` closes it from another thread — the loser of a
    hedge race sees its socket die, and the worker aborts the request
    (releasing its KV) when the stream write fails."""

    def __init__(self):
        self._lock = threading.Lock()
        self._conn = None
        self.cancelled = False

    def attach(self, conn):
        with self._lock:
            self._conn = conn
            if self.cancelled:
                self._close_locked()

    def _close_locked(self):
        conn, self._conn = self._conn, None
        if conn is not None:
            try:
                conn.close()
            except Exception:   # noqa: BLE001
                pass

    def cancel(self):
        with self._lock:
            self.cancelled = True
            self._close_locked()


def run_hedged(primary: Callable[[Canceller], Any],
               hedge: Optional[Callable[[Canceller], Any]],
               delay: float,
               on_hedge: Optional[Callable[[], None]] = None,
               prefer: Optional[Tuple[type, ...]] = None
               ) -> Tuple[Any, str]:
    """First-success-wins runner. ``primary``/``hedge`` take a
    :class:`Canceller` and either return a result or raise.

    Returns ``(result, outcome)`` with outcome one of ``"primary"``
    (no hedge launched), ``"primary_won"`` / ``"hedge_won"`` (hedge
    launched; the named attempt finished successfully first — the
    loser is cancelled). If every launched attempt fails the last
    error propagates (the router's failover loop handles it) —
    except that an error matching ``prefer`` wins over one that
    doesn't: the caller's backend-verdict exceptions (a 4xx to relay,
    a 503 shed) must not be masked by the other twin's later
    transport error, which would turn a should-be-relayed verdict
    into pointless failover retries. A fast primary *failure* before
    the delay is NOT hedged: hedging tames stragglers; failover
    handles failures.
    """
    if hedge is None:
        return primary(Canceller()), "primary"
    results: "queue.Queue[Tuple[int, str, Any]]" = queue.Queue()
    cancellers = (Canceller(), Canceller())

    def runner(idx: int, fn: Callable[[Canceller], Any]):
        try:
            results.put((idx, "ok", fn(cancellers[idx])))
        except BaseException as e:  # noqa: BLE001
            results.put((idx, "err", e))

    threading.Thread(target=runner, args=(0, primary),
                     daemon=True).start()
    try:
        first = results.get(timeout=max(delay, 0.0))
    except queue.Empty:
        first = None
    pending = 1
    hedged = False
    if first is None:
        hedged = True
        pending += 1
        if on_hedge is not None:
            on_hedge()
        threading.Thread(target=runner, args=(1, hedge),
                         daemon=True).start()
    last_err: Optional[BaseException] = None
    while True:
        idx, status, val = first if first is not None else results.get()
        first = None
        pending -= 1
        if status == "ok":
            # cancel the straggler; its worker aborts + releases KV
            cancellers[1 - idx].cancel()
            if not hedged:
                return val, "primary"
            return val, ("primary_won" if idx == 0 else "hedge_won")
        if last_err is None or prefer is None \
                or not isinstance(last_err, prefer):
            last_err = val
        if pending == 0:
            raise last_err

"""Wire protocol for the FL server/client: length-prefixed messages of
JSON structure + raw numpy buffers over TCP.

The reference uses gRPC/protobuf services (FLServer/NNService/PSIService);
we keep the same message shapes over a simpler transport. Crucially the
format is **data-only** — federated peers are across a trust boundary, so
the wire format must not be able to execute code on decode (pickle would).
Supported values: None/bool/int/float/str/bytes, lists/tuples, dicts with
str keys, and numpy arrays of a whitelisted numeric dtype. Message size is
capped at :data:`MAX_MESSAGE_BYTES`.

Layout per message::

    >I total_len | >I header_len | header JSON (utf-8) | raw array/bytes blobs

The header JSON mirrors the object tree; array/bytes leaves are replaced by
``{"__blob__": i, "dtype": ..., "shape": ...}`` descriptors indexing the
blob section in order.
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Any, List

import numpy as np


def _np_dtype(name: str) -> np.dtype:
    if name == "bfloat16":  # not a stock numpy dtype; jax ships ml_dtypes
        import ml_dtypes
        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)

MAX_MESSAGE_BYTES = 256 * 1024 * 1024

_ALLOWED_DTYPES = {
    "float16", "float32", "float64", "bfloat16",
    "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64", "bool",
}


def _encode(obj: Any, blobs: List[bytes]) -> Any:
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, bytes):
        blobs.append(obj)
        return {"__blob__": len(blobs) - 1, "dtype": "bytes", "shape": None,
                "size": len(obj)}
    if isinstance(obj, (list, tuple)):
        node = [_encode(v, blobs) for v in obj]
        return node if isinstance(obj, list) else {"__tuple__": node}
    if isinstance(obj, dict):
        out = {}
        for k, v in obj.items():
            if not isinstance(k, str):
                raise TypeError(f"dict keys must be str, got {type(k)}")
            if k.startswith("__") and k.endswith("__"):
                raise ValueError(f"reserved key name on the wire: {k!r}")
            out[k] = _encode(v, blobs)
        return out
    arr = np.asarray(obj)
    name = arr.dtype.name
    if name not in _ALLOWED_DTYPES:
        raise TypeError(f"dtype {name} not allowed on the FL wire")
    raw = np.ascontiguousarray(arr).tobytes()
    blobs.append(raw)
    return {"__blob__": len(blobs) - 1, "dtype": name,
            "shape": list(arr.shape), "size": len(raw)}


def _decode(node: Any, blobs: List[bytes]) -> Any:
    if node is None or isinstance(node, (bool, int, float, str)):
        return node
    if isinstance(node, list):
        return [_decode(v, blobs) for v in node]
    if isinstance(node, dict):
        if "__tuple__" in node:
            return tuple(_decode(v, blobs) for v in node["__tuple__"])
        if "__blob__" in node:
            raw = blobs[node["__blob__"]]
            if node["dtype"] == "bytes":
                return raw
            if node["dtype"] not in _ALLOWED_DTYPES:
                raise TypeError(f"dtype {node['dtype']} not allowed")
            arr = np.frombuffer(raw, dtype=_np_dtype(node["dtype"]))
            return arr.reshape(node["shape"]).copy()
        return {k: _decode(v, blobs) for k, v in node.items()}
    raise TypeError(f"undecodable node type {type(node)}")


def dumps(obj: Any) -> bytes:
    blobs: List[bytes] = []
    header = json.dumps(_encode(obj, blobs)).encode("utf-8")
    body = struct.pack(">I", len(header)) + header + b"".join(blobs)
    if len(body) > MAX_MESSAGE_BYTES:
        raise ValueError(f"message of {len(body)} bytes exceeds cap")
    return body


def loads(data: bytes) -> Any:
    (hlen,) = struct.unpack(">I", data[:4])
    header = json.loads(data[4:4 + hlen].decode("utf-8"))
    blob_section = data[4 + hlen:]
    # Re-slice the blob section in the order descriptors were emitted.
    # The header is attacker-controlled: indices must be exactly 0..n-1,
    # sizes non-negative, and the section length must match exactly.
    sizes = _blob_sizes(header)
    if any(s < 0 for s in sizes):
        raise ValueError("negative blob size in message header")
    if sum(sizes) != len(blob_section):
        raise ValueError(
            f"blob section is {len(blob_section)} bytes but header "
            f"declares {sum(sizes)}")
    blobs: List[bytes] = []
    offset = 0
    for size in sizes:
        blobs.append(blob_section[offset:offset + size])
        offset += size
    return _decode(header, blobs)


def _blob_sizes(node: Any) -> List[int]:
    """Walk the header collecting each blob's byte size by blob index.

    Raises ``ValueError`` unless the blob indices are exactly ``0..n-1``
    with no duplicates (the header comes from an untrusted peer).
    """
    sizes: dict = {}

    def walk(n):
        if isinstance(n, list):
            for v in n:
                walk(v)
        elif isinstance(n, dict):
            if "__tuple__" in n:
                walk(n["__tuple__"])
            elif "__blob__" in n:
                idx = n["__blob__"]
                if not isinstance(idx, int) or idx in sizes:
                    raise ValueError("bad or duplicate blob index")
                if not isinstance(n.get("size"), int):
                    raise ValueError("missing blob size")
                sizes[idx] = n["size"]
            else:
                for v in n.values():
                    walk(v)

    walk(node)
    if sorted(sizes) != list(range(len(sizes))):
        raise ValueError("non-contiguous blob indices in message header")
    return [sizes[i] for i in sorted(sizes)]


def send_msg(sock: socket.socket, obj: Any):
    payload = dumps(obj)
    sock.sendall(struct.pack(">I", len(payload)) + payload)


def recv_msg(sock: socket.socket) -> Any:
    header = _recv_exact(sock, 4)
    (length,) = struct.unpack(">I", header)
    if length > MAX_MESSAGE_BYTES:
        raise ValueError(f"incoming message of {length} bytes exceeds cap")
    return loads(_recv_exact(sock, length))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf

"""Wire protocol: length-prefixed pickled dicts over TCP (the reference
uses gRPC protobuf services — FLServer/NNService/PSIService; same message
shapes, simpler transport)."""

from __future__ import annotations

import pickle
import socket
import struct
from typing import Any


def send_msg(sock: socket.socket, obj: Any):
    payload = pickle.dumps(obj)
    sock.sendall(struct.pack(">I", len(payload)) + payload)


def recv_msg(sock: socket.socket) -> Any:
    header = _recv_exact(sock, 4)
    (length,) = struct.unpack(">I", header)
    return pickle.loads(_recv_exact(sock, length))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf

"""FGBoost — federated gradient-boosted decision trees.

Reference: ``scala/ppml`` FGBoostServiceImpl / FGBoostRegression — the
headline PPML capability beyond FedAvg (SURVEY.md §2.8 PPML row): several
parties hold horizontal shards of the same feature space and jointly grow
one XGBoost-style ensemble, exchanging only **aggregated gradient/hessian
histograms** — never raw rows.

Mapping onto this rebuild's FLServer substrate (fl_server.py):
- histogram aggregation = the generic keyed barrier-reduce (``agg`` /
  op=sum), the role the reference's gRPC FGBoostService aggregator plays;
- global feature ranges for binning = one ``agg`` min/max round;
- every client computes the SAME split decisions from the identical
  aggregated histograms, so all parties end each round holding the same
  tree — there is no central model to download (matches the reference,
  where the server is a pure aggregator for the histogram protocol).

Trees are grown breadth-first to ``max_depth`` with second-order gains
(g = dL/dpred, h = d2L/dpred2; squared loss for regression, logloss for
binary classification), leaf value -G/(H+lambda) — the standard XGBoost
update the reference implements natively.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import numpy as np

from bigdl_tpu.ppml.fl_client import FLClient


@dataclasses.dataclass
class _Node:
    feature: int = -1           # -1 = leaf
    threshold: float = 0.0      # split on x[feature] <= threshold
    value: float = 0.0          # leaf output
    left: int = -1              # child indices into the tree's node list
    right: int = -1


class _Tree:
    def __init__(self):
        self.nodes: List[_Node] = []

    def predict(self, X: np.ndarray) -> np.ndarray:
        out = np.empty(len(X), np.float64)
        for i, row in enumerate(X):
            n = 0
            while self.nodes[n].feature >= 0:
                nd = self.nodes[n]
                n = nd.left if row[nd.feature] <= nd.threshold else nd.right
            out[i] = self.nodes[n].value
        return out


class FGBoostRegression:
    """Federated GBDT regression (ref API: FGBoostRegression.fit/predict).

    Every participating party constructs one of these over its own
    ``FLClient`` and calls ``fit`` with its local shard; the calls
    synchronize through the server's histogram aggregation and return
    with identical ensembles.
    """

    _loss = "squared"

    def __init__(self, client: FLClient, n_estimators: int = 10,
                 max_depth: int = 4, learning_rate: float = 0.3,
                 n_bins: int = 32, reg_lambda: float = 1.0,
                 min_gain: float = 1e-6, model_id: str = "fgboost"):
        self.client = client
        self.n_estimators = n_estimators
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_bins = n_bins
        self.reg_lambda = reg_lambda
        self.min_gain = min_gain
        self.model_id = model_id
        self.trees: List[_Tree] = []
        self.base_score = 0.0
        self._bin_edges: Optional[np.ndarray] = None

    # -- gradients -----------------------------------------------------------
    def _grad_hess(self, y, pred):
        return pred - y, np.ones_like(y)

    def _init_base(self, y) -> float:
        # global mean via one sum-reduce of [sum_y, count]
        tot, cnt = self.client.agg(
            f"{self.model_id}:base",
            [np.array([y.sum()]), np.array([float(len(y))])], op="sum")
        return float(tot[0] / max(cnt[0], 1.0))

    # -- binning -------------------------------------------------------------
    def _global_bins(self, X: np.ndarray) -> np.ndarray:
        lo = self.client.agg(f"{self.model_id}:lo", [X.min(axis=0)],
                             op="min")[0]
        hi = self.client.agg(f"{self.model_id}:hi", [X.max(axis=0)],
                             op="max")[0]
        span = np.where(hi > lo, hi - lo, 1.0)
        # edges[f, b] = lo + (b+1)/B * span — bin b is x <= edges[f, b]
        steps = (np.arange(1, self.n_bins) / self.n_bins)
        return lo[:, None] + span[:, None] * steps[None, :]

    def _binize(self, X: np.ndarray) -> np.ndarray:
        F = X.shape[1]
        out = np.empty(X.shape, np.int32)
        for f in range(F):
            out[:, f] = np.searchsorted(self._bin_edges[f], X[:, f],
                                        side="left")
        return out

    # -- tree growth ---------------------------------------------------------
    def _grow_tree(self, t_idx: int, Xb, X, g, h) -> _Tree:
        tree = _Tree()
        F, B = X.shape[1], self.n_bins
        # frontier: (node_index, row_mask, depth)
        tree.nodes.append(_Node())
        frontier = [(0, np.ones(len(X), bool), 0)]
        while frontier:
            nxt = []
            for node_i, mask, depth in frontier:
                key = f"{self.model_id}:t{t_idx}:n{node_i}"
                hist_g = np.zeros((F, B))
                hist_h = np.zeros((F, B))
                rows = np.nonzero(mask)[0]
                for f in range(F):
                    np.add.at(hist_g[f], Xb[rows, f], g[rows])
                    np.add.at(hist_h[f], Xb[rows, f], h[rows])
                hist_g, hist_h = self.client.agg(key, [hist_g, hist_h],
                                                 op="sum")
                G, H = hist_g.sum(axis=1)[0], hist_h.sum(axis=1)[0]
                lam = self.reg_lambda
                node = tree.nodes[node_i]
                if depth >= self.max_depth or H <= 1.0:
                    node.value = float(-G / (H + lam)) * self.learning_rate
                    continue
                GL = np.cumsum(hist_g, axis=1)[:, :-1]
                HL = np.cumsum(hist_h, axis=1)[:, :-1]
                GR, HR = G - GL, H - HL
                gain = (GL ** 2 / (HL + lam) + GR ** 2 / (HR + lam)
                        - G ** 2 / (H + lam))
                gain[HL < 1.0] = -np.inf
                gain[HR < 1.0] = -np.inf
                best = np.unravel_index(np.argmax(gain), gain.shape)
                if not np.isfinite(gain[best]) \
                        or gain[best] <= self.min_gain:
                    node.value = float(-G / (H + lam)) * self.learning_rate
                    continue
                f_best, b_best = int(best[0]), int(best[1])
                node.feature = f_best
                node.threshold = float(self._bin_edges[f_best, b_best])
                node.left = len(tree.nodes)
                tree.nodes.append(_Node())
                node.right = len(tree.nodes)
                tree.nodes.append(_Node())
                go_left = mask & (Xb[:, f_best] <= b_best)
                nxt.append((node.left, go_left, depth + 1))
                nxt.append((node.right, mask & ~go_left, depth + 1))
            frontier = nxt
        return tree

    # -- public API ----------------------------------------------------------
    def fit(self, X, y) -> "FGBoostRegression":
        X = np.asarray(X, np.float64)
        y = np.asarray(y, np.float64).ravel()
        self.base_score = self._init_base(y)
        self._bin_edges = self._global_bins(X)
        Xb = self._binize(X)
        pred = np.full(len(y), self.base_score)
        for t in range(self.n_estimators):
            g, h = self._grad_hess(y, pred)
            tree = self._grow_tree(t, Xb, X, g, h)
            self.trees.append(tree)
            pred += tree.predict(X)
        return self

    def _raw_predict(self, X) -> np.ndarray:
        X = np.asarray(X, np.float64)
        out = np.full(len(X), self.base_score)
        for tree in self.trees:
            out += tree.predict(X)
        return out

    def predict(self, X) -> np.ndarray:
        return self._raw_predict(X)


class FGBoostClassification(FGBoostRegression):
    """Binary federated GBDT classifier (logloss; ref FGBoostClassification)."""

    _loss = "logloss"

    def _grad_hess(self, y, pred):
        p = 1.0 / (1.0 + np.exp(-pred))
        return p - y, np.maximum(p * (1.0 - p), 1e-12)

    def _init_base(self, y) -> float:
        tot, cnt = self.client.agg(
            f"{self.model_id}:base",
            [np.array([y.sum()]), np.array([float(len(y))])], op="sum")
        p = float(np.clip(tot[0] / max(cnt[0], 1.0), 1e-6, 1 - 1e-6))
        return float(np.log(p / (1 - p)))

    def predict_proba(self, X) -> np.ndarray:
        return 1.0 / (1.0 + np.exp(-self._raw_predict(X)))

    def predict(self, X) -> np.ndarray:
        return (self.predict_proba(X) >= 0.5).astype(np.int64)

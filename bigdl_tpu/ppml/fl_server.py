"""FLServer (ref: scala/ppml FLServer — gRPC NNService/PSIService with
client-number-gated synchronous rounds and FedAvg aggregation)."""

from __future__ import annotations

import hashlib
import socket
import threading
from typing import Dict, List, Optional

import numpy as np

from bigdl_tpu.ppml.protocol import recv_msg, send_msg


class FLServer:
    def __init__(self, client_num: int = 2, port: int = 0,
                 host: str = "127.0.0.1"):
        self.client_num = client_num
        self.host = host
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._conn_lock = threading.Lock()
        #: live client sockets — stop() severs them so handler threads
        #: blocked in recv_msg actually exit before the joins below
        self._conns: List[socket.socket] = []

        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # nn aggregation state
        self._version = 0
        self._uploads: Dict[str, List[np.ndarray]] = {}
        self._aggregated: Optional[List[np.ndarray]] = None
        # psi state
        self._psi_salt = "bigdl_tpu_psi"
        self._psi_sets: Dict[str, set] = {}
        self._psi_result: Optional[set] = None
        # barrier-reduce + kv state (FGBoost/VFL)
        self._agg_pending: Dict[str, Dict[str, list]] = {}
        self._agg_results: Dict[str, list] = {}
        self._agg_delivered: Dict[str, int] = {}
        self._kv: Dict[str, object] = {}
        self._kv_expect: Dict[str, int] = {}

    # -- lifecycle -----------------------------------------------------------
    def build(self):  # ref API name
        return self

    def start(self):
        self._sock.listen(16)
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def stop(self):
        self._stop.set()
        try:
            # shutdown BEFORE close: on Linux, close() alone does not
            # wake a thread blocked in accept(); shutdown() does
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        with self._conn_lock:
            conns, self._conns = self._conns, []
            threads, self._threads = self._threads, []
        for s in conns:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass
        for t in threads:
            t.join(timeout=1.0)

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve_client,
                                 args=(conn,), daemon=True)
            t.start()
            with self._conn_lock:
                self._threads = [c for c in self._threads
                                 if c.is_alive()]
                self._threads.append(t)
                self._conns = [s for s in self._conns
                               if s.fileno() >= 0]
                self._conns.append(conn)

    # -- per-connection handler ---------------------------------------------
    def _serve_client(self, conn: socket.socket):
        try:
            while not self._stop.is_set():
                try:
                    msg = recv_msg(conn)
                except (ValueError, TypeError, KeyError) as e:
                    # malformed message from an untrusted peer: reply with
                    # an error and drop the connection (the stream offset
                    # can no longer be trusted)
                    send_msg(conn, {"status": "error",
                                    "error": f"malformed message: {e}"})
                    return
                handler = getattr(self, f"_on_{msg['type']}", None)
                if handler is None:
                    send_msg(conn, {"status": "error",
                                    "error": f"unknown {msg['type']}"})
                    continue
                send_msg(conn, handler(msg))
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    # -- FedAvg rounds (ref: NNServiceImpl train logic) ----------------------
    def _on_upload(self, msg) -> dict:
        with self._cond:
            if msg["version"] != self._version:
                return {"status": "rejected", "version": self._version}
            self._uploads[msg["client_id"]] = msg["weights"]
            if len(self._uploads) >= self.client_num:
                ws = list(self._uploads.values())
                self._aggregated = [
                    np.mean([w[i] for w in ws], axis=0)
                    for i in range(len(ws[0]))]
                self._uploads.clear()
                self._version += 1
                self._cond.notify_all()
            return {"status": "ok", "version": self._version}

    def _on_download(self, msg) -> dict:
        with self._cond:
            target = msg["version"]
            ok = self._cond.wait_for(
                lambda: self._version > target or self._stop.is_set(),
                timeout=msg.get("timeout", 60.0))
            if not ok or self._aggregated is None:
                return {"status": "timeout"}
            return {"status": "ok", "version": self._version,
                    "weights": self._aggregated}

    # -- PSI (ref: PSIServiceImpl; salted-hash intersection) -----------------
    def _on_psi_salt(self, msg) -> dict:
        return {"status": "ok", "salt": self._psi_salt}

    def _on_psi_upload(self, msg) -> dict:
        with self._cond:
            self._psi_sets[msg["client_id"]] = set(msg["hashed_ids"])
            if len(self._psi_sets) >= self.client_num:
                sets = list(self._psi_sets.values())
                inter = sets[0]
                for s in sets[1:]:
                    inter = inter & s
                self._psi_result = inter
                self._cond.notify_all()
            return {"status": "ok"}

    def _on_psi_download(self, msg) -> dict:
        with self._cond:
            ok = self._cond.wait_for(
                lambda: self._psi_result is not None
                or self._stop.is_set(),
                timeout=msg.get("timeout", 60.0))
            if not ok or self._psi_result is None:
                return {"status": "timeout"}
            return {"status": "ok",
                    "intersection": sorted(self._psi_result)}

    # -- generic keyed barrier-reduce (FGBoost/VFL substrate) ----------------
    # Every client submits a payload under ``key``; once ``client_num``
    # payloads arrive, the server reduces them (sum/mean/min/max,
    # elementwise over array lists) and every submitter's blocked call
    # returns the reduced result. This is the role FGBoostServiceImpl's
    # gRPC aggregator plays in the reference: the server only ever sees
    # aggregated statistics, never raw rows.
    _REDUCERS = {
        "sum": lambda ps: [np.sum([p[i] for p in ps], axis=0)
                           for i in range(len(ps[0]))],
        "mean": lambda ps: [np.mean([p[i] for p in ps], axis=0)
                            for i in range(len(ps[0]))],
        "min": lambda ps: [np.min([p[i] for p in ps], axis=0)
                           for i in range(len(ps[0]))],
        "max": lambda ps: [np.max([p[i] for p in ps], axis=0)
                           for i in range(len(ps[0]))],
        "concat": lambda ps: [np.concatenate([p[i] for p in ps])
                              for i in range(len(ps[0]))],
    }

    def _on_agg(self, msg) -> dict:
        key = str(msg["key"])
        op = msg.get("op", "sum")
        if op not in self._REDUCERS:
            return {"status": "error", "error": f"unknown op {op!r}"}
        n = int(msg.get("n_parties", self.client_num))
        with self._cond:
            pend = self._agg_pending.setdefault(key, {})
            pend[msg["client_id"]] = msg["payload"]
            if len(pend) >= n:
                self._agg_results[key] = self._REDUCERS[op](
                    [pend[c] for c in sorted(pend)])
                del self._agg_pending[key]
                self._cond.notify_all()
            ok = self._cond.wait_for(
                lambda: key in self._agg_results or self._stop.is_set(),
                timeout=msg.get("timeout", 120.0))
            if not ok or key not in self._agg_results:
                return {"status": "timeout"}
            result = self._agg_results[key]
            self._agg_delivered[key] = self._agg_delivered.get(key, 0) + 1
            if self._agg_delivered[key] >= n:   # all parties served: GC
                del self._agg_results[key]
                del self._agg_delivered[key]
            return {"status": "ok", "payload": result}

    def _on_put(self, msg) -> dict:
        """Blocking kv broadcast: one party puts, any party gets. With
        ``expect`` = N the entry is garbage-collected after N gets (the
        VFL dz broadcast sets it to client_num - 1)."""
        with self._cond:
            key = str(msg["key"])
            self._kv[key] = msg["payload"]
            expect = msg.get("expect")
            if expect is not None:
                self._kv_expect[key] = int(expect)
            self._cond.notify_all()
            return {"status": "ok"}

    def _on_get(self, msg) -> dict:
        key = str(msg["key"])
        with self._cond:
            ok = self._cond.wait_for(
                lambda: key in self._kv or self._stop.is_set(),
                timeout=msg.get("timeout", 120.0))
            if not ok or key not in self._kv:
                return {"status": "timeout"}
            payload = self._kv[key]
            if key in self._kv_expect:
                self._kv_expect[key] -= 1
                if self._kv_expect[key] <= 0:
                    del self._kv[key]
                    del self._kv_expect[key]
            return {"status": "ok", "payload": payload}

    @staticmethod
    def hash_id(value: str, salt: str) -> str:
        return hashlib.sha256((salt + str(value)).encode()).hexdigest()

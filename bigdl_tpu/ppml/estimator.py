"""FLEstimator (ref: python ppml HFL logistic/linear regression — local
epochs on the party's data, FedAvg sync each round via FLClient)."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import numpy as np

from bigdl_tpu.nn.module import Criterion, Module
from bigdl_tpu.ppml.fl_client import FLClient


class FLEstimator:
    def __init__(self, model: Module, criterion: Criterion,
                 client: FLClient, lr: float = 0.1):
        self.model = model
        self.criterion = criterion
        self.client = client
        self.lr = lr

    def fit(self, x: np.ndarray, y: np.ndarray, rounds: int = 5,
            local_epochs: int = 1, batch_size: int = 32):
        from bigdl_tpu.optim.optim_method import SGD
        from bigdl_tpu.optim.optimizer import LocalOptimizer
        from bigdl_tpu.optim.trigger import Trigger

        for _ in range(rounds):
            opt = LocalOptimizer(self.model,
                                 (np.asarray(x), np.asarray(y)),
                                 self.criterion, batch_size=batch_size,
                                 end_trigger=Trigger.max_epoch(
                                     local_epochs))
            opt.set_optim_method(SGD(learning_rate=self.lr))
            opt.optimize()
            flat = jax.tree_util.tree_leaves(self.model.parameters_dict())
            averaged = self.client.sync_round(
                [np.asarray(w) for w in flat])
            tree = jax.tree_util.tree_structure(
                self.model.parameters_dict())
            self.model.load_parameters_dict(
                jax.tree_util.tree_unflatten(tree, averaged))
        return self

    def predict(self, x: np.ndarray) -> np.ndarray:
        return np.asarray(self.model.evaluate().forward(np.asarray(x)))

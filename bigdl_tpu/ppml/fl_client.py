"""FLClient (ref: scala/ppml FLClient + python ppml fl context)."""

from __future__ import annotations

import socket
from typing import List, Optional, Sequence

import numpy as np

from bigdl_tpu.ppml.fl_server import FLServer
from bigdl_tpu.ppml.protocol import recv_msg, send_msg


class FLClient:
    def __init__(self, client_id: str, target: str = "127.0.0.1:8980"):
        host, port = target.rsplit(":", 1)
        self.client_id = client_id
        self._sock = socket.create_connection((host, int(port)))
        self.version = 0

    def _call(self, msg: dict) -> dict:
        msg["client_id"] = self.client_id
        send_msg(self._sock, msg)
        return recv_msg(self._sock)

    # -- FedAvg --------------------------------------------------------------
    def upload(self, weights: Sequence[np.ndarray]) -> dict:
        return self._call({"type": "upload", "version": self.version,
                           "weights": [np.asarray(w) for w in weights]})

    def download(self, timeout: float = 60.0) -> List[np.ndarray]:
        resp = self._call({"type": "download", "version": self.version,
                           "timeout": timeout})
        if resp["status"] != "ok":
            raise TimeoutError("FL round did not complete")
        self.version = resp["version"]
        return resp["weights"]

    def sync_round(self, weights: Sequence[np.ndarray],
                   timeout: float = 60.0) -> List[np.ndarray]:
        """upload local weights, wait for the FedAvg of this round."""
        self.upload(weights)
        return self.download(timeout)

    # -- PSI -----------------------------------------------------------------
    def psi_get_salt(self) -> str:
        return self._call({"type": "psi_salt"})["salt"]

    def psi_upload_set(self, ids: Sequence[str], salt: str):
        hashed = [FLServer.hash_id(i, salt) for i in ids]
        self._hash_to_id = dict(zip(hashed, ids))
        return self._call({"type": "psi_upload", "hashed_ids": hashed})

    def psi_download_intersection(self, timeout: float = 60.0):
        resp = self._call({"type": "psi_download", "timeout": timeout})
        if resp["status"] != "ok":
            raise TimeoutError("PSI did not complete")
        return sorted(self._hash_to_id[h] for h in resp["intersection"]
                      if h in self._hash_to_id)

    # -- keyed barrier-reduce + kv (FGBoost / VFL substrate) -----------------
    def agg(self, key: str, payload: Sequence[np.ndarray], op: str = "sum",
            n_parties: Optional[int] = None,
            timeout: float = 120.0) -> List[np.ndarray]:
        """Submit arrays under ``key``; block until every party has
        submitted; return the elementwise ``op``-reduction."""
        msg = {"type": "agg", "key": key, "op": op, "timeout": timeout,
               "payload": [np.asarray(p) for p in payload]}
        if n_parties is not None:
            msg["n_parties"] = n_parties
        resp = self._call(msg)
        if resp["status"] != "ok":
            raise TimeoutError(f"agg {key!r}: {resp}")
        return resp["payload"]

    def put(self, key: str, payload, expect: Optional[int] = None):
        msg = {"type": "put", "key": key, "payload": payload}
        if expect is not None:
            msg["expect"] = expect
        resp = self._call(msg)
        if resp["status"] != "ok":
            raise RuntimeError(f"put {key!r}: {resp}")

    def get(self, key: str, timeout: float = 120.0):
        resp = self._call({"type": "get", "key": key, "timeout": timeout})
        if resp["status"] != "ok":
            raise TimeoutError(f"get {key!r}: {resp}")
        return resp["payload"]

    def close(self):
        self._sock.close()

"""bigdl_tpu.ppml — privacy-preserving ML (ref: scala/ppml + python/ppml:
gRPC FL server/client with HFL/VFL linear models, FGBoost federated GBDT,
PSI, SGX enclaves).

Scope here: the federated-learning core — FLServer/FLClient (length-
prefixed pickle over TCP standing in for the reference's gRPC), FedAvg
aggregation, PSI (salted-hash intersection; the reference uses ECDH-PSI —
documented gap), and an FLEstimator that federates any of our nn models.
SGX/Gramine enclave packaging and KMS/attestation are hardware/deploy
tooling with no TPU-environment analog — documented as out of scope.
"""

from bigdl_tpu.ppml.fl_server import FLServer
from bigdl_tpu.ppml.fl_client import FLClient
from bigdl_tpu.ppml.estimator import FLEstimator

__all__ = ["FLServer", "FLClient", "FLEstimator"]

"""bigdl_tpu.ppml — privacy-preserving ML (ref: scala/ppml + python/ppml:
gRPC FL server/client with HFL/VFL linear models, FGBoost federated GBDT,
PSI, SGX enclaves).

Scope here: the federated-learning core — FLServer/FLClient (length-
prefixed JSON+blob wire over TCP standing in for the reference's gRPC;
no code execution on decode), FedAvg aggregation, PSI (salted-hash
intersection; the reference uses ECDH-PSI — documented gap), an
FLEstimator that federates any of our nn models, FGBoost federated GBDT
(histogram aggregation; FGBoostRegression/FGBoostClassification), and
VFL linear/logistic regression (partial-logit aggregation).
SGX/Gramine enclave packaging and KMS/attestation are hardware/deploy
tooling with no TPU-environment analog — documented as out of scope.
"""

from bigdl_tpu.ppml.fl_server import FLServer
from bigdl_tpu.ppml.fl_client import FLClient
from bigdl_tpu.ppml.estimator import FLEstimator
from bigdl_tpu.ppml.fgboost import FGBoostClassification, FGBoostRegression
from bigdl_tpu.ppml.vfl import VFLLinearRegression, VFLLogisticRegression

__all__ = ["FLServer", "FLClient", "FLEstimator", "FGBoostRegression",
           "FGBoostClassification", "VFLLinearRegression",
           "VFLLogisticRegression"]

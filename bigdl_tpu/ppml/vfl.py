"""Vertical federated linear / logistic regression.

Reference: ``scala/ppml`` VFL NN (VflLinearRegression /
VflLogisticRegression — SURVEY.md §2.8 PPML row): parties hold disjoint
FEATURE COLUMNS of the same (PSI-aligned) rows; exactly one party holds
the labels. Raw features never leave a party; what crosses the wire is:

- each step, every party's partial logits  z_p = X_p @ w_p + b_p,
  summed by the FLServer's barrier-reduce (``agg`` op=sum) — the same
  interaction the reference routes through its gRPC NN aggregator;
- the label party computes dL/dz from the summed logits and publishes it
  through the server kv (``put``/``get``); every party then forms its
  local gradient  dW_p = X_p^T dz / B  and updates locally.

Train loop semantics follow the reference: full-batch or mini-batch SGD,
deterministic batching so all parties iterate the same row order.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from bigdl_tpu.ppml.fl_client import FLClient


class VFLLinearRegression:
    """One party's view of a vertically-federated linear model."""

    _kind = "linear"

    def __init__(self, client: FLClient, n_local_features: int,
                 has_labels: bool = False, learning_rate: float = 0.05,
                 model_id: str = "vfl", seed: int = 0):
        self.client = client
        self.has_labels = has_labels
        self.lr = learning_rate
        self.model_id = model_id
        rs = np.random.RandomState(seed)
        self.w = rs.randn(n_local_features) * 0.01
        # peers expected to fetch each dz broadcast (for server-side GC)
        self._n_peers: Optional[int] = None
        # only the label party owns the global bias (so the summed logits
        # carry exactly one bias term)
        self.b = 0.0
        self.history: list = []
        self._pred_step = 0
        self._fit_round = 0

    # -- local pieces --------------------------------------------------------
    def _partial_logits(self, X) -> np.ndarray:
        z = X @ self.w
        if self.has_labels:
            z = z + self.b
        return z

    def _dz(self, z, y):
        """Label-party loss gradient dL/dz (mean-reduced later)."""
        return z - y

    def _loss(self, z, y) -> float:
        return float(np.mean((z - y) ** 2) / 2.0)

    # -- protocol ------------------------------------------------------------
    def fit(self, X, y: Optional[np.ndarray] = None, epochs: int = 10,
            batch_size: int = 0) -> "VFLLinearRegression":
        """Collective: every party calls fit with its column shard; only
        the label party passes ``y``."""
        X = np.asarray(X, np.float64)
        if self.has_labels:
            if y is None:
                raise ValueError("label party must pass y")
            y = np.asarray(y, np.float64).ravel()
        n = len(X)
        bs = batch_size or n
        step = 0
        # per-fit round tag: every party increments on each fit() call
        # (collective contract), so a later fit never reads a previous
        # fit's still-cached dz from the server kv
        rnd = self._fit_round
        self._fit_round += 1
        if self._n_peers is None:
            # party-count collective: every party contributes 1; the sum
            # is the party count, and peers-expected-to-fetch-dz is that
            # minus the label party itself — this arms the server-side kv
            # GC (fl_server._kv_expect) so dz entries are dropped once
            # every non-label party has fetched them
            total = self.client.agg(f"{self.model_id}:r{rnd}:nparties",
                                    [np.ones(1)], op="sum")[0]
            self._n_peers = max(int(round(float(total[0]))) - 1, 1)
        for epoch in range(epochs):
            for start in range(0, n, bs):
                sl = slice(start, min(start + bs, n))
                Xb = X[sl]
                z = self.client.agg(
                    f"{self.model_id}:r{rnd}:z:{step}",
                    [self._partial_logits(Xb)], op="sum")[0]
                if self.has_labels:
                    dz = self._dz(z, y[sl]) / len(Xb)
                    self.client.put(f"{self.model_id}:r{rnd}:dz:{step}",
                                    [dz], expect=self._n_peers)
                    self.history.append(self._loss(z, y[sl]))
                else:
                    dz = self.client.get(
                        f"{self.model_id}:r{rnd}:dz:{step}")[0]
                self.w -= self.lr * (Xb.T @ dz)
                if self.has_labels:
                    self.b -= self.lr * float(dz.sum())
                step += 1
        return self

    def predict(self, X) -> np.ndarray:
        """Collective: every party contributes its partial logits; all
        parties receive the summed prediction."""
        X = np.asarray(X, np.float64)
        z = self.client.agg(f"{self.model_id}:pred:{self._pred_step}",
                            [self._partial_logits(X)], op="sum")[0]
        self._pred_step += 1
        return self._link(z)

    def _link(self, z):
        return z


class VFLLogisticRegression(VFLLinearRegression):
    """Vertically-federated binary logistic regression."""

    _kind = "logistic"

    @staticmethod
    def _sigmoid(z):
        return 1.0 / (1.0 + np.exp(-np.clip(z, -30, 30)))

    def _dz(self, z, y):
        return self._sigmoid(z) - y

    def _loss(self, z, y) -> float:
        p = np.clip(self._sigmoid(z), 1e-12, 1 - 1e-12)
        return float(-np.mean(y * np.log(p) + (1 - y) * np.log(1 - p)))

    def _link(self, z):
        return self._sigmoid(z)

    def predict_class(self, X) -> np.ndarray:
        return (self.predict(X) >= 0.5).astype(np.int64)

"""SparseTensor — COO sparse tensor (ref: S:dllib/tensor/
SparseTensor.scala — backs the reference's sparse recsys layers;
round 1 had nothing sparse).

TPU-first design: a frozen ``(indices, values, shape)`` triple. XLA has
no native sparse formats, so compute paths lower to dense gathers /
``segment_sum`` — which on TPU is exactly how the MXU wants embedding
workloads expressed (the reference's CPU CSR loops have no MXU analog).
Interops with ``jax.experimental.sparse.BCOO`` for ecosystem code.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp
import numpy as np


class SparseTensor:
    """COO: ``indices (nnz, ndim) int32``, ``values (nnz,)``, ``shape``."""

    def __init__(self, indices, values, shape: Sequence[int]):
        self.indices = jnp.asarray(indices, jnp.int32)
        self.values = jnp.asarray(values)
        self.shape = tuple(int(s) for s in shape)
        if self.indices.ndim != 2 or \
                self.indices.shape[1] != len(self.shape):
            raise ValueError(
                f"indices {self.indices.shape} do not match shape "
                f"{self.shape}")
        if self.indices.shape[0] != self.values.shape[0]:
            raise ValueError("indices/values nnz mismatch")

    # -- constructors --------------------------------------------------------
    @classmethod
    def from_dense(cls, dense) -> "SparseTensor":
        d = np.asarray(dense)
        idx = np.argwhere(d != 0)
        return cls(idx, d[tuple(idx.T)], d.shape)

    @classmethod
    def from_bcoo(cls, bcoo) -> "SparseTensor":
        return cls(bcoo.indices, bcoo.data, bcoo.shape)

    # -- views ---------------------------------------------------------------
    @property
    def nnz(self) -> int:
        return int(self.values.shape[0])

    @property
    def ndim(self) -> int:
        return len(self.shape)

    @property
    def dtype(self):
        return self.values.dtype

    def to_dense(self) -> jnp.ndarray:
        out = jnp.zeros(self.shape, self.values.dtype)
        return out.at[tuple(self.indices.T)].add(self.values)

    def to_bcoo(self):
        from jax.experimental import sparse as jsparse
        return jsparse.BCOO((self.values, self.indices), shape=self.shape)

    # -- math (the ops the sparse layers need) ------------------------------
    def matmul_dense(self, w: jnp.ndarray) -> jnp.ndarray:
        """(self: (B, F) sparse) @ (w: (F, O) dense) via segment-sum —
        the SparseLinear forward."""
        if self.ndim != 2:
            raise ValueError("matmul_dense needs a 2-D sparse tensor")
        rows, cols = self.indices[:, 0], self.indices[:, 1]
        contrib = w[cols] * self.values[:, None].astype(w.dtype)
        import jax
        return jax.ops.segment_sum(contrib, rows,
                                   num_segments=self.shape[0])

    # -- elementwise / structural ops (ref SparseTensor op surface:
    # add, narrow, concat, transpose, apply/map, reductions) ---------------
    def coalesce(self) -> "SparseTensor":
        """Merge duplicate indices (sum their values), sort row-major."""
        idx = np.asarray(self.indices)
        vals = np.asarray(self.values)
        flat = np.ravel_multi_index(tuple(idx.T), self.shape)
        order = np.argsort(flat, kind="stable")
        flat, vals = flat[order], vals[order]
        uniq, start = np.unique(flat, return_index=True)
        summed = np.add.reduceat(vals, start)
        new_idx = np.stack(np.unravel_index(uniq, self.shape), axis=1)
        return SparseTensor(new_idx, summed, self.shape)

    def add(self, other) -> "SparseTensor":
        """sparse + sparse (same shape) → coalesced sparse."""
        if not isinstance(other, SparseTensor):
            raise TypeError("add expects a SparseTensor; use to_dense() "
                            "for dense arithmetic")
        if other.shape != self.shape:
            raise ValueError(f"shape mismatch {self.shape} {other.shape}")
        idx = jnp.concatenate([self.indices, other.indices], 0)
        vals = jnp.concatenate([self.values.astype(jnp.result_type(
            self.values, other.values)),
            other.values.astype(jnp.result_type(self.values,
                                                other.values))], 0)
        return SparseTensor(idx, vals, self.shape).coalesce()

    def mul_scalar(self, a) -> "SparseTensor":
        return SparseTensor(self.indices, self.values * a, self.shape)

    def mul_dense(self, dense) -> "SparseTensor":
        """Elementwise multiply by a dense array (sparsity preserved)."""
        d = jnp.asarray(dense)
        if d.shape != self.shape:
            raise ValueError(f"shape mismatch {self.shape} {d.shape}")
        picked = d[tuple(self.indices.T)]
        return SparseTensor(self.indices,
                            self.values * picked, self.shape)

    def transpose(self) -> "SparseTensor":
        if self.ndim != 2:
            raise ValueError("transpose is 2-D only")
        return SparseTensor(self.indices[:, ::-1], self.values,
                            (self.shape[1], self.shape[0]))

    def narrow(self, dim: int, start: int, length: int) -> "SparseTensor":
        """Slice ``[start, start+length)`` along ``dim`` (0-based; the
        reference's 1-based narrow is the Tensor-facade's concern)."""
        keep = (self.indices[:, dim] >= start) \
            & (self.indices[:, dim] < start + length)
        keep = np.asarray(keep)
        idx = np.asarray(self.indices)[keep]
        idx[:, dim] -= start
        shape = list(self.shape)
        shape[dim] = length
        return SparseTensor(idx, np.asarray(self.values)[keep], shape)

    @staticmethod
    def concat(tensors: Sequence["SparseTensor"],
               dim: int = 0) -> "SparseTensor":
        """Concatenate along ``dim`` (ref: SparseTensor.concat backing
        SparseJoinTable)."""
        base = tensors[0]
        for t in tensors[1:]:
            for d in range(base.ndim):
                if d != dim and t.shape[d] != base.shape[d]:
                    raise ValueError("non-concat dims must match")
        parts_i, parts_v, off = [], [], 0
        for t in tensors:
            idx = np.asarray(t.indices).copy()
            idx[:, dim] += off
            parts_i.append(idx)
            parts_v.append(np.asarray(t.values))
            off += t.shape[dim]
        shape = list(base.shape)
        shape[dim] = off
        return SparseTensor(np.concatenate(parts_i, 0),
                            np.concatenate(parts_v, 0), shape)

    def sum(self) -> jnp.ndarray:
        return jnp.sum(self.values)

    def apply(self, fn) -> "SparseTensor":
        """Map ``fn`` over the stored values (ref applyFun; zeros stay
        zero, so fn must satisfy fn(0)=0 for dense equivalence — the
        reference has the same contract)."""
        return SparseTensor(self.indices, fn(self.values), self.shape)

    def __repr__(self):
        return (f"SparseTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")

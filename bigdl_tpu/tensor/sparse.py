"""SparseTensor — COO sparse tensor (ref: S:dllib/tensor/
SparseTensor.scala — backs the reference's sparse recsys layers;
round 1 had nothing sparse).

TPU-first design: a frozen ``(indices, values, shape)`` triple. XLA has
no native sparse formats, so compute paths lower to dense gathers /
``segment_sum`` — which on TPU is exactly how the MXU wants embedding
workloads expressed (the reference's CPU CSR loops have no MXU analog).
Interops with ``jax.experimental.sparse.BCOO`` for ecosystem code.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp
import numpy as np


class SparseTensor:
    """COO: ``indices (nnz, ndim) int32``, ``values (nnz,)``, ``shape``."""

    def __init__(self, indices, values, shape: Sequence[int]):
        self.indices = jnp.asarray(indices, jnp.int32)
        self.values = jnp.asarray(values)
        self.shape = tuple(int(s) for s in shape)
        if self.indices.ndim != 2 or \
                self.indices.shape[1] != len(self.shape):
            raise ValueError(
                f"indices {self.indices.shape} do not match shape "
                f"{self.shape}")
        if self.indices.shape[0] != self.values.shape[0]:
            raise ValueError("indices/values nnz mismatch")

    # -- constructors --------------------------------------------------------
    @classmethod
    def from_dense(cls, dense) -> "SparseTensor":
        d = np.asarray(dense)
        idx = np.argwhere(d != 0)
        return cls(idx, d[tuple(idx.T)], d.shape)

    @classmethod
    def from_bcoo(cls, bcoo) -> "SparseTensor":
        return cls(bcoo.indices, bcoo.data, bcoo.shape)

    # -- views ---------------------------------------------------------------
    @property
    def nnz(self) -> int:
        return int(self.values.shape[0])

    @property
    def ndim(self) -> int:
        return len(self.shape)

    @property
    def dtype(self):
        return self.values.dtype

    def to_dense(self) -> jnp.ndarray:
        out = jnp.zeros(self.shape, self.values.dtype)
        return out.at[tuple(self.indices.T)].add(self.values)

    def to_bcoo(self):
        from jax.experimental import sparse as jsparse
        return jsparse.BCOO((self.values, self.indices), shape=self.shape)

    # -- math (the ops the sparse layers need) ------------------------------
    def matmul_dense(self, w: jnp.ndarray) -> jnp.ndarray:
        """(self: (B, F) sparse) @ (w: (F, O) dense) via segment-sum —
        the SparseLinear forward."""
        if self.ndim != 2:
            raise ValueError("matmul_dense needs a 2-D sparse tensor")
        rows, cols = self.indices[:, 0], self.indices[:, 1]
        contrib = w[cols] * self.values[:, None].astype(w.dtype)
        import jax
        return jax.ops.segment_sum(contrib, rows,
                                   num_segments=self.shape[0])

    def __repr__(self):
        return (f"SparseTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")

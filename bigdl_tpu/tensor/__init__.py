from bigdl_tpu.tensor.tensor import Tensor, SparseTensor

__all__ = ["Tensor", "SparseTensor"]

from bigdl_tpu.tensor.sparse import SparseTensor
from bigdl_tpu.tensor.tensor import Tensor

__all__ = ["Tensor", "SparseTensor"]

"""Tensor — BigDL-style tensor facade over ``jax.Array``.

Reference: scala/dllib/.../tensor/DenseTensor.scala (+DenseTensorMath,
TensorNumericMath). The reference is a mutable, strided, storage-backed
Torch tensor whose math routes to MKL JNI. On TPU the compute path is
``jax.numpy`` under jit — so this facade exists for **API parity** (model
zoo code, tests, user code written against BigDL's Tensor), while the hot
path (nn layers, optimizers) operates on raw ``jax.Array`` pytrees.

Mutability: "in-place" methods (``add_``-style: here BigDL names like
``add``, ``fill``, ``copy``) rebind the underlying immutable ``jax.Array``
and return ``self``. This preserves reference semantics at the API layer
without fighting XLA's functional model (SURVEY.md §7.3 "Mutable Tensor
semantics vs functional jax").

Dtype dispatch (the reference's ``TensorNumeric[T]`` typeclass) degenerates
to the jnp dtype carried by the underlying array.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

ArrayLike = Union[np.ndarray, "jnp.ndarray", "Tensor", float, int, list, tuple]


def _unwrap(x):
    return x.data if isinstance(x, Tensor) else x


class Tensor:
    __slots__ = ("data",)
    __array_priority__ = 100

    def __init__(self, *args, dtype=jnp.float32):
        if len(args) == 0:
            self.data = jnp.zeros((), dtype=dtype)
        elif len(args) == 1 and isinstance(args[0], (np.ndarray, jnp.ndarray, jax.Array)):
            self.data = jnp.asarray(args[0])
        elif len(args) == 1 and isinstance(args[0], Tensor):
            self.data = args[0].data
        elif len(args) == 1 and isinstance(args[0], (list, tuple)):
            self.data = jnp.asarray(np.asarray(args[0], dtype=dtype))
        else:
            # Tensor(d1, d2, ...) — zero-filled with the given size
            self.data = jnp.zeros(tuple(int(a) for a in args), dtype=dtype)

    # -- shape queries ------------------------------------------------------
    def size(self, dim: Optional[int] = None):
        if dim is None:
            return tuple(self.data.shape)
        return self.data.shape[dim - 1]  # 1-based like the reference

    @property
    def shape(self):
        return tuple(self.data.shape)

    def dim(self) -> int:
        return self.data.ndim

    def n_element(self) -> int:
        return int(np.prod(self.data.shape)) if self.data.ndim else 1

    nElement = n_element

    def dtype(self):
        return self.data.dtype

    # -- creation helpers ---------------------------------------------------
    @staticmethod
    def zeros(*shape, dtype=jnp.float32):
        return Tensor(jnp.zeros(shape, dtype=dtype))

    @staticmethod
    def ones(*shape, dtype=jnp.float32):
        return Tensor(jnp.ones(shape, dtype=dtype))

    @staticmethod
    def randn(*shape, seed: int = 0, dtype=jnp.float32):
        key = jax.random.PRNGKey(seed)
        return Tensor(jax.random.normal(key, shape, dtype=dtype))

    @staticmethod
    def rand(*shape, seed: int = 0, dtype=jnp.float32):
        key = jax.random.PRNGKey(seed)
        return Tensor(jax.random.uniform(key, shape, dtype=dtype))

    @staticmethod
    def arange(start, stop=None, step=1, dtype=jnp.float32):
        if stop is None:
            start, stop = 1, start + 1  # Tensor.range semantics (1..n inclusive)
        return Tensor(jnp.arange(start, stop, step, dtype=dtype))

    # -- mutation-style ops (rebind + return self) --------------------------
    def fill(self, value):
        self.data = jnp.full_like(self.data, value)
        return self

    def zero(self):
        return self.fill(0)

    def copy(self, other: "Tensor"):
        self.data = jnp.broadcast_to(_unwrap(other), self.data.shape).astype(self.data.dtype)
        return self

    def set(self, other: Optional["Tensor"] = None):
        self.data = jnp.zeros((), self.data.dtype) if other is None else _unwrap(other)
        return self

    def resize(self, *sizes):
        sizes = tuple(int(s) for s in sizes)
        n_new = int(np.prod(sizes))
        flat = self.data.reshape(-1)
        if flat.size < n_new:
            flat = jnp.concatenate([flat, jnp.zeros(n_new - flat.size, flat.dtype)])
        self.data = flat[:n_new].reshape(sizes)
        return self

    resize_as = lambda self, other: self.resize(*_unwrap(other).shape)

    def apply_(self, fn):
        self.data = fn(self.data)
        return self

    def add(self, *args):
        """add(value) | add(other) | add(alpha, other) — in-place like reference."""
        if len(args) == 1:
            self.data = self.data + _unwrap(args[0])
        else:
            alpha, other = args
            self.data = self.data + alpha * _unwrap(other)
        return self

    def sub(self, *args):
        if len(args) == 1:
            self.data = self.data - _unwrap(args[0])
        else:
            alpha, other = args
            self.data = self.data - alpha * _unwrap(other)
        return self

    def mul(self, value):
        self.data = self.data * _unwrap(value)
        return self

    def cmul(self, other):
        self.data = self.data * _unwrap(other)
        return self

    def cdiv(self, other):
        self.data = self.data / _unwrap(other)
        return self

    def div(self, value):
        self.data = self.data / _unwrap(value)
        return self

    def pow(self, n):
        self.data = self.data ** n
        return self

    def sqrt(self):
        self.data = jnp.sqrt(self.data)
        return self

    def exp(self):
        self.data = jnp.exp(self.data)
        return self

    def log(self):
        self.data = jnp.log(self.data)
        return self

    def abs(self):
        self.data = jnp.abs(self.data)
        return self

    def clamp(self, min_v, max_v):
        self.data = jnp.clip(self.data, min_v, max_v)
        return self

    def addcmul(self, value, t1, t2):
        self.data = self.data + value * _unwrap(t1) * _unwrap(t2)
        return self

    def addcdiv(self, value, t1, t2):
        self.data = self.data + value * _unwrap(t1) / _unwrap(t2)
        return self

    def addmm(self, *args):
        """addmm([beta], [alpha,] mat1, mat2) — self = beta*self + alpha*mat1@mat2."""
        beta, alpha = 1.0, 1.0
        if len(args) == 2:
            m1, m2 = args
        elif len(args) == 3:
            beta, m1, m2 = args
        else:
            beta, alpha, m1, m2 = args
        self.data = beta * self.data + alpha * (_unwrap(m1) @ _unwrap(m2))
        return self

    def addmv(self, *args):
        beta, alpha = 1.0, 1.0
        if len(args) == 2:
            m, v = args
        elif len(args) == 3:
            beta, m, v = args
        else:
            beta, alpha, m, v = args
        self.data = beta * self.data + alpha * (_unwrap(m) @ _unwrap(v))
        return self

    def addr(self, alpha, v1, v2):
        self.data = self.data + alpha * jnp.outer(_unwrap(v1), _unwrap(v2))
        return self

    # -- functional (return new Tensor) -------------------------------------
    def clone(self) -> "Tensor":
        return Tensor(self.data)

    def contiguous(self) -> "Tensor":
        return self

    def view(self, *sizes) -> "Tensor":
        if len(sizes) == 1 and isinstance(sizes[0], (tuple, list)):
            sizes = tuple(sizes[0])
        return Tensor(self.data.reshape(sizes))

    reshape = view

    def t(self) -> "Tensor":
        return Tensor(self.data.T)

    def transpose(self, dim1: int, dim2: int) -> "Tensor":
        return Tensor(jnp.swapaxes(self.data, dim1 - 1, dim2 - 1))

    def narrow(self, dim: int, index: int, size: int) -> "Tensor":
        """1-based dim & index, like the reference."""
        sl = [slice(None)] * self.data.ndim
        sl[dim - 1] = slice(index - 1, index - 1 + size)
        return Tensor(self.data[tuple(sl)])

    def select(self, dim: int, index: int) -> "Tensor":
        return Tensor(jnp.take(self.data, index - 1, axis=dim - 1))

    def squeeze(self, dim: Optional[int] = None) -> "Tensor":
        if dim is None:
            return Tensor(jnp.squeeze(self.data))
        if self.data.shape[dim - 1] != 1:
            return Tensor(self.data)  # new facade, never alias self
        return Tensor(jnp.squeeze(self.data, axis=dim - 1))

    def unsqueeze(self, dim: int) -> "Tensor":
        return Tensor(jnp.expand_dims(self.data, dim - 1))

    def index_select(self, dim: int, indices) -> "Tensor":
        idx = jnp.asarray(_unwrap(indices)).astype(jnp.int32) - 1
        return Tensor(jnp.take(self.data, idx, axis=dim - 1))

    def mm(self, other) -> "Tensor":
        return Tensor(self.data @ _unwrap(other))

    def mv(self, other) -> "Tensor":
        return Tensor(self.data @ _unwrap(other))

    def dot(self, other) -> float:
        return float(jnp.vdot(self.data, _unwrap(other)))

    def sum(self, dim: Optional[int] = None):
        if dim is None:
            return float(jnp.sum(self.data))
        return Tensor(jnp.sum(self.data, axis=dim - 1, keepdims=True))

    def mean(self, dim: Optional[int] = None):
        if dim is None:
            return float(jnp.mean(self.data))
        return Tensor(jnp.mean(self.data, axis=dim - 1, keepdims=True))

    def max(self, dim: Optional[int] = None):
        if dim is None:
            return float(jnp.max(self.data))
        values = jnp.max(self.data, axis=dim - 1, keepdims=True)
        indices = jnp.argmax(self.data, axis=dim - 1, keepdims=True) + 1
        return Tensor(values), Tensor(indices.astype(jnp.float32))

    def min(self, dim: Optional[int] = None):
        if dim is None:
            return float(jnp.min(self.data))
        values = jnp.min(self.data, axis=dim - 1, keepdims=True)
        indices = jnp.argmin(self.data, axis=dim - 1, keepdims=True) + 1
        return Tensor(values), Tensor(indices.astype(jnp.float32))

    def norm(self, p: int = 2) -> float:
        return float(jnp.sum(jnp.abs(self.data) ** p) ** (1.0 / p))

    def almost_equal(self, other, tolerance: float = 1e-5) -> bool:
        return bool(jnp.allclose(self.data, _unwrap(other), atol=tolerance))

    def to_numpy(self) -> np.ndarray:
        return np.asarray(self.data)

    def astype(self, dtype) -> "Tensor":
        return Tensor(self.data.astype(dtype))

    # -- operators ----------------------------------------------------------
    def __add__(self, other):
        return Tensor(self.data + _unwrap(other))

    __radd__ = __add__

    def __sub__(self, other):
        return Tensor(self.data - _unwrap(other))

    def __rsub__(self, other):
        return Tensor(_unwrap(other) - self.data)

    def __mul__(self, other):
        return Tensor(self.data * _unwrap(other))

    __rmul__ = __mul__

    def __truediv__(self, other):
        return Tensor(self.data / _unwrap(other))

    def __neg__(self):
        return Tensor(-self.data)

    def __matmul__(self, other):
        return Tensor(self.data @ _unwrap(other))

    def __getitem__(self, item):
        return Tensor(self.data[item])

    def __setitem__(self, item, value):
        self.data = self.data.at[item].set(_unwrap(value))

    def __repr__(self):
        return f"Tensor({np.asarray(self.data)!r})"

    def __float__(self):
        return float(self.data)


# SparseTensor moved to bigdl_tpu.tensor.sparse (full COO type with
# segment-sum compute paths backing the sparse nn layers).

"""Composable sample transformers (ref: .../feature/dataset/Transformer.scala
and the image/text transformer families: BytesToGreyImg, GreyImgNormalizer,
GreyImgToSample, HFlip, ...).

A Transformer maps an iterator to an iterator; ``a >> b`` composes (the
reference uses Scala's ``->``).
"""

from __future__ import annotations

from typing import Callable, Iterator

import numpy as np

from bigdl_tpu.feature.dataset import Sample


class Transformer:
    def __call__(self, it: Iterator) -> Iterator:
        raise NotImplementedError

    def __rshift__(self, other: "Transformer") -> "ChainedTransformer":
        return ChainedTransformer(self, other)


class ChainedTransformer(Transformer):
    def __init__(self, *transformers):
        self.transformers = list(transformers)

    def __call__(self, it):
        for t in self.transformers:
            it = t(it)
        return it


class MapTransformer(Transformer):
    def __init__(self, fn: Callable):
        self.fn = fn

    def __call__(self, it):
        for x in it:
            yield self.fn(x)


class Normalizer(Transformer):
    """Per-sample (x - mean) / std on feature 0 (ref: GreyImgNormalizer)."""

    def __init__(self, mean: float, std: float):
        self.mean, self.std = mean, std

    def __call__(self, it):
        for s in it:
            feats = [(s.features[0].astype(np.float32) - self.mean) / self.std]
            feats += s.features[1:]
            yield Sample(feats, s.labels)


class OneHot(Transformer):
    """Label → one-hot vector (keras-style categorical targets)."""

    def __init__(self, n_classes: int, zero_based: bool = False):
        self.n_classes = n_classes
        self.zero_based = zero_based

    def __call__(self, it):
        for s in it:
            lab = int(np.asarray(s.labels[0]).reshape(()))
            if not self.zero_based:
                lab -= 1
            oh = np.zeros((self.n_classes,), np.float32)
            oh[lab] = 1.0
            yield Sample(s.features, [oh])


class HFlip(Transformer):
    """Random horizontal flip of HW or CHW images (ref: vision HFlip)."""

    def __init__(self, p: float = 0.5, seed: int = 0):
        self.p = p
        self.rng = np.random.RandomState(seed)

    def __call__(self, it):
        for s in it:
            if self.rng.rand() < self.p:
                img = s.features[0]
                yield Sample([np.ascontiguousarray(img[..., ::-1])]
                             + s.features[1:], s.labels)
            else:
                yield s


class RandomCrop(Transformer):
    """Random crop with padding (ref: vision RandomCropper)."""

    def __init__(self, height: int, width: int, padding: int = 0,
                 seed: int = 0):
        self.h, self.w, self.pad = height, width, padding
        self.rng = np.random.RandomState(seed)

    def __call__(self, it):
        for s in it:
            img = s.features[0]  # CHW or HW
            chw = img.ndim == 3
            if self.pad:
                widths = ((0, 0),) * (img.ndim - 2) + \
                    ((self.pad, self.pad), (self.pad, self.pad))
                img = np.pad(img, widths)
            H, W = img.shape[-2], img.shape[-1]
            top = self.rng.randint(0, H - self.h + 1)
            left = self.rng.randint(0, W - self.w + 1)
            crop = img[..., top:top + self.h, left:left + self.w]
            yield Sample([crop] + s.features[1:], s.labels)

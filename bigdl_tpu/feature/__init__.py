from bigdl_tpu.feature.dataset import (
    DataSet, DistributedDataSet, LocalDataSet, MiniBatch, PrefetchDataSet,
    Sample, SampleToMiniBatch)
from bigdl_tpu.feature.transformers import (
    ChainedTransformer, Normalizer, OneHot, Transformer)
from bigdl_tpu.feature import cifar, imagenet

__all__ = [
    "DataSet", "DistributedDataSet", "LocalDataSet", "MiniBatch",
    "PrefetchDataSet", "Sample", "SampleToMiniBatch", "Transformer",
    "ChainedTransformer", "Normalizer", "OneHot", "cifar", "imagenet",
]

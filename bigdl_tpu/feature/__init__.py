from bigdl_tpu.feature.dataset import (
    DataSet, DistributedDataSet, LocalDataSet, MiniBatch, Sample,
    SampleToMiniBatch)
from bigdl_tpu.feature.transformers import (
    ChainedTransformer, Normalizer, OneHot, Transformer)

__all__ = [
    "DataSet", "DistributedDataSet", "LocalDataSet", "MiniBatch", "Sample",
    "SampleToMiniBatch", "Transformer", "ChainedTransformer", "Normalizer",
    "OneHot",
]

"""MNIST loader (ref: .../models/lenet/Utils.scala load idx files +
BytesToGreyImg/GreyImgNormalizer transformer chain).

Reads idx-format files from ``folder`` when present (train-images-idx3-ubyte
etc.). With no files and ``synthetic=True`` (default in this offline
environment), generates a deterministic synthetic digit set: each class is
a fixed stroke pattern + noise — linearly separable enough for LeNet to
reach high accuracy fast, which is what the hello-world config needs.
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Optional, Tuple

import numpy as np

TRAIN_MEAN = 0.13066047740239506
TRAIN_STD = 0.3081078


def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        zero, dtype, ndim = struct.unpack(">HBB", f.read(4))
        shape = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        return np.frombuffer(f.read(), dtype=np.uint8).reshape(shape)


def _synthetic_digits(n: int, seed: int) -> Tuple[np.ndarray, np.ndarray]:
    rs = np.random.RandomState(seed)
    protos = np.zeros((10, 28, 28), np.float32)
    for k in range(10):
        prs = np.random.RandomState(1000 + k)
        # distinct blob pattern per class
        for _ in range(6):
            r, c = prs.randint(4, 22, 2)
            protos[k, r:r + 5, c:c + 5] += prs.rand() + 0.5
        protos[k] = np.clip(protos[k], 0, 1)
    labels = rs.randint(0, 10, n)
    imgs = protos[labels] + 0.15 * rs.randn(n, 28, 28).astype(np.float32)
    imgs = np.clip(imgs, 0, 1)
    return imgs.astype(np.float32), (labels + 1).astype(np.float32)  # 1-based


def load_mnist(folder: Optional[str] = None, train: bool = True,
               synthetic_size: int = 2048, seed: int = 0
               ) -> Tuple[np.ndarray, np.ndarray]:
    """Returns (images (N,28,28) float32 in [0,1], labels (N,) float32 1-based)."""
    if folder:
        prefix = "train" if train else "t10k"
        for ext in ("", ".gz"):
            ip = os.path.join(folder, f"{prefix}-images-idx3-ubyte{ext}")
            lp = os.path.join(folder, f"{prefix}-labels-idx1-ubyte{ext}")
            if os.path.exists(ip) and os.path.exists(lp):
                images = _read_idx(ip).astype(np.float32) / 255.0
                labels = _read_idx(lp).astype(np.float32) + 1.0
                return images, labels
    return _synthetic_digits(synthetic_size, seed if train else seed + 1)


def normalize(images: np.ndarray) -> np.ndarray:
    """ref: GreyImgNormalizer(trainMean, trainStd)."""
    return (images - TRAIN_MEAN) / TRAIN_STD

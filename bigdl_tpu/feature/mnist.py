"""MNIST loader (ref: .../models/lenet/Utils.scala load idx files +
BytesToGreyImg/GreyImgNormalizer transformer chain).

Reads idx-format files from ``folder`` when present (train-images-idx3-ubyte
etc.). With no files and ``synthetic=True`` (default in this offline
environment), generates a deterministic synthetic digit set: each class is
a fixed stroke pattern + noise — linearly separable enough for LeNet to
reach high accuracy fast, which is what the hello-world config needs.
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Optional, Tuple

import numpy as np

TRAIN_MEAN = 0.13066047740239506
TRAIN_STD = 0.3081078


def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        zero, dtype, ndim = struct.unpack(">HBB", f.read(4))
        shape = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        return np.frombuffer(f.read(), dtype=np.uint8).reshape(shape)


def _protos() -> np.ndarray:
    protos = np.zeros((10, 28, 28), np.float32)
    for k in range(10):
        prs = np.random.RandomState(1000 + k)
        # distinct blob pattern per class
        for _ in range(6):
            r, c = prs.randint(4, 22, 2)
            protos[k, r:r + 5, c:c + 5] += prs.rand() + 0.5
        protos[k] = np.clip(protos[k], 0, 1)
    return protos


def calibrate_sigma(protos: np.ndarray, target: float = 0.96,
                    n: int = 4096, seed: int = 123) -> float:
    """Noise level such that the Bayes-optimal-style nearest-prototype
    classifier on the clipped noisy draw scores ≈ ``target`` top-1
    (VERDICT r4 missing #2: the easy sets saturate at 1.0, which cannot
    falsify a subtly broken optimizer — the ``hard`` sets pin the
    ceiling below 1 by construction). Nearest-mean is exactly Bayes for
    isotropic equal-variance Gaussian classes pre-clip; post-clip it is
    a tight reference anchor."""
    c = protos.shape[0]
    pf = protos.reshape(c, -1).astype(np.float32)
    rs = np.random.RandomState(seed)
    labels = rs.randint(0, c, n)
    noise = rs.randn(n, pf.shape[1]).astype(np.float32)
    pn = (pf * pf).sum(1)
    lo, hi = 0.02, 3.0
    for _ in range(20):
        mid = 0.5 * (lo + hi)
        x = np.clip(pf[labels] + mid * noise, 0.0, 1.0)
        d = pn[None, :] - 2.0 * (x @ pf.T)      # argmin == full distance
        acc = float((d.argmin(1) == labels).mean())
        if acc > target:
            lo = mid
        else:
            hi = mid
    return 0.5 * (lo + hi)


_HARD_SIGMA: dict = {}


def _synthetic_digits(n: int, seed: int,
                      hard: bool = False) -> Tuple[np.ndarray, np.ndarray]:
    rs = np.random.RandomState(seed)
    protos = _protos()
    if hard:
        if "sigma" not in _HARD_SIGMA:
            _HARD_SIGMA["sigma"] = calibrate_sigma(protos)
        sigma = _HARD_SIGMA["sigma"]
    else:
        sigma = 0.15
    labels = rs.randint(0, 10, n)
    imgs = protos[labels] + sigma * rs.randn(n, 28, 28).astype(np.float32)
    imgs = np.clip(imgs, 0, 1)
    return imgs.astype(np.float32), (labels + 1).astype(np.float32)  # 1-based


def _nearest_prototype_accuracy(protos: np.ndarray, images: np.ndarray,
                                labels: np.ndarray) -> float:
    """Shared nearest-prototype top-1 (labels 1-based) — single source
    for the mnist AND cifar Bayes anchors."""
    pf = protos.reshape(len(protos), -1)
    x = images.reshape(len(images), -1)
    d = (pf * pf).sum(1)[None, :] - 2.0 * (x @ pf.T)
    return float((d.argmin(1) == (labels - 1).astype(np.int64)).mean())


def nearest_prototype_accuracy(images: np.ndarray,
                               labels: np.ndarray) -> float:
    """Top-1 of the nearest-prototype classifier on a synthetic draw —
    the Bayes reference the convergence bench reports next to the
    trained model's accuracy (labels 1-based)."""
    return _nearest_prototype_accuracy(_protos(), images, labels)


def load_mnist(folder: Optional[str] = None, train: bool = True,
               synthetic_size: int = 2048, seed: int = 0,
               hard: bool = False) -> Tuple[np.ndarray, np.ndarray]:
    """Returns (images (N,28,28) float32 in [0,1], labels (N,) float32 1-based).

    ``hard=True`` selects the Bayes-calibrated synthetic set (top-1
    ceiling ≈0.96 by construction) used by the convergence benchmarks;
    the default easy set stays for hello-world smoke paths."""
    if folder:
        prefix = "train" if train else "t10k"
        for ext in ("", ".gz"):
            ip = os.path.join(folder, f"{prefix}-images-idx3-ubyte{ext}")
            lp = os.path.join(folder, f"{prefix}-labels-idx1-ubyte{ext}")
            if os.path.exists(ip) and os.path.exists(lp):
                images = _read_idx(ip).astype(np.float32) / 255.0
                labels = _read_idx(lp).astype(np.float32) + 1.0
                return images, labels
    return _synthetic_digits(synthetic_size, seed if train else seed + 1,
                             hard=hard)


def normalize(images: np.ndarray) -> np.ndarray:
    """ref: GreyImgNormalizer(trainMean, trainStd)."""
    return (images - TRAIN_MEAN) / TRAIN_STD

"""Data layer (ref: .../feature/dataset/DataSet.scala, Sample.scala,
MiniBatch.scala, SampleToMiniBatch).

The reference's DistributedDataSet is an RDD cached per Spark partition;
the TPU-native analog shards each global batch across the mesh's data axis
(device_put with a NamedSharding happens in the optimizer — the DataSet
only needs to yield steady, shuffled host batches; per-host sharding for
multi-controller jax is a slice of the sample index space, the moral
equivalent of partition locality).
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Union

import numpy as np


class Sample:
    """(features, label) record (ref: Sample.scala / TensorSample)."""

    __slots__ = ("features", "labels")

    def __init__(self, features, labels=None):
        self.features = [np.asarray(f) for f in (
            features if isinstance(features, (list, tuple)) else [features])]
        if labels is None:
            self.labels = []
        else:
            self.labels = [np.asarray(l) for l in (
                labels if isinstance(labels, (list, tuple)) else [labels])]

    @staticmethod
    def from_ndarray(features, labels=None) -> "Sample":
        return Sample(features, labels)

    def feature(self, i: int = 0):
        return self.features[i]

    def label(self, i: int = 0):
        return self.labels[i] if self.labels else None


class MiniBatch:
    """Batched (input, target) pair (ref: MiniBatch.scala)."""

    __slots__ = ("input", "target")

    def __init__(self, input, target=None):
        self.input = input
        self.target = target

    def size(self) -> int:
        arr = self.input[0] if isinstance(self.input, (list, tuple)) \
            else self.input
        return arr.shape[0]

    def get_input(self):
        return self.input

    def get_target(self):
        return self.target


def _stack_samples(samples: Sequence[Sample], pad: bool = False) -> MiniBatch:
    n_feat = len(samples[0].features)
    n_lab = len(samples[0].labels)

    def stack(arrs: List[np.ndarray]) -> np.ndarray:
        if pad:
            # pad to the max shape in the batch (ref: PaddingParam)
            max_shape = np.max([a.shape for a in arrs], axis=0)
            out = np.zeros((len(arrs),) + tuple(max_shape), arrs[0].dtype)
            for i, a in enumerate(arrs):
                sl = (i,) + tuple(slice(0, s) for s in a.shape)
                out[sl] = a
            return out
        return np.stack(arrs)

    feats = [stack([s.features[i] for s in samples]) for i in range(n_feat)]
    labs = [stack([s.labels[i] for s in samples]) for i in range(n_lab)]
    inp = feats[0] if n_feat == 1 else feats
    tgt = (labs[0] if n_lab == 1 else labs) if n_lab else None
    return MiniBatch(inp, tgt)


class AbstractDataSet:
    def size(self) -> int:
        raise NotImplementedError

    def shuffle(self):
        return self

    def data(self, train: bool = True) -> Iterator:
        raise NotImplementedError

    def transform(self, transformer) -> "AbstractDataSet":
        return _TransformedDataSet(self, transformer)

    def prefetch(self, depth: int = 8) -> "AbstractDataSet":
        return PrefetchDataSet(self, depth)

    # sugar matching the reference's `dataset -> transformer` composition
    def __rshift__(self, transformer):
        return self.transform(transformer)


class LocalDataSet(AbstractDataSet):
    """In-memory dataset of Samples or raw arrays (ref: LocalArrayDataSet)."""

    def __init__(self, x, y: Optional[np.ndarray] = None, shuffle: bool = True,
                 seed: int = 0):
        if isinstance(x, (list, tuple)) and x and isinstance(x[0], Sample):
            self.samples = list(x)
            self._array_mode = False
        else:
            self.x = np.asarray(x)
            self.y = None if y is None else np.asarray(y)
            self._array_mode = True
        self._shuffle = shuffle
        self._rng = np.random.RandomState(seed)

    def size(self) -> int:
        return len(self.samples) if not self._array_mode else self.x.shape[0]

    def data(self, train: bool = True):
        n = self.size()
        order = np.arange(n)
        if train and self._shuffle:
            self._rng.shuffle(order)
        if self._array_mode:
            for i in order:
                yield Sample(self.x[i],
                             None if self.y is None else self.y[i])
        else:
            for i in order:
                yield self.samples[i]


class DistributedDataSet(LocalDataSet):
    """Host-sharded dataset for multi-controller jax (ref:
    CachedDistriDataSet). Each host sees samples [rank::world]; the global
    batch assembled per step is the union, matching the per-partition
    caching of the reference."""

    def __init__(self, x, y=None, shuffle: bool = True, seed: int = 0,
                 rank: Optional[int] = None, world: Optional[int] = None):
        super().__init__(x, y, shuffle, seed)
        if rank is None or world is None:
            import jax
            rank = jax.process_index()
            world = jax.process_count()
        self.rank, self.world = rank, world

    def data(self, train: bool = True):
        for i, s in enumerate(super().data(train)):
            if i % self.world == self.rank:
                yield s


class _TransformedDataSet(AbstractDataSet):
    def __init__(self, parent: AbstractDataSet, transformer):
        self.parent = parent
        self.transformer = transformer

    def size(self):
        return self.parent.size()

    def data(self, train: bool = True):
        return self.transformer(self.parent.data(train))


class PrefetchDataSet(AbstractDataSet):
    """Background-thread prefetch: host-side decode/augment overlaps the
    device step, so the Optimizer's per-iteration data timer shows only
    queue-pop latency (the role of the reference's multi-threaded
    transformer iterators over Spark partitions)."""

    def __init__(self, parent: AbstractDataSet, depth: int = 8):
        self.parent = parent
        self.depth = depth

    def size(self):
        return self.parent.size()

    def data(self, train: bool = True):
        import queue
        import threading

        q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        _END = object()
        stop = threading.Event()

        def put(item) -> bool:
            # bounded put that gives up when the consumer is gone, so an
            # abandoned iterator (early break / trigger fire) cannot leave
            # the producer blocked forever on a full queue
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            try:
                for s in self.parent.data(train):
                    if not put(s):
                        return
                put(_END)
            except BaseException as e:  # surface errors on the consumer
                put(e)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is _END:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()
            # retire the producer: put() gives up within its 0.1 s
            # poll once stop is set, so this never hangs the consumer
            t.join(timeout=5.0)


class SampleToMiniBatch:
    """Transformer: iterator[Sample] → iterator[MiniBatch]
    (ref: SampleToMiniBatch.scala)."""

    def __init__(self, batch_size: int, drop_remainder: bool = True,
                 pad: bool = False):
        self.batch_size = batch_size
        self.drop_remainder = drop_remainder
        self.pad = pad

    def __call__(self, it: Iterator[Sample]) -> Iterator[MiniBatch]:
        buf: List[Sample] = []
        for s in it:
            buf.append(s)
            if len(buf) == self.batch_size:
                yield _stack_samples(buf, self.pad)
                buf = []
        if buf and not self.drop_remainder:
            yield _stack_samples(buf, self.pad)


class DataSet:
    """Factory facade (ref: DataSet object)."""

    @staticmethod
    def array(x, y=None, shuffle: bool = True, seed: int = 0) -> LocalDataSet:
        return LocalDataSet(x, y, shuffle, seed)

    @staticmethod
    def distributed(x, y=None, shuffle: bool = True, seed: int = 0,
                    rank=None, world=None) -> DistributedDataSet:
        return DistributedDataSet(x, y, shuffle, seed, rank, world)

"""CIFAR-10/100 readers (ref: the reference ships CIFAR loaders with its
models — ``models/resnet/Utils.scala`` reads the CIFAR binary format;
SURVEY.md §2.4 "Built-in loaders". Round 1 shipped MNIST only.)

Binary layout (the "binary version" distribution):
- CIFAR-10:  per record ``1 label byte + 3072 image bytes`` (R,G,B planes
  of 32x32), files ``data_batch_{1..5}.bin`` / ``test_batch.bin``
- CIFAR-100: per record ``1 coarse + 1 fine label byte + 3072 bytes``,
  files ``train.bin`` / ``test.bin``

With no files on disk and ``synthetic=True`` (the default in this offline
environment) a deterministic per-class color-patch set is generated so
training pipelines exercise end-to-end.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

# per-channel statistics of the real CIFAR-10 training set
CIFAR10_MEAN = np.array([0.4914, 0.4822, 0.4465], np.float32)
CIFAR10_STD = np.array([0.2470, 0.2435, 0.2616], np.float32)


def _read_bin(path: str, label_bytes: int) -> Tuple[np.ndarray, np.ndarray]:
    raw = np.fromfile(path, np.uint8)
    rec = label_bytes + 3072
    if raw.size % rec:
        raise ValueError(f"{path}: size {raw.size} not a multiple of {rec}")
    raw = raw.reshape(-1, rec)
    labels = raw[:, label_bytes - 1].astype(np.float32)  # fine label last
    imgs = raw[:, label_bytes:].reshape(-1, 3, 32, 32).astype(np.float32)
    return imgs / 255.0, labels + 1.0                    # 1-based


def _protos(classes: int) -> np.ndarray:
    protos = np.zeros((classes, 3, 32, 32), np.float32)
    for k in range(classes):
        prs = np.random.RandomState(2000 + k)
        for _ in range(5):
            r, c = prs.randint(2, 26, 2)
            ch = prs.randint(0, 3)
            protos[k, ch, r:r + 6, c:c + 6] += prs.rand() * 0.8 + 0.4
        protos[k] = np.clip(protos[k], 0, 1)
    return protos


_HARD_SIGMA: dict = {}


def _synthetic_cifar(n: int, classes: int, seed: int,
                     hard: bool = False) -> Tuple[np.ndarray, np.ndarray]:
    rs = np.random.RandomState(seed)
    protos = _protos(classes)
    if hard:
        # Bayes-calibrated noise (see feature.mnist.calibrate_sigma):
        # pins the nearest-prototype ceiling at ~0.955 so the
        # convergence benchmark's accuracy is falsifiable
        from bigdl_tpu.feature.mnist import calibrate_sigma
        if classes not in _HARD_SIGMA:
            _HARD_SIGMA[classes] = calibrate_sigma(protos)
        sigma = _HARD_SIGMA[classes]
    else:
        sigma = 0.1
    labels = rs.randint(0, classes, n)
    imgs = protos[labels] + sigma * rs.randn(n, 3, 32, 32).astype(np.float32)
    return (np.clip(imgs, 0, 1).astype(np.float32),
            (labels + 1).astype(np.float32))


def nearest_prototype_accuracy(images: np.ndarray, labels: np.ndarray,
                               classes: int = 10) -> float:
    """Top-1 of the nearest-prototype classifier (the Bayes anchor the
    convergence bench reports; labels 1-based). Shares the mnist
    implementation — the math must not diverge between the two benches."""
    from bigdl_tpu.feature.mnist import _nearest_prototype_accuracy
    return _nearest_prototype_accuracy(_protos(classes), images, labels)


def load_cifar(folder: Optional[str] = None, train: bool = True,
               classes: int = 10, synthetic_size: int = 2048,
               seed: int = 0, hard: bool = False
               ) -> Tuple[np.ndarray, np.ndarray]:
    """Returns (images (N,3,32,32) float32 in [0,1], labels (N,) 1-based).

    Reads the binary distribution from ``folder`` when present; otherwise
    generates the synthetic set.
    """
    if folder:
        if classes == 10:
            names = ([f"data_batch_{i}.bin" for i in range(1, 6)]
                     if train else ["test_batch.bin"])
            label_bytes = 1
        else:
            names = ["train.bin" if train else "test.bin"]
            label_bytes = 2
        paths = [os.path.join(folder, n) for n in names]
        if all(os.path.exists(p) for p in paths):
            parts = [_read_bin(p, label_bytes) for p in paths]
            return (np.concatenate([p[0] for p in parts]),
                    np.concatenate([p[1] for p in parts]))
    return _synthetic_cifar(synthetic_size, classes,
                            seed if train else seed + 1, hard=hard)


def normalizer(x: np.ndarray) -> np.ndarray:
    """Channel normalization with the canonical CIFAR-10 statistics."""
    return ((x - CIFAR10_MEAN[:, None, None])
            / CIFAR10_STD[:, None, None]).astype(np.float32)


def train_transformer(pad: int = 4, seed: int = 0):
    """Standard CIFAR augmentation chain as a Sample transformer:
    reflect-pad + random crop + random hflip + normalize."""
    from bigdl_tpu.feature.dataset import Sample
    from bigdl_tpu.feature.transformers import MapTransformer

    rs = np.random.RandomState(seed)

    def aug(s: Sample) -> Sample:
        x = s.features[0]
        x = np.pad(x, ((0, 0), (pad, pad), (pad, pad)), mode="reflect")
        r, c = rs.randint(0, 2 * pad + 1, 2)
        x = x[:, r:r + 32, c:c + 32]
        if rs.rand() < 0.5:
            x = x[:, :, ::-1]
        return Sample(normalizer(np.ascontiguousarray(x)), s.labels)

    return MapTransformer(aug)


def eval_transformer():
    from bigdl_tpu.feature.dataset import Sample
    from bigdl_tpu.feature.transformers import MapTransformer

    return MapTransformer(
        lambda s: Sample(normalizer(s.features[0]), s.labels))


def cifar_dataset(folder: Optional[str] = None, train: bool = True,
                  classes: int = 10, synthetic_size: int = 2048,
                  seed: int = 0, augment: bool = True):
    """LocalDataSet with the standard transform chain attached."""
    from bigdl_tpu.feature.dataset import LocalDataSet

    x, y = load_cifar(folder, train, classes, synthetic_size, seed)
    ds = LocalDataSet(x, y, shuffle=train, seed=seed)
    return ds.transform(train_transformer(seed=seed) if (train and augment)
                        else eval_transformer())

"""Vision transformers (ref: vision/image/augmentation/*.scala — Resize,
AspectScale, CenterCrop, RandomCrop, HFlip, Brightness/Contrast/Hue/
Saturation, ChannelNormalize, MatToTensor, ImageFrameToSample...).

Each FeatureTransformer maps an ImageFeature in place (the reference
mutates the OpenCVMat); images are HWC numpy until MatToTensor emits CHW
floats. PIL is the decode/resize backend (the OpenCV-JNI stand-in)."""

from __future__ import annotations

import io
from typing import Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.feature.vision.image_frame import ImageFeature


class FeatureTransformer:
    """ref: FeatureTransformer — transform(feature); `>>` composes."""

    def __call__(self, feature: ImageFeature) -> ImageFeature:
        try:
            return self.transform_mat(feature)
        except Exception as e:  # ref: ignores per-image failures with log
            feature["isValid"] = False
            feature["error"] = str(e)
            return feature

    def transform_mat(self, feature: ImageFeature) -> ImageFeature:
        raise NotImplementedError

    def __rshift__(self, other):
        return _Chained(self, other)


class _Chained(FeatureTransformer):
    def __init__(self, *ts):
        self.ts = list(ts)

    def __call__(self, feature):
        for t in self.ts:
            feature = t(feature)
        return feature

    def __rshift__(self, other):
        return _Chained(*self.ts, other)


class PixelBytesToMat(FeatureTransformer):
    """Decode encoded bytes → HWC uint8 RGB (ref: PixelBytesToMat /
    BytesToMat via OpenCV imdecode; PIL here)."""

    def transform_mat(self, feature):
        from PIL import Image

        img = Image.open(io.BytesIO(feature[ImageFeature.BYTES]))
        mat = np.asarray(img.convert("RGB"))
        feature[ImageFeature.MAT] = mat
        feature[ImageFeature.ORIGINAL_SIZE] = mat.shape
        return feature


class Resize(FeatureTransformer):
    def __init__(self, resize_h: int, resize_w: int):
        self.h, self.w = resize_h, resize_w

    def transform_mat(self, feature):
        from PIL import Image

        mat = feature[ImageFeature.MAT]
        img = Image.fromarray(np.asarray(mat, np.uint8))
        feature[ImageFeature.MAT] = np.asarray(
            img.resize((self.w, self.h), Image.BILINEAR))
        return feature


class AspectScale(FeatureTransformer):
    """Scale the short side to ``scale`` keeping aspect (ref: AspectScale,
    the ImageNet eval resize)."""

    def __init__(self, scale: int, max_size: int = 1000):
        self.scale = scale
        self.max_size = max_size

    def transform_mat(self, feature):
        from PIL import Image

        mat = np.asarray(feature[ImageFeature.MAT], np.uint8)
        h, w = mat.shape[:2]
        ratio = self.scale / min(h, w)
        if max(h, w) * ratio > self.max_size:
            ratio = self.max_size / max(h, w)
        img = Image.fromarray(mat)
        feature[ImageFeature.MAT] = np.asarray(img.resize(
            (max(1, round(w * ratio)), max(1, round(h * ratio))),
            Image.BILINEAR))
        return feature


class CenterCrop(FeatureTransformer):
    def __init__(self, crop_h: int, crop_w: int):
        self.ch, self.cw = crop_h, crop_w

    def transform_mat(self, feature):
        mat = feature[ImageFeature.MAT]
        h, w = mat.shape[:2]
        top = max(0, (h - self.ch) // 2)
        left = max(0, (w - self.cw) // 2)
        feature[ImageFeature.MAT] = mat[top:top + self.ch,
                                        left:left + self.cw]
        return feature


class RandomCrop(FeatureTransformer):
    def __init__(self, crop_h: int, crop_w: int, seed: Optional[int] = None):
        self.ch, self.cw = crop_h, crop_w
        self._rs = np.random.RandomState(seed)

    def transform_mat(self, feature):
        mat = feature[ImageFeature.MAT]
        h, w = mat.shape[:2]
        top = self._rs.randint(0, max(h - self.ch, 0) + 1)
        left = self._rs.randint(0, max(w - self.cw, 0) + 1)
        feature[ImageFeature.MAT] = mat[top:top + self.ch,
                                        left:left + self.cw]
        return feature


class HFlip(FeatureTransformer):
    def transform_mat(self, feature):
        feature[ImageFeature.MAT] = feature[ImageFeature.MAT][:, ::-1]
        return feature


class RandomHFlip(FeatureTransformer):
    def __init__(self, p: float = 0.5, seed: Optional[int] = None):
        self.p = p
        self._rs = np.random.RandomState(seed)

    def transform_mat(self, feature):
        if self._rs.rand() < self.p:
            feature[ImageFeature.MAT] = feature[ImageFeature.MAT][:, ::-1]
        return feature


class Brightness(FeatureTransformer):
    def __init__(self, delta_low: float, delta_high: float,
                 seed: Optional[int] = None):
        self.lo, self.hi = delta_low, delta_high
        self._rs = np.random.RandomState(seed)

    def transform_mat(self, feature):
        delta = self._rs.uniform(self.lo, self.hi)
        mat = np.asarray(feature[ImageFeature.MAT], np.float32) + delta
        feature[ImageFeature.MAT] = np.clip(mat, 0, 255)
        return feature


class Contrast(FeatureTransformer):
    def __init__(self, delta_low: float, delta_high: float,
                 seed: Optional[int] = None):
        self.lo, self.hi = delta_low, delta_high
        self._rs = np.random.RandomState(seed)

    def transform_mat(self, feature):
        scale = self._rs.uniform(self.lo, self.hi)
        mat = np.asarray(feature[ImageFeature.MAT], np.float32) * scale
        feature[ImageFeature.MAT] = np.clip(mat, 0, 255)
        return feature


class Saturation(FeatureTransformer):
    def __init__(self, delta_low: float, delta_high: float,
                 seed: Optional[int] = None):
        self.lo, self.hi = delta_low, delta_high
        self._rs = np.random.RandomState(seed)

    def transform_mat(self, feature):
        s = self._rs.uniform(self.lo, self.hi)
        mat = np.asarray(feature[ImageFeature.MAT], np.float32)
        grey = mat.mean(axis=2, keepdims=True)
        feature[ImageFeature.MAT] = np.clip(grey + (mat - grey) * s, 0, 255)
        return feature


class Hue(FeatureTransformer):
    def __init__(self, delta_low: float = -18, delta_high: float = 18,
                 seed: Optional[int] = None):
        self.lo, self.hi = delta_low, delta_high
        self._rs = np.random.RandomState(seed)

    def transform_mat(self, feature):
        import colorsys  # noqa: F401  (documents the op)
        from PIL import Image

        delta = self._rs.uniform(self.lo, self.hi)
        img = Image.fromarray(np.asarray(feature[ImageFeature.MAT],
                                         np.uint8)).convert("HSV")
        hsv = np.asarray(img, np.int16)
        hsv[..., 0] = (hsv[..., 0] + int(delta * 255 / 360)) % 256
        feature[ImageFeature.MAT] = np.asarray(Image.fromarray(
            hsv.astype(np.uint8), "HSV").convert("RGB"))
        return feature


class ColorJitter(FeatureTransformer):
    """ref: ColorJitter — random brightness/contrast/saturation order."""

    def __init__(self, brightness: float = 32, contrast: float = 0.5,
                 saturation: float = 0.5, seed: Optional[int] = None):
        self._ts = [Brightness(-brightness, brightness, seed),
                    Contrast(1 - contrast, 1 + contrast, seed),
                    Saturation(1 - saturation, 1 + saturation, seed)]
        self._rs = np.random.RandomState(seed)

    def transform_mat(self, feature):
        order = self._rs.permutation(len(self._ts))
        for i in order:
            feature = self._ts[i](feature)
        return feature


class RandomTransformer(FeatureTransformer):
    """Apply inner transformer with probability p (ref: RandomTransformer)."""

    def __init__(self, transformer: FeatureTransformer, p: float = 0.5,
                 seed: Optional[int] = None):
        self.inner = transformer
        self.p = p
        self._rs = np.random.RandomState(seed)

    def transform_mat(self, feature):
        if self._rs.rand() < self.p:
            return self.inner(feature)
        return feature


class ChannelNormalize(FeatureTransformer):
    """(x - mean) / std per channel (ref: ChannelNormalize)."""

    def __init__(self, mean_r: float, mean_g: float, mean_b: float,
                 std_r: float = 1.0, std_g: float = 1.0,
                 std_b: float = 1.0):
        self.mean = np.array([mean_r, mean_g, mean_b], np.float32)
        self.std = np.array([std_r, std_g, std_b], np.float32)

    def transform_mat(self, feature):
        mat = np.asarray(feature[ImageFeature.MAT], np.float32)
        feature[ImageFeature.MAT] = (mat - self.mean) / self.std
        return feature


class ChannelScaledNormalizer(FeatureTransformer):
    def __init__(self, scale: float = 1.0 / 255):
        self.scale = scale

    def transform_mat(self, feature):
        feature[ImageFeature.MAT] = np.asarray(
            feature[ImageFeature.MAT], np.float32) * self.scale
        return feature


class MatToTensor(FeatureTransformer):
    """HWC → CHW float (ref: MatToTensor — emits the NCHW tensor jax
    models consume)."""

    def __init__(self, to_rgb: bool = False):
        self.to_rgb = to_rgb

    def transform_mat(self, feature):
        mat = np.asarray(feature[ImageFeature.MAT], np.float32)
        if mat.ndim == 2:
            mat = mat[..., None]
        if self.to_rgb:
            mat = mat[..., ::-1]
        feature[ImageFeature.FLOATS] = np.ascontiguousarray(
            mat.transpose(2, 0, 1))
        return feature


class ImageFrameToSample(FeatureTransformer):
    """Pack floats (+label) into a Sample (ref: ImageFrameToSample)."""

    def __init__(self, input_keys: Sequence[str] = (ImageFeature.FLOATS,),
                 target_keys: Optional[Sequence[str]] = None):
        self.input_keys = list(input_keys)
        self.target_keys = list(target_keys) if target_keys else None

    def transform_mat(self, feature):
        from bigdl_tpu.feature.dataset import Sample

        xs = [np.asarray(feature[k], np.float32) for k in self.input_keys]
        x = xs[0] if len(xs) == 1 else xs
        t = None
        if self.target_keys:
            ts = [np.asarray(feature[k]) for k in self.target_keys]
            t = ts[0] if len(ts) == 1 else ts
        elif ImageFeature.LABEL in feature:
            t = np.asarray(feature[ImageFeature.LABEL])
        feature[ImageFeature.SAMPLE] = Sample(x, t)
        return feature

"""Vision pipeline (ref: S:dllib/feature/transform/vision/image/ —
ImageFrame/ImageFeature + OpenCV-JNI-backed augmentation ops).

Host-side preprocessing stays on CPU (SURVEY.md §2.2: the OpenCV JNI role
maps to host numpy/PIL); the output of the pipeline is NCHW float arrays
ready to shard onto the mesh."""

from bigdl_tpu.feature.vision.image_frame import (
    ImageFeature, ImageFrame, LocalImageFrame)
from bigdl_tpu.feature.vision.transforms import (
    AspectScale, CenterCrop, ChannelNormalize, ChannelScaledNormalizer,
    ColorJitter, FeatureTransformer, Hue, ImageFrameToSample, MatToTensor,
    PixelBytesToMat, RandomCrop, RandomHFlip, RandomTransformer, Resize,
    Brightness, Contrast, Saturation, HFlip)

__all__ = [
    "ImageFeature", "ImageFrame", "LocalImageFrame",
    "FeatureTransformer", "Resize", "AspectScale", "CenterCrop",
    "RandomCrop", "RandomHFlip", "HFlip", "ChannelNormalize",
    "ChannelScaledNormalizer", "MatToTensor", "ImageFrameToSample",
    "PixelBytesToMat", "Brightness", "Contrast", "Saturation", "Hue",
    "ColorJitter", "RandomTransformer",
]

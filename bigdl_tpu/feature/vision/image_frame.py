"""ImageFrame / ImageFeature (ref: vision/image/ImageFrame.scala,
ImageFeature.scala — a keyed feature map per image flowing through
transformers; Local vs Distributed frame)."""

from __future__ import annotations

import glob as _glob
import os
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np


class ImageFeature(dict):
    """Keyed per-image state (ref keys kept: bytes/mat/floats/sample/
    label/uri/originalSize)."""

    BYTES = "bytes"
    MAT = "mat"          # HWC uint8/float numpy (the "OpenCVMat")
    FLOATS = "floats"
    SAMPLE = "sample"
    LABEL = "label"
    URI = "uri"
    ORIGINAL_SIZE = "originalSize"

    def __init__(self, data: Optional[bytes] = None,
                 label=None, uri: Optional[str] = None, **kwargs):
        super().__init__(**kwargs)
        if data is not None:
            self[self.BYTES] = data
        if label is not None:
            self[self.LABEL] = label
        if uri is not None:
            self[self.URI] = uri

    def get_image(self) -> Optional[np.ndarray]:
        return self.get(self.MAT)

    def get_label(self):
        return self.get(self.LABEL)


class ImageFrame:
    """Factory facade (ref: object ImageFrame — read/readParquet,
    fromImageFeature arrays; isLocal/isDistributed)."""

    @staticmethod
    def read(path: str, min_partitions: int = 1) -> "LocalImageFrame":
        """Read image file(s); glob patterns supported."""
        files = sorted(_glob.glob(path))
        if not files and os.path.exists(path):
            files = [path]
        feats = []
        for f in files:
            with open(f, "rb") as fh:
                feats.append(ImageFeature(data=fh.read(), uri=f))
        return LocalImageFrame(feats)

    @staticmethod
    def from_arrays(images: Sequence[np.ndarray],
                    labels: Optional[Sequence] = None) -> "LocalImageFrame":
        feats = []
        for i, img in enumerate(images):
            f = ImageFeature()
            f[ImageFeature.MAT] = np.asarray(img)
            f[ImageFeature.ORIGINAL_SIZE] = np.asarray(img).shape
            if labels is not None:
                f[ImageFeature.LABEL] = labels[i]
            feats.append(f)
        return LocalImageFrame(feats)


class LocalImageFrame(ImageFrame):
    """ref: LocalImageFrame — array-backed frame."""

    def __init__(self, features: List[ImageFeature]):
        self.features = list(features)

    def transform(self, transformer) -> "LocalImageFrame":
        self.features = [transformer(f) for f in self.features]
        return self

    __rshift__ = transform

    def is_local(self) -> bool:
        return True

    def is_distributed(self) -> bool:
        return False

    def get_image(self) -> List[np.ndarray]:
        return [f.get_image() for f in self.features]

    def get_label(self) -> List:
        return [f.get_label() for f in self.features]

    def to_samples(self):
        from bigdl_tpu.feature.dataset import Sample

        out = []
        for f in self.features:
            if ImageFeature.SAMPLE in f:
                out.append(f[ImageFeature.SAMPLE])
            else:
                out.append(Sample(f[ImageFeature.FLOATS]
                                  if ImageFeature.FLOATS in f
                                  else f[ImageFeature.MAT],
                                  f.get(ImageFeature.LABEL)))
        return out

    def __len__(self):
        return len(self.features)

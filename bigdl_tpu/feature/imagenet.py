"""ImageNet directory-format reader (ref: the reference reads ImageNet as
Hadoop sequence files — ``models/inception/ImageNet2012.scala`` — with an
OpenCV JNI augment chain, SURVEY.md §2.4. The TPU-native equivalent keeps
decode/augment on the host CPU: class-per-subdirectory of JPEGs, PIL
decode, streaming Samples with a background prefetcher so host IO overlaps
device compute — the overlap shows up in the Metrics data timer.)

Layout::

    root/train/n01440764/xxx.JPEG
    root/val/n01440764/yyy.JPEG

Labels are 1-based class indices in sorted-directory order (the
reference's convention).
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np

from bigdl_tpu.feature.dataset import AbstractDataSet, Sample

IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)

_EXTS = (".jpeg", ".jpg", ".png", ".bmp")


def _normalize(chw: np.ndarray) -> np.ndarray:
    return ((chw - IMAGENET_MEAN[:, None, None])
            / IMAGENET_STD[:, None, None]).astype(np.float32)


class ImageFolderDataSet(AbstractDataSet):
    """Streaming class-per-subdir image dataset: decode + augment on the
    host per sample (never materializes the full set in memory)."""

    def __init__(self, root: str, image_size: int = 224,
                 train: bool = True, seed: int = 0,
                 class_names: Optional[List[str]] = None):
        self.root = root
        self.image_size = image_size
        self.train = train
        self._rng = np.random.RandomState(seed)
        classes = class_names or sorted(
            d for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d)))
        self.class_names = classes
        self.files: List[Tuple[str, int]] = []
        for idx, cls in enumerate(classes):
            cdir = os.path.join(root, cls)
            for fn in sorted(os.listdir(cdir)):
                if fn.lower().endswith(_EXTS):
                    # 1-based labels, reference convention
                    self.files.append((os.path.join(cdir, fn), idx + 1))

    def size(self) -> int:
        return len(self.files)

    def _load(self, path: str) -> np.ndarray:
        from PIL import Image

        img = Image.open(path).convert("RGB")
        s = self.image_size
        if self.train:
            # inception-style random resized crop (area 0.3..1)
            w, h = img.size
            for _ in range(5):
                area = w * h * self._rng.uniform(0.3, 1.0)
                ar = self._rng.uniform(3 / 4, 4 / 3)
                cw = int(round(np.sqrt(area * ar)))
                ch = int(round(np.sqrt(area / ar)))
                if cw <= w and ch <= h:
                    x0 = self._rng.randint(0, w - cw + 1)
                    y0 = self._rng.randint(0, h - ch + 1)
                    img = img.crop((x0, y0, x0 + cw, y0 + ch))
                    break
            img = img.resize((s, s), Image.BILINEAR)
            arr = np.asarray(img, np.float32) / 255.0
            if self._rng.rand() < 0.5:
                arr = arr[:, ::-1]
        else:
            # resize shorter side to s*1.14 then center crop
            w, h = img.size
            scale = int(s * 1.14) / min(w, h)
            img = img.resize((max(s, int(w * scale)),
                              max(s, int(h * scale))), Image.BILINEAR)
            w, h = img.size
            x0, y0 = (w - s) // 2, (h - s) // 2
            img = img.crop((x0, y0, x0 + s, y0 + s))
            arr = np.asarray(img, np.float32) / 255.0
        chw = np.ascontiguousarray(arr.transpose(2, 0, 1))
        return _normalize(chw)

    def data(self, train: bool = True):
        order = np.arange(len(self.files))
        if train and self.train:
            self._rng.shuffle(order)
        for i in order:
            path, label = self.files[i]
            yield Sample(self._load(path), np.float32(label))


def synthetic_imagenet_dataset(n: int = 256, classes: int = 1000,
                               image_size: int = 224, seed: int = 0):
    """Streaming synthetic stand-in with ImageNet shapes (offline env)."""
    from bigdl_tpu.feature.dataset import LocalDataSet

    rs = np.random.RandomState(seed)
    labels = (rs.randint(0, classes, n) + 1).astype(np.float32)

    class _Synthetic(AbstractDataSet):
        def size(self):
            return n

        def data(self, train: bool = True):
            order = rs.permutation(n) if train else np.arange(n)
            for i in order:
                img = rs.rand(3, image_size, image_size).astype(np.float32)
                yield Sample(_normalize(img), labels[i])

    return _Synthetic()

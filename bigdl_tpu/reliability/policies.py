"""Policy primitives (ISSUE 2 tentpole part b).

The four building blocks every failure path in the codebase composes —
checkpointing, the optimizer retry loop, the redis queue backend, both
HTTP front-ends:

- :class:`RetryPolicy` — exponential backoff with seeded jitter and an
  attempt budget; injectable clock/sleep so tier-1 tests never sleep;
- :class:`Deadline` — a monotonic-clock budget propagated per-request
  (HTTP header ``X-BigDL-Deadline-Ms``);
- :class:`CircuitBreaker` — closed → open after N consecutive failures,
  half-open probe after ``reset_timeout``, with every transition
  counted (``bigdl_reliability_breaker_transitions_total``);
- health-check registry — named liveness callables rendered by the
  ``GET /healthz`` endpoints on ServingFrontend and LLMWorker.

All knobs default from the layered config (``bigdl.reliability.retry.*``)
so operators tune one place.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Dict, Iterator, Optional, Tuple


class DeadlineExceeded(TimeoutError):
    """A propagated per-request deadline ran out."""


class CircuitOpenError(RuntimeError):
    """The breaker is open: the call was rejected without being tried."""


class OverloadError(RuntimeError):
    """Admission control rejected new work (bounded queue full or the
    component is draining). HTTP surfaces map this to 503 + Retry-After."""


class TrainingPreempted(RuntimeError):
    """SIGTERM/SIGINT arrived mid-training: state was checkpointed and
    the training loop exited. A fresh ``optimize()`` auto-resumes."""


def _count(_metric: str, _help: str, **labels):
    # positional params are underscored: labels legitimately use keys
    # like ``name`` (breaker transitions), which must not collide.
    # Gated on the reliability switch too: a disabled process must mint
    # ZERO bigdl_reliability_* series (the structurally-absent contract)
    # even though the policy objects themselves keep working.
    from bigdl_tpu import observability as obs
    from bigdl_tpu.reliability import _state
    if not _state.enabled or not obs.enabled():
        return
    c = obs.counter(_metric, _help, labelnames=tuple(labels))
    (c.labels(**labels) if labels else c).inc()


# ---------------------------------------------------------------------------
# Deadline
# ---------------------------------------------------------------------------

#: Header carrying the remaining budget downstream, in integer ms.
DEADLINE_HEADER = "X-BigDL-Deadline-Ms"


class Deadline:
    """A fixed point on the monotonic clock. Cheap value object: callers
    pass it down the stack; every blocking wait takes
    ``min(its own timeout, deadline.remaining())``."""

    __slots__ = ("_at", "_clock")

    def __init__(self, seconds: float, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._at = clock() + float(seconds)

    def remaining(self) -> float:
        return self._at - self._clock()

    def expired(self) -> bool:
        return self.remaining() <= 0

    def check(self, what: str = "request"):
        """Raise :class:`DeadlineExceeded` (and count it) if expired."""
        if self.expired():
            _count("bigdl_reliability_deadline_expired_total",
                   "Deadlines that ran out before the work completed")
            raise DeadlineExceeded(f"deadline exceeded for {what}")

    def to_header(self) -> str:
        return str(max(int(self.remaining() * 1000), 0))

    @staticmethod
    def from_header(value: Optional[str],
                    clock: Callable[[], float] = time.monotonic
                    ) -> Optional["Deadline"]:
        """Parse a ``X-BigDL-Deadline-Ms`` header; None/garbage → None
        (an unparseable deadline must not fail the request)."""
        if not value:
            return None
        try:
            return Deadline(int(value) / 1000.0, clock=clock)
        except (TypeError, ValueError):
            return None


#: Process-wide RNG for Retry-After jitter. Module-level (not per-call)
#: so a shed storm decorrelates across requests within one process.
_RETRY_AFTER_RNG = random.Random()


def retry_after_seconds(queue_depth: int = 0,
                        rng: Optional[random.Random] = None) -> str:
    """Derive a ``Retry-After`` header value from observed queue depth
    (ISSUE 7 satellite — replaces the hardcoded ``1``).

    ``clamp(base + per_queued * depth, 1, max)`` stretched by up to
    ``jitter`` fraction so a thundering herd of shed clients does not
    retry in lockstep (at the cap the jitter spreads downward instead,
    so saturation never re-synchronizes the herd). All knobs under
    ``bigdl.llm.retry_after.*``.
    Returns the integer-second string HTTP wants; an empty queue with
    the default knobs still renders ``"1"`` (jitter stretches the value
    by at most 20% before rounding), so existing clients see no change
    until pressure actually builds."""
    from bigdl_tpu.utils.conf import conf
    base = conf.get_float("bigdl.llm.retry_after.base", 1.0)
    per = conf.get_float("bigdl.llm.retry_after.per_queued", 0.25)
    cap = conf.get_float("bigdl.llm.retry_after.max", 30.0)
    jitter = conf.get_float("bigdl.llm.retry_after.jitter", 0.2)
    r = (rng or _RETRY_AFTER_RNG).random()
    val = base + per * max(int(queue_depth), 0)
    if val >= cap:
        # saturated: jitter DOWN from the cap — stretching upward and
        # clamping would hand every shed client exactly the cap,
        # re-synchronizing the herd precisely at the deepest backlog
        val = cap * (1.0 - max(jitter, 0.0) * r)
    else:
        val = min(val * (1.0 + max(jitter, 0.0) * r), cap)
    return str(max(1, int(round(val))))


# ---------------------------------------------------------------------------
# RetryPolicy
# ---------------------------------------------------------------------------

class RetryPolicy:
    """Exponential backoff + seeded jitter + attempt budget.

    ``max_attempts`` counts *tries*, not retries: 3 means one initial
    attempt and up to two retries. Delay before retry ``i`` (0-based) is
    ``min(max_delay, base_delay * multiplier**i)`` stretched by up to
    ``jitter`` fraction via the policy's own seeded RNG — deterministic
    schedules for tests, decorrelated fleets in production (every
    process seeds from entropy by default).

    ``clock``/``sleep`` are injectable so the tier-1 suite exercises
    full schedules with a fake clock and zero real sleeping.
    """

    def __init__(self, max_attempts: Optional[int] = None,
                 base_delay: Optional[float] = None,
                 max_delay: Optional[float] = None,
                 multiplier: float = 2.0, jitter: float = 0.5,
                 seed: Optional[int] = None,
                 sleep: Callable[[float], None] = time.sleep):
        from bigdl_tpu.utils.conf import conf
        self.max_attempts = max_attempts if max_attempts is not None else \
            (conf.get_int("bigdl.reliability.retry.max.attempts", 3) or 3)
        self.base_delay = base_delay if base_delay is not None else \
            conf.get_float("bigdl.reliability.retry.base.delay", 0.05)
        self.max_delay = max_delay if max_delay is not None else \
            conf.get_float("bigdl.reliability.retry.max.delay", 2.0)
        self.multiplier = multiplier
        self.jitter = jitter
        self._rng = random.Random(seed)
        self._sleep = sleep

    def delays(self) -> Iterator[float]:
        """The backoff schedule: ``max_attempts - 1`` delays."""
        for i in range(max(self.max_attempts - 1, 0)):
            base = min(self.max_delay,
                       self.base_delay * self.multiplier ** i)
            yield base * (1.0 + self.jitter * self._rng.random())

    def call(self, fn: Callable, *args,
             retry_on: Tuple = (Exception,),
             deadline: Optional[Deadline] = None,
             on_retry: Optional[Callable] = None,
             component: str = "", **kwargs):
        """Run ``fn`` under the policy. ``on_retry(exc, attempt)`` is
        called before each backoff sleep; ``component`` labels the
        ``bigdl_reliability_retries_total`` increments."""
        delays = self.delays()
        attempt = 0
        while True:
            if deadline is not None:
                deadline.check(component or "retryable call")
            try:
                return fn(*args, **kwargs)
            except retry_on as e:
                attempt += 1
                try:
                    delay = next(delays)
                except StopIteration:
                    raise e
                _count("bigdl_reliability_retries_total",
                       "Retries performed under a RetryPolicy",
                       component=component or "unknown")
                if on_retry is not None:
                    on_retry(e, attempt)
                if deadline is not None and \
                        delay >= max(deadline.remaining(), 0):
                    raise e    # sleeping would blow the deadline anyway
                self._sleep(delay)


# ---------------------------------------------------------------------------
# CircuitBreaker
# ---------------------------------------------------------------------------

class CircuitBreaker:
    """Classic three-state breaker.

    closed --(``failure_threshold`` consecutive failures)--> open
    open --(``reset_timeout`` on the clock)--> half_open (one probe)
    half_open --success--> closed; --failure--> open (timer restarts)

    Thread-safe; ``clock`` injectable for sleep-free tests. Transitions
    increment ``bigdl_reliability_breaker_transitions_total{name,state}``
    so an operator can watch a trip and its recovery on /metrics.
    """

    def __init__(self, name: str, failure_threshold: int = 5,
                 reset_timeout: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self.failure_threshold = failure_threshold
        self.reset_timeout = reset_timeout
        self._clock = clock
        self._lock = threading.Lock()
        self._state = "closed"
        self._failures = 0
        self._opened_at = 0.0

    @property
    def state(self) -> str:
        with self._lock:
            return self._probe_locked()

    def _probe_locked(self) -> str:
        if self._state == "open" and \
                self._clock() - self._opened_at >= self.reset_timeout:
            self._transition("half_open")
        return self._state

    def _transition(self, new: str):
        if new != self._state:
            self._state = new
            _count("bigdl_reliability_breaker_transitions_total",
                   "CircuitBreaker state transitions",
                   name=self.name, state=new)

    def allow(self) -> bool:
        """May a call proceed right now? (open → False; the half-open
        probe slot is granted to the first caller after the timeout)."""
        with self._lock:
            return self._probe_locked() != "open"

    def record_success(self):
        with self._lock:
            self._failures = 0
            self._transition("closed")

    def record_failure(self):
        with self._lock:
            self._failures += 1
            if self._state == "half_open" or \
                    self._failures >= self.failure_threshold:
                self._opened_at = self._clock()
                self._transition("open")

    def call(self, fn: Callable, *args, **kwargs):
        if not self.allow():
            raise CircuitOpenError(
                f"circuit {self.name!r} is open (retry after "
                f"{self.reset_timeout:g}s)")
        try:
            out = fn(*args, **kwargs)
        except Exception:
            self.record_failure()
            raise
        self.record_success()
        return out


# ---------------------------------------------------------------------------
# Health checks
# ---------------------------------------------------------------------------

_health_lock = threading.Lock()
_health_checks: Dict[str, Callable[[], object]] = {}


def register_health(name: str, fn: Callable[[], object]):
    """Register a liveness callable. It should return quickly: truthy /
    a detail dict means healthy; raising or returning falsy means not.
    No-op when the reliability layer is disabled (the disabled-mode test
    asserts an empty registry)."""
    from bigdl_tpu.reliability import _state
    if not _state.enabled:
        return
    with _health_lock:
        _health_checks[name] = fn


def unregister_health(name: str):
    with _health_lock:
        _health_checks.pop(name, None)


def health_checks() -> Dict[str, Callable]:
    with _health_lock:
        return dict(_health_checks)


def health_report() -> Tuple[bool, Dict[str, dict]]:
    """Run every registered check. Returns (all_ok, per-check detail) —
    the body ``GET /healthz`` serves with 200/503."""
    report: Dict[str, dict] = {}
    ok = True
    for name, fn in sorted(health_checks().items()):
        try:
            out = fn()
            healthy = bool(out) if not isinstance(out, dict) else \
                bool(out.get("ok", True))
            detail = out if isinstance(out, dict) else {}
            report[name] = {"ok": healthy, **detail}
        except Exception as e:  # noqa: BLE001 — a check must never 500
            healthy = False
            report[name] = {"ok": False, "error": repr(e)}
        ok = ok and healthy
    return ok, report

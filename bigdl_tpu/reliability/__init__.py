"""Reliability layer for bigdl_tpu (ISSUE 2 tentpole).

BigDL's defining claim (SoCC'19) is that training and serving survive
failures in commodity clusters. This package makes the TPU rebuild's
failure paths designed and testable instead of incidental:

- :mod:`~bigdl_tpu.reliability.faults` — named **fault-injection
  points** (``reliability.inject("checkpoint.write")``) threaded through
  checkpointing, the optimizer iteration, the cluster-serving backends
  and both HTTP front-ends. Zero-cost no-ops in production (one
  attribute check); under a seeded :class:`FaultPlan` they
  deterministically raise, delay or corrupt.
- :mod:`~bigdl_tpu.reliability.policies` — the primitives the real
  paths compose: :class:`RetryPolicy` (exponential backoff + jitter +
  budget), :class:`Deadline` (propagated per-request),
  :class:`CircuitBreaker`, and the health-check registry behind
  ``GET /healthz``.

Every retry / shed / breaker trip / injected fault increments a
``bigdl_reliability_*`` counter in the observability registry, so an
operator can watch failure handling working on ``/metrics``.

Master switch: ``bigdl.reliability.enabled`` (env
``BIGDL_TPU_RELIABILITY_ENABLED``). Disabled means structurally absent:
no plan can be armed, no signal handlers install, no health checks
register, and checkpoint files keep the exact PR-1 layout.
"""

from __future__ import annotations

from bigdl_tpu.reliability import _state
from bigdl_tpu.reliability.faults import (
    SITES, FaultPlan, InjectedFault, active_plan, armed_sites, inject,
    set_plan)
from bigdl_tpu.reliability.policies import (
    DEADLINE_HEADER, CircuitBreaker, CircuitOpenError, Deadline,
    DeadlineExceeded, OverloadError, RetryPolicy, TrainingPreempted,
    health_checks, health_report, register_health, retry_after_seconds,
    unregister_health)


def enabled() -> bool:
    return _state.enabled


def enable():
    _state.enabled = True


def disable():
    """Structural no-op mode: disarms any plan; subsequent set_plan /
    register_health calls are rejected / ignored."""
    _state.enabled = False
    _state.plan = None


def count_shed(component: str, request_id=None, trace_id=None, **detail):
    """Record one load-shedding rejection (503 + Retry-After). Every
    increment also lands one flight-recorder ``shed`` event (when that
    recorder is enabled) carrying the caller's ledger snapshot — the
    chaos cross-check asserts events reconcile EXACTLY with this
    counter, so the two must share a call site."""
    from bigdl_tpu.reliability.policies import _count
    _count("bigdl_reliability_shed_total",
           "Requests rejected by admission control",
           component=component)
    from bigdl_tpu.observability import flight
    flight.record("shed", request_id=request_id, trace_id=trace_id,
                  component=component, **detail)


__all__ = [
    "DEADLINE_HEADER", "SITES",
    "CircuitBreaker", "CircuitOpenError", "Deadline", "DeadlineExceeded",
    "FaultPlan", "InjectedFault", "OverloadError", "RetryPolicy",
    "TrainingPreempted",
    "active_plan", "armed_sites", "count_shed", "disable", "enable",
    "enabled", "health_checks", "health_report", "inject",
    "register_health", "retry_after_seconds", "set_plan",
    "unregister_health",
]

"""Fault-injection registry (ISSUE 2 tentpole part a).

Named injection points are threaded through the paths whose failure
handling the SoCC'19 claim rests on — checkpointing, the optimizer
iteration, the cluster-serving backends and both HTTP front-ends:

    from bigdl_tpu import reliability
    reliability.inject("checkpoint.write.manifest")

In production ``inject`` is a no-op costing one module-attribute read
and one ``is None`` compare (``_state.plan``). Under a seeded test-mode
:class:`FaultPlan` the armed rules deterministically **raise**
(:class:`InjectedFault`), **delay** (``time.sleep``) or signal the call
site to **corrupt** its data (``inject`` returns ``"corrupt"`` and the
site — which knows its own bytes — does the flipping). Every fired
fault increments ``bigdl_reliability_injected_faults_total{site,action}``
so no injected failure can be silently swallowed.

The catalog of sites lives in docs/RELIABILITY.md; ``SITES`` below is
the authoritative list (``FaultPlan.randomize`` draws from it).
"""

from __future__ import annotations

import fnmatch
import random
import threading
import time
from typing import Dict, List, Optional

from bigdl_tpu.reliability import _state

#: Injection points wired into the codebase (docs/RELIABILITY.md
#: catalog). Plans may arm any site name (globs allowed); this list is
#: what ``randomize`` samples and what the docs promise exists.
SITES = (
    "checkpoint.write",            # save_checkpoint entry
    "checkpoint.write.arrays",     # after arrays land (corrupt-capable)
    "checkpoint.write.manifest",   # between arrays and manifest writes
    "checkpoint.commit",           # before the atomic rename
    "checkpoint.load",             # load_checkpoint entry
    "optimizer.step",              # top of each training iteration
    "optimizer.checkpoint",        # before the optimizer persists state
    "serving.backend.push",        # queue backend write
    "serving.backend.pop",         # queue backend read
    "serving.batch",               # cluster-serving batch execution
    "serving.frontend.request",    # HTTP /predict admission
    "llm.submit",                  # LLMServer request admission
    "llm.step",                    # LLM engine decode step
    "llm.chunk",                   # between chunks of one chunked
                                   # admission (ISSUE 14)
    "kvcache.evict",               # prefix-cache LRU eviction (ISSUE 5)
    "kvtier.spill",                # HBM->host page spill (ISSUE 6)
    "kvtier.fetch",                # host->HBM page fetch (ISSUE 6)
    "router.dispatch",             # router->backend call/stream (ISSUE 7)
    "worker.stall",                # hung engine decode step (ISSUE 7)
    "elastic.heartbeat",           # agent->supervisor beat (ISSUE 10)
    "elastic.step",                # elastic-guarded train step (ISSUE 10)
    "federation.scrape",           # fleet collector member scrape (ISSUE 12)
    "fleet.scale",                 # autoscaler scale action (ISSUE 15)
    "worker.drain",                # per-chain drain migration (ISSUE 15)
    "llm.preempt",                 # before a victim's KV chain is
                                   # exported (ISSUE 17)
    "llm.spec",                    # between drafting and the verify
                                   # dispatch (ISSUE 19)
)


class InjectedFault(RuntimeError):
    """Raised by an armed ``raise`` rule. Deliberately a RuntimeError:
    recovery paths must treat it like any real fault, never special-case
    it (special-casing would make the chaos suite test nothing)."""


class FaultPlan:
    """A seeded, deterministic schedule of faults.

    Rules are matched in insertion order against the site name
    (``fnmatch`` globs, so ``checkpoint.*`` arms the whole family)::

        plan = FaultPlan(seed=7)
        plan.add("checkpoint.write.manifest", "raise", after=1, times=1)
        plan.add("serving.backend.pop", "delay", delay=0.05, times=3)
        plan.add("checkpoint.write.arrays", "corrupt", times=1)
        reliability.set_plan(plan)

    ``after`` skips the first N calls of the site; ``times`` bounds how
    often the rule fires (None = forever); ``prob`` gates each firing on
    the plan's own seeded RNG, so "randomized" chaos runs are exactly
    reproducible from the seed.
    """

    def __init__(self, seed: int = 0):
        self.seed = seed
        self._rng = random.Random(seed)
        self._rules: List[Dict] = []
        self._calls: Dict[str, int] = {}
        self._lock = threading.Lock()
        #: chronological log of fired faults: (site, action) tuples —
        #: the chaos harness asserts injected == recovered from this.
        self.fired: List[tuple] = []

    def add(self, site: str, action: str = "raise", *, times: Optional[int] = 1,
            after: int = 0, delay: float = 0.01, prob: float = 1.0,
            exc: Optional[BaseException] = None) -> "FaultPlan":
        if action not in ("raise", "delay", "corrupt"):
            raise ValueError(f"unknown fault action {action!r}")
        self._rules.append({"site": site, "action": action, "times": times,
                            "after": after, "delay": delay, "prob": prob,
                            "exc": exc, "fired": 0, "seen": 0})
        return self

    def randomize(self, n: int, sites=SITES,
                  actions=("raise", "delay", "corrupt")) -> "FaultPlan":
        """Arm ``n`` random-but-seeded rules over ``sites`` (the chaos
        harness entry). Corrupt rules only make sense on corrupt-capable
        sites, so they are pinned to ``checkpoint.write.arrays``."""
        for _ in range(n):
            action = self._rng.choice(list(actions))
            site = ("checkpoint.write.arrays" if action == "corrupt"
                    else self._rng.choice(list(sites)))
            self.add(site, action, times=1,
                     after=self._rng.randint(0, 2),
                     delay=self._rng.uniform(0.001, 0.02))
        return self

    def sites(self) -> List[str]:
        """Site patterns this plan has armed (empty once disarmed)."""
        return sorted({r["site"] for r in self._rules})

    # -- firing --------------------------------------------------------------
    def fire(self, site: str) -> Optional[str]:
        with self._lock:
            self._calls[site] = self._calls.get(site, 0) + 1
            decision = None
            for r in self._rules:
                if not fnmatch.fnmatch(site, r["site"]):
                    continue
                r["seen"] += 1
                if r["seen"] <= r["after"]:
                    continue
                if r["times"] is not None and r["fired"] >= r["times"]:
                    continue
                if r["prob"] < 1.0 and self._rng.random() >= r["prob"]:
                    continue
                r["fired"] += 1
                decision = r
                break
            if decision is None:
                return None
            self.fired.append((site, decision["action"]))
        _count_injected(site, decision["action"])
        if decision["action"] == "delay":
            time.sleep(decision["delay"])
            return "delay"
        if decision["action"] == "raise":
            raise decision["exc"] or InjectedFault(
                f"injected fault at {site!r}")
        return "corrupt"


def _count_injected(site: str, action: str):
    from bigdl_tpu import observability as obs
    if obs.enabled():
        obs.counter(
            "bigdl_reliability_injected_faults_total",
            "Faults fired by the armed FaultPlan",
            labelnames=("site", "action")).labels(
                site=site, action=action).inc()


def inject(site: str) -> Optional[str]:
    """The injection point. Production fast path: one attribute read +
    ``is None`` — nothing else executes. Test mode: the armed plan may
    raise :class:`InjectedFault`, sleep, or return ``"corrupt"``."""
    plan = _state.plan
    if plan is None:
        return None
    return plan.fire(site)


def set_plan(plan: Optional[FaultPlan]):
    """Arm (or with ``None`` disarm) a fault plan. Requires the
    reliability layer enabled — a disabled process must stay structurally
    fault-free (the zero-overhead contract)."""
    if plan is not None and not _state.enabled:
        raise RuntimeError(
            "bigdl.reliability.enabled=false: fault plans cannot be armed "
            "in a disabled process")
    _state.plan = plan


def active_plan() -> Optional[FaultPlan]:
    return _state.plan


def armed_sites() -> List[str]:
    """Site patterns currently armed; ``[]`` in production/disabled mode
    (asserted by the disabled-mode no-op test)."""
    plan = _state.plan
    return plan.sites() if plan is not None else []

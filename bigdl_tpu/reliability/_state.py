"""Process-global reliability switches.

Mirrors ``bigdl_tpu.observability._state``: a bare module holding the
flags the hot paths read, living apart from the package ``__init__`` so
``faults``/``policies`` and the package itself can all import it without
cycles.

Two attributes matter:

- ``enabled`` — the master switch (config key
  ``bigdl.reliability.enabled``, env ``BIGDL_TPU_RELIABILITY_ENABLED``).
  When False: no fault plan can be armed, no signal handlers are
  installed, no health checks register — the reliability layer is
  structurally absent, not merely quiet.
- ``plan`` — the armed :class:`~bigdl_tpu.reliability.faults.FaultPlan`,
  or ``None`` in production. ``inject(site)``'s fast path is a single
  attribute check (``_state.plan is None``) so production code pays one
  dict lookup + one identity compare per injection point — the
  zero-overhead contract the disabled-mode test asserts.
"""

from __future__ import annotations

from typing import Optional


def _initial() -> bool:
    try:
        from bigdl_tpu.utils.conf import conf
        return conf.get_bool("bigdl.reliability.enabled", True)
    except Exception:
        return True


enabled: bool = _initial()

#: The armed fault plan. None in production — inject() early-returns.
plan = None  # type: Optional[object]


def refresh(key: str):
    """Re-read ONE reliability config key; called by ``BigDLConf.set``/
    ``unset`` so the programmatic layer works after import. Only the
    changed key is applied (a retry-knob change must not clobber a
    runtime ``enable()``/``disable()`` override of the switch)."""
    global enabled, plan
    from bigdl_tpu.utils.conf import conf
    if key == "bigdl.reliability.enabled":
        enabled = conf.get_bool("bigdl.reliability.enabled", True)
        if not enabled:
            plan = None   # disabling disarms any active plan

"""Stable, versioned checkpoint format (ref: ``S:dllib/utils/serializer/``
— the reference persists modules as protobuf ``bigdl.proto`` with a
registered serializer per layer; SURVEY.md §2.3 "Serialization").

TPU-first substitution: the load-bearing state of a jax model is a
**pytree of arrays**, so the stable on-disk surface is

``<path>/``
  ``manifest.json``        format name + version + tree structure + user
                           metadata (pure JSON — readable forever)
  ``arrays.safetensors``   every array leaf under a flat key (safetensors:
                           the HF-standard zero-copy tensor container,
                           bf16 supported via ml_dtypes)

Nothing in the format executes code on load (unlike pickle): the tree
structure is JSON and the arrays are raw buffers, so checkpoints are
portable across bigdl_tpu versions and across processes that never import
the producing classes. ``Module.save_module`` keeps a ``structure.pkl``
*sidecar* for same-version convenience reconstruction, but weights are
always loadable without it via :func:`load_checkpoint`.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import numpy as np

FORMAT_NAME = "bigdl_tpu.checkpoint"
FORMAT_VERSION = 1

_ARRAYS_FILE = "arrays.safetensors"
_MANIFEST_FILE = "manifest.json"


def _flatten(tree: Any, prefix: str, arrays: Dict[str, np.ndarray]) -> Any:
    """Tree -> JSON-able structure; array leaves move into ``arrays``."""
    if tree is None or isinstance(tree, (bool, int, float, str)):
        return {"t": "py", "v": tree}
    if isinstance(tree, dict):
        return {"t": "dict",
                "items": {str(k): _flatten(v, f"{prefix}{k}.", arrays)
                          for k, v in tree.items()}}
    if isinstance(tree, (list, tuple)):
        return {"t": "list" if isinstance(tree, list) else "tuple",
                "items": [_flatten(v, f"{prefix}{i}.", arrays)
                          for i, v in enumerate(tree)]}
    arr = np.asarray(tree)
    key = prefix.rstrip(".") or "_root"
    if key in arrays:
        raise ValueError(f"duplicate checkpoint key {key!r}")
    arrays[key] = np.ascontiguousarray(arr)
    return {"t": "arr", "key": key}


def _unflatten(node: Any, arrays: Dict[str, np.ndarray]) -> Any:
    t = node["t"]
    if t == "py":
        return node["v"]
    if t == "dict":
        return {k: _unflatten(v, arrays) for k, v in node["items"].items()}
    if t in ("list", "tuple"):
        seq = [_unflatten(v, arrays) for v in node["items"]]
        return seq if t == "list" else tuple(seq)
    if t == "arr":
        return arrays[node["key"]]
    raise ValueError(f"unknown node type {t!r} in checkpoint manifest")


def save_checkpoint(path: str, tree: Any,
                    metadata: Optional[Dict[str, Any]] = None) -> str:
    """Persist a pytree (dicts/lists/tuples/scalars/arrays) to ``path``.

    jax arrays are pulled to host; bf16 round-trips via ml_dtypes.
    """
    from safetensors.numpy import save_file

    os.makedirs(path, exist_ok=True)
    arrays: Dict[str, np.ndarray] = {}
    structure = _flatten(tree, "", arrays)
    manifest = {
        "format": FORMAT_NAME,
        "version": FORMAT_VERSION,
        "tree": structure,
        "metadata": metadata or {},
    }
    save_file(arrays, os.path.join(path, _ARRAYS_FILE))
    with open(os.path.join(path, _MANIFEST_FILE), "w") as f:
        json.dump(manifest, f, indent=1)
    return path


def load_checkpoint(path: str, to_jax: bool = True
                    ) -> Tuple[Any, Dict[str, Any]]:
    """Load ``(tree, metadata)`` saved by :func:`save_checkpoint`."""
    from safetensors.numpy import load_file

    with open(os.path.join(path, _MANIFEST_FILE)) as f:
        manifest = json.load(f)
    if manifest.get("format") != FORMAT_NAME:
        raise ValueError(f"{path} is not a {FORMAT_NAME} checkpoint")
    if manifest.get("version", 0) > FORMAT_VERSION:
        raise ValueError(
            f"checkpoint version {manifest['version']} is newer than this "
            f"build supports ({FORMAT_VERSION})")
    arrays = load_file(os.path.join(path, _ARRAYS_FILE))
    tree = _unflatten(manifest["tree"], arrays)
    if to_jax:
        import jax
        import jax.numpy as jnp
        tree = jax.tree_util.tree_map(
            lambda l: jnp.asarray(l) if isinstance(l, np.ndarray) else l,
            tree)
    return tree, manifest.get("metadata", {})

"""Stable, versioned checkpoint format (ref: ``S:dllib/utils/serializer/``
— the reference persists modules as protobuf ``bigdl.proto`` with a
registered serializer per layer; SURVEY.md §2.3 "Serialization").

TPU-first substitution: the load-bearing state of a jax model is a
**pytree of arrays**, so the stable on-disk surface is

``<path>/``
  ``manifest.json``        format name + version + tree structure + user
                           metadata (pure JSON — readable forever)
  ``arrays.safetensors``   every array leaf under a flat key (safetensors:
                           the HF-standard zero-copy tensor container,
                           bf16 supported via ml_dtypes)

Nothing in the format executes code on load (unlike pickle): the tree
structure is JSON and the arrays are raw buffers, so checkpoints are
portable across bigdl_tpu versions and across processes that never import
the producing classes. ``Module.save_module`` keeps a ``structure.pkl``
*sidecar* for same-version convenience reconstruction, but weights are
always loadable without it via :func:`load_checkpoint`.

**Crash safety (ISSUE 2).** Writes are atomic: everything lands in a
``<path>.tmp-*`` sibling, every file is fsynced, and one ``os.rename``
publishes the directory — a reader can never observe arrays without a
manifest (the seed's ordering bug) or a half-written file. The manifest
carries a per-file SHA-256 (``files`` key — extra JSON the PR-1 loader
ignores, so the on-disk layout is unchanged); :func:`load_checkpoint`
verifies it and raises :class:`CheckpointCorruptError` on mismatch, and
:func:`latest` skips (and quarantines) incomplete or corrupt directories
so recovery never resumes from garbage. Fault-injection sites:
``checkpoint.write`` / ``.write.arrays`` (corrupt-capable) /
``.write.manifest`` / ``.commit`` / ``checkpoint.load``.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
import uuid
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from bigdl_tpu import reliability

logger = logging.getLogger("bigdl_tpu.checkpoint")

FORMAT_NAME = "bigdl_tpu.checkpoint"
FORMAT_VERSION = 1

_ARRAYS_FILE = "arrays.safetensors"
_MANIFEST_FILE = "manifest.json"
_TMP_MARK = ".tmp-"
_CORRUPT_MARK = ".corrupt-"


class CheckpointCorruptError(ValueError):
    """The checkpoint's bytes do not match its manifest checksums."""


def _flatten(tree: Any, prefix: str, arrays: Dict[str, np.ndarray]) -> Any:
    """Tree -> JSON-able structure; array leaves move into ``arrays``."""
    if tree is None or isinstance(tree, (bool, int, float, str)):
        return {"t": "py", "v": tree}
    if isinstance(tree, dict):
        return {"t": "dict",
                "items": {str(k): _flatten(v, f"{prefix}{k}.", arrays)
                          for k, v in tree.items()}}
    if isinstance(tree, (list, tuple)):
        return {"t": "list" if isinstance(tree, list) else "tuple",
                "items": [_flatten(v, f"{prefix}{i}.", arrays)
                          for i, v in enumerate(tree)]}
    arr = np.asarray(tree)
    key = prefix.rstrip(".") or "_root"
    if key in arrays:
        raise ValueError(f"duplicate checkpoint key {key!r}")
    arrays[key] = np.ascontiguousarray(arr)
    return {"t": "arr", "key": key}


def _unflatten(node: Any, arrays: Dict[str, np.ndarray]) -> Any:
    t = node["t"]
    if t == "py":
        return node["v"]
    if t == "dict":
        return {k: _unflatten(v, arrays) for k, v in node["items"].items()}
    if t in ("list", "tuple"):
        seq = [_unflatten(v, arrays) for v in node["items"]]
        return seq if t == "list" else tuple(seq)
    if t == "arr":
        return arrays[node["key"]]
    raise ValueError(f"unknown node type {t!r} in checkpoint manifest")


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def _fsync_file(path: str):
    with open(path, "rb") as f:
        os.fsync(f.fileno())


def _fsync_dir(path: str):
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:       # platforms without O_RDONLY dir opens
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _corrupt_file(path: str):
    """Flip one byte in the middle of ``path`` (the injected-corruption
    action: a realistic torn write the checksums must catch)."""
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.seek(size // 2)
        b = f.read(1)
        f.seek(size // 2)
        f.write(bytes([b[0] ^ 0xFF]) if b else b"\xff")


def save_checkpoint(path: str, tree: Any,
                    metadata: Optional[Dict[str, Any]] = None,
                    extra_files: Optional[Dict[str, bytes]] = None) -> str:
    """Persist a pytree (dicts/lists/tuples/scalars/arrays) to ``path``.

    jax arrays are pulled to host; bf16 round-trips via ml_dtypes.

    Atomic visibility: arrays, ``extra_files`` sidecars and the manifest
    (which carries each file's SHA-256) land in a temp sibling, are
    fsynced, and a rename publishes the directory — a reader can never
    observe a torn checkpoint. Fresh saves survive a crash at any point
    (previous state or an ignorable ``.tmp-*`` orphan). Overwriting an
    EXISTING directory has one unavoidable non-torn window (there is no
    portable atomic directory swap): a crash between the move-aside and
    the publish leaves that one tag absent — ``latest()`` then falls
    back to the next-newest valid tag, so recovery degrades by one
    checkpoint rather than loading garbage.
    """
    from safetensors.numpy import save_file

    reliability.inject("checkpoint.write")
    path = path.rstrip("/")
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    tmp = f"{path}{_TMP_MARK}{os.getpid()}-{uuid.uuid4().hex[:8]}"
    os.makedirs(tmp)
    try:
        arrays: Dict[str, np.ndarray] = {}
        structure = _flatten(tree, "", arrays)
        save_file(arrays, os.path.join(tmp, _ARRAYS_FILE))
        # "corrupt" flips a byte AFTER the checksums are computed (below)
        # — modelling bit-rot/torn writes the manifest doesn't reflect,
        # which is exactly what load-time verification must catch
        corrupt_arrays = \
            reliability.inject("checkpoint.write.arrays") == "corrupt"
        for name, blob in (extra_files or {}).items():
            with open(os.path.join(tmp, name), "wb") as f:
                f.write(blob)
        # the seed's ordering bug lived here: arrays visible, manifest
        # not yet — this site lets the regression test kill the writer
        # between the two writes and assert the partial dir never loads
        reliability.inject("checkpoint.write.manifest")
        files = {name: {"sha256": _sha256(os.path.join(tmp, name)),
                        "bytes": os.path.getsize(os.path.join(tmp, name))}
                 for name in os.listdir(tmp)}
        manifest = {
            "format": FORMAT_NAME,
            "version": FORMAT_VERSION,
            "tree": structure,
            "metadata": metadata or {},
            "files": files,
        }
        with open(os.path.join(tmp, _MANIFEST_FILE), "w") as f:
            json.dump(manifest, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        for name in files:
            _fsync_file(os.path.join(tmp, name))
        _fsync_dir(tmp)
        if corrupt_arrays:
            _corrupt_file(os.path.join(tmp, _ARRAYS_FILE))
        reliability.inject("checkpoint.commit")
        if os.path.isdir(path):
            # directories can't be renamed over: move the old one aside
            # first so the destination slot is only ever empty or whole
            aside = f"{path}{_TMP_MARK}old-{uuid.uuid4().hex[:8]}"
            os.rename(path, aside)
            os.rename(tmp, path)
            shutil.rmtree(aside, ignore_errors=True)
        else:
            if os.path.isfile(path):
                os.remove(path)   # legacy single-file checkpoint
            os.rename(tmp, path)
        _fsync_dir(parent)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return path


def verify_checkpoint(path: str) -> bool:
    """True iff ``path`` is a complete checkpoint whose bytes match the
    manifest checksums. Manifests without a ``files`` key (pre-ISSUE-2)
    verify on existence only."""
    try:
        with open(os.path.join(path, _MANIFEST_FILE)) as f:
            manifest = json.load(f)
        if manifest.get("format") != FORMAT_NAME:
            return False
        if not os.path.exists(os.path.join(path, _ARRAYS_FILE)):
            return False
        for name, info in (manifest.get("files") or {}).items():
            fp = os.path.join(path, name)
            if not os.path.exists(fp):
                return False
            if info.get("sha256") and _sha256(fp) != info["sha256"]:
                return False
        return True
    except (OSError, ValueError):
        return False


def quarantine_checkpoint(path: str) -> Optional[str]:
    """Move a corrupt/incomplete checkpoint aside (``<path>.corrupt-N``)
    so no future ``latest()`` scan can pick it again; returns the new
    location (None if the move failed). Counted on /metrics. No-op when
    the reliability layer is disabled — a disabled process must neither
    rearrange on-disk layout nor mint reliability series (``latest()``
    still *skips* the bad candidate either way)."""
    if not reliability.enabled():
        return None
    base = path.rstrip("/")
    for n in range(1000):
        target = f"{base}{_CORRUPT_MARK}{n}"
        if not os.path.exists(target):
            try:
                os.rename(base, target)
            except OSError:
                return None
            from bigdl_tpu.reliability.policies import _count
            _count("bigdl_reliability_checkpoints_quarantined_total",
                   "Corrupt/incomplete checkpoints moved aside during "
                   "recovery scans")
            logger.warning("quarantined corrupt checkpoint %s -> %s",
                           base, target)
            return target
    return None


def _tag_sort_key(tag: str):
    try:
        return tuple(int(p) for p in tag.split("."))
    except ValueError:
        return (-1,)


def list_checkpoint_tags(root: str, prefix: str = "optim.") -> List[str]:
    """Tags of ``<prefix><tag>`` entries under ``root``, oldest first;
    ``.tmp-*`` orphans and ``.corrupt-*`` quarantine dirs are ignored."""
    if not os.path.isdir(root):
        return []
    tags = []
    for name in os.listdir(root):
        if not name.startswith(prefix) or _TMP_MARK in name \
                or _CORRUPT_MARK in name:
            continue
        tag = name[len(prefix):]
        if _tag_sort_key(tag) != (-1,):
            tags.append(tag)
    return sorted(tags, key=_tag_sort_key)


def latest(root: str, prefix: str = "optim.",
           paired_prefix: Optional[str] = None,
           quarantine: bool = True) -> Optional[str]:
    """Newest **valid** checkpoint tag under ``root`` — incomplete or
    corrupt candidates are skipped (and quarantined, so the next scan
    is cheap) instead of happily loaded, which is the whole point.

    ``paired_prefix`` additionally requires a valid sibling (the
    optimizer's ``model.<tag>`` + ``optim.<tag>`` pair: a tag with only
    half the pair intact is not resumable)."""
    for tag in reversed(list_checkpoint_tags(root, prefix)):
        members = [os.path.join(root, prefix + tag)]
        if paired_prefix is not None:
            members.append(os.path.join(root, paired_prefix + tag))
        bad = [m for m in members if not verify_checkpoint(m)]
        if not bad:
            return tag
        if quarantine:
            for m in bad:
                if os.path.isdir(m):
                    quarantine_checkpoint(m)
    return None


def prune_checkpoints(root: str, keep: int,
                      prefixes=("model.", "optim.")) -> List[str]:
    """Retention: delete all but the newest ``keep`` tags (and any
    ``.tmp-*`` orphans left by crashed writers). ``keep <= 0`` keeps
    everything. Returns the pruned tags."""
    if keep <= 0:
        return []
    if os.path.isdir(root):
        for name in os.listdir(root):
            if _TMP_MARK in name:
                shutil.rmtree(os.path.join(root, name),
                              ignore_errors=True)
    tags = sorted({t for p in prefixes
                   for t in list_checkpoint_tags(root, p)},
                  key=_tag_sort_key)
    doomed = tags[:-keep] if len(tags) > keep else []
    for tag in doomed:
        for p in prefixes:
            target = os.path.join(root, p + tag)
            if os.path.isdir(target):
                shutil.rmtree(target, ignore_errors=True)
    return doomed


def load_checkpoint(path: str, to_jax: bool = True, verify: bool = True
                    ) -> Tuple[Any, Dict[str, Any]]:
    """Load ``(tree, metadata)`` saved by :func:`save_checkpoint`.

    ``verify`` (default) checks the manifest's per-file SHA-256 before
    deserializing and raises :class:`CheckpointCorruptError` on
    mismatch; pre-ISSUE-2 checkpoints (no ``files`` key) skip the check.
    """
    from safetensors.numpy import load_file

    reliability.inject("checkpoint.load")
    with open(os.path.join(path, _MANIFEST_FILE)) as f:
        manifest = json.load(f)
    if manifest.get("format") != FORMAT_NAME:
        raise ValueError(f"{path} is not a {FORMAT_NAME} checkpoint")
    if manifest.get("version", 0) > FORMAT_VERSION:
        raise ValueError(
            f"checkpoint version {manifest['version']} is newer than this "
            f"build supports ({FORMAT_VERSION})")
    if verify:
        for name, info in (manifest.get("files") or {}).items():
            fp = os.path.join(path, name)
            if not os.path.exists(fp):
                raise CheckpointCorruptError(
                    f"{path}: manifest names {name} but it is missing")
            if info.get("sha256") and _sha256(fp) != info["sha256"]:
                raise CheckpointCorruptError(
                    f"{path}: {name} does not match its manifest sha256 "
                    "(torn or corrupted write)")
    arrays = load_file(os.path.join(path, _ARRAYS_FILE))
    tree = _unflatten(manifest["tree"], arrays)
    if to_jax:
        import jax
        import jax.numpy as jnp
        tree = jax.tree_util.tree_map(
            lambda l: jnp.asarray(l) if isinstance(l, np.ndarray) else l,
            tree)
    return tree, manifest.get("metadata", {})

"""Legacy checkpoint importers.

Reference: ``S:dllib/utils/serializer`` + ``S:dllib/utils/tf`` +
``CaffeLoader`` (SURVEY.md §2.3 serialization row): BigDL loads Caffe
prototxt/caffemodel, TF checkpoints/frozen graphs and Torch t7 files
into its own modules. The rebuild's own format is
``utils.checkpoint`` (manifest + safetensors) and HF safetensors load
directly (llm.transformers); this module covers the *legacy import
breadth*:

- :func:`load_torch_state_dict` — torch ``.pt``/``.pth`` state dicts
  (``weights_only=True``: no pickled code execution) into a Module tree;
- :func:`load_tf_checkpoint` — TF2 checkpoint variables (via the baked-in
  tensorflow) into a Module tree;
- :class:`CaffeLoader` — reads ``.caffemodel`` layer blobs with a
  built-in protobuf **wire-format** parser (no caffe/protobuf-schema
  dependency): NetParameter's repeated LayerParameter (field 100; V1
  ``layers`` field 2 also handled), each with name (1), type (2) and
  BlobProto blobs (7) carrying shape (7)/legacy num..width (1-4) and
  packed float data (5).

Name mapping: an explicit ``mapping`` {our_param_path: their_name} wins;
otherwise parameters are matched positionally by shape, the strategy the
reference's loaders fall back to for unnamed graphs.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np


# ---------------------------------------------------------------------------
# shared: assign a flat {name: array} set into a Module tree
# ---------------------------------------------------------------------------

def _flatten_params(tree, prefix=""):
    out = []
    if isinstance(tree, dict):
        for k in tree:
            out += _flatten_params(tree[k], f"{prefix}{k}.")
    else:
        out.append((prefix[:-1], tree))
    return out


def _assign(model, foreign: Dict[str, np.ndarray],
            mapping: Optional[Dict[str, str]] = None,
            transpose_linear: bool = False) -> int:
    """Write foreign arrays into ``model``'s params. Returns #assigned."""
    import jax.numpy as jnp

    params = model.parameters_dict()
    flat = _flatten_params(params)
    used = set()
    n = 0

    def fit(ours_shape, arr):
        if tuple(arr.shape) == tuple(ours_shape):
            return arr
        if transpose_linear and arr.ndim == 2 \
                and tuple(arr.T.shape) == tuple(ours_shape):
            return arr.T
        return None

    by_name = dict(foreign)
    # reserve every explicitly-mapped tensor FIRST so the positional
    # matcher can never consume one that a later parameter's mapping
    # entry names (which would double-assign it)
    if mapping:
        used.update(mapping.values())

    def write(path, leaf, src):
        node = params
        parts = path.split(".")
        for p in parts[:-1]:
            node = node[p]
        node[parts[-1]] = jnp.asarray(np.ascontiguousarray(src),
                                      leaf.dtype)

    for path, leaf in flat:
        src = None
        if mapping and path in mapping:
            cand = by_name.get(mapping[path])
            if cand is None:
                raise KeyError(f"mapping {path} -> {mapping[path]}: "
                               "no such tensor in the checkpoint")
            src = fit(leaf.shape, cand)
            if src is None:
                raise ValueError(
                    f"{mapping[path]} shape {cand.shape} does not fit "
                    f"{path} {leaf.shape}")
        else:
            for name, arr in by_name.items():
                if name in used:
                    continue
                src = fit(leaf.shape, arr)
                if src is not None:
                    used.add(name)
                    break
        if src is not None:
            write(path, leaf, src)
            n += 1
    model.load_parameters_dict(params)
    return n


# ---------------------------------------------------------------------------
# torch / tf
# ---------------------------------------------------------------------------

def load_torch_state_dict(model, src,
                          mapping: Optional[Dict[str, str]] = None,
                          transpose_linear: bool = False) -> int:
    """Load a torch checkpoint path / state_dict into ``model``."""
    if isinstance(src, (str, bytes)):
        import torch
        sd = torch.load(src, map_location="cpu", weights_only=True)
    else:
        sd = src
    if hasattr(sd, "state_dict"):
        sd = sd.state_dict()
    arrays = {k: (v.detach().cpu().numpy()
                  if hasattr(v, "detach") else np.asarray(v))
              for k, v in sd.items()}
    return _assign(model, arrays, mapping, transpose_linear)


def load_tf_checkpoint(model, path: str,
                       mapping: Optional[Dict[str, str]] = None,
                       transpose_linear: bool = True) -> int:
    """Load TF2 checkpoint variables into ``model`` (TF kernels are
    (in, out) — transposed into our (out, in) linears by default)."""
    import tensorflow as tf

    reader = tf.train.load_checkpoint(path)
    arrays = {}
    for name in reader.get_variable_to_shape_map():
        if ".OPTIMIZER_SLOT" in name or name.startswith("_CHECKPOINT"):
            continue
        arrays[name] = np.asarray(reader.get_tensor(name))
    return _assign(model, arrays, mapping, transpose_linear)


# ---------------------------------------------------------------------------
# caffe (hand-rolled protobuf wire parser — no caffe dependency)
# ---------------------------------------------------------------------------

def _read_varint(buf: memoryview, pos: int) -> Tuple[int, int]:
    out = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def _fields(buf: memoryview):
    """Yield (field_number, wire_type, value) over one message."""
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _read_varint(buf, pos)
        field, wt = key >> 3, key & 7
        if wt == 0:                       # varint
            val, pos = _read_varint(buf, pos)
        elif wt == 1:                     # 64-bit
            val = buf[pos:pos + 8]
            pos += 8
        elif wt == 2:                     # length-delimited
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wt == 5:                     # 32-bit
            val = buf[pos:pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield field, wt, val


def _parse_blob(buf: memoryview) -> np.ndarray:
    shape: List[int] = []
    legacy = {}
    data = b""
    for field, wt, val in _fields(buf):
        if field == 7 and wt == 2:        # BlobShape { repeated int64 dim }
            dims = []
            for f2, w2, v2 in _fields(val):
                if f2 == 1 and w2 == 0:
                    dims.append(v2)
                elif f2 == 1 and w2 == 2:  # packed
                    p = 0
                    while p < len(v2):
                        d, p = _read_varint(v2, p)
                        dims.append(d)
            shape = dims
        elif field == 5 and wt == 2:      # packed float data
            data += bytes(val)
        elif field == 5 and wt == 5:      # unpacked float
            data += bytes(val)
        elif field in (1, 2, 3, 4) and wt == 0:   # legacy num/ch/h/w
            legacy[field] = val
    arr = np.frombuffer(data, np.float32).copy()
    if not shape and legacy:
        shape = [legacy.get(i, 1) for i in (1, 2, 3, 4)]
    if shape and int(np.prod(shape)) == arr.size:
        arr = arr.reshape(shape)
    return arr


class CaffeLoader:
    """Read ``.caffemodel`` layer blobs (ref: CaffeLoader.scala).

    ``load(path)`` → {layer_name: [blob arrays]} (blob 0 = weights,
    blob 1 = bias, Caffe convention); ``load_into(model, path, mapping)``
    assigns them into a Module tree.
    """

    @staticmethod
    def load(path: str) -> Dict[str, List[np.ndarray]]:
        with open(path, "rb") as f:
            buf = memoryview(f.read())
        layers: Dict[str, List[np.ndarray]] = {}
        for field, wt, val in _fields(buf):
            # NetParameter: field 100 = repeated LayerParameter (V2),
            # field 2 = repeated V1LayerParameter — same sub-layout for
            # the pieces we need (name=1, blobs=6/7)
            if field in (100, 2) and wt == 2:
                # V2 LayerParameter blobs live in field 7 ONLY (field 6
                # is repeated ParamSpec, which would parse as a spurious
                # empty blob and shift the weight/bias convention);
                # V1LayerParameter blobs live in field 6. Names likewise
                # differ: V2 name = 1, V1 name = 4 (V1 fields 2/3 are
                # bottom/top strings).
                blob_field = 7 if field == 100 else 6
                name_field = 1 if field == 100 else 4
                name = f"layer{len(layers)}"
                blobs: List[np.ndarray] = []
                for f2, w2, v2 in _fields(val):
                    if f2 == name_field and w2 == 2:
                        name = bytes(v2).decode("utf-8", "replace")
                    elif f2 == blob_field and w2 == 2:
                        try:
                            b = _parse_blob(v2)
                        except Exception:   # not a blob (e.g. top name)
                            continue
                        if b.size:
                            blobs.append(b)
                if blobs:
                    layers[name] = blobs
        return layers

    @staticmethod
    def load_into(model, path: str,
                  mapping: Optional[Dict[str, str]] = None) -> int:
        layers = CaffeLoader.load(path)
        arrays: Dict[str, np.ndarray] = {}
        for lname, blobs in layers.items():
            for i, b in enumerate(blobs):
                suffix = {0: "weight", 1: "bias"}.get(i, str(i))
                arrays[f"{lname}.{suffix}"] = b
        return _assign(model, arrays, mapping)

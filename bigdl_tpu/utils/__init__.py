from bigdl_tpu.utils.engine import Engine, init_engine, get_mesh
from bigdl_tpu.utils.table import Table, T

__all__ = ["Engine", "init_engine", "get_mesh", "Table", "T"]

"""Runtime bootstrap — the TPU-native equivalent of BigDL's ``Engine``.

Reference: scala/dllib/.../utils/Engine.scala — detects node/core counts from
the Spark conf, selects an engine type (MklBlas | MklDnn) and owns thread
pools. Here the "cluster" is a JAX device mesh: ``Engine.init`` initialises
jax.distributed (multi-host, when applicable), discovers local/global devices,
and builds the default :class:`jax.sharding.Mesh` that the rest of the
framework (DistriOptimizer, Keras fit, Orca Estimator) trains over.

Engine types:
- ``"tpu"``  — compile to the TPU backend (the whole point).
- ``"cpu"``  — host CPU backend; with ``XLA_FLAGS=--xla_force_host_platform_
  device_count=N`` this gives an N-device virtual mesh, the moral equivalent
  of the reference's ``local[N]`` Spark mode used by its distributed tests
  (SURVEY.md §4).
"""

from __future__ import annotations

import dataclasses
import logging
import math
import os
import threading
from typing import Optional, Sequence

import numpy as np

logger = logging.getLogger("bigdl_tpu")


@dataclasses.dataclass
class EngineConfig:
    engine_type: str = "tpu"          # "tpu" | "cpu" | "gpu"
    node_number: int = 1              # number of host processes
    core_number: int = 1              # devices per host (was: cores per executor)
    mesh_axes: tuple = ("data",)      # default mesh axis names
    mesh_shape: Optional[tuple] = None
    coordinator_address: Optional[str] = None
    process_id: int = 0


class Engine:
    """Global runtime singleton (ref: Engine.scala object Engine)."""

    _lock = threading.RLock()
    _initialized = False
    _config: EngineConfig = EngineConfig()
    _mesh = None

    # Axis-name conventions used across the framework. BigDL only has data
    # parallelism (SURVEY.md §2.5); tensor/sequence/expert/pipeline axes are
    # the idiomatic TPU extensions used by bigdl_tpu.llm / parallel.
    DATA_AXIS = "data"
    MODEL_AXIS = "model"
    SEQ_AXIS = "seq"
    EXPERT_AXIS = "expert"
    PIPELINE_AXIS = "pipe"

    @classmethod
    def init(
        cls,
        engine_type: Optional[str] = None,
        mesh_shape: Optional[Sequence[int]] = None,
        mesh_axes: Optional[Sequence[str]] = None,
        coordinator_address: Optional[str] = None,
        num_processes: Optional[int] = None,
        process_id: Optional[int] = None,
    ):
        """Initialise the runtime and build the default device mesh.

        Multi-host: pass ``coordinator_address``/``num_processes``/
        ``process_id`` (or set JAX_COORDINATOR_ADDRESS etc.) and every host
        calls ``Engine.init`` — the analog of each Spark executor joining the
        BlockManager cluster in the reference's ``Engine.init``.
        """
        import jax

        from bigdl_tpu.utils.conf import conf

        with cls._lock:
            # layered config (ref: Engine.createSparkConf property
            # injection): call-site kwargs > conf.set > env > conf file
            # > defaults — see bigdl_tpu.utils.conf
            coordinator_address = (coordinator_address
                                   or conf.get("bigdl.coordinator.address")
                                   or None)
            num_processes = (num_processes
                             or conf.get_int("bigdl.num.processes"))
            if process_id is None:
                process_id = conf.get_int("bigdl.process.id")
            if coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS"):
                # explicit configuration (kwarg / conf.set / BIGDL env)
                # must fail LOUDLY: a multi-host job whose distributed
                # init silently fell back to single-process would train
                # on 1/N of the data and report success (ISSUE 10
                # satellite — this was a logger.debug). The
                # JAX_COORDINATOR_ADDRESS leg stays best-effort by
                # design: that env var is commonly injected by cluster
                # runtimes onto EVERY process of mixed jobs, where
                # running standalone is a legitimate outcome — but the
                # failure is still warned and counted
                # (bigdl_engine_init_failures_total), never silent.
                explicit = bool(coordinator_address)
                try:
                    jax.distributed.initialize(
                        coordinator_address=coordinator_address,
                        num_processes=num_processes,
                        process_id=process_id,
                    )
                except Exception as e:  # noqa: BLE001 — triaged below
                    if isinstance(e, RuntimeError) and \
                            "already" in str(e).lower():
                        # idempotent re-init: not a failure
                        logger.debug(
                            "jax.distributed.initialize skipped: %s", e)
                    else:
                        cls._count_init_failure()
                        if explicit:
                            raise RuntimeError(
                                "jax.distributed.initialize failed for "
                                "the explicitly configured coordinator "
                                f"{coordinator_address!r} (num_processes="
                                f"{num_processes}, process_id="
                                f"{process_id}): {e}") from e
                        logger.warning(
                            "best-effort jax.distributed init from env "
                            "autodetect failed; continuing single-"
                            "process: %s", e)

            backend = (engine_type or conf.get("bigdl.engine.type")
                       or os.environ.get("BIGDL_ENGINE_TYPE",
                                         jax.default_backend()))
            devices = jax.devices()
            local = jax.local_devices()
            if mesh_axes:
                axes = tuple(mesh_axes)
            else:
                axes = tuple(conf.get_list("bigdl.mesh.axes", ["data"]))
            if mesh_shape:
                shape = tuple(mesh_shape)
            else:
                cs = conf.get_list("bigdl.mesh.shape")
                shape = tuple(int(v) for v in cs) if cs else None
            if shape is None:
                shape = cls._default_shape(len(devices), axes)
            if math.prod(shape) != len(devices):
                raise ValueError(
                    f"mesh_shape {shape} does not cover {len(devices)} devices"
                )

            from jax.sharding import Mesh

            dev_array = np.asarray(devices).reshape(shape)
            cls._mesh = Mesh(dev_array, axes)
            cls._config = EngineConfig(
                engine_type=backend,
                node_number=jax.process_count(),
                core_number=len(local),
                mesh_axes=axes,
                mesh_shape=shape,
                coordinator_address=coordinator_address,
                process_id=jax.process_index(),
            )
            cls._initialized = True
            logger.info(
                "Engine initialized: backend=%s devices=%d hosts=%d mesh=%s%s",
                backend, len(devices), cls._config.node_number, axes, shape,
            )
            return cls._mesh

    @staticmethod
    def _count_init_failure():
        from bigdl_tpu import observability as obs
        if obs.enabled():
            obs.counter(
                "bigdl_engine_init_failures_total",
                "jax.distributed.initialize failures during "
                "Engine.init").inc()

    @classmethod
    def reinit_distributed(
            cls,
            coordinator_address: str,
            num_processes: Optional[int] = None,
            process_id: Optional[int] = None,
            **kwargs,
    ):
        """Rejoin a NEW distributed world (ISSUE 10): tear down the
        live jax.distributed client — the old coordinator died with
        the failed worker set — and run a fresh :meth:`init` against
        the next generation's coordinator. Shutdown is best-effort (a
        client wedged on a dead peer may refuse to close cleanly);
        the re-init itself follows the loud-failure contract above,
        so a rejoin that cannot reach the new coordinator raises
        instead of limping on solo."""
        import jax

        with cls._lock:
            try:
                jax.distributed.shutdown()
            except Exception as e:  # noqa: BLE001 — wedged client
                logger.warning(
                    "jax.distributed.shutdown during rejoin failed "
                    "(continuing to re-init): %s", e)
            cls._initialized = False
            cls._mesh = None
        return cls.init(coordinator_address=coordinator_address,
                        num_processes=num_processes,
                        process_id=process_id, **kwargs)

    @staticmethod
    def _default_shape(n_devices: int, axes: Sequence[str]) -> tuple:
        if len(axes) == 1:
            return (n_devices,)
        # put everything on the first axis by default
        return (n_devices,) + (1,) * (len(axes) - 1)

    @classmethod
    def mesh(cls):
        if not cls._initialized:
            cls.init()
        return cls._mesh

    @classmethod
    def config(cls) -> EngineConfig:
        return cls._config

    @classmethod
    def node_number(cls) -> int:
        return cls._config.node_number

    @classmethod
    def core_number(cls) -> int:
        return cls._config.core_number

    @classmethod
    def is_initialized(cls) -> bool:
        return cls._initialized

    @classmethod
    def reset(cls):
        with cls._lock:
            cls._initialized = False
            cls._mesh = None
            cls._config = EngineConfig()


def init_engine(**kwargs):
    """Python-API parity shim (ref: python dllib utils/engine.py init_engine)."""
    return Engine.init(**kwargs)


def get_mesh():
    return Engine.mesh()


def train_rng_key(seed: int = 0):
    """RNG key for training loops (dropout masks etc.).

    On TPU this returns a key for the hardware RBG generator: threefry
    dropout masks cost ~40% of a BERT-base fine-tune step on v5e
    (measured: batch 64, dropout 0.1 — threefry 992 samples/s / MFU
    0.36, RBG 1517 / MFU 0.52, dropout-off ceiling 1746 / MFU 0.60).
    Elsewhere it stays threefry for bit-exact test determinism. RBG is
    counter-based and splittable; it is not a cryptographic stream, which
    dropout does not need.
    """
    import jax

    if jax.default_backend() == "tpu":
        return jax.random.key(seed, impl="rbg")
    return jax.random.PRNGKey(seed)

"""Version-tolerant aliases for jax API that moved across releases.

The codebase targets the modern spelling (``jax.shard_map``,
``lax.axis_size``); this image ships the 0.4.x line where shard_map
still lives under ``jax.experimental`` and ``axis_size`` doesn't exist.
Import from here instead of hard-coding either location.
"""

from __future__ import annotations

import jax
from jax import lax

shard_map = getattr(jax, "shard_map", None)
if shard_map is None:
    from jax.experimental.shard_map import shard_map  # noqa: F401


def axis_size(axis_name: str):
    """Size of a bound mesh axis. The psum-of-unit fallback folds to the
    same compile-time constant on versions without ``lax.axis_size``."""
    fn = getattr(lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return lax.psum(1, axis_name)


def tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams`` on current jax, ``TPUCompilerParams`` on
    the 0.4.x line (same fields — the class was renamed in place)."""
    from jax.experimental.pallas import tpu as pltpu
    cls = getattr(pltpu, "CompilerParams", None) \
        or pltpu.TPUCompilerParams
    return cls(**kwargs)

"""Table — heterogeneous activity container (ref: .../utils/Table.scala, T()).

BigDL models whose layers take/produce multiple tensors pass a ``Table``
(torch's ``table``): 1-based integer keys by default, arbitrary keys allowed.
Here it is a thin ordered mapping that is also a JAX pytree, so Tables can
flow through jit/grad unchanged.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator

import jax


class Table:
    def __init__(self, *args, **kwargs):
        self._state: Dict[Any, Any] = {}
        for i, v in enumerate(args):
            self._state[i + 1] = v  # 1-based, matching the reference
        self._state.update(kwargs)

    # -- mapping protocol ---------------------------------------------------
    def __getitem__(self, key):
        return self._state[key]

    def __setitem__(self, key, value):
        self._state[key] = value

    def __contains__(self, key):
        return key in self._state

    def __len__(self):
        return len(self._state)

    def __iter__(self) -> Iterator:
        return iter(self._state.values())

    def keys(self):
        return self._state.keys()

    def values(self):
        return self._state.values()

    def items(self):
        return self._state.items()

    def get(self, key, default=None):
        return self._state.get(key, default)

    def insert(self, value):
        self._state[len(self._state) + 1] = value
        return self

    def to_list(self):
        return [self._state[k] for k in _sorted_keys(self._state)]

    def __repr__(self):
        inner = ", ".join(f"{k}: {v!r}" for k, v in self._state.items())
        return f"Table({{{inner}}})"

    def __eq__(self, other):
        if not isinstance(other, Table):
            return NotImplemented
        if set(self._state.keys()) != set(other._state.keys()):
            return False
        import numpy as np
        for k, v in self._state.items():
            w = other._state[k]
            if isinstance(v, Table) or isinstance(w, Table):
                if v != w:
                    return False
            elif not np.array_equal(np.asarray(v), np.asarray(w)):
                return False
        return True

    # mutable container: value-equal, identity-unhashable (like dict)
    __hash__ = None


def T(*args, **kwargs) -> Table:
    """Constructor sugar matching the reference's ``T()``."""
    return Table(*args, **kwargs)


def _sorted_keys(state):
    """Numeric keys first in numeric order, then others lexicographically —
    keeps Tables with ≥10 positional entries in insertion order."""
    return sorted(state.keys(),
                  key=lambda k: (0, k, "") if isinstance(k, int)
                  else (1, 0, str(k)))


def _table_flatten(t: Table):
    keys = _sorted_keys(t._state)
    return [t._state[k] for k in keys], tuple(keys)


def _table_unflatten(keys, children):
    t = Table()
    t._state = dict(zip(keys, children))
    return t


jax.tree_util.register_pytree_node(Table, _table_flatten, _table_unflatten)

"""Layered configuration system.

Reference: BigDL's property/conf/env layering (SURVEY.md §5 config row):
Java system properties (``bigdl.coreNumber``, ``bigdl.engineType``,
``bigdl.localMode``, ...), SparkConf keys injected by
``Engine.createSparkConf``, the shipped ``conf/spark-bigdl.conf`` defaults
file, and env vars for the native libs — resolved lowest-to-highest:
defaults file < environment < explicit ``set()`` calls < call-site kwargs.

TPU translation, same four layers:

1. **defaults** — baked-in table below (+ an optional
   ``bigdl-tpu.conf`` properties file: ``key=value`` lines, ``#``
   comments — the spark-bigdl.conf analog; path from
   ``BIGDL_TPU_CONF`` or ``./bigdl-tpu.conf``);
2. **environment** — ``BIGDL_TPU_<KEY>`` with dots mapped to
   underscores (``bigdl.engine.type`` ← ``BIGDL_TPU_ENGINE_TYPE``);
3. **programmatic** — ``conf.set("bigdl.engine.type", "cpu")``
   (the System.setProperty analog);
4. **call-site kwargs** — Engine.init(...) arguments win outright.

Typed getters (``get_int``/``get_bool``/``get_float``) validate at read
time, replacing the reference's scattered ad-hoc parses.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, Optional

_DEFAULTS: Dict[str, str] = {
    "bigdl.engine.type": "",            # "" = auto (jax.default_backend)
    "bigdl.mesh.axes": "data",          # comma-separated axis names
    "bigdl.mesh.shape": "",             # comma-separated ints; "" = auto
    "bigdl.coordinator.address": "",
    "bigdl.num.processes": "",
    "bigdl.process.id": "",
    "bigdl.optimizer.max.retry": "0",   # iteration-retry attempts
    "bigdl.observability.enabled": "true",    # metrics + trace spans
    "bigdl.observability.trace.capacity": "65536",  # span ring entries
    "bigdl.observability.exemplars": "8",     # slowest-N latency traces
    # quantile-sketch relative-error bound (ISSUE 12): every Sketch
    # series resolves percentiles to within this fraction, and only
    # same-alpha sketches merge across the fleet
    "bigdl.observability.sketch.alpha": "0.01",
    # fleet metric federation (ISSUE 12): router/supervisor-embedded
    # collectors scrape member /metrics/snapshot and serve the merged
    # view. false = no collector thread, endpoints 404
    "bigdl.observability.federation": "false",
    "bigdl.observability.federation.interval": "2.0",  # scrape cadence (s)
    # engine flight recorder + live roofline (ISSUE 16): typed
    # decision-event ring behind /debug/flight + /debug/explain/<id>,
    # and bigdl_device_* utilization gauges. false = no ring, no
    # series, endpoints 404
    "bigdl.observability.flight.enabled": "false",
    "bigdl.observability.flight.capacity": "4096",  # ring events
    # in-process time-series plane (ISSUE 18): bounded ring of periodic
    # registry snapshots with typed window queries (/metrics/query,
    # /fleet/timeline) + the declarative alert engine (/alerts).
    # false = no sampler thread, no ring, no bigdl_timeseries_* /
    # bigdl_alerts_* series, all three endpoints 404
    "bigdl.observability.timeseries.enabled": "false",
    "bigdl.observability.timeseries.interval": "5.0",   # sample cadence (s)
    "bigdl.observability.timeseries.retention": "600",  # history kept (s)
    # window backing the bigdl_slo_burn_rate gauges when the plane is
    # on (seconds of traffic instead of slo.py's last-N-requests deque)
    "bigdl.observability.timeseries.slo.window": "300",
    # JSON list of alert rules replacing the built-in multi-window SLO
    # burn set (see observability/alerts.py); "" = built-ins
    "bigdl.observability.alerts.rules": "",
    # per-platform peak specs for the roofline gauges; 0 = auto-detect
    # from the PJRT device_kind (see observability/utilization.py)
    "bigdl.device.peak.tflops": "0",          # dense bf16 TFLOP/s
    "bigdl.device.peak.gbps": "0",            # HBM GB/s
    # per-request SLO accounting (ISSUE 12): TTFT/ITL sketches +
    # threshold classification + rolling burn rate. false = no sketch
    # series, no bigdl_slo_* series
    "bigdl.slo.enabled": "false",
    "bigdl.slo.ttft_ms": "500",               # admission -> first token
    "bigdl.slo.itl_ms": "200",                # worst inter-token gap
    "bigdl.slo.window": "100",                # burn-rate request window
    # availability objective backing the alert engine's error budget:
    # burn = violation_ratio / (1 - objective)
    "bigdl.slo.objective": "0.99",
    "bigdl.reliability.enabled": "true",      # fault sites + policies
    "bigdl.reliability.retry.max.attempts": "3",   # tries, not retries
    "bigdl.reliability.retry.base.delay": "0.05",  # seconds
    "bigdl.reliability.retry.max.delay": "2.0",    # backoff cap
    "bigdl.checkpoint.keep": "0",             # retention; 0 = unlimited
    # async engine (ISSUE 4): decode steps dispatched ahead of the host
    # drain. 1 = fully synchronous (the pre-pipeline engine, exactly)
    "bigdl.llm.pipeline_depth": "2",
    # prefix-aware KV cache (ISSUE 5): radix-indexed page reuse with
    # refcounts + COW. false = the pre-kvcache engine exactly
    "bigdl.llm.kvcache.enabled": "false",
    # ragged in-place prefill (ISSUE 8): prefill attends cached prefix
    # pages where they sit (Mosaic ragged kernel) instead of staging
    # the context through a dense temp cache. auto = on where the
    # Mosaic kernel runs (TPU), dense elsewhere (the XLA twin would
    # gather the full worst-case table per layer under jit); true/false
    # force a path on any backend. false = the dense-staging prefill
    # paths exactly
    "bigdl.llm.prefill.ragged": "auto",
    # unified mixed prefill+decode dispatch (ISSUE 14): one compiled
    # engine step serves decode rows AND one page-aligned prefill
    # chunk, so a long admission never stalls in-flight decodes for a
    # whole pass. Requires the ragged in-place prefill (inert under
    # the dense escape hatch). false = the split engine exactly
    "bigdl.llm.mixed.enabled": "false",
    "bigdl.llm.prefill.chunk_tokens": "0",    # 0 = auto (4 pages)
    "bigdl.llm.prefill.chunk.wait": "30.0",   # budget-starved chunk ->
                                              # shed + clean rollback
    # model-free self-speculative decoding (ISSUE 19): n-gram drafts
    # from the request's own history verified by a fused chunk pass —
    # up to k+1 tokens per engine tick, greedy-only, bit-identical
    # output. false = structurally absent (no proposer state, no
    # bigdl_llm_spec_* series)
    "bigdl.llm.spec.enabled": "false",
    "bigdl.llm.spec.k": "4",           # draft ceiling per tick
    "bigdl.llm.spec.min_match": "2",   # shortest trusted suffix n-gram
    "bigdl.llm.spec.backoff": "0.5",   # acceptance EMA floor: below it
                                       # the live draft length halves
    # SLO-class priority scheduling (ISSUE 17): class-ordered admission
    # + lossless preemption of in-flight decodes (KV exported, request
    # re-queued as prompt+generated with its remaining budget). false =
    # FIFO, structurally absent (no scheduler objects, no class series)
    "bigdl.llm.priority.enabled": "false",
    # tiered KV cache (ISSUE 6): evicted chains spill to a pinned
    # host-RAM arena with async HBM<->host migration. Requires the
    # prefix cache; false = structurally absent (PR 5 engine exactly)
    "bigdl.llm.kvtier.enabled": "false",
    "bigdl.llm.kvtier.host_pages": "0",       # 0 = auto (4x device pool)
    "bigdl.llm.kvtier.fetch.timeout": "30.0", # stuck fetch -> plain miss
    "bigdl.llm.kvtier.sync": "false",         # inline migration (tests)
    # disaggregated serving (ISSUE 6): "" unified, "prefill" or
    # "decode" restricts an LLMWorker to one side of the KV handoff
    "bigdl.llm.role": "",
    # request-level failover (ISSUE 7): the router journals in-flight
    # requests and resumes prompt+generated on another backend after a
    # decode failure. false = PR 6 router byte-identical (no journal,
    # no prober thread, blocking dispatch)
    "bigdl.llm.failover.enabled": "false",
    "bigdl.llm.failover.max.attempts": "3",   # dispatch tries/request
    # OpenAI-compatible gateway (ISSUE 20): /v1/completions,
    # /v1/chat/completions and /v1/models on workers and the router,
    # with stream=true relayed as SSE from the failover journal drain.
    # false = structurally absent (routes 404 naming this gate, no
    # bigdl_api_* series, the api package is never imported)
    "bigdl.llm.api.enabled": "false",
    "bigdl.llm.api.tokenizer": "",            # "" token-ids only; "byte"
    "bigdl.llm.api.chat_template": "plain",   # plain | llama | chatglm
    "bigdl.llm.prober.interval": "0.5",       # /healthz poll (seconds)
    # hedged dispatch (ISSUE 7): duplicate a slow prefill/decode call
    # to a second backend after a p95-based delay; first success wins
    "bigdl.llm.hedge.enabled": "false",
    "bigdl.llm.hedge.delay.ms": "0",          # 0 = p95-based (observed)
    "bigdl.llm.hedge.min.delay.ms": "50",     # floor under the p95 rule
    "bigdl.llm.hedge.budget": "0.1",          # hedges / requests cap
    # engine watchdog (ISSUE 7): a device step stalled past the timeout
    # flips /healthz to 503 and fails pending requests retriably.
    # 0 = off (no watchdog thread, no series)
    "bigdl.llm.watchdog.step_timeout": "0",
    # derived Retry-After (ISSUE 7 satellite): seconds = clamp(base +
    # per_queued * queue_depth, 1, max) stretched by up to `jitter`
    "bigdl.llm.retry_after.base": "1.0",
    "bigdl.llm.retry_after.per_queued": "0.25",
    "bigdl.llm.retry_after.max": "30",
    "bigdl.llm.retry_after.jitter": "0.2",
    "bigdl.train.prefetch": "true",           # stage batch N+1 during N
    "bigdl.train.prefetch.depth": "2",        # staged batches held ahead
    # elastic multi-host training (ISSUE 10): supervisor + peer
    # heartbeats + collective-hang watchdog + snapshot-based recovery.
    # false = the optimizer loop, Engine and metric registry are exactly
    # the pre-elastic objects (no agent thread, no ring, no series)
    "bigdl.elastic.enabled": "false",
    "bigdl.elastic.supervisor.address": "",   # host:port; "" = ring-only
    "bigdl.elastic.heartbeat.interval": "0.5",  # agent beat cadence (s)
    "bigdl.elastic.heartbeat.timeout": "5.0",   # peer presumed dead (s)
    # a worker wedged before its FIRST heartbeat never registers, so
    # peer expiry can't see it: fail the generation if the world has
    # not fully joined within this budget. 0 = no join deadline
    "bigdl.elastic.join.timeout": "300",
    # stalled-collective watchdog: a step heartbeat older than this
    # while the loop is live means a wedged shard_map step. 0 = off
    "bigdl.elastic.step.timeout": "0",
    "bigdl.elastic.snapshot.every": "10",     # steps per RAM snapshot
    "bigdl.elastic.snapshot.ring": "2",       # RAM ring capacity
    # committed snapshots per durable flush (process 0 writes the PR 2
    # atomic checkpoint tier); 0 = never flush mid-epoch
    "bigdl.elastic.snapshot.flush.every": "1",
    "bigdl.elastic.max.restarts": "3",        # restart budget (both tiers)
    "bigdl.elastic.generation": "0",          # set by the launcher env
    # static-analysis runtime witness (ISSUE 11): wrap threading.Lock/
    # RLock creation to record acquisition order and flag inversions
    # against the static lock graph during chaos runs. false = the
    # stock factories, no table, no series (structurally absent)
    "bigdl.analysis.lockwatch": "false",
}


def _env_key(key: str) -> str:
    return "BIGDL_TPU_" + key.replace("bigdl.", "", 1) \
        .replace(".", "_").upper()


class BigDLConf:
    """The layered store. One process-global instance lives at
    ``bigdl_tpu.utils.conf.conf`` (the System-properties analog)."""

    def __init__(self, conf_file: Optional[str] = None):
        self._lock = threading.RLock()
        self._file_layer: Dict[str, str] = {}
        self._set_layer: Dict[str, str] = {}
        path = conf_file or os.environ.get("BIGDL_TPU_CONF",
                                           "bigdl-tpu.conf")
        if path and os.path.exists(path):
            self.load_file(path)

    # -- layers --------------------------------------------------------------
    def load_file(self, path: str) -> "BigDLConf":
        """Parse a ``key=value`` properties file (# comments)."""
        with self._lock, open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#") or "=" not in line:
                    continue
                k, v = line.split("=", 1)
                self._file_layer[k.strip()] = v.strip()
        return self

    def set(self, key: str, value: Any) -> "BigDLConf":
        with self._lock:
            self._set_layer[key] = str(value)
        self._apply_dynamic(key)
        return self

    def unset(self, key: str) -> "BigDLConf":
        with self._lock:
            self._set_layer.pop(key, None)
        self._apply_dynamic(key)
        return self

    def _apply_dynamic(self, key: str):
        """Keys consumed at import time by other modules get pushed to
        them on change, so programmatic set() works after import."""
        if key.startswith("bigdl.observability."):
            try:
                from bigdl_tpu.observability import _state
                _state.refresh(key)
            except Exception:
                pass
        elif key.startswith("bigdl.reliability."):
            try:
                from bigdl_tpu.reliability import _state
                _state.refresh(key)
            except Exception:
                pass

    # -- resolution ----------------------------------------------------------
    def get(self, key: str, default: Optional[str] = None) -> Optional[str]:
        with self._lock:
            if key in self._set_layer:
                return self._set_layer[key]
            env = os.environ.get(_env_key(key))
            if env is not None:
                return env
            if key in self._file_layer:
                return self._file_layer[key]
            if key in _DEFAULTS:
                return _DEFAULTS[key] or default
            return default

    def get_int(self, key: str, default: Optional[int] = None
                ) -> Optional[int]:
        v = self.get(key)
        if v in (None, ""):
            return default
        try:
            return int(v)
        except ValueError:
            raise ValueError(f"config {key}={v!r} is not an int") from None

    def get_float(self, key: str, default: Optional[float] = None
                  ) -> Optional[float]:
        v = self.get(key)
        if v in (None, ""):
            return default
        try:
            return float(v)
        except ValueError:
            raise ValueError(f"config {key}={v!r} is not a float") from None

    def get_bool(self, key: str, default: bool = False) -> bool:
        v = self.get(key)
        if v in (None, ""):
            return default
        if v.lower() in ("true", "1", "yes", "on"):
            return True
        if v.lower() in ("false", "0", "no", "off"):
            return False
        raise ValueError(f"config {key}={v!r} is not a bool")

    def get_list(self, key: str, default=None):
        v = self.get(key)
        if v in (None, ""):
            return default
        return [s.strip() for s in v.split(",") if s.strip()]

    def effective(self) -> Dict[str, str]:
        """Fully-resolved view of every known key (for logging/debug)."""
        keys = set(_DEFAULTS) | set(self._file_layer) | set(self._set_layer)
        return {k: self.get(k) for k in sorted(keys)}


conf = BigDLConf()

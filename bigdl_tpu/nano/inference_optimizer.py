"""InferenceOptimizer (ref: P:nano/pytorch/inference/optimizer.py —
quantize(precision=int8/bf16, accelerator=onnxruntime/openvino/jit) and
trace; plus optimize() which tries all pipelines and reports latency)."""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.nn.module import Module


class _CompiledModel:
    """A jitted, possibly re-precisioned forward with the Module API bit
    users touch (forward/__call__)."""

    def __init__(self, model: Module, dtype=None):
        self._model = model
        self._dtype = dtype
        params = model.parameters_dict()
        if dtype is not None:
            params = jax.tree_util.tree_map(
                lambda a: a.astype(dtype)
                if a.dtype in (jnp.float32, jnp.float64) else a, params)
        self._params = params
        self._states = model.states_dict()
        self._example_shape = None        # last traced input shape —
        self._example_dtype = np.float32  # what save() AOT-serializes
        self._aot = None                  # InferenceModel with a loaded
        self._aot_shape = None            # compiled artifact (load());
        self._aot_dtype = None            # gate is the SAVED signature,
        #                                   immutable after load

        @jax.jit
        def fwd(p, s, x):
            y, _ = model.apply(p, s, x, training=False, rng=None)
            return y

        self._fwd = fwd

    def forward(self, x):
        x = np.asarray(x)
        # the AOT executable serves exactly its compiled signature;
        # anything else falls back to the retracing jit path
        if (self._aot is not None
                and tuple(x.shape) == self._aot_shape
                and x.dtype == self._aot_dtype):
            return self._aot.predict_compiled(x)
        self._example_shape = x.shape
        self._example_dtype = x.dtype
        return np.asarray(self._fwd(self._params, self._states,
                                    jnp.asarray(x)))

    __call__ = forward


def _inference_model_from(compiled: "_CompiledModel"):
    """An InferenceModel wired to the pipeline's EXISTING leaves —
    load_bigdl would materialize a fresh fp32 copy of every parameter
    only to throw it away (review r5: transient 2x parameter memory)."""
    from bigdl_tpu.serving.inference_model import InferenceModel

    im = InferenceModel()
    im._model = compiled._model
    im._params = compiled._params
    im._states = compiled._states
    im._fwd = compiled._fwd
    return im


class InferenceOptimizer:
    @staticmethod
    def quantize(model, precision: str = "bf16",
                 calib_data=None, **kwargs):
        """precision: bf16 | fp16 | int8 | sym_int4/asym_int4/nf4/fp4.

        int8/int4 run the LowBitLinear surgery (ggml blocks, Pallas
        kernels); bf16/fp16 cast params (XLA computes in bf16 on MXU)."""
        model = getattr(model, "module", model)   # keras models
        if precision in ("bf16",):
            return _CompiledModel(model, jnp.bfloat16)
        if precision in ("fp16", "float16"):
            return _CompiledModel(model, jnp.float16)
        qtype = {"int8": "sym_int8", "int4": "sym_int4"}.get(
            precision, precision)
        from bigdl_tpu.llm.transformers.convert import ggml_convert_low_bit
        import copy

        qmodel = ggml_convert_low_bit(copy.deepcopy(model), qtype)
        return _CompiledModel(qmodel)

    @staticmethod
    def trace(model, accelerator: str = "jit", input_sample=None,
              **kwargs):
        """ref: trace(accelerator=jit/onnxruntime/openvino) — here every
        accelerator is XLA; input_sample warms the compile cache."""
        model = getattr(model, "module", model)
        compiled = _CompiledModel(model)
        if input_sample is not None:
            compiled.forward(np.asarray(input_sample))
        return compiled

    @staticmethod
    def _quantize_convs(model):
        """INT8 weight-only conv+linear surgery (nn.quantized)."""
        import copy
        from bigdl_tpu.nn.quantized import quantize_model
        return _CompiledModel(quantize_model(copy.deepcopy(model)))

    @staticmethod
    def optimize(model, x: np.ndarray,
                 latency_sample_num: int = 10,
                 validation_data=None,
                 metric: Optional[Callable] = None) -> Dict[str, dict]:
        """Try the available pipelines, time them, return a report (ref:
        InferenceOptimizer.optimize's trial table: latency per pipeline,
        plus an accuracy/metric column when ``validation_data=(x, y)``
        and a ``metric(pred, y) -> float`` are given)."""
        model = getattr(model, "module", model)
        report = {}
        for name, builder in {
            "original(jit)": lambda: InferenceOptimizer.trace(model),
            "bf16": lambda: InferenceOptimizer.quantize(model, "bf16"),
            "int8": lambda: InferenceOptimizer.quantize(model, "int8"),
            "int8-conv": lambda: InferenceOptimizer._quantize_convs(model),
            "int4": lambda: InferenceOptimizer.quantize(model, "sym_int4"),
        }.items():
            try:
                m = builder()
                m.forward(x)  # compile
                t0 = time.perf_counter()
                for _ in range(latency_sample_num):
                    m.forward(x)
                dt = (time.perf_counter() - t0) / latency_sample_num
                entry = {"latency_ms": dt * 1000, "model": m,
                         "status": "successful"}
                if validation_data is not None and metric is not None:
                    try:
                        vx, vy = validation_data
                        entry["metric"] = float(metric(m.forward(vx), vy))
                    except Exception as me:   # keep the timed pipeline
                        entry["metric_error"] = str(me)
                report[name] = entry
            except Exception as e:  # pipeline not applicable to model
                report[name] = {"status": f"failed: {e}"}
        return report

    @staticmethod
    def save(compiled: "_CompiledModel", path: str):
        """Persist an optimized pipeline as a deployable artifact (ref:
        P:nano InferenceOptimizer.save/load — the reference writes the
        accelerated model to a directory and reloads it without
        re-optimizing). Written pieces: the module (manifest +
        safetensors via Module.save_module, quantized leaves included)
        and the serialized COMPILED executable when a shape was already
        traced (serving.InferenceModel.save_compiled — skips
        trace+lower+XLA-compile on load)."""
        import json as _json
        import os as _os

        model = compiled._model
        _os.makedirs(path, exist_ok=True)
        model.save_module(_os.path.join(path, "module"))
        meta = {"dtype": (str(jnp.dtype(compiled._dtype))
                          if compiled._dtype is not None else None),
                "example_shape": list(compiled._example_shape)
                if compiled._example_shape else None,
                "example_dtype": str(np.dtype(compiled._example_dtype))}
        with open(_os.path.join(path, "nano_meta.json"), "w") as f:
            _json.dump(meta, f)
        if compiled._example_shape is not None:
            im = _inference_model_from(compiled)
            im.save_compiled(_os.path.join(path, "compiled"),
                             compiled._example_shape,
                             dtype=compiled._example_dtype)

    @staticmethod
    def load(path: str) -> "_CompiledModel":
        """Reload a pipeline written by :meth:`save`; prefers the
        serialized executable artifact when present."""
        import json as _json
        import os as _os

        model = Module.load_module(_os.path.join(path, "module"))
        with open(_os.path.join(path, "nano_meta.json")) as f:
            meta = _json.load(f)
        dtype = jnp.dtype(meta["dtype"]) if meta["dtype"] else None
        compiled = _CompiledModel(model, dtype)
        if meta.get("example_shape"):
            compiled._example_shape = tuple(meta["example_shape"])
            compiled._example_dtype = np.dtype(
                meta.get("example_dtype", "float32"))
        art = _os.path.join(path, "compiled")
        # load_compiled prefers the .xla executable and falls back to
        # the portable .hlo export — either artifact counts
        if meta.get("example_shape") and (
                _os.path.exists(art + ".xla")
                or _os.path.exists(art + ".hlo")):
            im = _inference_model_from(compiled)
            try:
                im.load_compiled(art)
                compiled._aot = im
                compiled._aot_shape = tuple(meta["example_shape"])
                compiled._aot_dtype = compiled._example_dtype
            except Exception:       # cross-platform artifact: fresh jit
                pass
        return compiled

    @staticmethod
    def summary(report: Dict[str, dict]) -> str:
        """The reference prints a trial table; same here."""
        lines = [f"{'pipeline':<16} {'latency(ms)':>12} {'metric':>10} "
                 f"status"]
        for name, e in report.items():
            lat = (f"{e['latency_ms']:.3f}"
                   if "latency_ms" in e else "-")
            met = (f"{e['metric']:.4f}" if "metric" in e else "-")
            lines.append(f"{name:<16} {lat:>12} {met:>10} {e['status']}")
        return "\n".join(lines)

    @staticmethod
    def get_best_model(report: Dict[str, dict]):
        ok = {k: v for k, v in report.items()
              if v.get("status") == "successful"}
        best = min(ok, key=lambda k: ok[k]["latency_ms"])
        return ok[best]["model"], best

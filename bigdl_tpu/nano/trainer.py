"""nano Trainer (ref: P:nano/pytorch/trainer.py — a pytorch-lightning
Trainer subclass with channels_last/ipex/bf16 knobs. Here: a thin
fit/validate driver over our Optimizer with the precision knob mapped to
bf16 params)."""

from __future__ import annotations

from typing import Optional

import numpy as np

from bigdl_tpu.nn.module import Criterion, Module


class Trainer:
    def __init__(self, max_epochs: int = 1, precision: str = "32",
                 use_ipex: bool = False, **kwargs):
        self.max_epochs = max_epochs
        self.precision = str(precision)

    def fit(self, model: Module, criterion: Criterion, x: np.ndarray,
            y: np.ndarray, batch_size: int = 32,
            optim_method=None):
        import jax
        import jax.numpy as jnp

        from bigdl_tpu.optim.optimizer import LocalOptimizer
        from bigdl_tpu.optim.trigger import Trigger

        model = getattr(model, "module", model)
        if self.precision in ("bf16", "16-mixed", "bf16-mixed"):
            model.load_parameters_dict(jax.tree_util.tree_map(
                lambda a: a.astype(jnp.bfloat16)
                if a.dtype == jnp.float32 else a,
                model.parameters_dict()))
        opt = LocalOptimizer(model, (np.asarray(x), np.asarray(y)),
                             criterion, batch_size=batch_size,
                             end_trigger=Trigger.max_epoch(
                                 self.max_epochs))
        if optim_method is not None:
            opt.set_optim_method(optim_method)
        opt.optimize()
        return model

"""nano Trainer (ref: P:nano/pytorch/trainer.py — a pytorch-lightning
Trainer subclass with channels_last/ipex/bf16 AND multi-instance
training knobs. Here: fit/validate over our Optimizer with the precision
knob mapped to bf16 params, and ``num_processes > 1`` running the
reference's multi-instance training role on the orca RayContext
spawn-process pool (VERDICT r3 weak #7 named the missing multi-instance
analog).

Multi-instance semantics: the dataset splits into ``num_processes``
shards; each communication round, every worker process loads the
current parameters, trains one epoch on its shard (CPU backend — the
pool exists for host-side parallelism; mesh data-parallelism on chips
is DistriOptimizer's job), and the driver averages the returned
parameters (local-SGD, the same statistical shape as the reference's
per-process DDP with a coarser sync period; per-step gradient sync
across OS processes without a collective fabric would be all overhead).
"""

from __future__ import annotations

import os
import tempfile
from typing import Optional

import numpy as np

from bigdl_tpu.nn.module import Criterion, Module


def _round_task(args):
    """One worker round: load model + params (+ carried optimizer
    state), train an epoch on the shard, return trained parameters and
    the optimizer state so the NEXT round resumes instead of resetting
    momenta / LR-schedule counters (runs in a spawned CPU worker;
    module-level so the payload stays small)."""
    (model_path, params, x, y, batch_size, criterion, optim_method,
     host_state, opt_state) = args
    import jax

    from bigdl_tpu.nn.module import Module
    from bigdl_tpu.optim.optimizer import LocalOptimizer
    from bigdl_tpu.optim.trigger import Trigger

    model = Module.load_module(model_path)
    model.load_parameters_dict(params)
    opt = LocalOptimizer(model, (x, y), criterion, batch_size=batch_size,
                         end_trigger=Trigger.max_epoch(1))
    if optim_method is not None:
        opt.set_optim_method(optim_method)
    if host_state is not None:
        opt.optim_method.load_state(host_state)
    if opt_state is not None:
        opt._resume_opt_state = opt_state
    opt.optimize()
    return (jax.tree_util.tree_map(np.asarray, model.parameters_dict()),
            opt.state["loss"], opt.optim_method.get_state(),
            getattr(opt, "_last_opt_state", None))


class Trainer:
    def __init__(self, max_epochs: int = 1, precision: str = "32",
                 use_ipex: bool = False, num_processes: int = 1,
                 round_timeout: float = 3600.0, **kwargs):
        self.max_epochs = max_epochs
        self.precision = str(precision)
        self.num_processes = num_processes
        self.round_timeout = round_timeout
        self.last_losses: list = []

    def fit(self, model: Module, criterion: Criterion, x: np.ndarray,
            y: np.ndarray, batch_size: int = 32,
            optim_method=None):
        import jax
        import jax.numpy as jnp

        from bigdl_tpu.optim.optimizer import LocalOptimizer
        from bigdl_tpu.optim.trigger import Trigger

        model = getattr(model, "module", model)
        if self.precision in ("bf16", "16-mixed", "bf16-mixed"):
            model.load_parameters_dict(jax.tree_util.tree_map(
                lambda a: a.astype(jnp.bfloat16)
                if a.dtype == jnp.float32 else a,
                model.parameters_dict()))
        if self.num_processes > 1:
            return self._fit_multi_instance(model, criterion,
                                            np.asarray(x), np.asarray(y),
                                            batch_size, optim_method)
        opt = LocalOptimizer(model, (np.asarray(x), np.asarray(y)),
                             criterion, batch_size=batch_size,
                             end_trigger=Trigger.max_epoch(
                                 self.max_epochs))
        if optim_method is not None:
            opt.set_optim_method(optim_method)
        opt.optimize()
        self.last_losses = [opt.state["loss"]]
        return model

    def _fit_multi_instance(self, model, criterion, x, y, batch_size,
                            optim_method):
        import jax

        from bigdl_tpu.orca.ray_pool import RayContext

        n = self.num_processes
        idx = np.array_split(np.arange(len(x)), n)
        params = jax.tree_util.tree_map(np.asarray,
                                        model.parameters_dict())
        self.last_losses = []
        host_state = None          # optimizer counters / LR schedule
        opt_state = None           # momenta etc., averaged like params
        with tempfile.TemporaryDirectory() as td, \
                RayContext(num_workers=n) as ctx:
            model_path = os.path.join(td, "model")
            model.save_module(model_path)
            for _ in range(self.max_epochs):     # one sync per epoch
                outs = ctx.map(_round_task,
                               [(model_path, params, x[i], y[i],
                                 batch_size, criterion, optim_method,
                                 host_state, opt_state)
                                for i in idx],
                               timeout=self.round_timeout)
                trees = [o[0] for o in outs]
                self.last_losses.append(
                    float(np.mean([o[1] for o in outs])))
                params = jax.tree_util.tree_map(
                    lambda *vs: np.mean(np.stack(vs), axis=0), *trees)
                # carry optimizer state across rounds: counters from
                # worker 0 (identical on all), slot arrays averaged the
                # same way as the parameters they track
                host_state = outs[0][2]
                slots = [o[3] for o in outs]
                if all(s is not None for s in slots):
                    opt_state = jax.tree_util.tree_map(
                        lambda *vs: (np.mean(np.stack(vs), axis=0)
                                     if np.asarray(vs[0]).dtype.kind
                                     == "f" else vs[0]), *slots)
        model.load_parameters_dict(params)
        return model

"""bigdl_tpu.nano — single-node acceleration toolkit (ref: python/nano:
Trainer + InferenceOptimizer.quantize/trace over IPEX/ONNX/OpenVINO/INC).

On TPU the acceleration levers are dtype (bf16), quantization (our ggml
low-bit surgery) and AOT jit — so InferenceOptimizer maps precision
choices onto those, keeping the reference's API verbs."""

from bigdl_tpu.nano.inference_optimizer import InferenceOptimizer
from bigdl_tpu.nano.trainer import Trainer

__all__ = ["InferenceOptimizer", "Trainer"]

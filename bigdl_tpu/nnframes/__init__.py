"""bigdl_tpu.nnframes — DataFrame ML pipeline integration (ref:
S:dllib/nnframes + P:dllib/nnframes: Spark-ML Estimator/Transformer
wrappers NNEstimator/NNModel/NNClassifier/NNImageReader).

The Spark DataFrame substrate maps to pandas here (SURVEY.md §7.2 step 5:
"whatever Spark-less DataFrame equivalent we define"); the fit/transform
contract, column conventions (featuresCol/labelCol/predictionCol) and the
sklearn-style pipeline compatibility are preserved."""

from bigdl_tpu.nnframes.nn_estimator import (
    NNClassifier, NNClassifierModel, NNEstimator, NNImageReader, NNModel)

__all__ = ["NNEstimator", "NNModel", "NNClassifier", "NNClassifierModel",
           "NNImageReader"]

"""NNEstimator / NNModel (ref: S:dllib/nnframes/NNEstimator.scala — a
Spark ML Estimator: fit(df) trains the wrapped module via Optimizer and
returns an NNModel Transformer whose transform(df) appends predictions)."""

from __future__ import annotations

from typing import Optional, Sequence, Union

import numpy as np
import pandas as pd

from bigdl_tpu.nn.module import Criterion, Module
from bigdl_tpu.optim.optim_method import OptimMethod
from bigdl_tpu.optim.trigger import Trigger


def _col_to_array(df: pd.DataFrame, col: str) -> np.ndarray:
    vals = df[col].to_numpy()
    if len(vals) and isinstance(vals[0], (list, tuple, np.ndarray)):
        return np.stack([np.asarray(v, np.float32) for v in vals])
    return vals.astype(np.float32)[:, None]


class NNEstimator:
    """ref ctor: NNEstimator(model, criterion, featureSize, labelSize)."""

    def __init__(self, model: Module, criterion: Criterion,
                 feature_size: Optional[Sequence[int]] = None,
                 label_size: Optional[Sequence[int]] = None):
        self.model = model
        self.criterion = criterion
        self.feature_size = feature_size
        self.label_size = label_size
        self.features_col = "features"
        self.label_col = "label"
        self.prediction_col = "prediction"
        self.batch_size = 32
        self.max_epoch = 10
        self.optim_method: Optional[OptimMethod] = None
        self.learning_rate = None

    # -- param setters (Spark ML naming) -------------------------------------
    def set_features_col(self, name: str):
        self.features_col = name
        return self

    def set_label_col(self, name: str):
        self.label_col = name
        return self

    def set_prediction_col(self, name: str):
        self.prediction_col = name
        return self

    def set_batch_size(self, n: int):
        self.batch_size = n
        return self

    def set_max_epoch(self, n: int):
        self.max_epoch = n
        return self

    def set_optim_method(self, method: OptimMethod):
        self.optim_method = method
        return self

    def set_learning_rate(self, lr: float):
        self.learning_rate = lr
        return self

    # -- Estimator contract ---------------------------------------------------
    def fit(self, df: pd.DataFrame) -> "NNModel":
        from bigdl_tpu.optim.optimizer import Optimizer

        x = _col_to_array(df, self.features_col)
        if self.feature_size:
            x = x.reshape((-1,) + tuple(self.feature_size))
        y = df[self.label_col].to_numpy()
        if len(y) and isinstance(y[0], (list, tuple, np.ndarray)):
            y = np.stack([np.asarray(v, np.float32) for v in y])
        opt = Optimizer(self.model, (x, np.asarray(y)), self.criterion,
                        batch_size=self.batch_size,
                        end_trigger=Trigger.max_epoch(self.max_epoch))
        if self.optim_method is not None:
            if self.learning_rate is not None:
                self.optim_method.learning_rate = self.learning_rate
            opt.set_optim_method(self.optim_method)
        elif self.learning_rate is not None:
            from bigdl_tpu.optim.optim_method import SGD
            opt.set_optim_method(SGD(learning_rate=self.learning_rate))
        opt.optimize()
        return self._make_model()

    def _make_model(self) -> "NNModel":
        m = NNModel(self.model, self.feature_size)
        m.features_col = self.features_col
        m.prediction_col = self.prediction_col
        m.batch_size = self.batch_size
        return m


class NNModel:
    """ref: NNModel — Spark ML Transformer appending predictions."""

    def __init__(self, model: Module,
                 feature_size: Optional[Sequence[int]] = None):
        self.model = model
        self.feature_size = feature_size
        self.features_col = "features"
        self.prediction_col = "prediction"
        self.batch_size = 32

    def transform(self, df: pd.DataFrame) -> pd.DataFrame:
        from bigdl_tpu.optim.optimizer import Predictor

        x = _col_to_array(df, self.features_col)
        if self.feature_size:
            x = x.reshape((-1,) + tuple(self.feature_size))
        pred = Predictor(self.model, self.batch_size).predict(x)
        out = df.copy()
        out[self.prediction_col] = [np.asarray(p) for p in pred]
        return out

    def save(self, path: str):
        self.model.save_module(path)
        return self

    @staticmethod
    def load(path: str) -> "NNModel":
        return NNModel(Module.load_module(path))


class NNClassifier(NNEstimator):
    """ref: NNClassifier — label is a scalar class; prediction is the
    argmax class (1-based, Spark ML double)."""

    def fit(self, df: pd.DataFrame) -> "NNClassifierModel":
        nn_model = super().fit(df)
        m = NNClassifierModel(self.model, self.feature_size)
        m.features_col = nn_model.features_col
        m.prediction_col = nn_model.prediction_col
        m.batch_size = nn_model.batch_size
        return m


class NNClassifierModel(NNModel):
    def transform(self, df: pd.DataFrame) -> pd.DataFrame:
        from bigdl_tpu.optim.optimizer import Predictor

        x = _col_to_array(df, self.features_col)
        if self.feature_size:
            x = x.reshape((-1,) + tuple(self.feature_size))
        pred = Predictor(self.model, self.batch_size).predict(x)
        out = df.copy()
        out[self.prediction_col] = (pred.argmax(axis=-1) + 1).astype(float)
        return out


class NNImageReader:
    """ref: NNImageReader.readImages — images into a DataFrame with an
    image-struct column; here: a pandas frame of decoded HWC arrays."""

    @staticmethod
    def read_images(path: str, min_partitions: int = 1) -> pd.DataFrame:
        from bigdl_tpu.feature.vision import (
            ImageFrame, ImageFeature, PixelBytesToMat)

        frame = ImageFrame.read(path).transform(PixelBytesToMat())
        rows = [{"image": f[ImageFeature.MAT],
                 "origin": f.get(ImageFeature.URI)}
                for f in frame.features]
        return pd.DataFrame(rows)

"""Model zoo (ref: scala …/dllib/models/ — lenet, resnet, inception, vgg,
autoencoder, rnn)."""

from bigdl_tpu.models import (
    autoencoder, inception, lenet, resnet, rnn, vgg)

__all__ = ["autoencoder", "inception", "lenet", "resnet", "rnn", "vgg"]

"""Model zoo (ref: scala …/dllib/models/ — lenet, resnet, inception, vgg,
autoencoder, rnn; bert per BASELINE config 4)."""

from bigdl_tpu.models import (
    autoencoder, bert, inception, lenet, resnet, rnn, vgg)

__all__ = ["autoencoder", "bert", "inception", "lenet", "resnet", "rnn",
           "vgg"]

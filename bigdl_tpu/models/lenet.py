"""LeNet-5 (ref: .../dllib/models/lenet/LeNet5.scala — the canonical BigDL
hello-world, BASELINE config 1)."""

from __future__ import annotations

import bigdl_tpu.nn as nn


def build_model(class_num: int = 10) -> nn.Sequential:
    """ref LeNet5.apply: conv(1→6,5x5) tanh pool conv(6→12,5x5) tanh pool
    fc(12*4*4→100) tanh fc(100→classNum) logsoftmax."""
    return (nn.Sequential()
            .add(nn.Reshape([1, 28, 28]))
            .add(nn.SpatialConvolution(1, 6, 5, 5).set_name("conv1_5x5"))
            .add(nn.Tanh())
            .add(nn.SpatialMaxPooling(2, 2, 2, 2))
            .add(nn.SpatialConvolution(6, 12, 5, 5).set_name("conv2_5x5"))
            .add(nn.Tanh())
            .add(nn.SpatialMaxPooling(2, 2, 2, 2))
            .add(nn.Reshape([12 * 4 * 4]))
            .add(nn.Linear(12 * 4 * 4, 100).set_name("fc_1"))
            .add(nn.Tanh())
            .add(nn.Linear(100, class_num).set_name("fc_2"))
            .add(nn.LogSoftMax()))

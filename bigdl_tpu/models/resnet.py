"""ResNet (ref: .../dllib/models/resnet/ResNet.scala — CIFAR-10 basic-block
variants and ImageNet bottleneck variants incl. ResNet-50, BASELINE
config 2).

The reference builds residual blocks as ConcatTable(path, shortcut) →
CAddTable → ReLU; the same composition is used here (it jits into one
fused XLA program, so the Table plumbing costs nothing at runtime).
"""

from __future__ import annotations

import bigdl_tpu.nn as nn


def conv_bn(n_in: int, n_out: int, k: int, stride: int = 1,
            pad: int = -1, relu: bool = True,
            format: str = "NCHW") -> nn.Sequential:
    seq = (nn.Sequential()
           .add(nn.SpatialConvolution(n_in, n_out, k, k, stride, stride,
                                      pad, pad, with_bias=False,
                                      format=format))
           .add(nn.SpatialBatchNormalization(n_out, format=format)))
    if relu:
        seq.add(nn.ReLU())
    return seq


def _shortcut(n_in: int, n_out: int, stride: int,
              format: str = "NCHW") -> nn.Module:
    if n_in != n_out or stride != 1:
        # type-B projection shortcut (1x1 conv + BN), the reference default
        return (nn.Sequential()
                .add(nn.SpatialConvolution(n_in, n_out, 1, 1, stride, stride,
                                           0, 0, with_bias=False,
                                           format=format))
                .add(nn.SpatialBatchNormalization(n_out, format=format)))
    return nn.Identity()


def basic_block(n_in: int, n_out: int, stride: int = 1,
                format: str = "NCHW") -> nn.Sequential:
    path = (nn.Sequential()
            .add(conv_bn(n_in, n_out, 3, stride, format=format))
            .add(conv_bn(n_out, n_out, 3, 1, relu=False, format=format)))
    return (nn.Sequential()
            .add(nn.ConcatTable().add(path).add(_shortcut(n_in, n_out,
                                                          stride, format)))
            .add(nn.CAddTable())
            .add(nn.ReLU()))


def bottleneck(n_in: int, n_mid: int, stride: int = 1,
               expansion: int = 4, format: str = "NCHW") -> nn.Sequential:
    n_out = n_mid * expansion
    path = (nn.Sequential()
            .add(conv_bn(n_in, n_mid, 1, 1, 0, format=format))
            .add(conv_bn(n_mid, n_mid, 3, stride, format=format))
            .add(conv_bn(n_mid, n_out, 1, 1, 0, relu=False, format=format)))
    return (nn.Sequential()
            .add(nn.ConcatTable().add(path).add(_shortcut(n_in, n_out,
                                                          stride, format)))
            .add(nn.CAddTable())
            .add(nn.ReLU()))


def resnet_cifar(depth: int = 20, class_num: int = 10) -> nn.Sequential:
    """CIFAR-10 ResNet (ref: ResNet.apply with dataSet=CIFAR-10): depth =
    6n+2 basic blocks over 16/32/64 channels on 32x32 inputs."""
    if (depth - 2) % 6 != 0:
        raise ValueError("cifar resnet depth must be 6n+2")
    n = (depth - 2) // 6
    model = nn.Sequential().add(conv_bn(3, 16, 3, 1))
    chans = [(16, 16, 1), (16, 32, 2), (32, 64, 2)]
    for c_in, c_out, stride in chans:
        model.add(basic_block(c_in, c_out, stride))
        for _ in range(n - 1):
            model.add(basic_block(c_out, c_out, 1))
    return (model
            .add(nn.GlobalAveragePooling2D())
            .add(nn.Linear(64, class_num))
            .add(nn.LogSoftMax()))


_IMAGENET_CFG = {
    50: (bottleneck, (3, 4, 6, 3)),
    101: (bottleneck, (3, 4, 23, 3)),
    152: (bottleneck, (3, 8, 36, 3)),
    18: (basic_block, (2, 2, 2, 2)),
    34: (basic_block, (3, 4, 6, 3)),
}


def resnet_imagenet(depth: int = 50, class_num: int = 1000,
                    format: str = "NCHW",
                    remat: bool = False) -> nn.Sequential:
    """ImageNet ResNet (ref: ResNet.apply with dataSet=ImageNet). 224x224
    input; depth 50 is the BASELINE north-star training model.

    ``format="NHWC"`` builds the channels-last variant (channels on the
    TPU's 128-lane minor dim — the layout the bench uses);
    ``remat=True`` wraps each residual block in nn.Checkpoint so block
    interiors are recomputed in backward instead of saved. On this model
    it measured net-negative for throughput (the recompute costs more
    than the saved bytes), so it stays opt-in — its value here is
    fitting larger batches/models in HBM."""
    if depth not in _IMAGENET_CFG:
        raise ValueError(f"unsupported depth {depth}")
    block, stages = _IMAGENET_CFG[depth]
    expansion = 4 if block is bottleneck else 1
    wrap = (lambda m: nn.Checkpoint(m)) if remat else (lambda m: m)
    model = (nn.Sequential()
             .add(conv_bn(3, 64, 7, 2, format=format))
             .add(nn.SpatialMaxPooling(3, 3, 2, 2, -1, -1, format=format)))
    n_in = 64
    width = 64
    for stage_idx, n_blocks in enumerate(stages):
        stride = 1 if stage_idx == 0 else 2
        if block is bottleneck:
            model.add(wrap(block(n_in, width, stride, format=format)))
            n_in = width * expansion
            for _ in range(n_blocks - 1):
                model.add(wrap(block(n_in, width, 1, format=format)))
        else:
            model.add(wrap(block(n_in, width, stride, format=format)))
            n_in = width
            for _ in range(n_blocks - 1):
                model.add(wrap(block(n_in, width, 1, format=format)))
        width *= 2
    return (model
            .add(nn.GlobalAveragePooling2D(format=format))
            .add(nn.Linear(n_in, class_num))
            .add(nn.LogSoftMax()))


def build_model(depth: int = 50, class_num: int = 1000,
                dataset: str = "imagenet") -> nn.Sequential:
    if dataset == "cifar10":
        return resnet_cifar(depth if depth != 50 else 20, class_num)
    return resnet_imagenet(depth, class_num)

"""MNIST autoencoder (ref: .../dllib/models/autoencoder/Autoencoder.scala —
784 → 32 → 784 MLP with sigmoid reconstruction, trained with MSE)."""

from __future__ import annotations

import bigdl_tpu.nn as nn


def build_model(class_num: int = 32) -> nn.Sequential:
    """``class_num`` is the bottleneck width (reference keeps this name)."""
    return (nn.Sequential()
            .add(nn.Reshape([28 * 28]))
            .add(nn.Linear(28 * 28, class_num))
            .add(nn.ReLU())
            .add(nn.Linear(class_num, 28 * 28))
            .add(nn.Sigmoid()))

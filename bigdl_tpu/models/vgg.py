"""VGG (ref: .../dllib/models/vgg/VggForCifar10.scala and the VGG-16
ImageNet graph used by the reference's examples)."""

from __future__ import annotations

import bigdl_tpu.nn as nn


def _conv_relu(n_in, n_out):
    return (nn.Sequential()
            .add(nn.SpatialConvolution(n_in, n_out, 3, 3, 1, 1, 1, 1))
            .add(nn.ReLU()))


def vgg_cifar(class_num: int = 10) -> nn.Sequential:
    """ref: VggForCifar10 — conv-BN stacks over 32x32 with 512-wide head."""
    def conv_bn(n_in, n_out):
        return (nn.Sequential()
                .add(nn.SpatialConvolution(n_in, n_out, 3, 3, 1, 1, 1, 1))
                .add(nn.SpatialBatchNormalization(n_out))
                .add(nn.ReLU()))

    model = nn.Sequential()
    cfg = [(3, 64), (64, 64), "M", (64, 128), (128, 128), "M",
           (128, 256), (256, 256), (256, 256), "M",
           (256, 512), (512, 512), (512, 512), "M",
           (512, 512), (512, 512), (512, 512), "M"]
    for c in cfg:
        if c == "M":
            model.add(nn.SpatialMaxPooling(2, 2, 2, 2))
        else:
            model.add(conv_bn(*c))
    return (model
            .add(nn.Flatten())
            .add(nn.Linear(512, 512))
            .add(nn.BatchNormalization(512))
            .add(nn.ReLU())
            .add(nn.Dropout(0.5))
            .add(nn.Linear(512, class_num))
            .add(nn.LogSoftMax()))


def vgg16(class_num: int = 1000) -> nn.Sequential:
    """VGG-16 ImageNet, 224x224 NCHW."""
    model = nn.Sequential()
    cfg = [(3, 64), (64, 64), "M", (64, 128), (128, 128), "M",
           (128, 256), (256, 256), (256, 256), "M",
           (256, 512), (512, 512), (512, 512), "M",
           (512, 512), (512, 512), (512, 512), "M"]
    for c in cfg:
        if c == "M":
            model.add(nn.SpatialMaxPooling(2, 2, 2, 2))
        else:
            model.add(_conv_relu(*c))
    return (model
            .add(nn.Flatten())
            .add(nn.Linear(512 * 7 * 7, 4096))
            .add(nn.ReLU())
            .add(nn.Dropout(0.5))
            .add(nn.Linear(4096, 4096))
            .add(nn.ReLU())
            .add(nn.Dropout(0.5))
            .add(nn.Linear(4096, class_num))
            .add(nn.LogSoftMax()))


build_model = vgg_cifar
